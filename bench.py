"""Headline benchmark: policy verdicts/sec at 10k rules (BASELINE.md).

Pipeline measured end to end the way the framework runs in production:
1. compile a 10k-rule repository + identity set into device tensors
   (the control-plane step, replacing the O(ids×rules) Go loop),
2. materialize per-endpoint policymap lookup tables on device,
3. stream large flow batches through the 3-gather lookup kernel
   (the bpf/lib/policy.h equivalent) and measure verdicts/sec.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is value / 100e6 (the ≥100M verdicts/s target on v5e-1).
"""

import json
import os
import random
import sys
import time
from typing import Tuple

os.environ.setdefault("XLA_FLAGS", "")
import jax

jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

import jax.numpy as jnp
import numpy as np

from cilium_tpu.engine import PolicyEngine
from cilium_tpu.identity import IdentityRegistry
from cilium_tpu.labels import parse_label_array
from cilium_tpu.ops.lookup import lookup_batch
from cilium_tpu.ops.materialize import materialize_endpoints
from cilium_tpu.policy.api import (
    EndpointSelector,
    IngressRule,
    PortProtocol,
    PortRule,
    rule,
)
from cilium_tpu.policy.repository import Repository

N_RULES = int(os.environ.get("BENCH_RULES", 10_000))
N_IDENTITIES = int(os.environ.get("BENCH_IDENTITIES", 2_048))
N_ENDPOINTS = int(os.environ.get("BENCH_ENDPOINTS", 64))
BATCH = int(os.environ.get("BENCH_BATCH", 1 << 22))
ITERS = int(os.environ.get("BENCH_ITERS", 10))


def build_world(rng: random.Random):
    n_apps = 512
    repo = Repository()
    rules = []
    for i in range(N_RULES):
        app = rng.randrange(n_apps)
        subject = [f"k8s:app=a{app}"]
        peer = EndpointSelector.make([f"k8s:app=a{rng.randrange(n_apps)}"])
        if rng.random() < 0.3:
            port = rng.choice([80, 443, 8080, 53, 5432])
            proto = "UDP" if port == 53 else "TCP"
            ing = IngressRule(
                from_endpoints=(peer,),
                to_ports=(PortRule(ports=(PortProtocol(port, proto),)),),
            )
        else:
            ing = IngressRule(from_endpoints=(peer,))
        rules.append(rule(subject, ingress=[ing]))
    repo.add_list(rules)

    reg = IdentityRegistry()
    idents = []
    for i in range(N_IDENTITIES):
        app = rng.randrange(n_apps)
        labels = [f"k8s:app=a{app}", f"k8s:zone=z{rng.randrange(8)}"]
        if rng.random() < 0.5:
            labels.append(f"k8s:env={'prod' if rng.random() < 0.5 else 'dev'}")
        idents.append(reg.allocate(parse_label_array(labels)))
    return repo, reg, idents


def _bench_ident_update(engine, reg):
    """Median blocking time for one identity allocation to be live in
    the verdict tensors (incremental row update). Returns
    (total_ms, host_ms): host_ms is the CPU-side work (selector match
    + row repack + dispatch enqueue); the remainder is the device
    round trip, which is sub-millisecond on local TPU hardware but
    ~100ms over the axon tunnel — the decomposition keeps environment
    latency from masquerading as engine cost."""
    from cilium_tpu.labels import parse_label_array

    samples = []
    host = []
    for i in range(8):
        labels = parse_label_array(
            [f"k8s:app=a{i % 512}", f"k8s:zone=z{i % 8}", "k8s:env=bench"]
        )
        t0 = time.time()
        ident = reg.allocate(labels)
        engine.refresh()
        host.append(time.time() - t0)
        jax.block_until_ready(engine.device_policy.sel_match)
        samples.append(time.time() - t0)
        # restore the world between samples: without this, each
        # sample's cost depends on how many prior samples accumulated
        # (a crossed row-capacity bucket would force a full rebuild
        # mid-series and skew the median)
        reg.release(ident)
        engine.refresh()
        jax.block_until_ready(engine.device_policy.sel_match)
    mid = len(samples) // 2
    return sorted(samples)[mid] * 1000, sorted(host)[mid] * 1000


def _bench_ident_burst(engine, reg) -> float:
    """Amortized per-identity blocking cost when a CHURN BURST lands as
    one delta batch — the row patches for all k identities ride ONE
    device dispatch (_set_rows2), so the tunnel round trip is paid
    once, not k times. Returns ms per identity (median of 4 bursts)."""
    from cilium_tpu.labels import parse_label_array

    k = 16
    samples = []
    for trial in range(4):
        labels = [
            parse_label_array(
                [f"k8s:app=a{(trial * k + j) % 512}", f"k8s:burst=b{j}"]
            )
            for j in range(k)
        ]
        t0 = time.time()
        batch = [reg.allocate(l) for l in labels]
        engine.refresh()
        jax.block_until_ready(engine.device_policy.sel_match)
        samples.append((time.time() - t0) / k)
        for ident in batch:
            reg.release(ident)
        engine.refresh()
        jax.block_until_ready(engine.device_policy.sel_match)
    return sorted(samples)[len(samples) // 2] * 1000


def _bench_rule_update(engine, repo, rng) -> float:
    """Median blocking time for a single-rule import to be live
    (in-place matrix append)."""
    from cilium_tpu.policy.api import (
        EndpointSelector,
        IngressRule,
        PortProtocol,
        PortRule,
        rule,
    )

    samples = []
    for i in range(8):
        r = rule(
            [f"k8s:app=a{rng.randrange(512)}"],
            ingress=[
                IngressRule(
                    from_endpoints=(
                        EndpointSelector.make([f"k8s:app=a{rng.randrange(512)}"]),
                    ),
                    to_ports=(PortRule(ports=(PortProtocol(443, "TCP"),)),),
                )
            ],
        )
        t0 = time.time()
        repo.add_list([r])
        engine.refresh()
        jax.block_until_ready(engine.device_policy.sel_match)
        samples.append(time.time() - t0)
    return sorted(samples)[len(samples) // 2] * 1000


def _bench_rule_delete(engine, repo, rng) -> float:
    """Median blocking time for a single-rule delete to be live
    (refcounted in-place retraction — the incremental path of
    repository.go DeleteByLabels:286)."""
    from cilium_tpu.labels import parse_label_array
    from cilium_tpu.policy.api import (
        EndpointSelector,
        IngressRule,
        PortProtocol,
        PortRule,
        rule,
    )

    samples = []
    for i in range(8):
        lbl = f"k8s:policy=bench-del-{i}"
        r = rule(
            [f"k8s:app=a{rng.randrange(512)}"],
            ingress=[
                IngressRule(
                    from_endpoints=(
                        EndpointSelector.make([f"k8s:app=a{rng.randrange(512)}"]),
                    ),
                    to_ports=(PortRule(ports=(PortProtocol(443, "TCP"),)),),
                )
            ],
            labels=[lbl],
        )
        repo.add_list([r])
        engine.refresh()
        jax.block_until_ready(engine.device_policy.sel_match)
        t0 = time.time()
        repo.delete_by_labels(parse_label_array([lbl]))
        engine.refresh()
        jax.block_until_ready(engine.device_policy.ingress.allow_t)
        samples.append(time.time() - t0)
    return sorted(samples)[len(samples) // 2] * 1000


def _bench_lpm_50k(nrng: np.random.Generator) -> Tuple[float, float]:
    """50k-prefix LPM match rates (BASELINE.md north-star: the ipcache
    identity-derivation stage at production prefix counts,
    bpf/node_config.h IPCACHE_MAP_SIZE envelope). Two shapes:

    - scattered: prefixes uniform over 2^32 — the adversarial spread
      that forces the 16-8-8 pointer layout (3 chained gathers)
    - clustered: prefixes inside 100 pod-CIDR /16s — the real cluster
      shape, which build_wide_trie serves with the flat 16+16 layout
      (2 chained gathers)
    """
    from cilium_tpu.ops.lpm import WideTrieBuilder, build_wide_trie, lpm_lookup_wide

    def rate(arrays, q):
        arrays = tuple(jnp.asarray(a) for a in arrays)
        q = jnp.asarray(q)
        r = lpm_lookup_wide(*arrays, q)
        jax.block_until_ready(r)
        iters = 10
        t0 = time.time()
        for _ in range(iters):
            r = lpm_lookup_wide(*arrays, q)
        jax.block_until_ready(r)
        return iters * q.shape[0] / (time.time() - t0)

    b = 1 << 20
    tb = WideTrieBuilder()
    addrs = nrng.integers(0, 2**32, 50_000, dtype=np.uint64).astype(np.uint32)
    plens = nrng.choice(np.array([8, 12, 16, 20, 24, 28, 32]), 50_000)
    for a, pl in zip(addrs.tolist(), plens.tolist()):
        tb.insert(a, pl, a % 65000)
    scattered = rate(
        tb.arrays(),
        nrng.integers(0, 2**32, b, dtype=np.uint64).astype(np.uint32),
    )

    hi16 = nrng.integers(0, 2**16, 100, dtype=np.uint64).astype(np.uint32)
    lo = nrng.integers(0, 2**16, 50_000, dtype=np.uint64).astype(np.uint32)
    c_addrs = (nrng.choice(hi16, 50_000) << np.uint32(16)) | lo
    c_plens = nrng.choice(np.array([20, 24, 28, 32]), 50_000)
    clustered = rate(
        build_wide_trie(
            (f"{a >> 24 & 255}.{a >> 16 & 255}.{a >> 8 & 255}.{a & 255}/{pl}", int(a % 65000))
            for a, pl in zip(c_addrs.tolist(), c_plens.tolist())
        ),
        (nrng.choice(hi16, b) << np.uint32(16))
        | nrng.integers(0, 2**16, b, dtype=np.uint64).astype(np.uint32),
    )
    return scattered, clustered


def _bench_l7_dfa() -> float:
    """HTTP multi-pattern DFA request rate (the NPDS regex matcher,
    envoy/cilium_network_policy.h:68-202, as one device dispatch)."""
    from cilium_tpu.l7.regex_compile import compile_patterns
    from cilium_tpu.ops.dfa import device_dfa, dfa_match_batch, strings_to_batch

    patterns = [f"/api/v{i}/[a-z0-9]*" for i in range(8)] + [
        f"/svc{i}/.*" for i in range(8)
    ]
    dev = device_dfa(compile_patterns(patterns))
    b = 1 << 17
    paths = [f"/api/v{i % 8}/obj{i % 97}".encode() for i in range(b)]
    sb, lens = strings_to_batch(paths, 64)
    sbj, lj = jnp.asarray(sb), jnp.asarray(lens)
    lo, hi = dfa_match_batch(*dev, sbj, lj, 64)
    jax.block_until_ready(lo)
    iters = 10
    t0 = time.time()
    for _ in range(iters):
        lo, hi = dfa_match_batch(*dev, sbj, lj, 64)
    jax.block_until_ready(lo)
    return iters * b / (time.time() - t0)


def _bench_kafka_acl() -> float:
    """Kafka ACL batch rate (pkg/kafka/policy.go MatchesRule hoisted to
    broadcast compares)."""
    from cilium_tpu.l7.kafka_policy import KafkaACL, KafkaRequest
    from cilium_tpu.policy.api import KafkaRule

    acl = KafkaACL(
        [(KafkaRule(role="produce", topic=f"t{i}"), None) for i in range(32)]
    )
    reqs = [
        KafkaRequest(api_key=0, api_version=2, client_id="c", topic=f"t{i % 48}")
        for i in range(100_000)
    ]
    acl.check_batch(reqs[:1000])
    iters = 5
    t0 = time.time()
    for _ in range(iters):
        acl.check_batch(reqs)
    return iters * len(reqs) / (time.time() - t0)


def _bench_l7() -> dict:
    """policyd-l7batch round: fused multi-field dispatch vs the split
    per-field program on the SAME 16-pattern corpus the full sweep's
    l7_dfa_rps tracks, per-length-rung rates, pipeline overlap
    (depth 2 vs 1, packing included), and the kafka ACL rate with and
    without device literal classification. Runs without the built
    world — L7 tables are per-(endpoint, port), not per-rule-set."""
    from cilium_tpu.datapath import l7_pipeline as l7rt
    from cilium_tpu.datapath.l7_pipeline import L7Pipeline
    from cilium_tpu.l7.regex_compile import compile_patterns
    from cilium_tpu.ops.dfa import (
        L7_LEN_LADDER,
        DeviceDFATable,
        device_dfa,
        dfa_intern_stats,
        dfa_match_batch,
        dfa_match_batch_fused,
        dfa_match_batch_pair,
        fuse_dfas,
        strings_to_batch,
        strings_to_batch_u8,
    )

    patterns = [f"/api/v{i}/[a-z0-9]*" for i in range(8)] + [
        f"/svc{i}/.*" for i in range(8)
    ]
    mdfa = compile_patterns(patterns)
    b = 1 << 17
    iters = 10
    paths = [f"/api/v{i % 8}/obj{i % 97}".encode() for i in range(b)]

    # split baseline: the exact pre-option program (one field's DFA,
    # 64-deep unbucketed int32 walk — the definition l7_dfa_rps has
    # carried since BENCH_r01, packing outside the timed loop)
    dev = device_dfa(mdfa)
    sb, lens = strings_to_batch(paths, 64)
    sbj, lj = jnp.asarray(sb), jnp.asarray(lens)
    jax.block_until_ready(dfa_match_batch(*dev, sbj, lj, 64)[0])
    t0 = time.time()
    for _ in range(iters):
        lo, _hi = dfa_match_batch(*dev, sbj, lj, 64)
    jax.block_until_ready(lo)
    split_rps = iters * b / (time.time() - t0)

    table = DeviceDFATable(("bench-l7",), fuse_dfas([mdfa]))
    starts = jnp.asarray(np.zeros(b, np.int32))

    # per-rung fused/pair rates, same dispatch-rate definition. The
    # corpus tops out at 13 bytes; for the taller rungs each path grows
    # an [a-z0-9]* tail so every row still matches its /api pattern.
    rung_rps = {}
    for rung in L7_LEN_LADDER:
        rp = (
            paths
            if rung == L7_LEN_LADDER[0]
            else [(p + b"x" * rung)[:rung] for p in paths]
        )
        usb, ulens = strings_to_batch_u8(rp, rung)
        usbj, ulj = jnp.asarray(usb), jnp.asarray(ulens)
        if table.has_pair:
            def walk(r=rung, sbuf=usbj, lbuf=ulj):
                return dfa_match_batch_pair(
                    table.pair, table.accept_lo, table.accept_hi,
                    starts, sbuf, lbuf, r,
                )
        else:
            def walk(r=rung, sbuf=usbj, lbuf=ulj):
                return dfa_match_batch_fused(
                    table.trans, table.accept_lo, table.accept_hi,
                    starts, sbuf, lbuf, r,
                )
        jax.block_until_ready(walk()[0])
        t0 = time.time()
        for _ in range(iters):
            lo, _hi = walk()
        jax.block_until_ready(lo)
        rung_rps[str(rung)] = round(iters * b / (time.time() - t0))

    # headline: the corpus's own rung (16) — what check_batch picks
    fused_rps = float(rung_rps[str(L7_LEN_LADDER[0])])

    # end-to-end submit() rate, packing + host_sync included, and the
    # overlap ratio the pipeline buys (depth 2 vs fully synchronous)
    def e2e(depth: int, it: int = 8) -> float:
        pipe = L7Pipeline(depth=depth)
        pipe.prewarm(table, [64])
        for pend in [pipe.submit(table, [(paths, 64)]) for _ in range(2)]:
            pend.result()  # warm lane buffers before timing
        t0 = time.time()
        pending = [pipe.submit(table, [(paths, 64)]) for _ in range(it)]
        for pend in pending:
            pend.result()
        return it * b / (time.time() - t0)

    e2e_d2 = e2e(2)
    e2e_d1 = e2e(1)

    # kafka in the same round (closes the r03→r04 kafka_acl_rps drop
    # investigation: both paths, same corpus, one report)
    kafka_host = _bench_kafka_acl()
    l7rt.set_device_batch(True)
    try:
        kafka_dev = _bench_kafka_acl()
    finally:
        l7rt.set_device_batch(False)

    return {
        "l7_dfa_rps": round(fused_rps),
        "split_l7_dfa_rps": round(split_rps),
        "fused_vs_split_ratio": round(fused_rps / split_rps, 1),
        "rung_rps": rung_rps,
        "pair_table": bool(table.has_pair),
        "e2e_submit_depth2_rps": round(e2e_d2),
        "e2e_submit_depth1_rps": round(e2e_d1),
        "overlap_ratio": round(e2e_d2 / e2e_d1, 2),
        "kafka_acl_rps": round(kafka_host),
        "kafka_acl_device_rps": round(kafka_dev),
        "interned_tables": dfa_intern_stats()[0],
    }


def _bench_native(snaps, idents, nrng: np.random.Generator):
    """Native C++ front-end rate on the SAME materialized state (the
    per-node enforcement loop; SURVEY native census item 1). Returns
    (single_thread_vps, {n_threads: vps}) — the multi-thread sweep
    exercises the snapshot-read/atomic-counter eval path (one loader /
    N evaluators)."""
    from cilium_tpu.identity.model import ID_WORLD
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.native import NativeFastpath, native_available

    if not native_available():
        return 0.0, {}
    cache = IPCache()
    for i, ident in enumerate(idents):
        cache.upsert(f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s")
    nf = NativeFastpath(ep_count=N_ENDPOINTS, ct_bits=0)
    nf.set_world_identity(ID_WORLD)
    nf.load_policy_snapshots(snaps)
    nf.load_ipcache(cache)
    b = 1 << 20
    i_sel = nrng.integers(0, len(idents), b)
    ips = (
        np.uint32(10) << 24
        | ((i_sel >> 8) & 255).astype(np.uint32) << 16
        | (i_sel & 255).astype(np.uint32) << 8
        | 1
    ).astype(np.uint32)
    eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
    dports = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b)
    protos = np.where(dports == 53, 17, 6).astype(np.int32)
    nf.process(ips[:1000], eps[:1000], dports[:1000], protos[:1000])
    iters = 5
    t0 = time.time()
    for _ in range(iters):
        nf.process(ips, eps, dports, protos)
    single = iters * b / (time.time() - t0)

    import threading

    def run_threads(k: int) -> float:
        barrier = threading.Barrier(k + 1)

        def worker():
            barrier.wait()
            for _ in range(iters):
                nf.process(ips, eps, dports, protos)

        ts = [threading.Thread(target=worker) for _ in range(k)]
        for t in ts:
            t.start()
        barrier.wait()
        t0 = time.time()
        for t in ts:
            t.join()
        return k * iters * b / (time.time() - t0)

    ncpu = os.cpu_count() or 1
    mt = {}
    for k in (4, 8):
        if ncpu >= 2:  # scaling is meaningless on one core
            mt[k] = run_threads(k)
    return single, mt


def _bench_pipeline_e2e(
    repo, reg, idents, nrng: np.random.Generator
) -> Tuple[float, float, float]:
    """→ (v4_rate, v6_rate, fused_prefilter_rate).

    Device-resident FULL datapath chain (deny-LPM skip on empty
    prefilter → identity LPM → policymap lookup → counters) on one
    pre-staged batch — the cold-flow batch path a host front-end feeds.
    Host→device transfer is excluded: over the axon tunnel the PCIe
    analogue costs ~seconds/GB and would measure the tunnel, not the
    engine (production front-ends stream batches asynchronously)."""
    from cilium_tpu.datapath.pipeline import (
        TRAFFIC_INGRESS,
        DatapathPipeline,
        process_flows_wide,
    )
    from cilium_tpu.engine import PolicyEngine
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.ipcache.prefilter import PreFilter

    eng = PolicyEngine(repo, reg)
    cache = IPCache()
    for i, ident in enumerate(idents):
        cache.upsert(
            f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s"
        )
    pipe = DatapathPipeline(eng, cache, PreFilter(), conntrack=None)
    pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    b = 1 << 20
    i_sel = nrng.integers(0, len(idents), b)
    ips = (
        np.uint32(10) << 24
        | ((i_sel >> 8) & 255).astype(np.uint32) << 16
        | (i_sel & 255).astype(np.uint32) << 8
        | 1
    ).astype(np.uint32)
    eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
    dports = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b)
    protos = np.where(dports == 53, 17, 6).astype(np.int32)
    pipe.process(ips[:1024], eps[:1024], dports[:1024], protos[:1024])
    t = pipe._tables[(TRAFFIC_INGRESS, 4)]
    d = [jnp.asarray(a) for a in (ips, eps, dports, protos)]
    pf_stage = not pipe._pf_empty[0]

    def run():
        v, _red, _c = process_flows_wide(
            t, *d, ep_count=N_ENDPOINTS, prefilter=pf_stage,
            row_override=None,
        )
        return v

    jax.block_until_ready(run())
    iters = 10
    t0 = time.time()
    for _ in range(iters):
        v = run()
    jax.block_until_ready(v)
    v4_rate = iters * b / (time.time() - t0)

    # ── ACTIVE prefilter: the fused deny+identity flat walk (ops/lpm
    # merge_flat_tries) — one 2-gather pass answers both the XDP deny
    # check and the identity derivation. Reported separately so the
    # fusion's effect is visible against the deny-stage-skipped number
    # above.
    pf2 = PreFilter()
    pf2.insert(pf2.revision, [
        "192.0.2.0/24", "198.51.100.0/24", "10.3.0.0/16", "10.250.7.0/28",
    ])
    pipe_pf = DatapathPipeline(eng, cache, pf2, conntrack=None)
    pipe_pf.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    pipe_pf.process(ips[:1024], eps[:1024], dports[:1024], protos[:1024])
    t_pf = pipe_pf._tables[(TRAFFIC_INGRESS, 4)]
    fused = t_pf.merged_sub_info.shape[-1] == 65536

    def run_pf():
        v, _red, _c = process_flows_wide(
            t_pf, *d, ep_count=N_ENDPOINTS, prefilter=True,
            row_override=None,
        )
        return v

    jax.block_until_ready(run_pf())
    t0 = time.time()
    for _ in range(iters):
        v = run_pf()
    jax.block_until_ready(v)
    pf_rate = iters * b / (time.time() - t0)
    if not fused:
        pf_rate = -pf_rate  # flag: fusion unexpectedly not built

    # IPv6: same chain over the elided stride-8 tries (shared-prefix
    # bytes compared, not walked)
    from cilium_tpu.datapath.pipeline import process_flows

    cache6 = IPCache()
    for i, ident in enumerate(idents):
        cache6.upsert(
            f"fd00::{(i >> 8) & 255:x}:{i & 255:x}/128", ident.id,
            source="k8s",
        )
    pipe6 = DatapathPipeline(eng, cache6, PreFilter(), conntrack=None)
    pipe6.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    b6 = 1 << 18
    i6 = nrng.integers(0, len(idents), b6)
    addrs = np.zeros((b6, 16), np.int32)
    addrs[:, 0] = 0xFD
    addrs[:, 13] = (i6 >> 8) & 255
    addrs[:, 15] = i6 & 255
    eps6 = nrng.integers(0, N_ENDPOINTS, b6).astype(np.int32)
    dp6 = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b6)
    pr6 = np.where(dp6 == 53, 17, 6).astype(np.int32)
    pipe6.process_v6(addrs[:1024], eps6[:1024], dp6[:1024], pr6[:1024])
    t6 = pipe6._tables[(TRAFFIC_INGRESS, 6)]
    d6 = [jnp.asarray(a) for a in (addrs, eps6, dp6, pr6)]

    def run6():
        v, _red, _c = process_flows(
            t6, *d6, ep_count=N_ENDPOINTS, levels=16,
            prefilter=False, row_override=None,
        )
        return v

    jax.block_until_ready(run6())
    t0 = time.time()
    for _ in range(iters):
        v = run6()
    jax.block_until_ready(v)
    return v4_rate, iters * b6 / (time.time() - t0), pf_rate


def _bench_overlap(
    repo, reg, idents, nrng: np.random.Generator
) -> Tuple[float, float]:
    """→ (overlap_ratio, pipelined_vps).

    Achieved dispatch overlap at depth 2: K host-fed batches run
    back-to-back synchronously (process() = enqueue + immediate pull)
    vs pipelined (submit() defers each pull behind the NEXT batch's
    host prep). The ratio reports how much of the pure device
    execution time the overlap hid:

        (t_sync − t_pipelined) / t_device   clamped to [0, 1]

    → 0 on a host-bound box (nothing worth hiding), → 1 when host prep
    fully covers device execution."""
    from cilium_tpu.datapath.pipeline import (
        TRAFFIC_INGRESS,
        DatapathPipeline,
        process_flows_wide,
    )
    from cilium_tpu.engine import PolicyEngine
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.ipcache.prefilter import PreFilter

    eng = PolicyEngine(repo, reg)
    cache = IPCache()
    for i, ident in enumerate(idents):
        cache.upsert(
            f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s"
        )
    pipe = DatapathPipeline(
        eng, cache, PreFilter(), conntrack=None, pipeline_depth=2
    )
    pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    b, k = 1 << 18, 8
    batches = []
    for _ in range(k):
        i_sel = nrng.integers(0, len(idents), b)
        ips = (
            np.uint32(10) << 24
            | ((i_sel >> 8) & 255).astype(np.uint32) << 16
            | (i_sel & 255).astype(np.uint32) << 8
            | 1
        ).astype(np.uint32)
        eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
        dports = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b)
        protos = np.where(dports == 53, 17, 6).astype(np.int32)
        batches.append((ips, eps, dports, protos))
    pipe.process(*batches[0])  # warm the jit cache + tables

    t0 = time.time()
    for bt in batches:
        pipe.process(*bt)
    t_sync = time.time() - t0

    t0 = time.time()
    pend = [pipe.submit(*bt) for bt in batches]
    for p in pend:
        p.result()
    t_pipe = time.time() - t0

    # pure device execution for the same K batches: pre-staged device
    # arrays, one fused dispatch each, single block at the end
    t = pipe._tables[(TRAFFIC_INGRESS, 4)]
    staged = [tuple(jnp.asarray(a) for a in bt) for bt in batches]
    pf_stage = not pipe._pf_empty[0]
    v = None
    for d in staged[:1]:  # warm this exact shape
        v, _red, _c = process_flows_wide(
            t, *d, ep_count=N_ENDPOINTS, prefilter=pf_stage,
            row_override=None,
        )
    jax.block_until_ready(v)
    t0 = time.time()
    for d in staged:
        v, _red, _c = process_flows_wide(
            t, *d, ep_count=N_ENDPOINTS, prefilter=pf_stage,
            row_override=None,
        )
    jax.block_until_ready(v)
    t_dev = time.time() - t0

    hidden = max(0.0, t_sync - t_pipe)
    ratio = min(1.0, hidden / t_dev) if t_dev > 0 else 0.0
    return ratio, k * b / t_pipe


def _bench_flows(
    repo, reg, idents, nrng: np.random.Generator
) -> Tuple[float, float, float]:
    """``--flows``: FlowAttribution cost on the N_RULES world →
    (off_vps, on_vps, overhead_pct).

    Same pipeline, same batches, pipelined dispatch at depth 2; the
    only variable is the attribution program — the origin tail in the
    verdict kernel, the [R] hit segment-sum, the wider completion pull
    (6 arrays instead of 3), the metric accounting, and the sampled
    flow-ring records. Verdicts are asserted bit-identical across the
    two modes, so the overhead number can never come from a diverged
    program."""
    from cilium_tpu.datapath.pipeline import DatapathPipeline
    from cilium_tpu.engine import PolicyEngine
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.ipcache.prefilter import PreFilter

    eng = PolicyEngine(repo, reg)
    cache = IPCache()
    for i, ident in enumerate(idents):
        cache.upsert(
            f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s"
        )
    pipe = DatapathPipeline(
        eng, cache, PreFilter(), conntrack=None, pipeline_depth=2
    )
    pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    b, k = 1 << 18, 8
    batches = []
    for _ in range(k):
        i_sel = nrng.integers(0, len(idents), b)
        ips = (
            np.uint32(10) << 24
            | ((i_sel >> 8) & 255).astype(np.uint32) << 16
            | (i_sel & 255).astype(np.uint32) << 8
            | 1
        ).astype(np.uint32)
        eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
        dports = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b)
        protos = np.where(dports == 53, 17, 6).astype(np.int32)
        batches.append((ips, eps, dports, protos))

    def timed_run():
        pipe.process(*batches[0])  # warm this mode's program
        t0 = time.time()
        pend = [pipe.submit(*bt) for bt in batches]
        out = [p.result() for p in pend]
        return time.time() - t0, out

    t_off, off = timed_run()
    pipe.set_attribution(True)
    pipe.rebuild()
    t_on, on = timed_run()
    for (v0, r0), (v1, r1) in zip(off, on):
        np.testing.assert_array_equal(v0, v1)
        np.testing.assert_array_equal(r0, r1)
    overhead = (t_on - t_off) / t_off * 100.0 if t_off > 0 else 0.0
    return k * b / t_off, k * b / t_on, overhead


def _bench_prof(repo, reg, idents, nrng: np.random.Generator, attached):
    """``--prof``: policyd-prof round → result dict for the one-line
    JSON. Three measurements on the N_RULES world, depth-1 pipeline
    (no overlap, so one batch's dispatch+host_sync spans ARE its RTT):

    1. RTT decomposition at sample_every=1: every batch pays the
       block_until_ready sandwiches; the mean h2d+compute+d2h sum per
       batch is compared against the tracer-measured dispatch +
       host_sync wall time of the SAME batches. Sound when the error
       is within 10% (the residual is host bookkeeping inside the
       dispatch span — chunk planning, metric accounting).
    2. Verdict parity: profiling must not change a single verdict.
    3. profiling_overhead_pct: e2e rate with sampling at the DEFAULT
       sample_every=64 vs fully off, both warm (<2% target).
    """
    from cilium_tpu.datapath.pipeline import DatapathPipeline
    from cilium_tpu.engine import PolicyEngine
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.ipcache.prefilter import PreFilter

    eng = PolicyEngine(repo, reg)
    cache = IPCache()
    for i, ident in enumerate(idents):
        cache.upsert(
            f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s"
        )
    pipe = DatapathPipeline(eng, cache, PreFilter(), conntrack=None)
    pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    b, k = 1 << 18, 8
    batches = []
    for _ in range(k):
        i_sel = nrng.integers(0, len(idents), b)
        ips = (
            np.uint32(10) << 24
            | ((i_sel >> 8) & 255).astype(np.uint32) << 16
            | (i_sel & 255).astype(np.uint32) << 8
            | 1
        ).astype(np.uint32)
        eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
        dports = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b)
        protos = np.where(dports == 53, 17, 6).astype(np.int32)
        batches.append((ips, eps, dports, protos))

    def run_all():
        pipe.process(*batches[0])  # warm this mode's program
        t0 = time.time()
        out = [pipe.process(*bt) for bt in batches]
        return time.time() - t0, out

    t_off, off = run_all()
    attached.stage("prof-baseline")

    # every batch sampled AND traced: the profiler's decomposition vs
    # the tracer's independent wall clock over the same dispatches
    pipe.tracer.enable()
    pipe.set_profiling(True, sample_every=1)
    _t, on = run_all()
    pipe.tracer.disable()
    for (v0, r0), (v1, r1) in zip(off, on):
        np.testing.assert_array_equal(v0, v1)
        np.testing.assert_array_equal(r0, r1)
    prof = pipe.profiler
    samples = prof.samples()
    n_s = max(1, len(samples))
    h2d = sum(s["h2d_ms"] for s in samples) / n_s
    comp = sum(s["device_compute_ms"] for s in samples) / n_s
    d2h = sum(s["d2h_ms"] for s in samples) / n_s
    span_ms, n_t = 0.0, 0
    for t in pipe.tracer.traces():
        durs = {name: dur for name, _rel, dur in t["phases"]}
        if "dispatch" in durs:
            span_ms += (durs["dispatch"] + durs.get("host_sync", 0)) / 1e6
            n_t += 1
    measured_ms = span_ms / max(1, n_t)
    decomposed_ms = h2d + comp + d2h
    err_pct = (
        abs(decomposed_ms - measured_ms) / measured_ms * 100.0
        if measured_ms > 0 else 100.0
    )
    attached.stage("prof-decomposition")

    # overhead at the shipping default, warm off-baseline re-measured
    # so jit warmup never lands in the delta
    pipe.set_profiling(True, sample_every=64)
    t_on64, _ = run_all()
    pipe.set_profiling(False)
    t_off2, _ = run_all()
    base = min(t_off, t_off2)
    overhead = (t_on64 - base) / base * 100.0 if base > 0 else 0.0
    return {
        "dispatch_rtt_ms": round(measured_ms, 3),
        "h2d_ms": round(h2d, 3),
        "device_compute_ms": round(comp, 3),
        "d2h_ms": round(d2h, 3),
        "rtt_decomposition_err_pct": round(err_pct, 2),
        "rtt_decomposition_sound": bool(err_pct <= 10.0),
        "profiling_overhead_pct": round(overhead, 2),
        "prof_off_vps": round(k * b / base) if base > 0 else 0,
        "prof_on_vps": round(k * b / t_on64) if t_on64 > 0 else 0,
        "profile_samples": len(samples),
        "jit_sites": len(prof.jit_costs()),
        "sample_every": 64,
    }


def _bench_tune(repo, reg, idents, nrng: np.random.Generator, attached):
    """``--tune``: policyd-autotune round → result dict for the
    one-line JSON. Three measurements on the N_RULES world:

    - depth sweep 1..verdict-pipeline-max-depth: pipelined vps and the
      achieved overlap_ratio per depth (the PR 3 methodology with the
      depth held fixed), plus the smallest depth within 3% of the best
      vps as ``sweep_optimal_depth`` (ties go shallow — extra depth
      past saturation only ages batches);
    - controller convergence: the same pipeline reset to depth 1 with
      DispatchAutoTune on (short epochs), fed until the tuner rests —
      its depth lands within ±1 of the sweep optimum;
    - pad waste: CT-miss tails of awkward sizes (1100/3000/5000
      flows) through the bucket ladder, reported as pad/(live+pad)
      from dispatch_pad_lanes_total next to what the single-4096-
      bucket scheme pads for the same tails."""
    from cilium_tpu import metrics as _m
    from cilium_tpu.datapath.conntrack import FlowConntrack
    from cilium_tpu.datapath.pipeline import (
        TRAFFIC_INGRESS,
        DatapathPipeline,
        process_flows_wide,
    )
    from cilium_tpu.engine import PolicyEngine
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.ipcache.prefilter import PreFilter
    from cilium_tpu.option import get_config

    eng = PolicyEngine(repo, reg)
    cache = IPCache()
    for i, ident in enumerate(idents):
        cache.upsert(
            f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s"
        )

    def make_batch(b):
        i_sel = nrng.integers(0, len(idents), b)
        ips = (
            np.uint32(10) << 24
            | ((i_sel >> 8) & 255).astype(np.uint32) << 16
            | (i_sel & 255).astype(np.uint32) << 8
            | 1
        ).astype(np.uint32)
        eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
        dports = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b)
        protos = np.where(dports == 53, 17, 6).astype(np.int32)
        return ips, eps, dports, protos

    max_depth = get_config().verdict_pipeline_max_depth
    pipe = DatapathPipeline(
        eng, cache, PreFilter(), conntrack=None,
        pipeline_depth=1, pipeline_max_depth=max_depth,
    )
    pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    b, k = 1 << 16, 8
    batches = [make_batch(b) for _ in range(k)]
    pipe.process(*batches[0])  # warm the jit cache + tables

    # pure device execution for the same K batches — the denominator
    # of overlap_ratio (what there is to hide)
    t = pipe._tables[(TRAFFIC_INGRESS, 4)]
    staged = [tuple(jnp.asarray(a) for a in bt) for bt in batches]
    pf_stage = not pipe._pf_empty[0]
    v, _red, _c = process_flows_wide(
        t, *staged[0], ep_count=N_ENDPOINTS, prefilter=pf_stage,
        row_override=None,
    )
    jax.block_until_ready(v)
    t0 = time.time()
    for d_ in staged:
        v, _red, _c = process_flows_wide(
            t, *d_, ep_count=N_ENDPOINTS, prefilter=pf_stage,
            row_override=None,
        )
    jax.block_until_ready(v)
    t_dev = time.time() - t0

    per_depth = {}
    t_sync = None
    for depth in range(1, max_depth + 1):
        attached.stage(f"tune-sweep:d{depth}")
        pipe.pipeline_depth = depth
        for p in [pipe.submit(*bt) for bt in batches]:  # settle
            p.result()
        t0 = time.time()
        for p in [pipe.submit(*bt) for bt in batches]:
            p.result()
        td = time.time() - t0
        if depth == 1:
            t_sync = td
        hidden = max(0.0, t_sync - td)
        per_depth[depth] = {
            "vps": round(k * b / td),
            "overlap_ratio": round(
                min(1.0, hidden / t_dev) if t_dev > 0 else 0.0, 3
            ),
        }
    best = max(s["vps"] for s in per_depth.values())
    sweep_optimal = min(
        d for d, s in per_depth.items() if s["vps"] >= best * 0.97
    )

    # controller convergence from a cold depth-1 start (short epochs
    # so ~16 decision points fit in the round)
    attached.stage("tune-converge")
    pipe.pipeline_depth = 1
    pipe.set_autotune(True, max_depth=max_depth, epoch=4)
    small = [make_batch(1 << 14) for _ in range(8)]
    for _ in range(8):
        for p in [pipe.submit(*bt) for bt in small]:
            p.result()
    converged = pipe.pipeline_depth
    snap = pipe.autotune_state()
    pipe.set_autotune(False)

    # bucket-ladder pad waste on CT-miss tails (the ISSUE's 1100-flow
    # example padded to 4096 under the single-bucket scheme)
    attached.stage("tune-padwaste")
    ct_pipe = DatapathPipeline(
        eng, cache, PreFilter(),
        conntrack=FlowConntrack(capacity_bits=12), pipeline_depth=2,
    )
    ct_pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    pad0 = _m.dispatch_pad_lanes_total.get({"family": "v4"})
    tails = (1100, 3000, 5000)
    live = 0
    for n in tails:
        # each new rung shape compiles the fused CT program — heartbeat
        # per tail so a slow compile is distinguishable from a wedge
        attached.stage(f"tune-padwaste:{n}")
        bt = make_batch(n)
        ct_pipe.process(
            *bt, sports=nrng.integers(1024, 60000, n).astype(np.int32)
        )
        live += n
    ct_pipe.drain()
    pad = _m.dispatch_pad_lanes_total.get({"family": "v4"}) - pad0
    single = sum(-(-n // 4096) * 4096 for n in tails)
    return {
        "per_depth": {str(d): s for d, s in per_depth.items()},
        "sweep_optimal_depth": sweep_optimal,
        "converged_depth": converged,
        "converged_within_one": abs(converged - sweep_optimal) <= 1,
        "autotune_adjustments": snap["adjustments"],
        "pad_lanes": int(pad),
        "pad_waste_pct": round(pad / (live + pad) * 100.0, 2),
        "pad_waste_pct_single_bucket": round(
            (single - live) / single * 100.0, 2
        ),
    }


def _bench_chaos(repo, reg, idents, nrng: np.random.Generator, attached):
    """``--chaos``: policyd-failsafe round → result dict for the
    one-line JSON. Fixed-seed fault injection at ≥4 distinct sites
    through the REAL pipeline:

    - transient faults at h2d/complete retried invisibly (verdicts
      match the clean reference bit-for-bit);
    - a kvstore partition (transient pump fault) proven eventually
      consistent — the withheld event applies on the next pump;
    - poisoned faults trip the breaker down the full ladder
      (sharded → single-device → host), with host-mode verdicts
      asserted equal to the device reference;
    - clean traffic re-promotes back to level 0 without a restart
      (``recovery_s`` measures fault → healthy);
    - a transient attach fault exercises the bounded attach retry.

    Every submitted flow must come back with a verdict —
    ``verdicts_lost`` is computed, not assumed, and must be 0;
    fail-closed batches carry DROP_DEGRADED (monitor reason 155)."""
    from cilium_tpu import faults as _faults
    from cilium_tpu import metrics as _m
    from cilium_tpu.datapath.pipeline import DROP_DEGRADED, DatapathPipeline
    from cilium_tpu.engine import PolicyEngine
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.ipcache.prefilter import PreFilter
    from cilium_tpu.kvstore.backend import InMemoryBackend, InMemoryStore
    from cilium_tpu.kvstore.store import SharedStore

    _faults.hub.reset()
    eng = PolicyEngine(repo, reg)
    cache = IPCache()
    for i, ident in enumerate(idents):
        cache.upsert(
            f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s"
        )
    pipe = DatapathPipeline(
        eng, cache, PreFilter(), conntrack=None, pipeline_depth=2
    )
    pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    # shrink the breaker so the full ladder fits in a bench round
    pipe.breaker_threshold = 2
    pipe.recover_after_clean = 3
    pipe.retry_min_s = pipe.retry_max_s = 0.001

    b = 1 << 12
    batches = []
    for _ in range(4):
        i_sel = nrng.integers(0, len(idents), b)
        ips = (
            np.uint32(10) << 24
            | ((i_sel >> 8) & 255).astype(np.uint32) << 16
            | (i_sel & 255).astype(np.uint32) << 8
            | 1
        ).astype(np.uint32)
        eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
        dports = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b)
        protos = np.where(dports == 53, 17, 6).astype(np.int32)
        batches.append((ips, eps, dports, protos))

    submitted = 0
    resolved = 0
    degraded_flows = 0

    def run(bt):
        nonlocal submitted, resolved, degraded_flows
        submitted += bt[0].shape[0]
        v, _red = pipe.process(*bt)
        resolved += int(v.shape[0])
        degraded_flows += int((v == DROP_DEGRADED).sum())
        return v

    reason0 = _m.drop_reasons_total.get({"reason": "pipeline-degraded"})
    attached.stage("chaos-baseline")
    ref_v = run(batches[0])  # clean level-0 reference (warms the jit)

    # transient faults: retried inside the pipeline, invisible outside
    attached.stage("chaos-transient")
    _faults.hub.fail(_faults.SITE_H2D, _faults.KIND_TRANSIENT, times=1)
    _faults.hub.fail(_faults.SITE_COMPLETE, _faults.KIND_TRANSIENT, times=1)
    v = run(batches[0])
    transparent = bool(np.array_equal(v, ref_v))

    # kvstore partition: the pump returns 0 applied, the event is NOT
    # lost — it lands on the next pump
    attached.stage("chaos-kvstore")
    store = SharedStore(InMemoryBackend(InMemoryStore()), "chaos")
    store.backend.update(store._key_path("k1"), b'{"v": 1}')
    _faults.hub.fail(_faults.SITE_KVSTORE, _faults.KIND_TRANSIENT, times=1)
    partition_held = store.pump() == 0 and "k1" not in store.shared
    kv_recovered = store.pump() >= 1 and "k1" in store.shared

    # poisoned faults: breaker trips down the full ladder
    attached.stage("chaos-descend")
    t_fault = time.time()
    for site in (_faults.SITE_COMPLETE, _faults.SITE_COMPLETE,
                 _faults.SITE_DISPATCH, _faults.SITE_DISPATCH):
        _faults.hub.fail(site, _faults.KIND_POISONED, times=1)
        run(batches[1])
    modes = [pipe.pipeline_mode]
    host_v = run(batches[0])  # clean batch on the host/numpy path
    host_parity = bool(np.array_equal(host_v, ref_v))

    # recovery: clean traffic walks the ladder back up, no restart
    attached.stage("chaos-recover")
    recovery_rounds = 0
    while pipe.pipeline_mode != "sharded" and recovery_rounds < 64:
        run(batches[2 + (recovery_rounds % 2)])
        recovery_rounds += 1
        if pipe.pipeline_mode not in modes:
            modes.append(pipe.pipeline_mode)
    recovery_s = time.time() - t_fault
    v = run(batches[0])
    recovered_parity = bool(np.array_equal(v, ref_v))

    # attach: a transient handshake fault absorbed by the bounded retry
    attached.stage("chaos-attach")
    _faults.hub.fail(_faults.SITE_ATTACH, _faults.KIND_TRANSIENT, times=1)
    reattached = _attach_backend(attached, 60.0, attempts=2)

    # overload: oversubscribed storm with queue_full + stall injected
    overload = _chaos_overload(eng, cache, idents, nrng, attached)

    # federation: partition + lease expiry during two-node allocation
    federation = _chaos_federation(attached)

    # survive: kill -9 restart, raced rule change, SIGTERM drain, and a
    # torn CT write, each in a real subprocess daemon (policyd-survive)
    survive = _chaos_survive(attached)

    snap = _faults.hub.snapshot()
    _faults.hub.reset()
    sites = sorted({k.split(":")[0] for k in snap["injected"]})
    return {
        "chaos_seed": 21,  # the nrng seed main() hands every round
        "sites_injected": sites,
        "distinct_sites": len(sites),
        "faults_injected": int(sum(snap["injected"].values())),
        "verdicts_lost": submitted - resolved,
        "degraded_flows": degraded_flows,
        "reason_155_flows": int(
            _m.drop_reasons_total.get({"reason": "pipeline-degraded"})
            - reason0
        ),
        "transient_transparent": transparent,
        "kv_partition_held": bool(partition_held),
        "kv_recovered": bool(kv_recovered),
        "modes_visited": modes,
        "host_parity": host_parity,
        "recovery_rounds": recovery_rounds,
        "recovery_s": round(recovery_s, 3),
        "recovered_parity": recovered_parity,
        "final_mode": pipe.pipeline_mode,
        "reattached": reattached,
        "failsafe": pipe.failsafe_state(),
        "overload": overload,
        "federation": federation,
        # top-level so _diff_records' _ms suffix rule tracks them
        # (restart_downtime_ms down, drain_ms down)
        "restart_downtime_ms": survive["restart_downtime_ms"],
        "drain_ms": survive["drain_ms"],
        "survive": survive,
    }


def _chaos_overload(eng, cache, idents, nrng, attached):
    """Overload sub-round of ``--chaos``: a 10x-oversubscribed submit
    storm against a pipeline with AdmissionControl + Prefilter armed, a
    250ms verdict deadline, and the stuck-dispatch watchdog at 100ms,
    with queue_full + stall faults injected mid-storm. Gates:

    - ``verdicts_lost`` computed from the returned result() arrays (a
      shed flow still comes back with a verdict) — must be 0;
    - per-submit wall time stays bounded (``queue_wait_p99_ms``): the
      gate sheds or defers instead of letting callers pile up behind
      the device;
    - shed flows carry DROP_PREFILTER and land in the reason-144
      counter (``reason_144_flows`` vs ``shed_verdict_flows``);
    - the stall injections trip the breaker, and clean traffic after
      the storm re-promotes the ladder to ``pipeline_mode=sharded``."""
    from cilium_tpu import faults as _faults
    from cilium_tpu import metrics as _m
    from cilium_tpu.datapath.pipeline import (
        DROP_PREFILTER,
        DatapathPipeline,
        ipv4_to_bytes,
    )
    from cilium_tpu.ipcache.prefilter import PreFilter

    attached.stage("chaos-overload")
    pipe = DatapathPipeline(
        eng, cache, PreFilter(), conntrack=None, pipeline_depth=2,
        admission=True, prefilter_shed=True, deadline_ms=250.0,
    )
    pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    pipe.breaker_threshold = 2
    pipe.recover_after_clean = 3
    pipe.retry_min_s = pipe.retry_max_s = 0.001

    b = 1 << 11
    n_world = (b * 4) // 5  # 80% unknown sources on ephemeral ports
    storm = []
    for _ in range(20):  # depth 2 -> 10x oversubscription
        i_sel = nrng.integers(0, len(idents), b - n_world)
        legit = (
            np.uint32(10) << 24
            | ((i_sel >> 8) & 255).astype(np.uint32) << 16
            | (i_sel & 255).astype(np.uint32) << 8
            | 1
        ).astype(np.uint32)
        world = (
            nrng.integers(11, 200, n_world).astype(np.uint32) << 24
            | nrng.integers(0, 1 << 24, n_world).astype(np.uint32)
        )
        ips = np.concatenate([world, legit])
        eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
        dports = np.concatenate([
            nrng.integers(32768, 61000, n_world).astype(np.int32),
            nrng.choice(np.array([80, 443], np.int32), b - n_world),
        ])
        storm.append((ips, eps, dports, np.full(b, 6, np.int32)))

    # warm the verdict jit AND the shed walk before arming the 100ms
    # watchdog — first-compile pulls take seconds on CPU and must not
    # read as wedges
    v_warm, _ = pipe.process(*storm[0])
    pipe._shed_walk(
        ipv4_to_bytes(storm[0][0]), storm[0][2], storm[0][3], family=4
    )
    pipe.set_stall_ms(100.0)

    # the overload round sheds at the HOST admission gate — reason
    # 144's producer="admission" slice, not the device prefilter's
    reason0 = _m.drop_reasons_total.get(
        {"reason": "prefilter", "producer": "admission"})
    _faults.hub.fail(_faults.SITE_QUEUE_FULL, _faults.KIND_TRANSIENT, times=4)
    _faults.hub.fail(_faults.SITE_STALL, _faults.KIND_TRANSIENT, times=2)

    submitted = 0
    submit_walls = []
    pendings = []
    for bt in storm:
        submitted += bt[0].shape[0]
        t0 = time.monotonic()
        pendings.append(pipe.submit(*bt))
        submit_walls.append(time.monotonic() - t0)

    resolved = 0
    shed_verdicts = 0
    for pend in pendings:
        v, _red = pend.result()
        resolved += int(v.shape[0])
        shed_verdicts += int((v == DROP_PREFILTER).sum())

    # the stall injections fed the breaker — clean traffic must walk
    # the ladder back up without a restart
    attached.stage("chaos-overload-recover")
    recovery_rounds = 0
    while pipe.pipeline_mode != "sharded" and recovery_rounds < 64:
        pipe.process(*storm[recovery_rounds % 2])
        recovery_rounds += 1
    v_after, _ = pipe.process(*storm[0])

    adm = pipe.admission_state()
    pipe.set_stall_ms(0)
    return {
        "oversubscription": len(storm) * b // (2 * b),
        "submitted": submitted,
        "verdicts_lost": submitted - resolved,
        "queue_wait_p99_ms": round(
            float(np.percentile(np.array(submit_walls), 99)) * 1e3, 2
        ),
        "shed_verdict_flows": shed_verdicts,
        "reason_144_flows": int(
            _m.drop_reasons_total.get(
                {"reason": "prefilter", "producer": "admission"})
            - reason0
        ),
        "admission_limit": adm["limit"],
        "admission_shed": adm["shed"],
        "watchdog_stalls": (adm.get("watchdog") or {}).get("stalls", 0),
        "overload_recovery_rounds": recovery_rounds,
        "final_mode": pipe.pipeline_mode,
        "recovered_parity": bool(np.array_equal(v_after, v_warm)),
    }


def _chaos_federation(attached):
    """Federation sub-round of ``--chaos`` (policyd-fed): a kvstore
    partition on one node's CAS path plus a third node's lease expiry,
    both landing during concurrent two-node identity allocation. The
    reserve/confirm allocator must converge to identical injective
    id maps (zero double-assigns), ride ``utils/backoff`` through the
    partition, and ``run_gc`` must reap only the dead node's ids.

    A journal leg rides along (policyd-journal): three event journals
    with wall clocks skewed ±120s exchange tail frames over the same
    store — the merged fleet timeline must stay HLC-consistent with
    the causal emission order preserved despite the skew."""
    import threading

    from cilium_tpu.federation import ClusterIdentityAllocator
    from cilium_tpu.kvstore.backend import InMemoryBackend, InMemoryStore
    from cilium_tpu.kvstore.filestore import FlakyBackend
    from cilium_tpu.kvstore.paths import IDENTITIES_PATH
    from cilium_tpu.utils.backoff import Backoff

    attached.stage("chaos-federation")
    store = InMemoryStore()

    def bo():
        return Backoff(
            min_s=0.001, max_s=0.02, full_jitter=True, max_elapsed_s=30.0
        )

    def node(backend, name):
        return ClusterIdentityAllocator(
            backend, IDENTITIES_PATH, node_name=name,
            min_id=256, max_id=8192, backoff_factory=bo,
        )

    # node c holds identities, then dies mid-storm (lease expiry)
    c = node(InMemoryBackend(store, "c"), "c")
    c_ids = {c.allocate(f"k8s:app=ephemeral-{i}")[0] for i in range(8)}
    a = node(InMemoryBackend(store, "a"), "a")
    flaky = FlakyBackend(InMemoryBackend(store, "b"))
    b = node(flaky, "b")

    keys = [f"k8s:app=chaos-fed-{i}" for i in range(40)]
    got = {"a": {}, "b": {}}

    def worker(alloc, tag):
        for k in keys:
            got[tag][k] = alloc.allocate(k)[0]

    flaky.fail(True)  # partition lands BEFORE the storm starts
    threads = [
        threading.Thread(target=worker, args=(a, "a")),
        threading.Thread(target=worker, args=(b, "b")),
    ]
    for t in threads:
        t.start()
    time.sleep(0.005)
    store.revoke_lease(c.backend.lease_id)  # node c dies mid-storm
    time.sleep(0.005)
    flaky.fail(False)  # partition heals; b's backoff retries land
    for t in threads:
        t.join(60.0)

    reaped = a.run_gc()  # release-on-lease-expiry: c's masters go
    ids = sorted(got["a"].values())

    # --- merged fleet timeline under injected wall-clock skew
    attached.stage("chaos-fed-timeline")
    from cilium_tpu.observe import journal as _journal

    skews = {"jn-a": 120.0, "jn-b": 0.0, "jn-c": -120.0}
    journals, pubs = {}, {}
    for name, skew in skews.items():
        j = _journal.EventJournal(
            node=name, capacity=64,
            clock=(lambda s=skew: time.time() + s),
        )
        pub = _journal.JournalPublisher(j, tail_n=32)
        pub.attach_exchange(_journal.JournalExchange(
            InMemoryBackend(store, name), name, cluster="chaos-journal",
        ))
        journals[name], pubs[name] = j, pub
    # a causal chain hopping across the skewed nodes: every node hears
    # the fleet (publish_once folds peer HLCs) before its own step, so
    # the merge order must reproduce the emission order even though
    # jn-c's wall clock lags jn-a's by 240s
    chain = [
        ("jn-a", "drain_begin"), ("jn-b", "boot"),
        ("jn-c", "ct_restore"), ("jn-a", "drain_end"),
        ("jn-b", "rebuild"), ("jn-c", "restore_done"),
    ]
    for name, kind in chain:
        for pub in pubs.values():
            pub.publish_once()
        journals[name].emit(kind=kind)
        pubs[name].publish_once()
    merged = pubs["jn-b"].merged_timeline(limit=64)
    timeline_ok = (
        _journal.timeline_consistent(merged)
        and [e["kind"] for e in merged] == [k for _, k in chain]
    )
    assert timeline_ok, (
        "skewed 3-node merge broke causal order: "
        + str([(e["node"], e["kind"]) for e in merged])
    )
    for pub in pubs.values():
        pub.stop()

    return {
        "keys": len(keys),
        "identical_maps": got["a"] == got["b"],
        "no_double_assign": len(set(ids)) == len(keys),
        "dead_node_disjoint": not (set(ids) & c_ids),
        "reaped_ids": len(reaped),
        "reap_sound": set(reaped) == c_ids,
        "partition_retries": b.state()["allocations"].get("retry", 0),
        "kv_op_errors": flaky.op_errors,
        "timeline_nodes": len(skews),
        "timeline_skew_spread_s": 240.0,
        "timeline_hlc_consistent": bool(timeline_ok),
    }


# Subprocess driver for the survive sub-round: one script, four
# phases, so each leg runs (and dies) in a REAL process the way a node
# agent does. ``serve`` is killed -9 by the parent mid-storm; ``restore``
# measures state-load -> first verdict; ``mutate`` models a crash landing
# between a rule change and the next CT sync; ``drain`` exits 0 through
# the SIGTERM -> drain() path.
_SURVIVE_DRIVER_SRC = r'''
import json, os, signal, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np

phase, state_dir = sys.argv[1], sys.argv[2]

from cilium_tpu.option import DaemonConfig, set_config

# every phase boots with the lifecycle journal on — the chaos round
# asserts the journal-derived restore/drain story against the
# independently measured numbers (policyd-journal)
set_config(DaemonConfig(lifecycle_journal=True))

from cilium_tpu.daemon import Daemon
from cilium_tpu.ops.lpm import ip_strings_to_u32

ALLOW = json.dumps([{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "ingress": [{"fromEndpoints": [{"matchLabels": {"app": "client"}}]}],
}])
EXTRA = json.dumps([{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "ingress": [{"fromEndpoints": [{"matchLabels": {"app": "extra"}}]}],
}])
N = 128


def seed(dm):
    dm.policy_add(ALLOW)
    dm.endpoint_add(1, ["unspec:app=web"], ipv4="10.0.0.1")
    dm.endpoint_add(2, ["unspec:app=client"], ipv4="10.0.0.2")


def storm(dm, i):
    # distinct sports per round -> fresh CT entries every round; sport
    # 10000 (round 0, lane 0) is the established flow restore replays
    peers = ip_strings_to_u32(["10.0.0.2"] * N)
    sports = (10000 + (i * N + np.arange(N)) % 40000).astype(np.int32)
    v, _ = dm.pipeline.process(
        peers, np.zeros(N, np.int32), np.full(N, 80, np.int32),
        np.full(N, 6, np.int32), sports=sports)
    return v


if phase == "serve":
    dm = Daemon(state_dir=state_dir)
    seed(dm)
    i = 0
    while True:
        storm(dm, i)
        i += 1
        dm._save_ct_snapshot(force=True)
        print("SYNC %d %d" % (i, len(dm.conntrack)), flush=True)
        time.sleep(0.02)

elif phase == "restore":
    t0 = time.perf_counter()
    dm = Daemon(state_dir=state_dir)
    info = dict(dm.ct_restore_info() or {})
    peers = ip_strings_to_u32(["10.0.0.2"])
    v, _ = dm.pipeline.process(
        peers, np.zeros(1, np.int32), np.array([80], np.int32),
        np.full(1, 6, np.int32), sports=np.array([10000], np.int32))
    downtime_ms = (time.perf_counter() - t0) * 1000.0
    # leave a coherent pair on disk for the next leg: CT + compiled
    # written back-to-back while quiescent (same tail order drain uses)
    dm._save_compiled_snapshot(force=True)
    dm._save_ct_snapshot(force=True)
    from cilium_tpu import metrics as _m
    # the journal's version of the same restart: boot anchors the
    # downtime window, ct_restore carries the basis verdict,
    # restore_done closes the window
    jevs = dm.events(limit=128)["events"]
    jfirst = {}
    for e in jevs:
        jfirst.setdefault(e["kind"], e)
    jboot, jct = jfirst.get("boot"), jfirst.get("ct_restore")
    jdone = jfirst.get("restore_done")
    print("RESULT " + json.dumps({
        "downtime_ms": downtime_ms,
        "downtime_gauge_ms": _m.restart_downtime_seconds.get() * 1000.0,
        "kept": int(info.get("kept", -1)),
        "expired": int(info.get("expired", -1)),
        "flushed": int(info.get("flushed", -1)),
        "basis_match": bool(info.get("basis_match", False)),
        "verdict_forward": bool(int(v[0]) == 1),
        "ct_len": len(dm.conntrack),
        "journal_basis_match": bool(
            jct and jct["attrs"].get("basis_match", False)),
        "journal_downtime_ms": (
            (jdone["wall_ts"] - jboot["wall_ts"]) * 1000.0
            if jboot and jdone else -1.0),
    }), flush=True)

elif phase == "mutate":
    dm = Daemon(state_dir=state_dir)
    # crash window: rule change lands, compiled.npz moves, the process
    # dies before the next CT sync -> ct.npz keeps the OLD basis stamp
    dm.controllers.remove_controller("ct-snapshot-sync")
    dm._save_ct_snapshot = lambda *a, **k: None
    dm.policy_add(EXTRA)
    # the post-restore recompile is async and the saver skips sentinel
    # (revision < 0) state — wait for the real compile to land so
    # compiled.npz actually moves
    dm.engine.refresh()
    dm.engine.wait_refreshed(60)
    dm.engine.refresh()
    dm._save_compiled_snapshot(force=True)
    print("MUTATED", flush=True)
    os._exit(0)

elif phase == "drain":
    def _raise(signum, frame):
        raise KeyboardInterrupt
    signal.signal(signal.SIGTERM, _raise)
    dm = Daemon(state_dir=state_dir)
    seed(dm)
    print("READY", flush=True)
    i = 0
    try:
        while True:
            storm(dm, i)
            i += 1
            print("BATCH %d" % i, flush=True)
    except KeyboardInterrupt:
        rep = dm.drain(deadline_s=5.0)
        rep = {k: v for k, v in rep.items()
               if isinstance(v, (int, float, bool, str))}
        # the drain bracket on the journal: drain_begin ... drain_end
        # with the structural zero-loss stamp in drain_end's attrs
        jevs = dm.events(limit=128)["events"]
        kinds = [e["kind"] for e in jevs]
        jend = [e for e in jevs if e["kind"] == "drain_end"]
        rep["journal_drain_bracket"] = bool(
            "drain_begin" in kinds and "drain_end" in kinds
            and kinds.index("drain_begin") < kinds.index("drain_end"))
        rep["journal_drain_verdicts_lost"] = (
            int(jend[-1]["attrs"]["verdicts_lost"]) if jend else -1)
        print("DRAIN " + json.dumps(rep), flush=True)
        sys.exit(0)
'''


def _drv_spawn(phase, state_dir, src=None, extra=()):
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-u", "-c", src or _SURVIVE_DRIVER_SRC,
         phase, state_dir, *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
    )


def _drv_expect(proc, prefix, timeout_s=300.0):
    """Read driver stdout until a ``prefix``-marked line (daemon log
    noise is interleaved on the same pipe and skipped)."""
    end = time.time() + timeout_s
    tail = []
    while time.time() < end:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    "survive driver exited rc=%s waiting for %r:\n%s"
                    % (proc.returncode, prefix, "".join(tail[-20:]))
                )
            time.sleep(0.05)
            continue
        tail.append(line)
        if line.startswith(prefix):
            return line.strip()
    proc.kill()
    raise RuntimeError("timeout waiting for %r:\n%s"
                       % (prefix, "".join(tail[-20:])))


_FLEETOBS_DRIVER_SRC = r'''
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np

node, state_dir, store_path = sys.argv[1], sys.argv[2], sys.argv[3]

from cilium_tpu.daemon import Daemon
from cilium_tpu.kvstore.filestore import FileBackend
from cilium_tpu.observe.fleet import TelemetryExchange
from cilium_tpu.ops.lpm import ip_strings_to_u32

ALLOW = json.dumps([{
    "endpointSelector": {"matchLabels": {"app": "web"}},
    "ingress": [{"fromEndpoints": [{"matchLabels": {"app": "client"}}]}],
}])
N = 256

dm = Daemon(state_dir=state_dir)
dm.policy_add(ALLOW)
dm.endpoint_add(1, ["unspec:app=web"], ipv4="10.0.0.1")
dm.endpoint_add(2, ["unspec:app=client"], ipv4="10.0.0.2")
dm.config_patch({"FleetTelemetry": "true"})
sampler = dm._fleet_sampler
sampler.attach_exchange(TelemetryExchange(
    FileBackend(store_path, node, lease_ttl=60.0), node, cluster="bench",
))
# lifecycle journal beside the telemetry plane (policyd-journal): the
# rebuild/epoch_swap events from the storm below ride tail frames the
# parent merges into one fleet timeline
dm.config_patch({"LifecycleJournal": "true"})
dm._journal.node = node  # unfederated daemon defaults to "local"
from cilium_tpu.observe.journal import JournalExchange
dm._journal_publisher.attach_exchange(JournalExchange(
    FileBackend(store_path, node + "-j", lease_ttl=60.0), node,
    cluster="bench",
))

peers = ip_strings_to_u32(["10.0.0.2"] * N)
eps = np.zeros(N, np.int32)
dports = np.full(N, 80, np.int32)
protos = np.full(N, 6, np.int32)
dm.pipeline.process(peers, eps, dports, protos)  # warm: compile before t0
print("READY", flush=True)

verdicts = 0
t0 = time.perf_counter()
i = 0
while True:
    dm.pipeline.process(peers, eps, dports, protos)
    verdicts += N
    i += 1
    if i % 4 == 0:
        # deterministic extra cadence beside the 1s sampler thread so
        # short storm windows still fill the ring
        sampler.sample_once()
        print("SYNC " + json.dumps({
            "i": i, "vps": verdicts / (time.perf_counter() - t0),
        }), flush=True)
'''


def _bench_fleetobs(attached):
    """``--fleetobs``: policyd-fleetobs round → result dict for the
    one-line JSON. Three REAL daemon processes (FleetTelemetry on)
    storm the verdict path and publish telemetry frames over ONE
    FileBackend SQLite store; the parent runs the aggregator side:

    - aggregation parity: the scoreboard's fleet vps must match the
      sum of the drivers' independently-accounted verdict rates
      within tolerance;
    - timeline: every node also publishes its lifecycle-journal tail
      (LifecycleJournal on) — the parent merges the three tails into
      one fleet timeline that must be HLC-consistent;

    - chaos: one node dies by SIGKILL — its frames age out by
      wall-clock staleness (the lease is deliberately slower), the
      scoreboard drops to 2 reporting nodes, nothing crashes."""
    import tempfile
    import threading

    from cilium_tpu import metrics as _metrics
    from cilium_tpu.kvstore.filestore import FileBackend
    from cilium_tpu.observe import fleet as _fleet

    attached.stage("fleetobs-build")
    path, names, _ = _cluster_store(attach=False)

    attached.stage("fleetobs-spawn")
    procs = []
    for n in names:
        sd = tempfile.mkdtemp(prefix=f"bench-fleetobs-{n}-")
        procs.append(_drv_spawn(n, sd, src=_FLEETOBS_DRIVER_SRC,
                                extra=(path,)))
    try:
        for p in procs:
            _drv_expect(p, "READY")

        # reader threads keep the pipes drained (no 64K stalls) and
        # remember each node's latest self-reported rate
        last_sync = {}

        def _reader(name, proc):
            for line in iter(proc.stdout.readline, ""):
                if line.startswith("SYNC "):
                    last_sync[name] = json.loads(line[5:])

        for n, p in zip(names, procs):
            threading.Thread(
                target=_reader, args=(n, p), daemon=True
            ).start()

        attached.stage("fleetobs-storm")
        time.sleep(10.0)  # long enough for the 10s frame window to fill

        agg_be = FileBackend(path, "bench-agg", lease_ttl=60.0)
        ex = _fleet.TelemetryExchange(agg_be, "bench-agg", cluster="bench")
        deadline = time.time() + 30.0
        frames = {}
        while time.time() < deadline:
            ex.pump()
            frames = ex.frames(stale_s=10.0)
            if len(frames) == len(names):
                break
            time.sleep(0.2)
        assert len(frames) == len(names), (
            f"only {sorted(frames)} of {names} published frames"
        )
        agg = _fleet.aggregate(frames)
        node_sum_vps = sum(
            last_sync[n]["vps"] for n in names if n in last_sync
        )
        parity = (
            node_sum_vps > 0
            and abs(agg["fleet_vps"] - node_sum_vps) / node_sum_vps < 0.5
        )
        assert parity, (
            f"aggregation parity broke: fleet_vps={agg['fleet_vps']} "
            f"vs node sum {node_sum_vps}"
        )
        worst = agg.get("worst_burn") or {}

        # merged fleet timeline (policyd-journal): every node's journal
        # tail frame must be live on the store and the merge must be
        # HLC-consistent
        attached.stage("fleetobs-timeline")
        from cilium_tpu.observe import journal as _journal

        jex = _journal.JournalExchange(
            FileBackend(path, "bench-agg-j", lease_ttl=60.0),
            "bench-agg", cluster="bench",
        )
        jdeadline = time.time() + 30.0
        jframes = {}
        while time.time() < jdeadline:
            jex.pump()
            jframes = jex.frames()
            if len(jframes) == len(names):
                break
            time.sleep(0.2)
        assert set(jframes) == set(names), (
            f"journal frames from {sorted(jframes)}, expected {names}"
        )
        jmerged = _journal.merge_timelines(jframes)
        timeline_ok = bool(jmerged) and _journal.timeline_consistent(
            jmerged)
        assert timeline_ok, "merged fleet timeline not HLC-consistent"
        journal_events = sum(
            len(f.get("events", [])) for f in jframes.values()
        )
        jex.close()

        attached.stage("fleetobs-kill")
        procs[-1].kill()  # SIGKILL: no drain, no lease revoke
        procs[-1].wait()
        time.sleep(4.0)
        ex.pump()
        agg2 = _fleet.aggregate(ex.frames(stale_s=3.0))
        survivors = {r["node"] for r in agg2["nodes"]}
        assert agg2["nodes_reporting"] == len(names) - 1, (
            f"expected {len(names) - 1} nodes after kill, "
            f"got {agg2['nodes_reporting']} ({sorted(survivors)})"
        )
        assert names[-1] not in survivors, "killed node's frame not aged out"
        assert _metrics.fleet_nodes_reporting.get() == len(names) - 1

        ex.close()
        return {
            "nodes": len(names),
            "fleet_agg_vps": round(agg["fleet_vps"]),
            "node_sum_vps": round(node_sum_vps),
            "agg_parity": bool(parity),
            "fleet_epoch_lag_max": int(agg["epoch_lag_max"] or 0),
            "epoch_skew": int(agg["epoch_skew"] or 0),
            "slo_worst_burn_ratio": round(float(worst.get("ratio") or 0.0), 4),
            "slo_worst_objective": worst.get("objective") or "",
            "nodes_reporting_after_kill": int(agg2["nodes_reporting"]),
            "kill_survived": True,
            "timeline_merge_ok": bool(timeline_ok),
            "journal_events_total": int(journal_events),
        }
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def _chaos_survive(attached):
    """Survive sub-round of ``--chaos`` (policyd-survive), four legs:

    - kill -9 mid-storm, restart: ``restart_downtime_ms`` is
      state-load -> first verdict in the restarted process, with the
      established flows KEPT (basis matches) and still forwarding;
    - raced rule change: compiled.npz moves after the last CT sync ->
      the restore classifies the stale ct.npz and cold-flushes;
    - SIGTERM drain: in-flight storm completes, state persists,
      ``verdicts_lost == 0``, exit code 0;
    - torn write: SITE_STATE_WRITE truncates ct.npz mid-write -> the
      next boot classifies, cold-starts, never crashes.

    Every daemon boots with LifecycleJournal on: the journal's event
    spine (boot/ct_restore/restore_done, drain_begin/drain_end) is
    asserted against the independently measured downtime, basis
    verdict, and zero-loss drain (policyd-journal)."""
    import signal as _signal
    import tempfile

    from cilium_tpu import faults as _faults
    from cilium_tpu.daemon import Daemon as _Daemon

    # --- leg 1: kill -9 -> restart with established flows kept
    attached.stage("chaos-restart")
    sdir = tempfile.mkdtemp(prefix="bench-survive-")
    serve = _drv_spawn("serve", sdir)
    line = _drv_expect(serve, "SYNC ")
    while int(line.split()[2]) < 1:
        line = _drv_expect(serve, "SYNC ")
    ct_at_kill = int(line.split()[2])
    serve.kill()  # SIGKILL: no drain, no goodbye
    serve.wait(timeout=30)
    rest = _drv_spawn("restore", sdir)
    keep = json.loads(_drv_expect(rest, "RESULT ")[len("RESULT "):])
    rest.wait(timeout=60)

    # journal-derived restore invariants (policyd-journal): the event
    # spine must tell the same restart story the measured numbers do
    assert keep["journal_basis_match"] == keep["basis_match"], (
        "journal ct_restore event disagrees with ct_restore_info"
    )
    jdt = keep["journal_downtime_ms"]
    assert jdt > 0, "journal boot/restore_done events missing"
    # boot→restore_done wall span vs the driver's perf_counter window:
    # same restart, two clocks — they must agree within ±20% (with a
    # small absolute floor so a near-instant warm restore can't flake)
    assert abs(jdt - keep["downtime_ms"]) <= max(
        0.2 * keep["downtime_ms"], 50.0
    ), (
        f"journal downtime {jdt:.1f}ms vs measured "
        f"{keep['downtime_ms']:.1f}ms"
    )

    # --- leg 2: raced rule change voids the stale CT snapshot
    attached.stage("chaos-restart-raced")
    mut = _drv_spawn("mutate", sdir)
    _drv_expect(mut, "MUTATED")
    mut.wait(timeout=60)
    rest2 = _drv_spawn("restore", sdir)
    raced = json.loads(_drv_expect(rest2, "RESULT ")[len("RESULT "):])
    rest2.wait(timeout=60)

    # --- leg 3: SIGTERM -> graceful drain -> exit 0
    attached.stage("chaos-drain")
    ddir = tempfile.mkdtemp(prefix="bench-drain-")
    drainp = _drv_spawn("drain", ddir)
    _drv_expect(drainp, "READY")
    _drv_expect(drainp, "BATCH ")  # storm is in flight
    drainp.send_signal(_signal.SIGTERM)
    drain_rep = json.loads(_drv_expect(drainp, "DRAIN ")[len("DRAIN "):])
    drain_rc = drainp.wait(timeout=60)
    # the journal brackets the drain with verdicts_lost == 0 stamped
    # in drain_end — the invariant a rolling-restart runbook reads
    assert drain_rep["journal_drain_bracket"], (
        "drain_begin/drain_end events missing or out of order"
    )
    assert drain_rep["journal_drain_verdicts_lost"] == 0, (
        f"journal drain_end carries verdicts_lost="
        f"{drain_rep['journal_drain_verdicts_lost']}"
    )

    # --- leg 4: torn CT write -> next boot cold-starts, no crash
    attached.stage("chaos-torn-write")
    tdir = tempfile.mkdtemp(prefix="bench-torn-")
    dmt = _Daemon(state_dir=tdir)
    dmt.controllers.remove_all()  # no background resave heals the tear
    dmt.policy_add(
        '[{"endpointSelector": {"matchLabels": {"app": "web"}}, '
        '"ingress": [{"fromEndpoints": [{"matchLabels": '
        '{"app": "client"}}]}]}]'
    )
    dmt.endpoint_add(1, ["unspec:app=web"], ipv4="10.0.0.1")
    dmt.endpoint_add(2, ["unspec:app=client"], ipv4="10.0.0.2")
    from cilium_tpu.ops.lpm import ip_strings_to_u32 as _ip2u32

    dmt.pipeline.process(
        _ip2u32(["10.0.0.2"]), np.zeros(1, np.int32),
        np.array([80], np.int32), np.full(1, 6, np.int32),
        sports=np.array([4242], np.int32),
    )
    dmt._save_compiled_snapshot(force=True)
    _faults.hub.fail(_faults.SITE_STATE_WRITE, _faults.KIND_TRANSIENT,
                     times=1)
    dmt._save_ct_snapshot(force=True)  # tears ct.npz, logged not raised
    torn_bytes = os.path.getsize(os.path.join(tdir, "ct.npz"))
    dmtr = _Daemon(state_dir=tdir)  # must classify + boot cold
    torn_info = dict(dmtr.ct_restore_info() or {})
    for d in (dmt, dmtr):
        d.controllers.remove_all()
        d.health.stop()
        d.fqdn.stop()
        d.endpoint_manager.shutdown()

    return {
        # headline numbers (hoisted top-level by _bench_chaos so --diff
        # applies the _ms lower-is-better direction)
        "restart_downtime_ms": round(keep["downtime_ms"], 3),
        "drain_ms": round(drain_rep["drain_s"] * 1000.0, 3),
        # leg 1: established flows survive kill -9
        "restart_ct_at_kill": ct_at_kill,
        "restart_kept": keep["kept"],
        "restart_expired": keep["expired"],
        "restart_basis_match": bool(keep["basis_match"]),
        "restart_established_forward": bool(keep["verdict_forward"]),
        "restart_downtime_gauge_ms": round(keep["downtime_gauge_ms"], 3),
        # journal-derived mirror of leg 1 (asserted above)
        "journal_restore_basis_match": bool(keep["journal_basis_match"]),
        "journal_restore_downtime_ms": round(
            keep["journal_downtime_ms"], 3),
        # leg 2: stale snapshot classified, cold-flushed
        "raced_flushed": raced["flushed"],
        "raced_basis_match": bool(raced["basis_match"]),
        "raced_kept": raced["kept"],
        # leg 3: graceful drain
        "drain_exit_code": drain_rc,
        "drain_verdicts_lost": drain_rep["verdicts_lost"],
        "journal_drain_bracket": bool(drain_rep["journal_drain_bracket"]),
        "drain_report": drain_rep,
        # leg 4: torn write never crashes a boot
        "torn_ct_bytes": torn_bytes,
        "torn_restore_cold": bool(
            torn_info.get("kept", -1) == 0
            and not torn_info.get("basis_match", True)
        ),
        "torn_boot_ok": True,
    }


def _bench_overload(repo, reg, idents, nrng: np.random.Generator, attached):
    """``--overload``: policyd-overload round → result dict for the
    one-line JSON. A deny-heavy DoS mix (90% unknown world sources on
    ephemeral ports, 10% legitimate identities on service ports)
    measured two ways on the SAME batches:

    - ``full_vps``: the complete verdict path at pipeline depth 2;
    - ``prefilter_shed_vps``: the coarse [identity, proto/port-class]
      shed gather the admission gate runs ahead of the full path.

    The round driver gates on ``shed_over_full_ratio >= 3`` — the shed
    stage only earns its place in the gate if it disposes of the DoS
    bulk at a multiple of full-pipeline rate — and on ``shed_sound``:
    no flow the full path would FORWARD may appear in the shed mask
    (the gate re-labels deny-for-sure flows only)."""
    from cilium_tpu.datapath.pipeline import (
        FORWARD,
        DatapathPipeline,
        ipv4_to_bytes,
    )
    from cilium_tpu.engine import PolicyEngine
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.ipcache.prefilter import PreFilter

    eng = PolicyEngine(repo, reg)
    cache = IPCache()
    for i, ident in enumerate(idents):
        cache.upsert(
            f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s"
        )
    pipe = DatapathPipeline(
        eng, cache, PreFilter(), conntrack=None, pipeline_depth=2,
        prefilter_shed=True,
    )
    pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
    attached.stage("overload-build")

    b = 1 << 14
    n_legit = b // 10
    n_world = b - n_legit
    world = (
        nrng.integers(11, 200, n_world).astype(np.uint32) << 24
        | nrng.integers(0, 1 << 24, n_world).astype(np.uint32)
    )
    i_sel = nrng.integers(0, len(idents), n_legit)
    legit = (
        np.uint32(10) << 24
        | ((i_sel >> 8) & 255).astype(np.uint32) << 16
        | (i_sel & 255).astype(np.uint32) << 8
        | 1
    ).astype(np.uint32)
    ips = np.concatenate([world, legit])
    eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
    dports = np.concatenate([
        nrng.integers(32768, 61000, n_world).astype(np.int32),
        nrng.choice(np.array([80, 443], np.int32), n_legit),
    ])
    protos = np.full(b, 6, np.int32)
    peer_bytes = ipv4_to_bytes(ips)

    attached.stage("overload-full-path")
    v_full, _ = pipe.process(ips, eps, dports, protos)  # warm
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        pipe.process(ips, eps, dports, protos)
    full_vps = b * iters / (time.perf_counter() - t0)

    attached.stage("overload-shed-walk")
    mask = pipe._shed_walk(peer_bytes, dports, protos, family=4)  # warm
    if mask is None:
        raise RuntimeError("prefilter shed table not published")
    t0 = time.perf_counter()
    for _ in range(iters):
        pipe._shed_walk(peer_bytes, dports, protos, family=4)
    shed_vps = b * iters / (time.perf_counter() - t0)

    # soundness before any rate is reported: the shed mask may only
    # cover flows the full path denies
    shed_sound = not bool(np.any(mask & (v_full == FORWARD)))
    return {
        "full_vps": round(full_vps),
        "prefilter_shed_vps": round(shed_vps),
        "shed_over_full_ratio": round(shed_vps / full_vps, 2),
        "shed_fraction": round(float(mask.mean()), 4),
        "shed_sound": shed_sound,
        "deny_fraction": round(float((v_full != FORWARD).mean()), 4),
        "batch": b,
        "pipeline_depth": 2,
        "admission": pipe.admission_state(),
    }



def _cluster_store(n_nodes=3, attach=True):
    """Shared FileBackend harness for the kvstore-backed rounds
    (``--cluster``, ``--fleetobs``): ONE durable SQLite store under a
    fresh tempdir plus the node-name roster. With ``attach`` each node
    gets an in-process backend handle (the --cluster thread harness);
    without, callers spawn real subprocesses that open their own
    handles against the returned path (the --fleetobs storm)."""
    import tempfile

    from cilium_tpu.kvstore.filestore import FileBackend

    tmp = tempfile.mkdtemp(prefix="bench-cluster-")
    path = os.path.join(tmp, "kvstore.sqlite")
    names = [f"node-{i}" for i in range(n_nodes)]
    backends = (
        [FileBackend(path, n, lease_ttl=60.0) for n in names]
        if attach else []
    )
    return path, names, backends


def _bench_cluster(attached):
    """``--cluster``: policyd-fed round → result dict for the one-line
    JSON. Three in-process federation nodes share ONE FileBackend
    SQLite store (the durable kvstore path, not the in-memory test
    double) and the round measures the three allocation regimes plus
    the epoch barrier:

    - contended: all nodes race ``allocate`` over one overlapping key
      set — reserve/confirm CAS both ways, injectivity asserted;
    - cached: re-allocation of held keys (the local-refcount fast
      path every endpoint-create after the first rides);
    - epoch convergence: wall time from all nodes publishing a new
      policy epoch to ``wait_cluster_epoch`` observing the fleet
      minimum reach it."""
    import threading

    from cilium_tpu.federation import ClusterIdentityAllocator, EpochExchange
    from cilium_tpu.kvstore.paths import IDENTITIES_PATH
    from cilium_tpu.utils.backoff import Backoff

    attached.stage("cluster-build")
    path, names, backends = _cluster_store()

    def bo():
        return Backoff(
            min_s=0.001, max_s=0.05, full_jitter=True, max_elapsed_s=30.0
        )
    allocs = [
        ClusterIdentityAllocator(
            be, IDENTITIES_PATH, node_name=n,
            min_id=256, max_id=1 << 16, backoff_factory=bo,
        )
        for be, n in zip(backends, names)
    ]

    n_keys = 48
    keys = [f"k8s:app=bench-{i}" for i in range(n_keys)]
    got = [dict() for _ in allocs]

    def worker(i):
        for k in keys:
            got[i][k] = allocs[i].allocate(k)[0]

    attached.stage("cluster-contended")
    t0 = time.time()
    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(len(allocs))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120.0)
    contended_s = time.time() - t0
    assert got[0] == got[1] == got[2], "federated id maps diverged"
    assert len(set(got[0].values())) == n_keys, "double-assigned ids"

    attached.stage("cluster-cached")
    t0 = time.time()
    for _ in range(10):
        for k in keys:
            allocs[0].allocate(k)
    cached_s = time.time() - t0

    attached.stage("cluster-epoch")
    epochs = [{"v": 0} for _ in names]
    exchanges = [
        EpochExchange(
            be, n, cluster="bench",
            epoch_source=(lambda e=e: e["v"]),
        )
        for be, n, e in zip(backends, names, epochs)
    ]

    def pump_all():
        for x in exchanges:
            x.publish()
            x.pump()

    # warm the view so the barrier measures propagation, not join
    for _ in range(4):
        pump_all()
    for e in epochs:
        e["v"] = 1
    t0 = time.time()
    converged = exchanges[0].wait_cluster_epoch(
        1, timeout=30.0, min_nodes=len(names), pump=pump_all
    )
    epoch_converge_s = time.time() - t0

    counts = [a.state()["allocations"] for a in allocs]
    for x in exchanges:
        x.close()
    for a in allocs:
        a.close()
    for be in backends:
        be.close()

    contended_ops = n_keys * len(allocs)
    return {
        "nodes": len(names),
        "keys": n_keys,
        "contended_alloc_rps": round(contended_ops / contended_s, 1),
        "cached_alloc_rps": round(10 * n_keys / cached_s, 1),
        "epoch_converged": bool(converged),
        "epoch_converge_ms": round(epoch_converge_s * 1e3, 2),
        "alloc_outcomes": {
            "new": sum(c.get("new", 0) for c in counts),
            "adopted": sum(c.get("adopted", 0) for c in counts),
            "cached": sum(c.get("cached", 0) for c in counts),
            "retry": sum(c.get("retry", 0) for c in counts),
        },
    }


def _bench_mesh(repo, reg, idents, nrng: np.random.Generator, attached):
    """``--mesh``: policyd-mesh round → result dict for the one-line
    JSON. The 2D ``flows×ident`` placement against the 1D sharded
    baseline on the SAME world and batches:

    - mesh shape actually resolved (``{'flows': n/2, 'ident': 2}`` on
      an even device count) plus the plan generation/device ids;
    - per-device policymap bytes sharded vs replicated — the point of
      the ident axis is that table bytes stop scaling with the full
      identity count (reduction ≈ the ident factor);
    - verdicts asserted bit-identical 2D vs 1D before any rate is
      reported, so the number can never come from a diverged program;
    - ``verdicts_2d_vps`` measured through the real pipelined submit
      path at depth 2;
    - the OFF path spy-asserted: with 2D off, a fresh batch shape is
      traced with the one-hot ident-gather kernel replaced by a
      tripwire — reaching it would mean the off path compiles the new
      program.

    Needs ≥2 visible devices to form any mesh; on one device the round
    reports the degenerate plan instead of failing."""
    from cilium_tpu.datapath.pipeline import DatapathPipeline
    from cilium_tpu.engine import PolicyEngine
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.ipcache.prefilter import PreFilter
    from cilium_tpu.ops import lookup as _lookup

    def mk_pipe():
        eng = PolicyEngine(repo, reg)
        cache = IPCache()
        for i, ident in enumerate(idents):
            cache.upsert(
                f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s"
            )
        pipe = DatapathPipeline(
            eng, cache, PreFilter(), conntrack=None, pipeline_depth=2
        )
        pipe.set_endpoints([idents[j].id for j in range(N_ENDPOINTS)])
        return pipe

    b, k = 1 << 16, 6
    batches = []
    for _ in range(k):
        i_sel = nrng.integers(0, len(idents), b)
        ips = (
            np.uint32(10) << 24
            | ((i_sel >> 8) & 255).astype(np.uint32) << 16
            | (i_sel & 255).astype(np.uint32) << 8
            | 1
        ).astype(np.uint32)
        eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
        dports = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b)
        protos = np.where(dports == 53, 17, 6).astype(np.int32)
        batches.append((ips, eps, dports, protos))

    def timed_run(pipe):
        pipe.process(*batches[0])  # warm this mode's program
        t0 = time.time()
        pend = [pipe.submit(*bt) for bt in batches]
        out = [p.result() for p in pend]
        return time.time() - t0, out

    attached.stage("mesh-1d")
    pipe_1d = mk_pipe()
    pipe_1d.set_sharding(True)
    pipe_1d.rebuild()
    t_1d, out_1d = timed_run(pipe_1d)

    attached.stage("mesh-2d")
    pipe_2d = mk_pipe()
    pipe_2d.set_sharding(True)
    pipe_2d.set_mesh_2d(True)
    pipe_2d.rebuild()
    plan = pipe_2d._plan
    t_2d, out_2d = timed_run(pipe_2d)

    attached.stage("mesh-parity")
    for (v1, r1), (v2, r2) in zip(out_1d, out_2d):
        np.testing.assert_array_equal(v1, v2)
        np.testing.assert_array_equal(r1, r2)

    # per-device policymap bytes: replicated = every device holds the
    # whole table; ident-sharded = rows divide over the ident axis
    pm_total = sum(
        int(np.prod(m.tables.id_bits.shape)) * 4
        for m in pipe_2d._mat.values()
    )
    ident = plan.ident_size if plan.is_2d else 1
    pm_sharded = pm_total // ident

    # rule_tab only materializes under FlowAttribution — flip it on for
    # one batch to measure the [N, C] origin table under the same plan
    attached.stage("mesh-ruletab")
    pipe_2d.set_attribution(True)
    pipe_2d.rebuild()
    pipe_2d.process(*batches[0])
    rt_total = sum(
        int(np.prod(m.rule_tab.shape)) * 4
        for m in pipe_2d._mat.values()
        if m.rule_tab is not None
    )
    rt_sharded = rt_total // ident

    # OFF-path spy: a NEW batch shape (fresh trace) with 2D off must
    # never reach the ident-gather kernel
    attached.stage("mesh-offspy")
    def _trip(*a, **kw):
        raise AssertionError("ident gather reached with MeshSharding2D off")
    real = _lookup.ident_gather_rows
    _lookup.ident_gather_rows = _trip
    try:
        spy = (
            batches[0][0][: b // 2 + 3],
            batches[0][1][: b // 2 + 3],
            batches[0][2][: b // 2 + 3],
            batches[0][3][: b // 2 + 3],
        )
        pipe_1d.process(*spy)
        off_spy = "clean"
    finally:
        _lookup.ident_gather_rows = real

    return {
        "mesh_axes": dict(plan.axes),
        "mesh_devices": list(plan.device_ids),
        "ident_factor": ident,
        "plan_generation": plan.generation,
        "mesh_2d_formed": bool(plan.is_2d),
        "verdicts_1d_vps": round(k * b / t_1d),
        "verdicts_2d_vps": round(k * b / t_2d),
        "parity_2d_vs_1d": True,  # asserted above, batch-for-batch
        "pm_bytes_per_device_replicated": pm_total,
        "pm_bytes_per_device_sharded": pm_sharded,
        "pm_bytes_reduction_ratio": round(pm_total / max(1, pm_sharded), 2),
        "rt_bytes_per_device_replicated": rt_total,
        "rt_bytes_per_device_sharded": rt_sharded,
        "off_path_spy": off_spy,
        "placement": pipe_2d.placement_state(),
    }


def _bench_native_e2e(snaps, idents, nrng: np.random.Generator):
    """The native front-end's FULL per-node pipeline (conntrack probe →
    identity LPM → policymap, bpf_lxc.c end to end) — (mixed_vps,
    established_vps). 'Established' replays only allowed flows, the
    kernel's CT-bypass steady state; this is the e2e number to hold
    against the pure policymap-lookup rate (the reference amortizes the
    LPM exactly this way via conntrack, bpf/lib/conntrack.h)."""
    from cilium_tpu.identity.model import ID_WORLD
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.native import NativeFastpath, native_available

    if not native_available():
        return 0.0, 0.0
    cache = IPCache()
    for i, ident in enumerate(idents):
        cache.upsert(f"10.{(i >> 8) & 255}.{i & 255}.1/32", ident.id, source="k8s")
    nf = NativeFastpath(ep_count=N_ENDPOINTS, ct_bits=22)
    nf.set_world_identity(ID_WORLD)
    nf.load_policy_snapshots(snaps)
    nf.load_ipcache(cache)
    b = 1 << 20
    i_sel = nrng.integers(0, len(idents), b)
    ips = (
        np.uint32(10) << 24
        | ((i_sel >> 8) & 255).astype(np.uint32) << 16
        | (i_sel & 255).astype(np.uint32) << 8
        | 1
    ).astype(np.uint32)
    eps = nrng.integers(0, N_ENDPOINTS, b).astype(np.int32)
    dports = nrng.choice(np.array([80, 443, 8080, 53, 22], np.int32), b)
    protos = np.where(dports == 53, 17, 6).astype(np.int32)
    sports = nrng.integers(1024, 60000, b).astype(np.int32)
    v, _ = nf.process(ips, eps, dports, protos, sports=sports)
    iters = 5
    t0 = time.time()
    for _ in range(iters):
        v, _ = nf.process(ips, eps, dports, protos, sports=sports)
    mixed = iters * b / (time.time() - t0)
    allow = v == 1
    al = int(allow.sum())
    if al == 0:
        # nothing allowed → no established set to replay; reporting a
        # rate from zero-length batches would be nonsense
        return mixed, 0.0
    reps = b // al + 1
    ips2 = np.tile(ips[allow], reps)[:b]
    eps2 = np.tile(eps[allow], reps)[:b]
    dp2 = np.tile(dports[allow], reps)[:b]
    pr2 = np.tile(protos[allow], reps)[:b]
    sp2 = np.tile(sports[allow], reps)[:b]
    nf.process(ips2, eps2, dp2, pr2, sports=sp2)
    t0 = time.time()
    for _ in range(iters):
        nf.process(ips2, eps2, dp2, pr2, sports=sp2)
    est = iters * b / (time.time() - t0)
    return mixed, est


def _bench_native_l7() -> float:
    """Native L7 HTTP enforcement rate (DFA walk + rule chain in C++,
    the envoy/cilium_l7policy.cc role; SURVEY native census item 3)."""
    from cilium_tpu.l7.http_policy import HTTPPolicy, HTTPRequest
    from cilium_tpu.native import NativeFastpath, native_available
    from cilium_tpu.policy.api import HTTPRule

    if not native_available():
        return 0.0
    pol = HTTPPolicy(
        [(HTTPRule(path=f"/api/v{i}/[a-z0-9]*"), None) for i in range(8)]
        + [(HTTPRule(path=f"/svc{i}/.*"), {100 + i}) for i in range(8)]
    )
    nf = NativeFastpath(ep_count=1, ct_bits=0)
    nf.load_l7_http(7, 80, pol)
    b = 1 << 17
    reqs = [
        HTTPRequest(
            method="GET", path=f"/api/v{i % 8}/obj{i % 97}",
            src_identity=100 + (i % 16),
        )
        for i in range(b)
    ]
    nf.check_http_batch(7, 80, reqs[:1000])
    # pre-marshal once: in production the wire front-end hands the
    # enforcer packed buffers; re-encoding Python strings per iteration
    # would measure the test harness, not the DFA walk
    import ctypes

    from cilium_tpu.ops.dfa import strings_to_batch

    mb, ml = strings_to_batch([r.method.encode() for r in reqs], 16)
    pb, pl = strings_to_batch([r.path.encode() for r in reqs], 256)
    hb, hl = strings_to_batch([r.host.encode() for r in reqs], 256)
    src = np.ascontiguousarray([r.src_identity for r in reqs], np.uint64)
    mb = np.ascontiguousarray(mb, np.uint8)
    pb = np.ascontiguousarray(pb, np.uint8)
    hb = np.ascontiguousarray(hb, np.uint8)
    ml = np.ascontiguousarray(ml, np.int32)
    pl = np.ascontiguousarray(pl, np.int32)
    hl = np.ascontiguousarray(hl, np.int32)
    allow = np.empty(b, np.uint8)

    def ptr(a, ct):
        return a.ctypes.data_as(ctypes.POINTER(ct))

    c = ctypes
    iters = 5
    t0 = time.time()
    for _ in range(iters):
        nf._lib.nf_l7_http_batch(
            nf._h, 7, 80, 1, b,
            ptr(mb, c.c_uint8), 16, ptr(ml, c.c_int32),
            ptr(pb, c.c_uint8), 256, ptr(pl, c.c_int32),
            ptr(hb, c.c_uint8), 256, ptr(hl, c.c_int32),
            ptr(src, c.c_uint64), ptr(allow, c.c_uint8),
        )
    return iters * b / (time.time() - t0)


def _stretch_world(n_rules: int, n_ids: int, n_apps: int = 2048):
    """The stretch-config world generator (BASELINE.json configs[4]) at
    a parameterized scale — shared by --stretch inside the full sweep
    and the 100k leg of --updates. ``n_apps`` widens the label space:
    the default 2048 apps × 64 zones × 3 envs caps unique identities at
    ~393k, so the 1M rung passes 8192."""
    import random as _random

    from cilium_tpu.identity import IdentityRegistry as _IR
    from cilium_tpu.policy.repository import Repository as _Repo

    rng = _random.Random(1)
    repo = _Repo()
    rules = []
    for _ in range(n_rules):
        subject = [f"k8s:app=a{rng.randrange(n_apps)}"]
        peer = EndpointSelector.make([f"k8s:app=a{rng.randrange(n_apps)}"])
        if rng.random() < 0.3:
            port = rng.choice([80, 443, 8080, 53, 5432])
            ing = IngressRule(
                from_endpoints=(peer,),
                to_ports=(PortRule(
                    ports=(PortProtocol(port, "UDP" if port == 53 else "TCP"),)
                ),),
            )
        else:
            ing = IngressRule(from_endpoints=(peer,))
        rules.append(rule(subject, ingress=[ing]))
    repo.add_list(rules)

    reg = _IR()
    idents = []
    combos = set()
    while len(idents) < n_ids:
        app = rng.randrange(n_apps)
        zone = rng.randrange(64)
        env = rng.randrange(3)
        if (app, zone, env) in combos:
            continue
        combos.add((app, zone, env))
        labels = [f"k8s:app=a{app}", f"k8s:zone=z{zone}"]
        if env:
            labels.append(f"k8s:env={'prod' if env == 1 else 'dev'}")
        # user range first (256..65535), then the local/CIDR high range
        idents.append(
            reg.allocate(parse_label_array(labels), local=len(idents) >= 65000)
        )
    return repo, reg, idents


def _bench_stretch(world=None) -> dict:
    """The north-star stretch config (BASELINE.json configs[4]):
    100k identities × 100k rules, 64 endpoints — the reference's full
    identity envelope (pkg/identity/allocator.go:77-78) merged with
    local/CIDR identities in the high range, at 10× its per-endpoint
    rule scale. Reports compile + full-materialize time and sustained
    verdicts/s on the materialized policymap. ``world`` reuses a
    prebuilt (repo, reg, idents) — the --stretch tier shares one world
    between this and the sparse-update legs instead of paying the
    multi-minute 100k build twice."""
    from cilium_tpu.engine import PolicyEngine as _PE

    n_rules = int(os.environ.get("BENCH_STRETCH_RULES", 100_000))
    n_ids = int(os.environ.get("BENCH_STRETCH_IDS", 100_000))
    repo, reg, idents = world if world is not None else _stretch_world(
        n_rules, n_ids
    )

    engine = _PE(repo, reg)
    t0 = time.time()
    compiled = engine.refresh()
    jax.block_until_ready(engine.device_policy.sel_match)
    compile_s = time.time() - t0

    from cilium_tpu.ops.materialize import (
        TRAFFIC_INGRESS as _TI,
        materialize_endpoints_state as _mes,
    )

    ep_ids = [idents[i].id for i in range(N_ENDPOINTS)]
    t0 = time.time()
    mat_state = _mes(compiled, engine.device_policy, ep_ids, ingress=True)
    tables = mat_state.tables
    jax.block_until_ready(tables.id_bits)
    materialize_s = time.time() - t0

    nrng = np.random.default_rng(7)
    b = 1 << 22
    live_rows = np.array([compiled.id_to_row[i.id] for i in idents], np.int32)
    ep_idx = jnp.asarray(nrng.integers(0, N_ENDPOINTS, b, dtype=np.int32))
    src = jnp.asarray(nrng.choice(live_rows, b).astype(np.int32))
    dport = jnp.asarray(
        nrng.choice(np.array([80, 443, 8080, 53, 22, 0], np.int32), b)
    )
    proto = jnp.asarray(np.where(np.asarray(dport) == 53, 17, 6).astype(np.int32))
    dec, _red = lookup_batch(tables, ep_idx, src, dport, proto)
    jax.block_until_ready(dec)
    iters = 10
    t0 = time.time()
    for _ in range(iters):
        dec, _red = lookup_batch(tables, ep_idx, src, dport, proto)
    jax.block_until_ready(dec)
    vps = iters * b / (time.time() - t0)

    # ── the restart path (pinned-map persistence analog): save the
    # compiled arrays + materialized policymap, restore into a FRESH
    # engine, and measure time-to-first-verdict — what a daemon restart
    # pays instead of the compile_s + materialize_s above.
    import os as _os
    import tempfile as _tempfile

    snap_dir = _tempfile.mkdtemp(prefix="bench-snap-")
    snap_path = _os.path.join(snap_dir, "compiled.npz")
    t0 = time.time()
    engine.save_snapshot(snap_path, {_TI: mat_state})
    save_s = time.time() - t0
    engine2 = _PE(repo, reg)
    t0 = time.time()
    # same-process restore: repo/reg ARE the snapshotted objects, so
    # counter equality is content equality (trust_counters contract)
    restored = engine2.restore_snapshot(snap_path, trust_counters=True)
    dec2, _ = lookup_batch(
        restored[_TI].tables, ep_idx[:1024], src[:1024], dport[:1024],
        proto[:1024],
    )
    jax.block_until_ready(dec2)
    restore_s = time.time() - t0
    try:
        _os.unlink(snap_path)
        _os.rmdir(snap_dir)
    except OSError:
        pass

    return {
        "identities": len(idents),
        "local_identities": sum(1 for x in idents if x.is_local),
        "rules": n_rules,
        "endpoints": N_ENDPOINTS,
        "verdicts_vps": round(vps),
        "compile_s": round(compile_s, 1),
        "materialize_s": round(materialize_s, 1),
        "snapshot_save_s": round(save_s, 1),
        # time from restore() to the first enforced verdict batch —
        # the restart-to-enforcement number (target: < 5s)
        "restore_to_verdict_s": round(restore_s, 2),
        "selectors": compiled.num_selectors,
        "rows": int(compiled.id_bits.shape[0]),
        "allow_fraction": round(float((np.asarray(dec) == 1).mean()), 4),
    }


def _bench_updates(repo, reg, idents) -> dict:
    """policyd-delta churn round (--updates): update-latency
    percentiles with the O(delta) refresh paths live. Samples are
    DEVICE-BLOCKING via engine.wait_device() — refresh() itself never
    blocks on the device (the coalesced _set_rows2 / CSR column
    scatters are enqueue-only), so the wait is the true device RTT of
    the delta — and the pipeline leg is measured through the REAL
    rebuild() so what's timed is the delta routing: row patches,
    patch_endpoints_state column patches, and an epoch-swapped full
    rebuild."""
    from cilium_tpu.datapath.pipeline import DatapathPipeline
    from cilium_tpu.ipcache.ipcache import IPCache
    from cilium_tpu.labels import parse_label_array as _pla

    engine = PolicyEngine(repo, reg)
    engine.refresh()
    engine.wait_device()
    pipe = DatapathPipeline(engine, IPCache())
    pipe.set_endpoints([i.id for i in idents[:N_ENDPOINTS]])
    pipe.rebuild()

    def pcts(samples):
        s = sorted(samples)
        return (
            round(s[len(s) // 2] * 1000, 2),
            round(s[min(len(s) - 1, int(len(s) * 0.99))] * 1000, 2),
        )

    # Warm every measured path once (jit the row/column patch kernels
    # + the sweep): percentiles should report steady-state churn, not
    # first-compile cost — the sweep idiom of the main bench.
    warm_ident = reg.allocate(_pla(["k8s:app=a1", "k8s:env=updwarm"]))
    engine.refresh()
    engine.wait_device()
    pipe.rebuild()
    reg.release(warm_ident)
    engine.refresh()
    engine.wait_device()
    pipe.rebuild()
    warm_rule = rule(
        ["k8s:app=a1"],
        ingress=[IngressRule(
            from_endpoints=(EndpointSelector.make(["k8s:app=a2"]),),
        )],
        labels=["k8s:policy=updwarm"],
    )
    repo.add_list([warm_rule])
    engine.refresh()
    engine.wait_device()
    pipe.rebuild()
    repo.delete_by_labels(_pla(["k8s:policy=updwarm"]))
    engine.refresh()
    engine.wait_device()
    pipe.rebuild()

    # identity churn: allocate → refresh (coalesced row-delta enqueue)
    # → wait_device; restore between samples so row-capacity crossings
    # can't skew the series (the _bench_ident_update discipline)
    ident_s = []
    for i in range(20):
        labels = _pla([f"k8s:app=a{i % 512}", "k8s:env=updbench"])
        t0 = time.perf_counter()
        ident = reg.allocate(labels)
        engine.refresh()
        engine.wait_device()
        ident_s.append(time.perf_counter() - t0)
        reg.release(ident)
        engine.refresh()
        engine.wait_device()
    ident_p50, ident_p99 = pcts(ident_s)
    # Drain the 40 accumulated row deltas into one coalesced
    # patch_identity_rows replay (unmeasured) so the rule-loop
    # percentiles time pure column patches — without this the first
    # rule rebuild also pays the whole ident backlog's re-sweep.
    pipe.rebuild()

    # single-rule append: engine-side in-place matrix append + CSR
    # sel_match window scatter, then the pipeline's O(delta) column
    # patch; patch_hits counts rebuilds that kept the MaterializedState
    # objects (i.e. actually took patch_endpoints_state, not a full
    # re-materialization)
    rng = random.Random(77)
    rule_s, delta_s = [], []
    patch_hits = 0
    n_rule_samples = 12
    # i == -1 peels one full iteration of the exact measured body as a
    # discard: the first column patch jit-compiles the sweep at the
    # patch segment-bucket shape (a shape the L3-only warm rule above
    # does not produce), and that one-time compile would otherwise BE
    # the p99
    for i in range(-1, n_rule_samples):
        r = rule(
            [f"k8s:app=a{rng.randrange(512)}"],
            ingress=[IngressRule(
                from_endpoints=(
                    EndpointSelector.make([f"k8s:app=a{rng.randrange(512)}"]),
                ),
            )],
            labels=[f"k8s:policy=updbench-{i}"],
        )
        t0 = time.perf_counter()
        repo.add_list([r])
        engine.refresh()
        engine.wait_device()
        if i >= 0:
            rule_s.append(time.perf_counter() - t0)
        base = dict(pipe._mat)
        t0 = time.perf_counter()
        pipe.rebuild()
        if i >= 0:
            delta_s.append(time.perf_counter() - t0)
            if all(pipe._mat.get(d) is base[d] for d in base):
                patch_hits += 1
        repo.delete_by_labels(_pla([f"k8s:policy=updbench-{i}"]))
        engine.refresh()
        engine.wait_device()
        pipe.rebuild()
    rule_p50, rule_p99 = pcts(rule_s)
    delta_p50, delta_p99 = pcts(delta_s)

    # epoch swap: a forced full recompile served through the shadow
    # thread — wall time from the kicking rebuild() to the publishing
    # one. Dispatches would keep verdicting the old generation for all
    # but the final publish instant.
    pipe.set_epoch_swap(True)
    engine.refresh(force=True)
    t0 = time.perf_counter()
    pipe.rebuild()  # kicks the shadow, keeps serving
    swapped = pipe.wait_epoch_swap(600)
    pipe.rebuild()  # the batch-boundary publish
    epoch_swap_ms = (time.perf_counter() - t0) * 1000
    pipe.set_epoch_swap(False)

    return {
        "identities": len(idents),
        "rules": len(repo),
        "update_ident_p50_ms": ident_p50,
        "update_ident_p99_ms": ident_p99,
        "update_rule_p50_ms": rule_p50,
        "update_rule_p99_ms": rule_p99,
        "delta_materialize_ms": delta_p50,
        "delta_materialize_p99_ms": delta_p99,
        "delta_patch_hits": patch_hits,
        "delta_patch_samples": n_rule_samples,
        "epoch_swap_ms": round(epoch_swap_ms, 1),
        "epoch_swap_completed": bool(swapped),
        "policy_epoch": pipe.policy_epoch,
    }


def _bench_sparse_updates(repo, reg, idents) -> dict:
    """policyd-sparse churn round (--stretch): single-update latency
    percentiles at the CALLER'S scale with SparseDeltas on — the
    placed sel_match patched from the engine delta log (rows + CSR
    column windows) and the LPM tries patched in place from the
    ipcache delta ring. Each leg also reports the h2d transfer-byte
    ledger delta per update: the O(k) evidence (a dense re-place of
    the [N, S/32] matrix or a trie re-upload would show as MBs)."""
    from cilium_tpu.datapath.pipeline import DatapathPipeline
    from cilium_tpu.ipcache.ipcache import IPCache, SOURCE_AGENT as _SA
    from cilium_tpu.labels import parse_label_array as _pla
    from cilium_tpu import metrics as _m

    engine = PolicyEngine(repo, reg)
    engine.refresh()
    engine.wait_device()
    cache = IPCache()
    # enough v4 prefixes to shape a real trie; idents map to live rows
    for i, ident in enumerate(idents[:4096]):
        cache.upsert(
            f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}",
            ident.id, _SA,
        )
    pipe = DatapathPipeline(engine, cache, sparse_deltas=True)
    pipe.set_endpoints([i.id for i in idents[:N_ENDPOINTS]])
    pipe.rebuild()

    def pcts(samples):
        s = sorted(samples)
        return (
            round(s[len(s) // 2] * 1000, 2),
            round(s[min(len(s) - 1, int(len(s) * 0.99))] * 1000, 2),
        )

    h2d = _m.device_transfer_bytes_total

    def ledger():
        return h2d.get({"direction": "h2d"})

    # ── identity churn: alloc → refresh → rebuild; the rebuild patches
    # the engine rows AND the ident-placed copy (i == -1 peels the
    # shape-bucket jit compile, the --updates discipline)
    ident_s, ident_bytes = [], []
    for i in range(-1, 12):
        labels = _pla([f"k8s:app=a{(i + 3) % 512}", "k8s:env=sparsebench"])
        b0 = ledger()
        t0 = time.perf_counter()
        ident = reg.allocate(labels)
        engine.refresh()
        engine.wait_device()
        pipe.rebuild()
        if i >= 0:
            ident_s.append(time.perf_counter() - t0)
            ident_bytes.append(ledger() - b0)
        reg.release(ident)
        engine.refresh()
        engine.wait_device()
        pipe.rebuild()
    ident_p50, ident_p99 = pcts(ident_s)

    # ── single-rule append with a NEW selector: the engine grows the
    # selector window, logs a "cols" event, and the rebuild patches
    # the placed sel_match with the O(k) column scatter
    rng = random.Random(99)
    sel_s, sel_bytes = [], []
    for i in range(-1, 12):
        # a label value no identity carries → genuinely new selector
        r = rule(
            [f"k8s:app=a{rng.randrange(512)}"],
            ingress=[IngressRule(from_endpoints=(
                EndpointSelector.make([f"k8s:sparse=new{i}"]),
            ),)],
            labels=[f"k8s:policy=sparsebench-{i}"],
        )
        b0 = ledger()
        t0 = time.perf_counter()
        repo.add_list([r])
        engine.refresh()
        engine.wait_device()
        pipe.rebuild()
        if i >= 0:
            sel_s.append(time.perf_counter() - t0)
            sel_bytes.append(ledger() - b0)
        repo.delete_by_labels(_pla([f"k8s:policy=sparsebench-{i}"]))
        engine.refresh()
        engine.wait_device()
        pipe.rebuild()
    sel_p50, sel_p99 = pcts(sel_s)

    # ── ipcache churn: /32 upsert+delete patched into the placed trie
    # tensors (dirty node rows / dense spans only)
    trie_s, trie_bytes = [], []
    patches0 = _m.lpm_trie_patches_total.get({"family": "4"})
    for i in range(-1, 12):
        b0 = ledger()
        t0 = time.perf_counter()
        cache.upsert(f"172.16.{i + 1}.9", idents[7].id, _SA)
        pipe.rebuild()
        if i >= 0:
            trie_s.append(time.perf_counter() - t0)
            trie_bytes.append(ledger() - b0)
        cache.delete(f"172.16.{i + 1}.9", _SA)
        pipe.rebuild()
    trie_p50, trie_p99 = pcts(trie_s)
    trie_patches = _m.lpm_trie_patches_total.get({"family": "4"}) - patches0

    # ── before/after rebuild-phase breakdown (PhaseTracing): the same
    # churn traced through process()'s "rebuild" span with the option
    # OFF (dense re-place + classic trie build) then ON (row/col/trie
    # patches) — the phase-level view of the O(k) claim
    rng2 = np.random.default_rng(11)
    bsz = 4096
    batch = (
        (10 << 24) + rng2.integers(0, 4096, bsz).astype(np.uint32),
        rng2.integers(0, N_ENDPOINTS, bsz).astype(np.int32),
        rng2.choice(np.array([80, 443, 53], np.int32), bsz),
        np.full(bsz, 6, np.int32),
    )

    def traced_rebuild_ms(on: bool) -> float:
        pipe.set_sparse_deltas(on)
        pipe.rebuild()
        pipe.process(*batch)  # warm this mode's programs
        pipe.tracer.clear()
        pipe.tracer.enable()
        for i in range(3):
            cache.upsert(f"172.17.{i + 1}.9", idents[7].id, _SA)
            pipe.process(*batch)
            cache.delete(f"172.17.{i + 1}.9", _SA)
            pipe.process(*batch)
        pipe.tracer.disable()
        spans = [
            dur for t in pipe.tracer.traces()
            for name, _rel, dur in t["phases"] if name == "rebuild"
        ]
        return round(sum(spans) / max(1, len(spans)) / 1e6, 2)

    rebuild_dense_ms = traced_rebuild_ms(False)
    rebuild_sparse_ms = traced_rebuild_ms(True)

    def med(v):
        return int(sorted(v)[len(v) // 2]) if v else 0

    return {
        # mean process()-traced "rebuild" phase across the same churn,
        # option off vs on — the before/after phase breakdown
        "sparse_rebuild_phase_dense_ms": rebuild_dense_ms,
        "sparse_rebuild_phase_ms": rebuild_sparse_ms,
        "sparse_update_ident_p50_ms": ident_p50,
        "sparse_update_ident_p99_ms": ident_p99,
        "sparse_update_selector_p50_ms": sel_p50,
        "sparse_update_selector_p99_ms": sel_p99,
        "sparse_update_trie_p50_ms": trie_p50,
        "sparse_update_trie_p99_ms": trie_p99,
        # h2d ledger delta per single update — the O(k) transfer
        # evidence (int: bytes are attribution, not a diffed rate)
        "sparse_ident_h2d_bytes": med(ident_bytes),
        "sparse_selector_h2d_bytes": med(sel_bytes),
        "sparse_trie_h2d_bytes": med(trie_bytes),
        "sparse_trie_patches_applied": int(trie_patches),
    }


def _bench_stretch_1m() -> dict:
    """The 1M-identity rung (policyd-sparse envelope target): compile
    the full policy tensors at 1M identities WITHOUT OOM and time one
    O(delta) identity update on top. Materialization/verdict reps stay
    at the 100k leg — this rung gates the compile envelope and the
    sparse update path at 10× scale. BENCH_STRETCH_1M=0 skips;
    BENCH_STRETCH_1M_IDS/_RULES rescale (the schema regression test
    runs a tiny rung)."""
    from cilium_tpu.engine import PolicyEngine as _PE
    from cilium_tpu.labels import parse_label_array as _pla

    if os.environ.get("BENCH_STRETCH_1M", "1") == "0":
        return {"skipped": "BENCH_STRETCH_1M=0"}
    n_ids = int(os.environ.get("BENCH_STRETCH_1M_IDS", 1_000_000))
    n_rules = int(os.environ.get("BENCH_STRETCH_1M_RULES", 20_000))
    t0 = time.time()
    repo, reg, idents = _stretch_world(n_rules, n_ids, n_apps=8192)
    build_s = time.time() - t0

    engine = _PE(repo, reg)
    t0 = time.time()
    compiled = engine.refresh()
    jax.block_until_ready(engine.device_policy.sel_match)
    compile_s = time.time() - t0
    sel_match_mb = (
        int(compiled.id_bits.shape[0])
        * int(engine.device_policy.sel_match.shape[1]) * 4 / 1e6
    )

    # one blocking identity update at 1M rows — the O(delta) row patch
    # must stay flat in N
    t0 = time.perf_counter()
    ident = reg.allocate(_pla(["k8s:app=a1", "k8s:env=rung1m"]))
    engine.refresh()
    engine.wait_device()
    update_ms = (time.perf_counter() - t0) * 1000
    reg.release(ident)
    engine.refresh()

    return {
        "identities": len(idents),
        "rules": n_rules,
        "rows": int(compiled.id_bits.shape[0]),
        "selectors": compiled.num_selectors,
        "world_build_s": round(build_s, 1),
        "compile_s": round(compile_s, 1),
        "sel_match_mb": int(sel_match_mb),
        "update_ident_blocking_ms": round(update_ms, 1),
    }


def _host_envelope() -> dict:
    """The bench host's compute envelope (VERDICT r04 #2): host-side
    numbers (kafka_acl_rps, native_vps) track the machine as much as
    the code, and a ±50% swing is uninterpretable without knowing
    whether the machine changed. Reports CPU count/model plus a FIXED
    single-core calibration op — a pure-Python token loop and a pinned
    64MB sha256 — so rounds can be compared per unit of host compute
    (rate ÷ calib) instead of raw."""
    import hashlib
    import platform

    model = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        model = platform.processor()

    # pure-Python single-core loop (interpreter + scalar ALU proxy —
    # what the Kafka ACL host path is made of)
    t0 = time.perf_counter()
    acc = 0
    for i in range(2_000_000):
        acc += i & 7
    py_loops = 2_000_000 / (time.perf_counter() - t0)

    # pinned-size sha256 (memory-streaming + vector proxy — closer to
    # the native C++ front-end's profile)
    blob = b"\x5a" * (1 << 26)
    t0 = time.perf_counter()
    hashlib.sha256(blob).digest()
    sha_mbps = (1 << 26) / (time.perf_counter() - t0) / 1e6

    return {
        "host_cpus": os.cpu_count(),
        "cpu_model": model,
        "calib_py_loops_per_s": round(py_loops),
        "calib_sha256_mb_per_s": round(sha_mbps, 1),
        "py_version": platform.python_version(),
    }


def _bench_dispatch_rtt() -> float:
    """Median blocking round trip for a trivial pre-compiled dispatch —
    the environment's latency floor for ANY blocking device update
    (under the axon tunnel this dominates update_ident_ms; on local
    TPU hardware it is sub-millisecond)."""
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8, jnp.int32)
    jax.block_until_ready(f(x))
    samples = []
    for _ in range(10):
        t0 = time.time()
        jax.block_until_ready(f(x))
        samples.append(time.time() - t0)
    return sorted(samples)[len(samples) // 2] * 1000


class _AttachStages:
    """Staged attach heartbeat: ``stage(name)`` records progress so a
    wedged round reports the LAST COMPLETED stage instead of a bare
    timeout; ``set()`` disarms the watchdog."""

    def __init__(self) -> None:
        import threading

        self.done = threading.Event()
        self.last = "start"
        self.t0 = time.time()
        self.history: list = []

    def stage(self, name: str) -> None:
        self.last = name
        self.history.append([name, round(time.time() - self.t0, 3)])

    def set(self) -> None:
        self.done.set()


def _attach_watchdog(timeout_s: float) -> _AttachStages:
    """The axon tunnel can wedge indefinitely at device attach (seen
    in-round: >6h unresponsive). A silent hang records NOTHING for the
    round — this watchdog emits an explanatory one-line JSON and exits
    instead, so the failure is visible and bounded. Disarmed once the
    full attach sequence (backend init → device visible → first
    compile → first batch) completes; on timeout the JSON tail names
    the last completed stage."""
    import threading

    st = _AttachStages()

    def watch():
        if st.done.wait(timeout_s):
            return
        print(json.dumps({
            "metric": f"policy verdicts/sec at {N_RULES} rules",
            "value": 0,
            "unit": "verdicts/s",
            "vs_baseline": 0.0,
            "attach_stage": st.last,
            "attach_history": st.history,
            # never comparable to device rates AND machine-greppable:
            # a wedged round must still leave one parseable record
            "backend": "attach-timeout",
            "host_cpus": os.cpu_count(),
            "error": (
                f"TPU attach did not complete within {timeout_s:.0f}s "
                f"(axon tunnel wedged?) — last completed stage: "
                f"{st.last} — no measurements taken"
            ),
        }), flush=True)
        os._exit(3)

    threading.Thread(target=watch, daemon=True).start()
    return st


def _attach_backend(
    attached: _AttachStages,
    attempt_timeout_s: float,
    attempts: int = 2,
    local_fallback: bool = False,
) -> str:
    """Bounded attach with retry: the backend handshake + first compile
    run on a worker thread under a per-attempt deadline (the watchdog
    above still bounds the WHOLE attach sequence). A wedged axon tunnel
    sometimes recovers on reconnect, so one backoff retry is cheap
    insurance before declaring the round dead; ``--local-fallback``
    swaps in the host CPU backend after the final timeout instead of
    aborting — the result JSON records backend=local-fallback so the
    numbers are never mistaken for device rates. Returns the platform
    name actually attached."""
    import threading

    for attempt in range(1, attempts + 1):
        attached.stage(f"backend-init:attempt{attempt}")
        out: dict = {}

        def probe():
            try:
                if os.environ.get("BENCH_FAKE_HUNG_ATTACH"):
                    # regression hook (r05's wedge): park exactly like a
                    # dead axon tunnel so tests can drive the timeout
                    # path without real hardware
                    time.sleep(3600)
                from cilium_tpu import faults as _faults

                if _faults.hub.active:
                    # chaos rounds rehearse the wedged-attach failure
                    # (round 5's rc-3-no-data) through the same bounded
                    # retry that real reattaches take
                    _faults.hub.check(_faults.SITE_ATTACH)
                devs = jax.devices()  # backend handshake; no program yet
                # first device op: forces the first XLA compile
                # through the tunnel
                jax.block_until_ready(jnp.zeros(8) + 1)
                out["platform"] = devs[0].platform
            except Exception as e:  # init raised cleanly — retryable
                out["error"] = f"{type(e).__name__}: {e}"

        th = threading.Thread(target=probe, daemon=True)
        th.start()
        th.join(attempt_timeout_s)
        if "platform" in out:
            attached.stage(f"device-visible:{out['platform']}")
            attached.stage("first-compile")
            return out["platform"]
        attached.stage(
            f"attach-{'timeout' if th.is_alive() else 'error'}"
            f":attempt{attempt}"
        )
        if attempt < attempts:
            time.sleep(2.0 * attempt)  # backoff before reattaching
            try:
                jax.clear_backends()  # drop the wedged client if possible
            except Exception:
                pass
    if not local_fallback:
        print(json.dumps({
            "metric": f"policy verdicts/sec at {N_RULES} rules",
            "value": 0,
            "unit": "verdicts/s",
            "vs_baseline": 0.0,
            "attach_stage": attached.last,
            "attach_history": attached.history,
            "backend": "attach-timeout",
            "host_cpus": os.cpu_count(),
            "error": (
                f"TPU attach failed after {attempts} bounded attempt(s) "
                f"({attempt_timeout_s:.0f}s each) — last stage: "
                f"{attached.last} — no measurements taken "
                "(re-run with --local-fallback for host-CPU numbers)"
            ),
        }), flush=True)
        os._exit(3)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.clear_backends()
    except Exception:
        pass
    jax.block_until_ready(jnp.zeros(8) + 1)
    attached.stage("local-fallback")
    return "local-fallback"


def _lint_preflight() -> None:
    """``--lint``: refuse the round when the package carries NEW
    policyd-lint findings — a fresh device sync, lock convoy, or
    contract drift would make the numbers lie about the architecture.
    Always emits one per-rule finding-count stats line first (no
    "metric" key, so --diff never mistakes it for the round's record;
    same backend/host_cpus pair every artifact line carries), then the
    same one-line-JSON refusal idiom as the attach watchdog when new
    findings exist. Runs BEFORE device attach (pure-AST, ~1s)."""
    from cilium_tpu.analysis import analyze_paths, default_target
    from cilium_tpu.analysis.baseline import (
        default_baseline_path, load_baseline, new_findings,
    )

    counts, _ = load_baseline(default_baseline_path())
    bench_path = os.path.abspath(__file__)
    findings = analyze_paths([default_target(), bench_path])
    fresh = new_findings(findings, counts)
    per_rule: dict = {}
    for f in findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    print(json.dumps({
        "lint": {
            "findings_per_rule": dict(sorted(per_rule.items())),
            "total": len(findings),
            "new": len(fresh),
        },
        # no device attached yet (lint runs first) but the line keeps
        # the always-present pair every artifact line carries
        "backend": "unattached",
        "host_cpus": os.cpu_count(),
    }), flush=True)
    if not fresh:
        return
    print(json.dumps({
        "metric": f"policy verdicts/sec at {N_RULES} rules",
        "value": 0,
        "unit": "verdicts/s",
        "vs_baseline": 0.0,
        "backend": "unattached",
        "host_cpus": os.cpu_count(),
        "error": (
            f"lint pre-flight: {len(fresh)} new finding(s) — "
            + "; ".join(f.render() for f in fresh[:3])
            + (" ..." if len(fresh) > 3 else "")
            + " — fix or baseline (python -m cilium_tpu.analysis) "
            "before benching"
        ),
    }), flush=True)
    sys.exit(3)


# ── --diff: bench regression diffing (policyd-prof) ──────────────────

# the direction vocabulary is a STABLE contract shared with the
# BENCH001 lint rule — cilium_tpu/contracts.py is the one definition
from cilium_tpu.contracts import (  # noqa: E402
    DIFF_HIGHER_SUFFIXES as _DIFF_HIGHER,
    DIFF_LOWER_SUFFIXES as _DIFF_LOWER,
    DIFF_SKIP_KEYS as _DIFF_SKIP,
)


def _flag_value(argv, name):
    """Value following a bare ``--flag VALUE`` pair (bench has no
    argparse — every mode is a sys.argv scan)."""
    if name in argv:
        i = argv.index(name)
        if i + 1 < len(argv):
            return argv[i + 1]
    return None


def _load_artifact(path: str) -> dict:
    """Parse a BENCH/TRACES artifact: a bare metric-line JSON object,
    or a round log with one JSON object per line (stdout + stderr
    concatenated). The first line carrying "metric" is the record; a
    ``{"detail": ...}`` line contributes the calibration envelope and
    "traces"/"phases" found on other lines are merged in when the
    record lacks them."""
    rec: dict = {}
    detail: dict = {}
    extra: dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if not isinstance(obj, dict):
                continue
            if "metric" in obj and "metric" not in rec:
                rec = obj
            elif isinstance(obj.get("detail"), dict):
                detail = obj["detail"]
            for key in ("traces", "phases"):
                if key in obj and key not in extra:
                    extra[key] = obj[key]
    if not rec and not extra:
        raise ValueError(f"no metric/traces JSON line found in {path}")
    for key, val in extra.items():
        rec.setdefault(key, val)
    if detail:
        rec.setdefault("detail", detail)
    return rec


def _diff_calib(rec: dict, key: str):
    v = rec.get(key)
    if v is None:
        v = (rec.get("detail") or {}).get(key)
    try:
        return float(v) if v else None
    except (TypeError, ValueError):
        return None


def _diff_host_scale(key: str, prev: dict, cur: dict):
    """cur/prev calibration ratio for host-side metrics, or None when
    the key is device-side or either artifact lacks the envelope.
    Interpreter-bound paths normalize by the python loop, the native
    front-end by the sha stream (same split the full sweep's
    *_per_* normalizations use)."""
    if key.startswith(("kafka_", "p99")):
        calib = "calib_py_loops_per_s"
    elif key.startswith("native_"):
        calib = "calib_sha256_mb_per_s"
    else:
        return None
    pv, cv = _diff_calib(prev, calib), _diff_calib(cur, calib)
    if not pv or not cv:
        return None
    return cv / pv


def _diff_phase_means(rec: dict) -> dict:
    """{phase: mean_ms} from an explicit "phases" dict or a TRACES
    artifact ("traces": [{"phases": [[name, rel_ns, dur_ns], ...]}])."""
    ph = rec.get("phases")
    if isinstance(ph, dict):
        return {k: float(v) for k, v in ph.items()
                if isinstance(v, (int, float))}
    tot: dict = {}
    n: dict = {}
    for t in rec.get("traces", ()) or ():
        for name, _rel, dur in t.get("phases", ()):
            tot[name] = tot.get(name, 0.0) + dur / 1e6
            n[name] = n.get(name, 0) + 1
    return {k: tot[k] / n[k] for k in tot}


def _diff_records(prev: dict, cur: dict, threshold_pct: float) -> int:
    """Compare two bench records, print ONE machine-greppable verdict
    line, and return the process exit code (0 pass/incomparable, 4
    regression). Direction comes from the key's unit suffix; host-side
    metrics are normalized by the calibration envelope when both
    records carry one."""
    prev_b, cur_b = prev.get("backend"), cur.get("backend")
    verdict = {
        "threshold_pct": round(threshold_pct, 1),
        "backend": [prev_b, cur_b],
        "host_cpus": [prev.get("host_cpus"), cur.get("host_cpus")],
    }
    if prev_b != cur_b and prev_b is not None and cur_b is not None:
        # local-fallback vs device rates (or a wedged round) must
        # never produce a pass OR fail — only an explicit refusal
        verdict["verdict"] = "incomparable"
        verdict["reason"] = f"backend mismatch: {prev_b} vs {cur_b}"
        print(json.dumps({"diff": verdict}), flush=True)
        return 0

    cpus_differ = (
        prev.get("host_cpus") is not None
        and cur.get("host_cpus") is not None
        and prev.get("host_cpus") != cur.get("host_cpus")
    )
    thr = threshold_pct / 100.0
    regressions, improvements, skipped = [], [], []

    def compare(key, pval, cval, higher, normalized):
        delta = (cval - pval) / abs(pval) * 100.0
        entry = {"key": key, "prev": round(pval, 3), "cur": round(cval, 3),
                 "delta_pct": round(delta, 1)}
        if normalized:
            entry["normalized"] = True
        worse = cval < pval * (1 - thr) if higher else cval > pval * (1 + thr)
        better = cval > pval * (1 + thr) if higher else cval < pval * (1 - thr)
        if worse:
            regressions.append(entry)
        elif better:
            improvements.append(entry)
        return 1

    compared = 0
    for key, pval in prev.items():
        if key in _DIFF_SKIP or key.startswith("calib_"):
            continue
        cval = cur.get(key)
        if not isinstance(pval, (int, float)) or isinstance(pval, bool):
            continue
        if not isinstance(cval, (int, float)) or isinstance(cval, bool):
            continue
        if key.endswith(_DIFF_HIGHER):
            higher = True
        elif key.endswith(_DIFF_LOWER):
            higher = False
        else:
            continue
        if pval <= 0 or cval <= 0:
            # zeroed (skipped sub-bench) or flag-negated values carry
            # no rate/latency meaning — refuse silently failing on them
            skipped.append({"key": key, "reason": "non-positive"})
            continue
        scale = _diff_host_scale(key, prev, cur)
        if scale is None and cpus_differ and key.startswith(
            ("kafka_", "native_", "p99")
        ):
            skipped.append({
                "key": key,
                "reason": "host_cpus mismatch, no calibration envelope",
            })
            continue
        if scale is not None:
            # expected cur = prev moved with the machine: rates scale
            # with calib, times against it
            pval = pval * scale if higher else pval / scale
        compared += compare(key, pval, cval, higher, scale is not None)

    # the headline "value" has no suffixed twin in the full sweep —
    # diff it via the unit field when the metric lines match
    if (prev.get("metric") == cur.get("metric")
            and isinstance(prev.get("value"), (int, float))
            and isinstance(cur.get("value"), (int, float))
            and prev["value"] > 0 and cur["value"] > 0):
        unit = str(prev.get("unit", ""))
        if unit.endswith("/s"):
            compared += compare("value", float(prev["value"]),
                                float(cur["value"]), True, False)
        elif unit in ("ms", "us", "s", "pct"):
            compared += compare("value", float(prev["value"]),
                                float(cur["value"]), False, False)

    # phase waterfall: every phase is a duration → lower is better
    pph, cph = _diff_phase_means(prev), _diff_phase_means(cur)
    for name in sorted(set(pph) & set(cph)):
        if pph[name] > 0 and cph[name] > 0:
            compared += compare(f"phase:{name}", pph[name], cph[name],
                                False, False)

    verdict["verdict"] = "regression" if regressions else "pass"
    verdict["compared"] = compared
    verdict["regressions"] = regressions
    verdict["improvements"] = improvements
    if skipped:
        verdict["skipped"] = skipped
    print(json.dumps({"diff": verdict}), flush=True)
    return 4 if regressions else 0


def _diff_threshold(argv) -> float:
    raw = _flag_value(argv, "--diff-threshold") or os.environ.get(
        "BENCH_DIFF_THRESHOLD", "25"
    )
    return float(raw)


def main() -> None:
    diff_prev = _flag_value(sys.argv[1:], "--diff")
    if diff_prev is not None:
        cur_path = _flag_value(sys.argv[1:], "--cur")
        if cur_path is not None:
            # pure file-vs-file compare: runs BEFORE the attach
            # watchdog — no device, no world build, sub-second
            sys.exit(_diff_records(
                _load_artifact(diff_prev), _load_artifact(cur_path),
                _diff_threshold(sys.argv[1:]),
            ))
    if "--lint" in sys.argv[1:]:
        _lint_preflight()
    attached = _attach_watchdog(
        float(os.environ.get("BENCH_ATTACH_TIMEOUT", 900))
    )
    backend = _attach_backend(
        attached,
        float(os.environ.get("BENCH_ATTACH_ATTEMPT_TIMEOUT", 300)),
        local_fallback="--local-fallback" in sys.argv[1:],
    )

    if "--l7" in sys.argv[1:]:
        # policyd-l7batch round: fused DFA dispatch per length rung,
        # fused-vs-split speedup, pipeline overlap ratio, and
        # kafka_acl_rps host/device in one report — no world build
        # needed (L7 tables are per-endpoint-port). The round driver
        # diffs l7_dfa_rps against the full sweep's split-path number.
        out = _bench_l7()
        attached.set()
        print(json.dumps({
            "metric": "L7 fused DFA dispatch rate",
            "value": out["l7_dfa_rps"],
            "unit": "rps",
            **out,
            "backend": backend,
            "host_cpus": os.cpu_count(),
        }))
        return

    if "--cluster" in sys.argv[1:]:
        # policyd-fed round: federated identity allocation + epoch
        # barrier across 3 in-process nodes on one filestore — the
        # round driver gates on epoch_converged and the injectivity
        # asserts inside. No world build needed.
        out = _bench_cluster(attached)
        attached.set()
        print(json.dumps({
            "metric": "federated contended identity allocation rate",
            "value": out["contended_alloc_rps"],
            "unit": "ops/s",
            **out,
            "backend": backend,
            "host_cpus": os.cpu_count(),
        }))
        return

    if "--fleetobs" in sys.argv[1:]:
        # policyd-fleetobs round: 3 real daemon processes publish
        # telemetry frames over one filestore; the aggregator side is
        # gated inline on vps parity and on surviving a SIGKILL'd
        # node (frames age out, scoreboard drops to 2, no crash). No
        # world build needed.
        out = _bench_fleetobs(attached)
        attached.set()
        print(json.dumps({
            "metric": "fleet-aggregated verdict rate over 3 nodes",
            "value": out["fleet_agg_vps"],
            "unit": "vps",
            **out,
            "backend": backend,
            "host_cpus": os.cpu_count(),
        }))
        return

    if "--stretch" in sys.argv[1:]:
        # policyd-sparse round: the 100k×100k stretch envelope as a
        # standalone tier (no 10k world build), plus sparse single-
        # update percentiles at stretch scale and the 1M-identity
        # compile rung — the round driver gates on
        # stretch_100k_materialize_s and the <10ms sparse update p50s
        n_rules = int(os.environ.get("BENCH_STRETCH_RULES", 100_000))
        n_ids = int(os.environ.get("BENCH_STRETCH_IDS", 100_000))
        t0 = time.time()
        world = _stretch_world(n_rules, n_ids)
        t_build = time.time() - t0
        attached.stage("stretch-world")
        stretch = _bench_stretch(world=world)
        attached.stage("stretch-100k")
        sparse = _bench_sparse_updates(*world)
        attached.stage("sparse-updates")
        rung_1m = _bench_stretch_1m()
        attached.set()
        print(json.dumps({
            "metric": f"stretch full-materialize at {n_ids} identities",
            "value": stretch["materialize_s"],
            "unit": "s",
            # BENCH001: the sub-metrics the round driver tracks ride at
            # top level with direction suffixes (the nested stretch_100k
            # record is history/context, not the regression surface)
            "stretch_100k_materialize_s": stretch["materialize_s"],
            "stretch_100k_compile_s": stretch["compile_s"],
            "stretch_100k_vps": stretch["verdicts_vps"],
            **sparse,
            "stretch_100k": stretch,
            "stretch_1m": rung_1m,
            "backend": backend,
            "host_cpus": os.cpu_count(),
            "build_s": round(t_build, 2),
        }))
        return

    rng = random.Random(42)
    t0 = time.time()
    repo, reg, idents = build_world(rng)
    t_build = time.time() - t0

    if "--flows" in sys.argv[1:]:
        # attribution-overhead round (policyd-flows): ONE number, fast,
        # instead of the full sweep — the round driver diffs
        # attribution_overhead_pct across PRs
        off_vps, on_vps, overhead = _bench_flows(
            repo, reg, idents, np.random.default_rng(21)
        )
        print(json.dumps({
            "metric": f"FlowAttribution overhead at {N_RULES} rules",
            "value": round(overhead, 2),
            "unit": "pct",
            "attribution_overhead_pct": round(overhead, 2),
            "flows_off_vps": round(off_vps),
            "flows_on_vps": round(on_vps),
            "pipeline_depth": 2,
            "backend": backend,
            "host_cpus": os.cpu_count(),
            "build_s": round(t_build, 2),
        }))
        return

    if "--prof" in sys.argv[1:]:
        # policyd-prof round: RTT decomposition soundness + sampled
        # profiling overhead — the round driver gates on
        # rtt_decomposition_sound and profiling_overhead_pct < 2
        out = _bench_prof(
            repo, reg, idents, np.random.default_rng(19), attached
        )
        attached.set()
        print(json.dumps({
            "metric": f"DeviceProfiling overhead at {N_RULES} rules",
            "value": out["profiling_overhead_pct"],
            "unit": "pct",
            **out,
            "backend": backend,
            "host_cpus": os.cpu_count(),
            "build_s": round(t_build, 2),
        }))
        return

    if "--chaos" in sys.argv[1:]:
        # policyd-failsafe round: fixed-seed fault injection through
        # the real pipeline — the round driver gates on verdicts_lost
        # == 0 and a completed ladder round-trip
        out = _bench_chaos(
            repo, reg, idents, np.random.default_rng(21), attached
        )
        attached.set()
        print(json.dumps({
            "metric": f"chaos recovery at {N_RULES} rules",
            "value": out["recovery_s"],
            "unit": "s",
            **out,
            "backend": backend,
            "host_cpus": os.cpu_count(),
            "build_s": round(t_build, 2),
        }))
        return

    if "--overload" in sys.argv[1:]:
        # policyd-overload round: deny-heavy DoS mix — the round driver
        # gates on shed_over_full_ratio >= 3 and shed_sound
        out = _bench_overload(
            repo, reg, idents, np.random.default_rng(21), attached
        )
        attached.set()
        print(json.dumps({
            "metric": f"prefilter shed rate at {N_RULES} rules",
            "value": out["prefilter_shed_vps"],
            "unit": "flows/s",
            **out,
            "backend": backend,
            "host_cpus": os.cpu_count(),
            "build_s": round(t_build, 2),
        }))
        return

    if "--mesh" in sys.argv[1:]:
        # policyd-mesh round: 2D flows×ident placement vs the 1D
        # sharded baseline — the round driver gates on bit-identical
        # parity, a clean off-path spy, and the per-device table-bytes
        # reduction tracking the ident factor
        out = _bench_mesh(
            repo, reg, idents, np.random.default_rng(21), attached
        )
        attached.set()
        print(json.dumps({
            "metric": f"2D mesh verdicts/sec at {N_RULES} rules",
            "value": out["verdicts_2d_vps"],
            "unit": "verdicts/s",
            **out,
            "backend": backend,
            "host_cpus": os.cpu_count(),
            "build_s": round(t_build, 2),
        }))
        return

    if "--updates" in sys.argv[1:]:
        # policyd-delta round: churn latency percentiles at 10k scale
        # (the built world) and, unless BENCH_STRETCH=0, at the 100k
        # stretch scale — the round driver tracks the <10ms
        # update_ident target per round from these
        out10 = _bench_updates(repo, reg, idents)
        out100 = {}
        if os.environ.get("BENCH_STRETCH", "1") != "0":
            srepo, sreg, sidents = _stretch_world(
                int(os.environ.get("BENCH_STRETCH_RULES", 100_000)),
                int(os.environ.get("BENCH_STRETCH_IDS", 100_000)),
            )
            out100 = _bench_updates(srepo, sreg, sidents)
        attached.set()
        print(json.dumps({
            "metric": f"policy update latency at {N_RULES} rules",
            "value": out10["update_ident_p50_ms"],
            "unit": "ms",
            **out10,
            "scale_100k": out100,
            "backend": backend,
            "host_cpus": os.cpu_count(),
            "build_s": round(t_build, 2),
        }))
        return

    if "--tune" in sys.argv[1:]:
        # policyd-autotune round: depth sweep vs controller convergence
        # + bucket-ladder pad waste — the round driver diffs
        # converged_depth/pad_waste_pct across PRs
        out = _bench_tune(
            repo, reg, idents, np.random.default_rng(23), attached
        )
        attached.set()
        print(json.dumps({
            "metric": f"autotune converged pipeline depth at {N_RULES} rules",
            "value": out["converged_depth"],
            "unit": "depth",
            **out,
            "backend": backend,
            "host_cpus": os.cpu_count(),
            "build_s": round(t_build, 2),
        }))
        return

    engine = PolicyEngine(repo, reg)
    t0 = time.time()
    compiled = engine.refresh()
    jax.block_until_ready(engine.device_policy.sel_match)
    t_compile = time.time() - t0
    attached.stage("policy-compile")

    ep_ids = [idents[i].id for i in range(N_ENDPOINTS)]
    t0 = time.time()
    tables, _snaps = materialize_endpoints(
        compiled, engine.device_policy, ep_ids, ingress=True
    )
    jax.block_until_ready(tables.id_bits)
    t_mat = time.time() - t0

    # Flow batch (fixed device arrays; realistic mixed ports).
    nrng = np.random.default_rng(7)
    n_rows = compiled.id_bits.shape[0]
    live_rows = np.array([compiled.id_to_row[i.id] for i in idents], np.int32)
    ep_idx = jnp.asarray(nrng.integers(0, N_ENDPOINTS, BATCH, dtype=np.int32))
    src = jnp.asarray(nrng.choice(live_rows, BATCH).astype(np.int32))
    dport = jnp.asarray(
        nrng.choice(np.array([80, 443, 8080, 53, 22, 0], np.int32), BATCH)
    )
    proto = jnp.asarray(np.where(np.asarray(dport) == 53, 17, 6).astype(np.int32))

    dec, red = lookup_batch(tables, ep_idx, src, dport, proto)
    jax.block_until_ready(dec)
    attached.stage("first-batch")
    attached.set()

    t0 = time.time()
    for _ in range(ITERS):
        dec, red = lookup_batch(tables, ep_idx, src, dport, proto)
    jax.block_until_ready(dec)
    elapsed = time.time() - t0
    verdicts_per_sec = ITERS * BATCH / elapsed

    # ── p99 per-flow latency: the enforcement front-end fast path
    # (datapath/fastpath.py) against the realized policymap snapshots —
    # the role of the ≤3-hash-lookup kernel path (bpf/lib/policy.h:46).
    from cilium_tpu.datapath.fastpath import VerdictFastpath

    fp = VerdictFastpath(_snaps)
    nrng2 = np.random.default_rng(11)
    probe_ep = nrng2.integers(0, N_ENDPOINTS, 50_000)
    probe_id = nrng2.choice([i.id for i in idents], 50_000)
    probe_port = nrng2.choice(np.array([0, 80, 443, 8080], np.int32), 50_000)
    lat_ns = np.empty(50_000)
    for i in range(50_000):
        e, s, p = int(probe_ep[i]), int(probe_id[i]), int(probe_port[i])
        t1 = time.perf_counter_ns()
        fp.lookup(e, s, p, 6)
        lat_ns[i] = time.perf_counter_ns() - t1
    p99_us = float(np.percentile(lat_ns, 99)) / 1000.0

    # ── incremental update cost at N_RULES rules (blocking, i.e. time
    # until the new state is live on device): identity churn and
    # single-rule import (pkg/endpoint/policy.go:506 analog).
    update_ident_ms, update_ident_host_ms = _bench_ident_update(engine, reg)
    update_ident_burst_ms = _bench_ident_burst(engine, reg)
    update_rule_ms = _bench_rule_update(engine, repo, rng)
    update_rule_delete_ms = _bench_rule_delete(engine, repo, rng)
    dispatch_rtt_ms = _bench_dispatch_rtt()

    # ── the other north-star configs (BASELINE.md): LPM at 50k
    # prefixes, L7 DFA request rate, Kafka ACL batch rate, plus the
    # native C++ front-end on the same realized state, and a warm full
    # re-materialization (the rebuild path rule deletion takes).
    extra = os.environ.get("BENCH_EXTRA", "1") != "0"
    lpm50k, lpm50k_clustered = (
        _bench_lpm_50k(np.random.default_rng(3)) if extra else (0.0, 0.0)
    )
    l7_dfa = _bench_l7_dfa() if extra else 0.0
    kafka_acl = _bench_kafka_acl() if extra else 0.0
    native_vps, native_mt = (
        _bench_native(_snaps, idents, np.random.default_rng(5))
        if extra else (0.0, {})
    )
    native_l7_rps = _bench_native_l7() if extra else 0.0
    native_e2e_vps, native_e2e_est_vps = (
        _bench_native_e2e(_snaps, idents, np.random.default_rng(9))
        if extra else (0.0, 0.0)
    )
    pipeline_e2e_vps, pipeline_e2e_v6_vps, pipeline_e2e_fused_pf_vps = (
        _bench_pipeline_e2e(repo, reg, idents, np.random.default_rng(13))
        if extra else (0.0, 0.0, 0.0)
    )
    overlap_ratio, pipeline_submit_vps = (
        _bench_overlap(repo, reg, idents, np.random.default_rng(17))
        if extra else (0.0, 0.0)
    )
    t0 = time.time()
    tables2, _ = materialize_endpoints(
        compiled, engine.device_policy, ep_ids, ingress=True
    )
    jax.block_until_ready(tables2.id_bits)
    rebuild_warm_s = time.time() - t0

    # ── the 100k×100k stretch envelope (BASELINE configs[4])
    stretch = (
        _bench_stretch()
        if os.environ.get("BENCH_STRETCH", "1") != "0" and extra
        else {}
    )

    allow_frac = float(jnp.mean((dec == 1).astype(jnp.float32)))
    result = {
        "metric": f"policymap verdicts/sec at {N_RULES} rules",
        "value": round(verdicts_per_sec),
        "unit": "verdicts/s",
        "vs_baseline": round(verdicts_per_sec / 100e6, 4),
        "p99_us": round(p99_us, 2),
        # PRIMARY identity-churn metric: the engine's own cost (selector
        # match + row repack + dispatch enqueue). The blocking total is
        # environment-laden — under the axon tunnel it is ~dispatch_rtt
        # (see detail), not engine work — so it's reported second.
        "update_ident_ms": round(update_ident_host_ms, 1),
        "update_ident_blocking_ms": round(update_ident_ms, 1),
        "update_ident_burst_ms": round(update_ident_burst_ms, 1),
        "update_rule_ms": round(update_rule_ms, 1),
        "update_rule_delete_ms": round(update_rule_delete_ms, 1),
        "lpm50k_lps": round(lpm50k),
        "lpm50k_clustered_lps": round(lpm50k_clustered),
        "l7_dfa_rps": round(l7_dfa),
        "kafka_acl_rps": round(kafka_acl),
        "native_vps": round(native_vps),
        "native_vps_mt": (
            {k: round(v) for k, v in native_mt.items()}
            if native_mt
            # an empty sweep is a skip, not a failure — say why
            else {"skipped": f"{os.cpu_count()} host cpu(s)"}
        ),
        "native_l7_rps": round(native_l7_rps),
        "native_e2e_vps": round(native_e2e_vps),
        "native_e2e_est_vps": round(native_e2e_est_vps),
        "pipeline_e2e_vps": round(pipeline_e2e_vps),
        "pipeline_e2e_v6_vps": round(pipeline_e2e_v6_vps),
        # pipelined dispatch (submit/result, depth 2): rate + the share
        # of pure device time hidden behind host prep of the successor
        "pipeline_submit_vps": round(pipeline_submit_vps),
        "overlap_ratio": round(overlap_ratio, 3),
        "pipeline_depth": 2,
        # which backend produced these numbers (local-fallback = host
        # CPU after device attach failed; NOT comparable to device runs)
        "backend": backend,
        "host_cpus": os.cpu_count(),
        # deny stage ACTIVE via the fused one-walk table (negative =
        # fusion unexpectedly absent)
        "pipeline_e2e_fused_pf_vps": round(pipeline_e2e_fused_pf_vps),
        "rebuild_warm_s": round(rebuild_warm_s, 2),
        # BENCH001: the stretch sub-metrics the round driver gates on
        # ride at top level with direction suffixes — nested record
        # values fall outside --diff's regression coverage
        "stretch_100k_materialize_s": stretch.get("materialize_s", 0.0),
        "stretch_100k_compile_s": stretch.get("compile_s", 0.0),
        "stretch_100k_vps": stretch.get("verdicts_vps", 0),
        "stretch_100k": stretch,
    }
    envelope = _host_envelope()
    # per-unit-of-host-compute normalizations: compare THESE across
    # rounds for the host-side paths — a machine change moves the raw
    # rate and the calibration together, leaving the ratio stable
    calib = max(1.0, envelope["calib_py_loops_per_s"])
    result["kafka_acl_per_py_loop_ratio"] = round(kafka_acl / calib, 4)
    sha = max(1.0, envelope["calib_sha256_mb_per_s"])
    result["native_vps_per_sha_mb_ratio"] = round(native_vps / sha / 1000, 2)
    print(json.dumps(result))
    print(
        json.dumps(
            {
                "detail": {
                    "device": str(jax.devices()[0]),
                    "build_s": round(t_build, 2),
                    "compile_s": round(t_compile, 2),
                    "materialize_s": round(t_mat, 2),
                    "lookup_elapsed_s": round(elapsed, 3),
                    "allow_fraction": round(allow_frac, 4),
                    "identities": N_IDENTITIES,
                    "endpoints": N_ENDPOINTS,
                    "batch": BATCH,
                    "dispatch_rtt_ms": round(dispatch_rtt_ms, 1),
                    **envelope,
                }
            }
        ),
        file=sys.stderr,
    )
    if diff_prev is not None:
        # --diff without --cur: this fresh sweep IS the current record
        # (the detail envelope rides along for calibration)
        sys.exit(_diff_records(
            _load_artifact(diff_prev), {**result, "detail": envelope},
            _diff_threshold(sys.argv[1:]),
        ))


if __name__ == "__main__":
    main()
