"""cilium_tpu — a TPU-native policy-verdict framework.

A from-scratch re-design of Cilium's capability surface (reference:
cilium v1.2.90) for TPU hardware:

- host control plane owning labels, security identities, rules and IP caches
  (reference: pkg/labels, pkg/identity, pkg/policy, pkg/ipcache);
- a *policy compiler* lowering the rule repository into dense device arrays
  (selector bitmaps, L4 tables, CIDR bit-tries, L7 DFA tables);
- a jit/pjit *verdict engine* evaluating batches of flow tuples on TPU
  (replaces the eBPF per-packet path bpf/lib/policy.h);
- a verdict-cache / enforcement front-end (the pkg/maps/policymap
  equivalent) consumed by datapath front-ends;
- endpoint lifecycle, kvstore-backed distribution, REST-ish API, CLI and
  observability around it.

Nothing in here is a port: the architecture is JAX/XLA-first (static
shapes, functional transforms, sharding via jax.sharding.Mesh).
"""

__version__ = "0.1.0"

# Honor an EXPLICIT JAX_PLATFORMS env choice over any site-level
# override (the axon sitecustomize force-sets jax_platforms="axon,cpu"
# at interpreter startup, which routes subprocesses — e.g. the daemon
# children of the three-process cluster tests — onto the TPU tunnel
# even when the parent asked for CPU). Only acts when the variable is
# set, so bench/production runs keep the real device.
import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    except Exception:  # pragma: no cover - jax absent or too old
        pass
del _os
