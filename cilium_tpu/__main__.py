import sys

from .cli import run

sys.exit(run())
