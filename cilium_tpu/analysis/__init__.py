"""policyd-lint: AST-based hot-path & lock-discipline analyzer.

Pure-stdlib static analysis for the two bug classes that kill the
paper's target (≥100M verdicts/s, p99 <50µs) silently:

- Family A (TPU hot path): implicit host↔device syncs, jnp-in-loop
  tracing, jit closures over mutable globals, dtype drift — see
  ``hotpath``.
- Family B (lock discipline): lock-order cycles, blocking ops and
  callbacks under locks, guard inconsistency — see ``locks``.
- Family C (stable-API contracts): option discipline, stable-literal
  drift, bench metric-key direction — see ``contracts``. Family C and
  the one-edge-deep inter-procedural variants of TPU001/LOCK002 run on
  a package-wide call graph (``callgraph``).

Run ``python -m cilium_tpu.analysis`` (CI gate: exits non-zero on any
finding not covered by the checked-in ``baseline.json``). See
``README.md`` in this directory for rule ids, the hot-module
convention, suppression syntax, and baseline maintenance.
"""

from __future__ import annotations

import os
from typing import Iterable, List, Optional, Sequence, Set

from .callgraph import CallGraph, build_callgraph
from .contracts import analyze_contracts
from .core import Finding, ModuleSource
from .hotpath import analyze_hotpath
from .locks import LockIndex, analyze_locks_module, cycle_findings
from .obsdocs import analyze_obsdocs
from .obsjournal import analyze_obsjournal
from .obslabels import analyze_obslabels

__all__ = [
    "CallGraph",
    "Finding",
    "ModuleSource",
    "analyze_paths",
    "build_callgraph",
    "collect_files",
    "default_target",
]


def default_target() -> str:
    """The cilium_tpu package directory (the default analysis root)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(os.path.abspath(p))
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    out.append(os.path.abspath(os.path.join(root, name)))
    return sorted(set(out))


def analyze_paths(
    paths: Sequence[str],
    rules: Optional[Iterable[str]] = None,
    restrict: Optional[Iterable[str]] = None,
    changed: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run all three rule families over every .py under ``paths``.

    Suppressions (line/file) are already applied; the baseline is NOT —
    callers diff against it via ``baseline.new_findings``.

    ``restrict`` (relpaths) keeps only findings anchored in those
    files — the whole set is still parsed and graphed (cross-module
    rules need full context), only the reporting is narrowed.
    ``changed`` (relpaths) is the incremental mode: the restriction
    set becomes the changed files plus their direct call-graph
    dependents (modules importing them), so a changed helper still
    surfaces the caller-side inter-procedural findings it causes.
    """
    files = collect_files(paths)
    modules: List[ModuleSource] = []
    findings: List[Finding] = []
    for path in files:
        try:
            modules.append(ModuleSource(path))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(
                Finding(
                    rule="PARSE",
                    severity="error",
                    path=os.path.basename(path),
                    line=getattr(e, "lineno", 0) or 0,
                    message=f"cannot parse: {type(e).__name__}: {e}",
                )
            )

    # pass 1: package-wide lock index (cross-method edges need it)
    index = LockIndex()
    for mod in modules:
        index.add_module(mod)
    index.finalize()

    # pass 2: package-wide call graph (inter-procedural TPU001/LOCK002
    # and Family C consume it)
    graph = build_callgraph(modules, lock_index=index)

    all_edges = []
    for mod in modules:
        findings.extend(analyze_hotpath(mod, graph=graph))
        findings.extend(analyze_obsdocs(mod))
        lock_findings, edges = analyze_locks_module(mod, index, graph=graph)
        findings.extend(lock_findings)
        all_edges.extend(edges)
    findings.extend(cycle_findings(all_edges))
    findings.extend(analyze_contracts(modules, graph))
    findings.extend(analyze_obslabels(modules))
    findings.extend(analyze_obsjournal(modules))

    if changed is not None:
        closure = graph.dependents_of(list(changed))
        restrict = closure if restrict is None else set(restrict) | closure
    if restrict is not None:
        keep_paths = set(restrict)
        findings = [f for f in findings if f.path in keep_paths]

    # apply suppressions (cycle findings self-filter on edge sites,
    # but their anchor line suppression is honored here too)
    by_path = {m.relpath: m for m in modules}
    kept: List[Finding] = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.is_suppressed(f.rule, f.line):
            continue
        kept.append(f)

    wanted: Optional[Set[str]] = (
        {r.strip().upper() for r in rules} if rules else None
    )
    if wanted:
        kept = [f for f in kept if f.rule in wanted]
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept
