"""policyd-lint runner CLI.

Usage::

    python -m cilium_tpu.analysis [paths...] [--format text|json]
        [--baseline PATH | --no-baseline] [--write-baseline]
        [--rules TPU001,LOCK002] [--all]

Exit codes: 0 = clean against baseline; 1 = new findings; 2 = usage /
internal error. With no paths, analyzes the cilium_tpu package.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from . import analyze_paths, default_target
from .baseline import (
    default_baseline_path,
    load_baseline,
    new_findings,
    write_baseline,
)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cilium_tpu.analysis",
        description="policyd-lint: hot-path & lock-discipline analyzer",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: the checked-in analysis/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="every finding is 'new' (full inventory mode)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="regenerate the baseline from this run's findings and exit 0",
    )
    ap.add_argument(
        "--rules", default=None, help="comma-separated rule id filter"
    )
    ap.add_argument(
        "--all",
        action="store_true",
        help="print all findings, not just new ones",
    )
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    paths = args.paths or [default_target()]
    rules = args.rules.split(",") if args.rules else None
    try:
        findings = analyze_paths(paths, rules=rules)
    except Exception as e:  # pragma: no cover - internal error surface
        print(f"policyd-lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        _, notes = (
            load_baseline(baseline_path)
            if not args.no_baseline
            else ({}, {})
        )
        write_baseline(findings, baseline_path, justifications=notes)
        print(
            f"policyd-lint: wrote {len(findings)} finding(s) to "
            f"{baseline_path}",
            file=sys.stderr,
        )
        return 0

    if args.no_baseline:
        fresh = list(findings)
        baseline_used = None
    else:
        counts, _notes = load_baseline(baseline_path)
        fresh = new_findings(findings, counts)
        baseline_used = baseline_path

    if args.format == "json":
        payload = {
            "tool": "policyd-lint",
            "total": len(findings),
            "new": len(fresh),
            "baseline": baseline_used,
            "new_findings": [f.to_dict() for f in fresh],
        }
        if args.all:
            payload["findings"] = [f.to_dict() for f in findings]
        print(json.dumps(payload))
    else:
        shown = findings if args.all else fresh
        for f in shown:
            print(f.render())
        print(
            f"policyd-lint: {len(findings)} finding(s), "
            f"{len(fresh)} new"
            + (f" (baseline: {baseline_used})" if baseline_used else ""),
            file=sys.stderr,
        )
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
