"""policyd-lint runner CLI.

Usage::

    python -m cilium_tpu.analysis [paths...] [--format text|json|github]
        [--baseline PATH | --no-baseline] [--write-baseline]
        [--rules TPU001,LOCK002] [--all] [--changed [REF]]

Exit codes: 0 = clean against baseline; 1 = new findings; 2 = usage /
internal error. With no paths, analyzes the cilium_tpu package plus
the sibling ``bench.py`` (the BENCH001 surface).

``--changed [REF]`` is the incremental mode: the full set is still
parsed and call-graphed (cross-module rules need whole-package
context), but reporting narrows to files changed vs REF (default
HEAD, per ``git diff`` + untracked) plus their direct call-graph
dependents. ``--format github`` emits ::error/::warning workflow
annotations for the new findings.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from . import analyze_paths, default_target
from .baseline import (
    default_baseline_path,
    load_baseline,
    new_findings,
    write_baseline,
)


def _repo_root() -> str:
    """Directory containing the package (where relpaths anchor)."""
    return os.path.dirname(default_target())


def _default_paths() -> List[str]:
    paths = [default_target()]
    bench = os.path.join(_repo_root(), "bench.py")
    if os.path.isfile(bench):
        paths.append(bench)
    return paths


def _changed_relpaths(ref: str) -> List[str]:
    """Repo-relative .py paths changed vs ``ref`` (plus untracked)."""
    root = _repo_root()
    out: List[str] = []
    for cmd in (
        ["git", "diff", "--name-only", ref, "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            cmd, cwd=root, capture_output=True, text=True, check=False
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)} failed: {proc.stderr.strip()}"
            )
        out.extend(
            line.strip()
            for line in proc.stdout.splitlines()
            if line.strip().endswith(".py")
        )
    return sorted(set(out))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cilium_tpu.analysis",
        description="policyd-lint: hot-path & lock-discipline analyzer",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs (default: package)")
    ap.add_argument(
        "--format", choices=("text", "json", "github"), default="text"
    )
    ap.add_argument(
        "--changed",
        nargs="?",
        const="HEAD",
        default=None,
        metavar="REF",
        help="restrict reporting to files changed vs REF (default HEAD) "
        "plus their direct call-graph dependents",
    )
    ap.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: the checked-in analysis/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="every finding is 'new' (full inventory mode)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="regenerate the baseline from this run's findings and exit 0",
    )
    ap.add_argument(
        "--rules", default=None, help="comma-separated rule id filter"
    )
    ap.add_argument(
        "--all",
        action="store_true",
        help="print all findings, not just new ones",
    )
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    paths = args.paths or _default_paths()
    rules = args.rules.split(",") if args.rules else None
    changed: Optional[List[str]] = None
    if args.changed is not None:
        try:
            changed = _changed_relpaths(args.changed)
        except (RuntimeError, OSError) as e:
            print(f"policyd-lint: --changed: {e}", file=sys.stderr)
            return 2
        if not changed:
            print(
                f"policyd-lint: no .py changes vs {args.changed}",
                file=sys.stderr,
            )
            return 0
    try:
        findings = analyze_paths(paths, rules=rules, changed=changed)
    except Exception as e:  # pragma: no cover - internal error surface
        print(f"policyd-lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        _, notes = (
            load_baseline(baseline_path)
            if not args.no_baseline
            else ({}, {})
        )
        write_baseline(findings, baseline_path, justifications=notes)
        print(
            f"policyd-lint: wrote {len(findings)} finding(s) to "
            f"{baseline_path}",
            file=sys.stderr,
        )
        return 0

    if args.no_baseline:
        fresh = list(findings)
        baseline_used = None
    else:
        counts, _notes = load_baseline(baseline_path)
        fresh = new_findings(findings, counts)
        baseline_used = baseline_path

    if args.format == "json":
        payload = {
            "tool": "policyd-lint",
            "total": len(findings),
            "new": len(fresh),
            "baseline": baseline_used,
            "new_findings": [f.to_dict() for f in fresh],
        }
        if args.all:
            payload["findings"] = [f.to_dict() for f in findings]
        print(json.dumps(payload))
    elif args.format == "github":
        shown = findings if args.all else fresh
        for f in shown:
            level = "error" if f.severity == "error" else "warning"
            # workflow-command message body must stay single-line
            msg = f.message.replace("\n", " ")
            print(
                f"::{level} file={f.path},line={f.line}::"
                f"{f.rule} {msg}"
            )
        print(
            f"policyd-lint: {len(findings)} finding(s), {len(fresh)} new",
            file=sys.stderr,
        )
    else:
        shown = findings if args.all else fresh
        for f in shown:
            print(f.render())
        print(
            f"policyd-lint: {len(findings)} finding(s), "
            f"{len(fresh)} new"
            + (f" (baseline: {baseline_used})" if baseline_used else ""),
            file=sys.stderr,
        )
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
