"""Baseline persistence for policyd-lint.

The baseline is a checked-in inventory of accepted findings. CI fails
only on findings NOT covered by it, so the gate stops regressions the
day it lands without demanding every pre-existing finding be fixed
first.

Matching is by (rule, path, context) — context is the stripped source
text of the flagged line — with a per-key count, so:

- edits elsewhere in a file (line drift) don't break the baseline;
- editing the flagged line itself invalidates its baseline entry (the
  new text is a new finding — re-justify or fix);
- adding a second identical violation on an identical line is caught
  by the count.

Entries may carry a ``justification`` string; ``--write-baseline``
preserves justifications for keys that survive regeneration.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from .core import Finding

Key = Tuple[str, str, str]

BASELINE_VERSION = 1


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str) -> Tuple[Dict[Key, int], Dict[Key, str]]:
    """→ (counts per key, justifications per key). Missing file → empty
    baseline (everything is "new")."""
    counts: Dict[Key, int] = {}
    notes: Dict[Key, str] = {}
    if not os.path.exists(path):
        return counts, notes
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} "
            f"in {path}"
        )
    for entry in data.get("findings", []):
        key = (entry["rule"], entry["path"], entry.get("context", ""))
        counts[key] = counts.get(key, 0) + int(entry.get("count", 1))
        if entry.get("justification"):
            notes[key] = entry["justification"]
    return counts, notes


def new_findings(
    findings: Iterable[Finding], baseline: Dict[Key, int]
) -> List[Finding]:
    """Findings not covered by the baseline (count-aware)."""
    budget = dict(baseline)
    out: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out


def write_baseline(
    findings: Iterable[Finding],
    path: str,
    justifications: Optional[Dict[Key, str]] = None,
) -> None:
    """Serialize ``findings`` as the new baseline, carrying over any
    surviving justifications."""
    justifications = justifications or {}
    counts: Dict[Key, int] = {}
    lines: Dict[Key, int] = {}
    sev: Dict[Key, str] = {}
    for f in findings:
        k = f.key()
        counts[k] = counts.get(k, 0) + 1
        lines.setdefault(k, f.line)
        sev.setdefault(k, f.severity)
    entries = []
    for k in sorted(counts):
        rule, relpath, context = k
        entry = {
            "rule": rule,
            "path": relpath,
            "context": context,
            "severity": sev[k],
            # advisory only (drifts with edits); matching ignores it
            "line_hint": lines[k],
        }
        if counts[k] > 1:
            entry["count"] = counts[k]
        if k in justifications:
            entry["justification"] = justifications[k]
        entries.append(entry)
    payload = {
        "version": BASELINE_VERSION,
        "tool": "policyd-lint",
        "findings": entries,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
