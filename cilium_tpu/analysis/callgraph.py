"""Cross-module symbol table + call graph for policyd-lint.

The per-function analyzers (``hotpath``, ``locks``) see one body at a
time, so a helper in ``ops/`` doing the ``.item()`` for a caller in
``datapath/pipeline.py`` is invisible to both. This module builds the
package-wide view the inter-procedural rules consume:

- a symbol table of every module-level function, class, and method in
  the analyzed set, keyed ``"pkg.mod:func"`` / ``"pkg.mod:Class.meth"``;
- import resolution (absolute, relative, aliased, ``from X import Y``)
  against the analyzed set only — nothing outside the set (jax, numpy,
  stdlib) ever resolves, by design;
- method binding for ``self.m()``, for locals typed by construction
  (``e = Engine(...)``; ``e.run()``), and for module-level singletons
  (``hub = FaultHub()`` in one module, ``faults.hub.enable()`` in
  another);
- per-function effect summaries: which parameters the body host-pulls
  (``int(x)`` / ``x.item()`` / ``np.asarray(x)`` — feeds TPU001 one
  edge deep) and which blocking operations it performs (``open`` /
  subprocess / socket / sleep / ``block_until_ready`` — feeds LOCK002
  one edge deep);
- held-context lifted from ``locks.LockIndex``: a callee whose every
  entry already assumes a lock held (``*_locked`` naming or the
  all-call-sites fixpoint) reports its blocking sites directly, so the
  caller-side propagation skips it rather than double-reporting.

Resolution is deliberately conservative: a call resolves only through
an explicit chain of evidence (import alias, constructor-typed name,
``self``). There is no resolve-by-method-name fallback, so the graph
adds edges, never guesses them.

Everything here is pure stdlib; the graph is built once per
``analyze_paths`` run and shared by every rule (and by the CLI's
``--changed`` dependent closure).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import ModuleSource, attr_chain, call_name, walk_skipping
from .locks import LockIndex, blocking_kind

# host-pull shapes a summary records on a parameter (mirrors the
# hotpath TPU001 vocabulary — kept small so a summary hit is always a
# guaranteed sync, never a maybe)
_COERCIONS = {"int", "float", "bool"}
_NP_SYNC_FUNCS = {"asarray", "array", "copy"}
_SYNC_METHODS = {"item", "tolist", "__array__"}
_NP_MODULES = {"numpy"}


def module_name_of(mod: ModuleSource) -> str:
    """Dotted module name derived from the package-relative path
    (``cilium_tpu/ops/verdict.py`` → ``cilium_tpu.ops.verdict``)."""
    rel = mod.relpath
    if rel.endswith("/__init__.py"):
        rel = rel[: -len("/__init__.py")]
    elif rel.endswith(".py"):
        rel = rel[:-3]
    return rel.replace("/", ".")


class FuncInfo:
    """One function/method in the symbol table, with its effect
    summaries."""

    __slots__ = (
        "qual", "mod", "node", "cls_name", "params",
        "pull_params", "blocking", "held_on_entry", "calls",
    )

    def __init__(
        self,
        qual: str,
        mod: ModuleSource,
        node: ast.AST,
        cls_name: Optional[str],
    ) -> None:
        self.qual = qual
        self.mod = mod
        self.node = node
        self.cls_name = cls_name
        args = node.args
        names = [
            a.arg
            for a in list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
        ]
        if cls_name is not None and names and names[0] in ("self", "cls"):
            names = names[1:]
        self.params: List[str] = names
        # param name -> (line, pull shape) for host pulls ON the param
        self.pull_params: Dict[str, Tuple[int, str]] = {}
        # (line, kind, call name) for blocking ops in the body
        self.blocking: List[Tuple[int, str, str]] = []
        # locks assumed held on entry (lifted from LockIndex.finalize)
        self.held_on_entry: Tuple[str, ...] = ()
        # resolved callee quals (call-graph edges out of this body)
        self.calls: List[str] = []

    @property
    def display(self) -> str:
        leaf = self.qual.split(":", 1)[1]
        return leaf

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<FuncInfo {self.qual}>"


class _ModuleSymbols:
    """Per-module import aliases + top-level defs."""

    def __init__(self, mod: ModuleSource, name: str) -> None:
        self.mod = mod
        self.name = name
        # local alias -> dotted module name (may be outside the set)
        self.mod_aliases: Dict[str, str] = {}
        # local alias -> (dotted module, symbol name)
        self.sym_aliases: Dict[str, Tuple[str, str]] = {}
        # module-level names -> class qual ("mod:Class") by construction
        self.var_types: Dict[str, str] = {}
        self.np_aliases: Set[str] = set()

    def package(self) -> str:
        if self.mod.relpath.endswith("/__init__.py"):
            return self.name
        return self.name.rpartition(".")[0]


class CallGraph:
    """Package-wide symbol table, resolved call edges, and summaries."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleSource] = {}  # dotted name -> mod
        self.symbols: Dict[str, _ModuleSymbols] = {}
        self.functions: Dict[str, FuncInfo] = {}  # qual -> info
        # class qual "mod:Class" -> {method name -> func qual}
        self.classes: Dict[str, Dict[str, str]] = {}
        # id(ast.Call) -> resolved callee (the analyzers' entry point)
        self.resolved: Dict[int, FuncInfo] = {}
        # dotted module -> analyzed modules it imports from
        self.module_deps: Dict[str, Set[str]] = {}

    # -- queries ----------------------------------------------------------
    def resolved_callee(self, call: ast.Call) -> Optional[FuncInfo]:
        return self.resolved.get(id(call))

    def dependents_of(self, relpaths: Iterable[str]) -> Set[str]:
        """Relpaths of modules that directly import any of ``relpaths``
        (the --changed closure: changed files + one reverse edge)."""
        by_rel = {m.relpath: name for name, m in self.modules.items()}
        changed = {by_rel[r] for r in relpaths if r in by_rel}
        out = set(relpaths)
        for name, deps in self.module_deps.items():
            if deps & changed:
                out.add(self.modules[name].relpath)
        return out

    # -- construction -----------------------------------------------------
    def build(
        self,
        modules: Sequence[ModuleSource],
        lock_index: Optional[LockIndex] = None,
    ) -> "CallGraph":
        for mod in modules:
            name = module_name_of(mod)
            self.modules[name] = mod
            self.symbols[name] = _ModuleSymbols(mod, name)
        for name in self.modules:
            self._collect_defs(name)
        for name in self.modules:
            self._collect_imports(name)
        # module-level singletons need aliases, so a third pass
        for name in self.modules:
            self._collect_module_vars(name)
        for name in self.modules:
            self._resolve_module(name)
        self._summarize()
        if lock_index is not None:
            self._lift_held_context(lock_index)
        return self

    def _collect_defs(self, name: str) -> None:
        mod = self.modules[name]
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{name}:{node.name}"
                self.functions[qual] = FuncInfo(qual, mod, node, None)
            elif isinstance(node, ast.ClassDef):
                cqual = f"{name}:{node.name}"
                methods: Dict[str, str] = {}
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        mq = f"{name}:{node.name}.{item.name}"
                        self.functions[mq] = FuncInfo(
                            mq, mod, item, node.name
                        )
                        methods[item.name] = mq
                self.classes[cqual] = methods

    def _collect_imports(self, name: str) -> None:
        sym = self.symbols[name]
        deps = self.module_deps.setdefault(name, set())
        for node in ast.walk(sym.mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in _NP_MODULES:
                        sym.np_aliases.add(a.asname or a.name)
                        continue
                    if a.asname:
                        sym.mod_aliases[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        sym.mod_aliases.setdefault(root, root)
                    self._note_dep(deps, a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = sym.package()
                    for _ in range(node.level - 1):
                        base = base.rpartition(".")[0]
                    prefix = (
                        f"{base}.{node.module}" if node.module else base
                    )
                else:
                    prefix = node.module or ""
                if prefix in _NP_MODULES:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    full = f"{prefix}.{a.name}" if prefix else a.name
                    if full in self.modules:
                        sym.mod_aliases[local] = full
                        deps.add(full)
                    else:
                        sym.sym_aliases[local] = (prefix, a.name)
                        self._note_dep(deps, prefix)

    def _note_dep(self, deps: Set[str], target: str) -> None:
        # an import of pkg.sub counts as depending on every analyzed
        # prefix (pkg/__init__.py re-exports make the prefix real)
        parts = target.split(".")
        for i in range(1, len(parts) + 1):
            cand = ".".join(parts[:i])
            if cand in self.modules:
                deps.add(cand)

    def _collect_module_vars(self, name: str) -> None:
        sym = self.symbols[name]
        for node in sym.mod.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                hit = self._lookup_chain(
                    name, attr_chain(node.value.func), None, None
                )
                if hit and hit[0] == "class":
                    sym.var_types[node.targets[0].id] = hit[1]

    # -- resolution -------------------------------------------------------
    def _lookup(
        self, modname: str, parts: Sequence[str]
    ) -> Optional[Tuple[str, str]]:
        """Resolve ``parts`` inside analyzed module ``modname``:
        ("func", qual) / ("class", qual) / None. Walks into submodules
        while the prefix names one."""
        parts = list(parts)
        while parts and f"{modname}.{parts[0]}" in self.modules:
            modname = f"{modname}.{parts[0]}"
            parts.pop(0)
        if modname not in self.modules:
            return None
        if len(parts) == 1:
            leaf = parts[0]
            if f"{modname}:{leaf}" in self.functions:
                return ("func", f"{modname}:{leaf}")
            if f"{modname}:{leaf}" in self.classes:
                return ("class", f"{modname}:{leaf}")
            sym = self.symbols[modname]
            if leaf in sym.var_types:
                return ("class", sym.var_types[leaf])
            # re-exported symbol: follow one alias hop
            if leaf in sym.sym_aliases:
                tmod, tname = sym.sym_aliases[leaf]
                if tmod in self.modules:
                    return self._lookup(tmod, [tname])
            if leaf in sym.mod_aliases and sym.mod_aliases[leaf] in self.modules:
                return ("module", sym.mod_aliases[leaf])
            return None
        if len(parts) == 2:
            first, second = parts
            # Class.method
            meth = self.classes.get(f"{modname}:{first}", {}).get(second)
            if meth:
                return ("func", meth)
            # module-level instance: singleton.method()
            sym = self.symbols[modname]
            inst_cls = sym.var_types.get(first)
            if inst_cls:
                meth = self.classes.get(inst_cls, {}).get(second)
                if meth:
                    return ("func", meth)
        return None

    def _lookup_chain(
        self,
        modname: str,
        chain: Optional[List[str]],
        cls_name: Optional[str],
        local_types: Optional[Dict[str, str]],
    ) -> Optional[Tuple[str, str]]:
        if not chain:
            return None
        sym = self.symbols[modname]
        root = chain[0]
        if root == "self" and cls_name is not None and len(chain) == 2:
            meth = self.classes.get(f"{modname}:{cls_name}", {}).get(
                chain[1]
            )
            return ("func", meth) if meth else None
        if local_types and root in local_types and len(chain) == 2:
            meth = self.classes.get(local_types[root], {}).get(chain[1])
            return ("func", meth) if meth else None
        if root in sym.var_types and len(chain) == 2:
            meth = self.classes.get(sym.var_types[root], {}).get(chain[1])
            return ("func", meth) if meth else None
        if root in sym.sym_aliases:
            tmod, tname = sym.sym_aliases[root]
            if tmod in self.modules:
                return self._lookup(tmod, [tname] + chain[1:])
            return None
        if root in sym.mod_aliases:
            target = sym.mod_aliases[root]
            rest = chain[1:]
            if target in self.modules:
                # _lookup walks into submodules, so ``import pkg`` +
                # ``pkg.sub.f()`` resolves when pkg/__init__ is analyzed
                return (
                    self._lookup(target, rest) if rest
                    else ("module", target)
                )
            return None
        # same-module bare name
        if len(chain) <= 2:
            return self._lookup(modname, chain)
        return None

    def _resolve_module(self, name: str) -> None:
        mod = self.modules[name]
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._resolve_function(name, node, None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._resolve_function(name, item, node.name)

    def _resolve_function(
        self, modname: str, func: ast.AST, cls_name: Optional[str]
    ) -> None:
        qual = (
            f"{modname}:{cls_name}.{func.name}" if cls_name
            else f"{modname}:{func.name}"
        )
        info = self.functions.get(qual)
        local_types: Dict[str, str] = {}
        # statement-ordered walk so ``e = Engine(); e.run()`` types e
        # before the method call resolves
        for node in ast.walk(func):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                hit = self._lookup_chain(
                    modname, attr_chain(node.value.func), cls_name,
                    local_types,
                )
                if hit and hit[0] == "class":
                    local_types[node.targets[0].id] = hit[1]
        for node in walk_skipping(
            func, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            if node is func or not isinstance(node, ast.Call):
                continue
            hit = self._lookup_chain(
                modname, attr_chain(node.func), cls_name, local_types
            )
            if hit is None:
                continue
            kind, target = hit
            if kind == "class":
                # constructor call: bind to __init__ when it exists
                target = self.classes.get(target, {}).get("__init__")
                if target is None:
                    continue
                kind = "func"
            if kind != "func":
                continue
            callee = self.functions.get(target)
            if callee is None or callee.node is func:
                continue
            self.resolved[id(node)] = callee
            if info is not None:
                info.calls.append(target)

    # -- summaries --------------------------------------------------------
    def _summarize(self) -> None:
        for info in self.functions.values():
            sym = self.symbols[module_name_of(info.mod)]
            params = set(info.params)
            for node in walk_skipping(
                info.node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
            ):
                if node is not info.node and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                kind = blocking_kind(node)
                if kind is not None:
                    self._record_blocking(info, node, kind)
                self._record_pull(info, sym, params, node)

    @staticmethod
    def _param_of(expr: ast.AST, params: Set[str]) -> Optional[str]:
        while isinstance(expr, ast.Subscript):
            expr = expr.value
        if isinstance(expr, ast.Name) and expr.id in params:
            return expr.id
        return None

    def _record_pull(
        self,
        info: FuncInfo,
        sym: _ModuleSymbols,
        params: Set[str],
        node: ast.Call,
    ) -> None:
        fchain = attr_chain(node.func)
        # param.item() / param.tolist() / param.block_until_ready()
        if isinstance(node.func, ast.Attribute):
            p = self._param_of(node.func.value, params)
            if p is not None and (
                node.func.attr in _SYNC_METHODS
                or node.func.attr == "block_until_ready"
            ):
                info.pull_params.setdefault(
                    p, (node.lineno, f".{node.func.attr}()")
                )
                return
        if not fchain or not node.args:
            return
        p = self._param_of(node.args[0], params)
        if p is None:
            return
        if len(fchain) == 1 and fchain[0] in _COERCIONS:
            info.pull_params.setdefault(p, (node.lineno, f"{fchain[0]}()"))
        elif (
            len(fchain) == 2
            and fchain[0] in sym.np_aliases
            and fchain[1] in _NP_SYNC_FUNCS
        ):
            info.pull_params.setdefault(
                p, (node.lineno, f"{'.'.join(fchain)}()")
            )
        elif fchain[-1] == "block_until_ready":
            info.pull_params.setdefault(
                p, (node.lineno, "block_until_ready()")
            )

    def _record_blocking(
        self, info: FuncInfo, node: ast.Call, kind_name: Tuple[str, str]
    ) -> None:
        kind, cn = kind_name
        info.blocking.append((node.lineno, kind, cn))

    def _lift_held_context(self, index: LockIndex) -> None:
        for ci in index.classes:
            modname = module_name_of(ci.mod)
            for mname, held in ci.assumed_held.items():
                info = self.functions.get(
                    f"{modname}:{ci.name}.{mname}"
                )
                if info is not None:
                    info.held_on_entry = held


def build_callgraph(
    modules: Sequence[ModuleSource],
    lock_index: Optional[LockIndex] = None,
) -> CallGraph:
    return CallGraph().build(modules, lock_index)
