"""Family C: stable-API contract rules (policyd-contracts).

These rules machine-check the ROADMAP's standing contracts against the
canonical tables in ``cilium_tpu/contracts.py``. Unlike Families A/B
(per-module pattern rules), every rule here is cross-file by nature:
an option registered in ``option.py`` is judged by what ``daemon.py``
and ``tests/`` do with it, and a bench metric key is judged by what
``bench.py --diff``'s direction engine would do to it three PRs later.

Rules
-----
OPT001  option discipline (the L7DeviceBatch-class bug): every option
        in ``OPTION_SPECS`` must have an ``OPTION_BOOT_FIELDS`` entry
        (a DaemonConfig boot field, or an annotated None exemption);
        a declared boot field must exist on DaemonConfig and be
        consulted by the daemon; a runtime-mutable option must have a
        consumption site (an ``_on_option_change`` branch or a literal
        ``options.get``/``_opt`` read) — otherwise toggling it changes
        nothing; a non-mutable option must at least be seeded or read;
        a datapath-gated option (non-None boot field) must be named by
        at least one tripwire test under ``tests/``; and hot modules
        must never read options through ``options.get(...)`` per batch
        (the hub pushes option values into one pipeline attribute —
        that attribute is the only hot-path gate). Error.
OPT002  option-gated mutation: state mutated ONLY inside an
        ``if self.<gate>:`` ON branch but read by a method that never
        consults the gate — the OFF path observes ON-path state, the
        exact shape that breaks the OFF-path bit-identical contract
        (jit cache keys, parity tests). Hot modules only. Warning.
API001  stable-literal drift: int-valued ``REASON_*``/``ATTR_*``
        constants, ``.phase("...")`` literals, and ``BUCKET_LADDER``
        definitions anywhere in the package must match the canonical
        tables — these names and numbers are diffed across bench
        rounds and stored in flow logs, so drift is an incompatible
        wire/schema change. Error.
BENCH001  bench metric-key direction: a computed (``round(...)``)
        top-level metric key in ``bench.py`` must carry a suffix the
        ``--diff`` direction engine understands (higher-is-better
        ``_vps/_rps/_lps/_qps/_ratio`` vs lower-is-better
        ``_ms/_us/_ns/_s/_pct``) or be a declared bookkeeping key;
        rate-shaped names ending ``_per_s``/``_ops_s`` are flagged as
        errors — their ``_s`` suffix reads as a *duration*, so a
        throughput gain would be reported as a regression.

Canonical tables resolve from the analyzed set first (a module
literally defining ``WIRE_REASONS``/``OPTION_BOOT_FIELDS``/... wins,
which keeps fixture packages self-contained) and fall back to
importing ``cilium_tpu.contracts``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
    ModuleSource,
    attr_chain,
    walk_skipping,
)

_REASON_RE = re.compile(r"^REASON_[A-Z0-9_]+$")
_ATTR_RE = re.compile(r"^ATTR_[A-Z0-9_]+$")
_RATE_AS_DURATION_RE = re.compile(r"(_per_s|_ops_s)$")

_CANON_NAMES = (
    "TRACE_PHASES",
    "WIRE_REASONS",
    "ATTR_CODES",
    "BUCKET_LADDER",
    "DIFF_HIGHER_SUFFIXES",
    "DIFF_LOWER_SUFFIXES",
    "BENCH_BOOKKEEPING_KEYS",
    "OPTION_BOOT_FIELDS",
    "METRIC_BOUNDED_LABEL_KEYS",
    "JOURNAL_KINDS",
)


def _const_assign(node: ast.stmt) -> Optional[Tuple[str, ast.AST]]:
    """(name, value expr) for ``NAME = ...`` / ``NAME: T = ...``."""
    if (
        isinstance(node, ast.Assign)
        and len(node.targets) == 1
        and isinstance(node.targets[0], ast.Name)
    ):
        return (node.targets[0].id, node.value)
    if (
        isinstance(node, ast.AnnAssign)
        and isinstance(node.target, ast.Name)
        and node.value is not None
    ):
        return (node.target.id, node.value)
    return None


class _Canon:
    """Canonical tables: extracted from the analyzed set when a module
    defines them as literals, imported from cilium_tpu.contracts
    otherwise."""

    def __init__(self, modules: Sequence[ModuleSource]) -> None:
        self.tables: Dict[str, object] = {}
        # name -> (module, line) of the extracted definition
        self.sources: Dict[str, Tuple[ModuleSource, int]] = {}
        for mod in modules:
            # only a module NAMED contracts.py may define canon —
            # anything else redefining these names is drift for API001
            # to flag, not a new source of truth
            if os.path.basename(mod.path) != "contracts.py":
                continue
            for node in mod.tree.body:
                hit = _const_assign(node)
                if hit is None or hit[0] not in _CANON_NAMES:
                    continue
                name, value = hit
                if name in self.tables:
                    continue
                try:
                    self.tables[name] = ast.literal_eval(value)
                except (ValueError, TypeError, SyntaxError, MemoryError):
                    continue
                self.sources[name] = (mod, node.lineno)

    def get(self, name: str):
        if name in self.tables:
            return self.tables[name]
        try:
            from .. import contracts as _c
        except ImportError:  # analysis used outside the package tree
            return None
        return getattr(_c, name, None)


# ---------------------------------------------------------------- API001


def _check_api001(
    modules: Sequence[ModuleSource],
    canon: _Canon,
    findings: List[Finding],
) -> None:
    reasons = dict(canon.get("WIRE_REASONS") or {})
    attr_codes = dict(canon.get("ATTR_CODES") or {})
    phases = set(canon.get("TRACE_PHASES") or ())
    ladder = tuple(canon.get("BUCKET_LADDER") or ())
    for mod in modules:
        for node in ast.walk(mod.tree):
            hit = _const_assign(node) if isinstance(node, ast.stmt) else None
            if hit is not None:
                name, value = hit
                if isinstance(value, ast.Constant) and isinstance(
                    value.value, int
                ) and not isinstance(value.value, bool):
                    for regex, table, what in (
                        (_REASON_RE, reasons, "drop-reason"),
                        (_ATTR_RE, attr_codes, "attribution"),
                    ):
                        if not regex.match(name) or not table:
                            continue
                        if name not in table:
                            findings.append(mod.finding(
                                "API001", SEV_ERROR, node.lineno,
                                f"unknown {what} constant {name} = "
                                f"{value.value} — not in the canonical "
                                "taxonomy (cilium_tpu/contracts.py); "
                                "extend the table first, codes there "
                                "are the single source of truth",
                            ))
                        elif table[name] != value.value:
                            findings.append(mod.finding(
                                "API001", SEV_ERROR, node.lineno,
                                f"{what} constant {name} = {value.value} "
                                f"drifts from the canonical value "
                                f"{table[name]} — these codes are "
                                "STABLE wire/API numbers (stored flow "
                                "logs and bench --diff key on them)",
                            ))
                if name == "BUCKET_LADDER" and ladder:
                    try:
                        got = tuple(ast.literal_eval(value))
                    except (ValueError, TypeError, SyntaxError):
                        got = None
                    if got is not None and got != ladder:
                        findings.append(mod.finding(
                            "API001", SEV_ERROR, node.lineno,
                            f"BUCKET_LADDER {got} drifts from the "
                            f"canonical ladder {ladder} — the rungs are "
                            "a compile-count contract (jit program "
                            "budget, bench compile_s); import it from "
                            "cilium_tpu.contracts instead of redefining",
                        ))
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "phase"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and phases
                and node.args[0].value not in phases
            ):
                findings.append(mod.finding(
                    "API001", SEV_ERROR, node.lineno,
                    f"trace phase literal {node.args[0].value!r} is not "
                    "in the canonical TRACE_PHASES vocabulary — phase "
                    "names are STABLE (bench --diff compares waterfalls "
                    "by name; TRACES_PR*.md archives key on them); add "
                    "it to cilium_tpu/contracts.py deliberately or use "
                    "an existing phase",
                ))


# -------------------------------------------------------------- BENCH001


def _is_round_call(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Name)
        and expr.func.id == "round"
    )


def _check_bench_key(
    mod: ModuleSource,
    key: str,
    line: int,
    higher: Tuple[str, ...],
    lower: Tuple[str, ...],
    bookkeeping: Set[str],
    findings: List[Finding],
) -> None:
    if key in bookkeeping or key.startswith("calib_"):
        return
    if _RATE_AS_DURATION_RE.search(key):
        findings.append(mod.finding(
            "BENCH001", SEV_ERROR, line,
            f"metric key '{key}' is a rate but ends in '_s', which the "
            "--diff direction engine reads as a duration (lower-is-"
            "better) — a throughput gain would be reported as a "
            "regression; rename with a rate suffix "
            f"({'/'.join(higher)})",
        ))
        return
    if key.endswith(tuple(higher) + tuple(lower)):
        return
    findings.append(mod.finding(
        "BENCH001", SEV_WARNING, line,
        f"computed metric key '{key}' carries no --diff direction "
        f"suffix (higher: {'/'.join(higher)}; lower: "
        f"{'/'.join(lower)}) — it silently falls out of regression "
        "coverage; suffix it, or add it to BENCH_BOOKKEEPING_KEYS if "
        "it describes the scenario rather than measuring it",
    ))


def _check_bench001(
    modules: Sequence[ModuleSource],
    canon: _Canon,
    findings: List[Finding],
) -> None:
    higher = tuple(canon.get("DIFF_HIGHER_SUFFIXES") or ())
    lower = tuple(canon.get("DIFF_LOWER_SUFFIXES") or ())
    bookkeeping = set(canon.get("BENCH_BOOKKEEPING_KEYS") or ())
    if not higher or not lower:
        return
    for mod in modules:
        if os.path.basename(mod.path) != "bench.py":
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Dict):
                items = [
                    (k.value, v)
                    for k, v in zip(node.keys, node.values)
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                ]
                round_items = [
                    (k, v) for k, v in items if _is_round_call(v)
                ]
                # record-like: an explicit artifact record, or a dict
                # computing ≥3 rounded measurements (sub-bench results
                # merged into records by the caller)
                record_like = (
                    any(k == "metric" for k, _ in items)
                    or len(round_items) >= 3
                )
                if not record_like:
                    continue
                for key, value in round_items:
                    _check_bench_key(
                        mod, key, value.lineno, higher, lower,
                        bookkeeping, findings,
                    )
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].slice, ast.Constant)
                and isinstance(node.targets[0].slice.value, str)
                and _is_round_call(node.value)
            ):
                _check_bench_key(
                    mod, node.targets[0].slice.value, node.lineno,
                    higher, lower, bookkeeping, findings,
                )


# ---------------------------------------------------------------- OPT001


def _extract_option_specs(mod: ModuleSource) -> Dict[str, int]:
    """Option name -> registration line, from an ``OPTION_SPECS``
    assignment built of ``OptionSpec("Name", ...)`` calls."""
    for node in mod.tree.body:
        hit = _const_assign(node)
        if hit is None or hit[0] != "OPTION_SPECS":
            continue
        out: Dict[str, int] = {}
        for n in ast.walk(node):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id == "OptionSpec"
                and n.args
                and isinstance(n.args[0], ast.Constant)
                and isinstance(n.args[0].value, str)
            ):
                out[n.args[0].value] = n.lineno
        return out
    return {}


def _daemonconfig_fields(mod: ModuleSource) -> Optional[Set[str]]:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == "DaemonConfig":
            fields: Set[str] = set()
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name
                ):
                    fields.add(item.target.id)
                elif isinstance(item, ast.Assign):
                    for t in item.targets:
                        if isinstance(t, ast.Name):
                            fields.add(t.id)
            return fields
    return None


class _DaemonView:
    """What the daemon module does with options, extracted once."""

    def __init__(self, mod: ModuleSource) -> None:
        self.mod = mod
        self.handler_names: Set[str] = set()
        self.mutable: Set[str] = set()
        self.attr_refs: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                self.attr_refs.add(node.attr)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == "_on_option_change":
                    for n in ast.walk(node):
                        if isinstance(n, ast.Compare):
                            for comp in n.comparators:
                                if isinstance(
                                    comp, ast.Constant
                                ) and isinstance(comp.value, str):
                                    self.handler_names.add(comp.value)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Name)
                        and t.id == "_MUTABLE_OPTIONS"
                        or isinstance(t, ast.Attribute)
                        and t.attr == "_MUTABLE_OPTIONS"
                    ):
                        self.mutable |= _frozenset_literal(node.value)


def _frozenset_literal(expr: ast.AST) -> Set[str]:
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Name)
        and expr.func.id == "frozenset"
        and expr.args
    ):
        expr = expr.args[0]
    try:
        value = ast.literal_eval(expr)
    except (ValueError, TypeError, SyntaxError):
        return set()
    if isinstance(value, (set, frozenset, list, tuple)):
        return {v for v in value if isinstance(v, str)}
    return set()


def _collect_option_io(
    modules: Sequence[ModuleSource],
) -> Tuple[Set[str], Set[str]]:
    """(seeded names, read names) from literal ``options.set("X", ..)``
    seeds and ``options.get("X")`` / ``self._opt(ep, "X", ..)`` reads
    anywhere in the analyzed set."""
    seeded: Set[str] = set()
    reads: Set[str] = set()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            chain = attr_chain(node.func) or []
            if node.func.attr in ("set", "get") and any(
                "options" in part for part in chain[:-1]
            ):
                if node.args and isinstance(
                    node.args[0], ast.Constant
                ) and isinstance(node.args[0].value, str):
                    (seeded if node.func.attr == "set" else reads).add(
                        node.args[0].value
                    )
            elif node.func.attr == "_opt" and len(node.args) >= 2:
                arg = node.args[1]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    reads.add(arg.value)
    return seeded, reads


def _tests_dir_text(mod: ModuleSource) -> Optional[str]:
    """Concatenated source of every .py under the sibling ``tests/``
    of the option module's top-level package, or None when there is no
    such directory (single-file analyses stay self-contained)."""
    root = os.path.dirname(mod.path)
    while os.path.isfile(os.path.join(root, "__init__.py")):
        parent = os.path.dirname(root)
        if parent == root:
            break
        root = parent
    tests_dir = os.path.join(root, "tests")
    if not os.path.isdir(tests_dir):
        return None
    chunks: List[str] = []
    for dirpath, dirnames, files in os.walk(tests_dir):
        dirnames[:] = sorted(
            d for d in dirnames
            if d != "__pycache__" and not d.startswith(".")
        )
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            try:
                with open(
                    os.path.join(dirpath, name), "r", encoding="utf-8"
                ) as f:
                    chunks.append(f.read())
            except OSError:
                continue
    return "\n".join(chunks)


def _check_opt001(
    modules: Sequence[ModuleSource],
    canon: _Canon,
    findings: List[Finding],
) -> None:
    option_mods = [
        (mod, specs)
        for mod in modules
        for specs in (_extract_option_specs(mod),)
        if specs
    ]
    # hot modules must never pay a per-batch option-map read: the hub
    # pushes option values into one pipeline attribute at change time
    for mod in modules:
        if not mod.is_hot():
            continue
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
            ):
                chain = attr_chain(node.func) or []
                if any("options" in part for part in chain[:-1]):
                    findings.append(mod.finding(
                        "OPT001", SEV_ERROR, node.lineno,
                        "option-map read in a hot module — options are "
                        "read through the hub-pushed pipeline attribute "
                        "(one attribute read per batch), never through "
                        "options.get() on the verdict path",
                    ))
    if not option_mods:
        return
    boot_fields: Dict[str, Optional[str]] = dict(
        canon.get("OPTION_BOOT_FIELDS") or {}
    )
    seeded, reads = _collect_option_io(modules)
    daemons = [
        _DaemonView(mod)
        for mod in modules
        if any(
            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == "_on_option_change"
            for n in ast.walk(mod.tree)
        )
    ]
    for mod, specs in option_mods:
        top = mod.relpath.split("/")[0]
        daemon = next(
            (d for d in daemons if d.mod.relpath.split("/")[0] == top),
            None,
        )
        cfg_fields = _daemonconfig_fields(mod)
        tests_text = _tests_dir_text(mod)
        for name, line in sorted(specs.items(), key=lambda kv: kv[1]):
            if boot_fields and name not in boot_fields:
                findings.append(mod.finding(
                    "OPT001", SEV_ERROR, line,
                    f"option {name} has no OPTION_BOOT_FIELDS entry in "
                    "the canonical table (cilium_tpu/contracts.py) — "
                    "declare its DaemonConfig boot field, or record "
                    "None with the reason it is boot-exempt",
                ))
                continue
            field = boot_fields.get(name)
            if field is not None:
                if cfg_fields is not None and field not in cfg_fields:
                    findings.append(mod.finding(
                        "OPT001", SEV_ERROR, line,
                        f"option {name} declares boot field '{field}' "
                        "but DaemonConfig has no such field — the "
                        "option cannot be enabled at boot",
                    ))
                elif daemon is not None and field not in daemon.attr_refs:
                    findings.append(mod.finding(
                        "OPT001", SEV_ERROR, line,
                        f"boot field '{field}' of option {name} is "
                        "never consulted by the daemon — the configured "
                        "boot value is dead; seed the option map from "
                        "it in Daemon.__init__",
                    ))
                if tests_text is not None and (
                    f'"{name}"' not in tests_text
                    and f"'{name}'" not in tests_text
                ):
                    findings.append(mod.finding(
                        "OPT001", SEV_ERROR, line,
                        f"datapath-gated option {name} has no tripwire "
                        "test under tests/ naming it — the OFF-path "
                        "bit-identical contract (ROADMAP) is unenforced "
                        "for this option",
                    ))
            if daemon is not None:
                if name in daemon.mutable:
                    if (
                        name not in daemon.handler_names
                        and name not in reads
                    ):
                        findings.append(mod.finding(
                            "OPT001", SEV_ERROR, line,
                            f"runtime-mutable option {name} has no "
                            "consumption site: no _on_option_change "
                            "branch and no literal option read — "
                            "toggling it changes nothing (the "
                            "L7DeviceBatch-class bug); wire a handler "
                            "or drop it from _MUTABLE_OPTIONS",
                        ))
                elif name not in seeded and name not in reads:
                    findings.append(mod.finding(
                        "OPT001", SEV_ERROR, line,
                        f"option {name} is not runtime-mutable, never "
                        "seeded at boot, and never read — it is "
                        "registered surface that cannot do anything; "
                        "seed it, read it, or make it mutable with a "
                        "handler",
                    ))
        # reverse direction: table entries with no registration rot
        if boot_fields and "OPTION_BOOT_FIELDS" in canon.sources:
            src_mod, src_line = canon.sources["OPTION_BOOT_FIELDS"]
            if src_mod.relpath.split("/")[0] == top:
                for name in sorted(boot_fields):
                    if name not in specs:
                        findings.append(src_mod.finding(
                            "OPT001", SEV_ERROR, src_line,
                            f"OPTION_BOOT_FIELDS entry '{name}' has no "
                            "OPTION_SPECS registration — stale table "
                            "row; remove it or register the option",
                        ))


# ---------------------------------------------------------------- OPT002


class _ClassOptGates:
    """Per-class OPT002 state: gate attrs, assignment sites with their
    gate context, reads and gate mentions per method."""

    def __init__(self, cls: ast.ClassDef) -> None:
        self.cls = cls
        self.methods: Dict[str, ast.AST] = {
            item.name: item
            for item in cls.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.gates: Set[str] = self._gate_attrs()
        # attr -> [(method, active gates, line)]
        self.assigns: Dict[str, List[Tuple[str, frozenset, int]]] = {}
        # method -> self attrs read / mentioned at all
        self.reads: Dict[str, Set[str]] = {}
        self.mentions: Dict[str, Set[str]] = {}
        if not self.gates:
            return
        for mname, mnode in self.methods.items():
            self.reads[mname] = set()
            self.mentions[mname] = set()
            for n in walk_skipping(
                mnode, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                if (
                    isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                ):
                    self.mentions[mname].add(n.attr)
                    if isinstance(n.ctx, ast.Load):
                        self.reads[mname].add(n.attr)
            for stmt in mnode.body:
                self._walk(mname, stmt, frozenset())

    def _gate_attrs(self) -> Set[str]:
        gates: Set[str] = set()
        for mname, mnode in self.methods.items():
            if not mname.startswith("set_"):
                continue
            args = mnode.args
            params = {
                a.arg
                for a in list(args.posonlyargs) + list(args.args)
                + list(args.kwonlyargs)
                if a.arg not in ("self", "cls")
            }
            for n in ast.walk(mnode):
                if not isinstance(n, ast.Assign):
                    continue
                value = n.value
                if isinstance(value, ast.Call) and isinstance(
                    value.func, ast.Name
                ) and value.func.id == "bool" and value.args:
                    value = value.args[0]
                if not (
                    isinstance(value, ast.Name) and value.id in params
                ):
                    continue
                for t in n.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        gates.add(t.attr)
        return gates

    def _gate_of_test(self, test: ast.AST) -> Optional[str]:
        if (
            isinstance(test, ast.Attribute)
            and isinstance(test.value, ast.Name)
            and test.value.id == "self"
            and test.attr in self.gates
        ):
            return test.attr
        return None

    def _record(
        self, method: str, target: ast.AST, gates: frozenset, line: int
    ) -> None:
        node = target
        while isinstance(node, ast.Subscript):
            node = node.value
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            self.assigns.setdefault(node.attr, []).append(
                (method, gates, line)
            )

    def _walk(
        self, method: str, stmt: ast.stmt, gates: frozenset
    ) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(stmt, ast.If):
            g = self._gate_of_test(stmt.test)
            body_gates = gates | {g} if g else gates
            for s in stmt.body:
                self._walk(method, s, body_gates)
            for s in stmt.orelse:
                self._walk(method, s, gates)
            return
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._record(method, t, gates, stmt.lineno)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            self._record(method, stmt.target, gates, stmt.lineno)
        for attr in ("body", "orelse", "finalbody"):
            for s in getattr(stmt, attr, []) or []:
                self._walk(method, s, gates)
        for h in getattr(stmt, "handlers", []) or []:
            for s in h.body:
                self._walk(method, s, gates)


def _check_opt002(
    modules: Sequence[ModuleSource], findings: List[Finding]
) -> None:
    for mod in modules:
        if not mod.is_hot():
            continue
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            view = _ClassOptGates(cls)
            if not view.gates:
                continue
            for attr, sites in sorted(view.assigns.items()):
                if attr in view.gates:
                    continue
                live = [s for s in sites if s[0] != "__init__"
                        and not s[0].startswith("set_")]
                if not live:
                    continue
                gate_sets = [s[1] for s in live]
                common = frozenset.intersection(*gate_sets)
                if not common:
                    continue  # some mutation happens outside any gate
                gate = sorted(common)[0]
                off_readers = sorted(
                    m for m, attrs in view.reads.items()
                    if attr in attrs
                    and gate not in view.mentions.get(m, ())
                    and m != "__init__"
                    and not m.startswith("set_")
                )
                if not off_readers:
                    continue
                line = min(s[2] for s in live)
                findings.append(mod.finding(
                    "OPT002", SEV_WARNING, line,
                    f"{cls.name}.{attr} is mutated only while option "
                    f"gate '{gate}' is ON, but {off_readers[0]}() reads "
                    "it without consulting the gate — the OFF path "
                    "observes ON-path state (breaks the OFF-path "
                    "bit-identical contract; a jit cache key built "
                    "from it recompiles on toggle); gate the reader or "
                    "reset the state when the option turns off",
                ))


# ---------------------------------------------------------------- entry


def analyze_contracts(
    modules: Sequence[ModuleSource], graph=None
) -> List[Finding]:
    """Run Family C over the whole analyzed set at once (every rule
    here is cross-file; per-module iteration happens inside)."""
    findings: List[Finding] = []
    canon = _Canon(modules)
    _check_api001(modules, canon, findings)
    _check_bench001(modules, canon, findings)
    _check_opt001(modules, canon, findings)
    _check_opt002(modules, findings)
    return findings
