"""Shared infrastructure for the policyd-lint static analyzers.

Everything here is pure stdlib (``ast`` + ``re``): the analyzers must
run in CI contexts (and in bench --lint pre-flight) without importing
jax or touching a device.

Concepts
--------
hot module
    A module on the verdict hot path. Determined by path convention
    (``*/ops/*.py``, ``*/engine.py``, ``*/datapath/pipeline.py``) or an
    explicit ``# policyd: hot`` marker comment anywhere in the file.
suppression
    ``# policyd-lint: disable=RULE[,RULE...]`` on a finding's line (or
    on a comment-only line directly above it) silences those rules at
    that site. ``# policyd-lint: disable-file=RULE`` silences a rule
    for the whole file. Suppressions are for *justified* findings —
    the comment should say why the pattern is safe.
baseline
    Pre-existing findings checked into ``baseline.json``. CI fails
    only on findings NOT covered by the baseline, so the gate catches
    regressions without demanding a flag-day cleanup.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
import re
from typing import Dict, List, Optional, Set, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"

# Path conventions marking the verdict hot path (relative to the
# package root, forward slashes).
HOT_PATH_PATTERNS = (
    "*/ops/*.py",
    "*/engine.py",
    "*/datapath/pipeline.py",
)

_HOT_MARKER_RE = re.compile(r"#\s*policyd:\s*hot\b")
_SUPPRESS_RE = re.compile(r"#\s*policyd-lint:\s*disable=([A-Za-z0-9_,\s]+)")
_FILE_SUPPRESS_RE = re.compile(
    r"#\s*policyd-lint:\s*disable-file=([A-Za-z0-9_,\s]+)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer finding.

    ``context`` is the stripped source text of the flagged line — the
    baseline matches on (rule, path, context) rather than line numbers
    so unrelated edits above a baselined finding don't break CI.
    """

    rule: str
    severity: str
    path: str  # package-relative, forward slashes
    line: int
    message: str
    context: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.context)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "context": self.context,
        }

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} "
            f"{self.severity}: {self.message}"
        )


def package_relpath(path: str) -> str:
    """Path of ``path`` relative to the topmost enclosing package
    (walks up while __init__.py exists). Stable across invocation
    directories, so baseline keys survive being run from anywhere."""
    path = os.path.abspath(path)
    root = os.path.dirname(path)
    while os.path.isfile(os.path.join(root, "__init__.py")):
        parent = os.path.dirname(root)
        if parent == root:
            break
        root = parent
    return os.path.relpath(path, root).replace(os.sep, "/")


class ModuleSource:
    """A parsed module plus its comment-derived metadata (markers and
    suppressions live in comments, which ``ast`` discards)."""

    def __init__(self, path: str, text: Optional[str] = None) -> None:
        self.path = os.path.abspath(path)
        if text is None:
            with open(self.path, "r", encoding="utf-8") as f:
                text = f.read()
        self.text = text
        self.relpath = package_relpath(self.path)
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=self.path)  # may raise
        self.hot_marked = False
        self.file_suppressed: Set[str] = set()
        # line number -> set of suppressed rule ids
        self.suppressed: Dict[int, Set[str]] = {}
        for i, ln in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(ln)
            if m:
                rules = {
                    r.strip().upper()
                    for r in m.group(1).split(",")
                    if r.strip()
                }
                self.suppressed.setdefault(i, set()).update(rules)
                if ln.split("#", 1)[0].strip() == "":
                    # comment-only line: applies to the next line too
                    self.suppressed.setdefault(i + 1, set()).update(rules)
            m = _FILE_SUPPRESS_RE.search(ln)
            if m:
                self.file_suppressed.update(
                    r.strip().upper()
                    for r in m.group(1).split(",")
                    if r.strip()
                )
            if _HOT_MARKER_RE.search(ln):
                self.hot_marked = True

    # ------------------------------------------------------------------
    def is_hot(self) -> bool:
        if self.hot_marked:
            return True
        rp = "/" + self.relpath  # anchor so "*/ops/*" can't match root
        return any(fnmatch.fnmatch(rp, "*" + p.lstrip("*")) or
                   fnmatch.fnmatch(rp, p) for p in HOT_PATH_PATTERNS)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def is_suppressed(self, rule: str, line: int) -> bool:
        rule = rule.upper()
        if rule in self.file_suppressed or "ALL" in self.file_suppressed:
            return True
        at = self.suppressed.get(line, ())
        return rule in at or "ALL" in at

    def finding(
        self, rule: str, severity: str, line: int, message: str
    ) -> Finding:
        return Finding(
            rule=rule,
            severity=severity,
            path=self.relpath,
            line=line,
            message=message,
            context=self.line_text(line),
        )


# ---------------------------------------------------------------------------
# small AST helpers shared by both rule families


def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` → ["a", "b", "c"]; None for non-name-rooted chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Dotted name of a call target, e.g. "jnp.take" / "int"."""
    chain = attr_chain(node.func)
    return ".".join(chain) if chain else None


def walk_skipping(node: ast.AST, skip: Tuple[type, ...]):
    """ast.walk that does not descend into node types in ``skip``
    (the node itself is always yielded)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, skip):
                continue
            stack.append(child)


def iter_target_names(target: ast.AST):
    """Names bound by an assignment target (tuples unpacked)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from iter_target_names(elt)
    elif isinstance(target, ast.Starred):
        yield from iter_target_names(target.value)
