"""Family A: TPU hot-path rules.

The verdict path dies silently from host↔device round trips — an
``int()`` on a device value blocks the dispatch queue, a ``jnp`` call
inside a Python loop traces one XLA op per iteration, a jit closing
over a mutable global silently recompiles (or worse, bakes in stale
state). These rules flag the syntactic shapes of those bugs inside
modules marked hot (ops/, engine.py, datapath/pipeline.py, or a
``# policyd: hot`` marker).

Rules
-----
TPU001  host-sync coercion: ``int()/float()/bool()/np.asarray()/
        .item()/.tolist()`` applied to a value that flows from a jnp
        op or a jit-decorated function (error), or to an array
        reduction (``x.max()``, ``x.sum()``, ...) on a parameter-
        derived array (warning — may be numpy, but on the hot path
        the coercion belongs off-path or on numpy before device_put).
TPU002  ``jnp``/``jax.lax`` call inside a Python ``for``/``while``
        loop — the per-flow gather anti-pattern (ops/verdict.py
        documents the ~1000× regression). Intentional static unrolls
        carry an inline suppression.
TPU003  jit-decorated function closes over a mutable module-level
        global (list/dict/set): jit traces the value once and never
        sees later mutation.
TPU004  dtype-literal drift: a matmul (``@``, ``jnp.matmul``,
        ``jnp.dot``, ``lax.dot_general``) whose two operands are cast
        to different integer/float dtype literals.
TPU005  synchronous host pull on the engine refresh path: inside a
        function marked ``# policyd: refresh-path`` (the comment sits
        on the line above the def or its first decorator), a
        ``block_until_ready`` call, an ``.item()/.tolist()``, or an
        ``np.asarray()/int()``-style coercion whose argument is
        device-resident (a jnp/jax chain, a name or attribute chain
        mentioning the device tables — ``sel_match``/``id_bits``/
        ``rule_tab``/``*device*``). Each such pull is a full device
        RTT *per call*; policyd-delta exists because a churny tick
        multiplied exactly this cost — batch the pull or keep the
        patch on device.
ROBUST001  bare/broad ``except`` (no type, ``Exception``, or
        ``BaseException``) in a hot module whose handler neither
        re-raises nor routes through the ``faults.classify`` taxonomy
        — on the verdict path a swallowed error leaves the in-flight
        FIFO, CT epoch, and staging free-lists in an undefined state
        (policyd-failsafe exists because of exactly these blocks).
ROBUST002  unbounded blocking wait in a hot module: ``.join()`` /
        ``.wait()`` / ``.acquire()`` / queue-style ``.get()`` with
        neither a timeout argument nor ``block=False`` parks the
        calling thread forever behind a wedged device call — the
        policyd-overload watchdog can fire events and abandon batches
        but cannot unwind a thread stuck in an untimed C wait. Bound
        the wait (timeout + retry loop) or suppress with a written
        justification. ``with lock:`` blocks are Family B's domain
        (LOCK rules) and are not flagged here.
ROBUST003  non-atomic state-file write in a hot module: a write-mode
        ``open()`` whose path expression never mentions a temp file
        (no ``tmp`` in any name/attribute/string, no ``mkstemp``/
        ``NamedTemporaryFile``) writes the final path in place — a
        crash mid-write leaves a torn file the next boot restores
        from (the policyd-survive failure mode). Write a sibling tmp
        file, fsync, then ``os.replace`` onto the final name; reads
        (default mode / ``"r"``/``"rb"``) are exempt.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
    ModuleSource,
    attr_chain,
    call_name,
    iter_target_names,
    walk_skipping,
)

COERCIONS = {"int", "float", "bool"}
NP_SYNC_FUNCS = {"asarray", "array", "copy"}
SYNC_METHODS = {"item", "tolist", "__array__"}
REDUCTIONS = {
    "max", "min", "sum", "mean", "prod", "any", "all",
    "argmax", "argmin", "item",
}
DTYPE_LITERALS = {
    "int4", "int8", "int16", "int32", "int64",
    "uint4", "uint8", "uint16", "uint32", "uint64",
    "float16", "float32", "float64", "bfloat16",
}
MUTABLE_FACTORIES = {
    "list", "dict", "set", "bytearray", "deque", "defaultdict",
    "OrderedDict", "Counter",
}
# TPU005: the marker that opts a function into the refresh-path pull
# audit, and the attribute names that identify the device-table state
# (DeviceTables fields the engine scatters into).
_REFRESH_RE = re.compile(r"#\s*policyd:\s*refresh-path\b")
DEVICE_ATTRS = {"sel_match", "id_bits", "rule_tab"}


class _Imports:
    """Resolved aliases for jax / jax.numpy / jax.lax / numpy."""

    def __init__(self, tree: ast.Module) -> None:
        self.jnp: Set[str] = set()
        self.jax: Set[str] = set()
        self.lax: Set[str] = set()
        self.np: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if a.name == "jax.numpy":
                        self.jnp.add(a.asname or "jax")
                    elif a.name == "jax.lax":
                        self.lax.add(a.asname or "jax")
                    elif a.name == "jax":
                        self.jax.add(name)
                    elif a.name == "numpy":
                        self.np.add(name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "numpy":
                            self.jnp.add(a.asname or "numpy")
                        elif a.name == "lax":
                            self.lax.add(a.asname or "lax")
                elif node.module == "jax.numpy":
                    # from jax.numpy import X — treat X as device op
                    for a in node.names:
                        self.jnp.add(a.asname or a.name)

    def is_device_chain(self, chain: Optional[List[str]]) -> bool:
        """True for jnp.*, jax.lax.*, jax.* chains (device-producing)."""
        if not chain:
            return False
        root = chain[0]
        if root in self.jnp or root in self.lax:
            return True
        if root in self.jax and len(chain) >= 2:
            # jax.jit / jax.device_put / jax.lax... — device side
            return chain[1] not in ("tree_util", "typing", "config")
        return False


def _collect_jit_names(tree: ast.Module, imports: _Imports) -> Set[str]:
    """Names of functions decorated with jax.jit (bare, called, or via
    functools.partial(jax.jit, ...))."""
    jit_names: Set[str] = set()

    def is_jit_deco(d: ast.AST) -> bool:
        chain = attr_chain(d)
        if chain and chain[0] in imports.jax and chain[-1] in ("jit", "pmap"):
            return True
        if isinstance(d, ast.Call):
            fchain = attr_chain(d.func)
            if fchain and fchain[-1] == "partial":
                return any(is_jit_deco(a) for a in d.args)
            return is_jit_deco(d.func)
        return False

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(is_jit_deco(d) for d in node.decorator_list):
                jit_names.add(node.name)
    return jit_names


def _collect_mutable_globals(tree: ast.Module) -> Dict[str, int]:
    """Module-level names bound to mutable containers → def line."""
    out: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            value = node.value
            mutable = isinstance(
                value,
                (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
                 ast.DictComp),
            )
            if isinstance(value, ast.Call):
                cn = call_name(value)
                if cn and cn.split(".")[-1] in MUTABLE_FACTORIES:
                    mutable = True
            if mutable:
                for name in iter_target_names(
                    node.targets[0] if len(node.targets) == 1
                    else ast.Tuple(elts=list(node.targets))
                ):
                    out[name] = node.lineno
        elif isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Name
        ):
            out[node.target.id] = node.lineno
    return out


class _FuncTaint:
    """Intra-function taint walk for TPU001.

    ``device``: names known to flow from jnp ops / jit calls — an
    ``int()`` on these is a guaranteed host-device sync.
    ``arrayish``: parameter-derived names in hot modules — probably
    arrays; only reduction-coercions on these are flagged (warning).
    """

    def __init__(
        self,
        mod: ModuleSource,
        imports: _Imports,
        jit_names: Set[str],
        func: ast.AST,
        findings: List[Finding],
        graph=None,
    ) -> None:
        self.mod = mod
        self.imports = imports
        self.jit_names = jit_names
        self.findings = findings
        self.graph = graph
        self.device: Set[str] = set()
        self.arrayish: Set[str] = set()
        args = func.args
        all_args = (
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
        )
        for a in all_args:
            if a.arg in ("self", "cls"):
                continue
            ann = getattr(a, "annotation", None)
            if ann is not None and self._mentions_device(ann):
                self.device.add(a.arg)
            else:
                self.arrayish.add(a.arg)
        self.run(func)

    # -- expression classification -------------------------------------
    def _mentions_device(self, expr: ast.AST) -> bool:
        """Expression contains a jnp/lax-rooted chain, a tainted name,
        or a call of a jit-decorated function."""
        for n in walk_skipping(expr, (ast.FunctionDef, ast.Lambda)):
            if isinstance(n, ast.Name) and n.id in self.device:
                return True
            if isinstance(n, ast.Attribute):
                chain = attr_chain(n)
                if self.imports.is_device_chain(chain):
                    return True
            if isinstance(n, ast.Call):
                cn = call_name(n)
                if cn and cn.split(".")[-1] in self.jit_names:
                    return True
        return False

    def _mentions_arrayish(self, expr: ast.AST) -> bool:
        for n in walk_skipping(expr, (ast.FunctionDef, ast.Lambda)):
            if isinstance(n, ast.Name) and n.id in self.arrayish:
                return True
        return False

    def _is_host_pull(self, expr: ast.AST) -> bool:
        """True when ``expr`` is an explicit host pull — np.asarray(x),
        int(x), possibly sliced or .astype()'d. The pull itself is
        flagged once at the call site; its RESULT is host data and must
        not re-taint downstream uses."""
        while True:
            if isinstance(expr, (ast.Subscript, ast.Attribute)):
                expr = expr.value
            elif (
                isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and not attr_chain(expr.func)
            ):
                # method on a call result: np.asarray(x).astype(...)
                expr = expr.func.value
            else:
                break
        if not isinstance(expr, ast.Call):
            return False
        fchain = attr_chain(expr.func)
        if not fchain:
            return False
        if len(fchain) == 1 and fchain[0] in COERCIONS:
            return True
        return (
            len(fchain) == 2
            and fchain[0] in self.imports.np
            and fchain[1] in NP_SYNC_FUNCS
        )

    # -- walk ------------------------------------------------------------
    def run(self, func: ast.AST) -> None:
        for stmt in func.body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested scopes get their own walk from the rule
        if isinstance(stmt, ast.Assign):
            self._check_expr(stmt.value)
            names = [
                n
                for t in stmt.targets
                for n in iter_target_names(t)
            ]
            if self._is_host_pull(stmt.value):
                self.arrayish.update(names)
                self.device.difference_update(names)
            elif self._mentions_device(stmt.value):
                self.device.update(names)
                self.arrayish.difference_update(names)
            elif self._mentions_arrayish(stmt.value):
                self.arrayish.update(names)
            else:
                for n in names:
                    self.device.discard(n)
                    self.arrayish.discard(n)
        elif isinstance(stmt, ast.AugAssign):
            self._check_expr(stmt.value)
            if isinstance(stmt.target, ast.Name) and self._mentions_device(
                stmt.value
            ):
                self.device.add(stmt.target.id)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_expr(stmt.iter)
            names = list(iter_target_names(stmt.target))
            if self._mentions_device(stmt.iter):
                self.device.update(names)
            elif self._mentions_arrayish(stmt.iter):
                self.arrayish.update(names)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
        elif isinstance(stmt, ast.While):
            self._check_expr(stmt.test)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
        elif isinstance(stmt, ast.If):
            self._check_expr(stmt.test)
            for s in stmt.body + stmt.orelse:
                self._stmt(s)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_expr(item.context_expr)
            for s in stmt.body:
                self._stmt(s)
        elif isinstance(stmt, ast.Try):
            for s in (
                stmt.body + stmt.orelse + stmt.finalbody
                + [h for hh in stmt.handlers for h in hh.body]
            ):
                self._stmt(s)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self._check_expr(stmt.value)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._check_expr(stmt.exc)

    # -- the actual TPU001 checks ---------------------------------------
    def _check_expr(self, expr: ast.AST) -> None:
        for node in walk_skipping(expr, (ast.FunctionDef, ast.Lambda)):
            if not isinstance(node, ast.Call):
                continue
            self._check_call(node)

    def _check_call(self, node: ast.Call) -> None:
        fchain = attr_chain(node.func)
        fname = ".".join(fchain) if fchain else None
        self._check_callee_pull(node)

        # .item() / .tolist() on a device-tainted value
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in SYNC_METHODS
            and self._mentions_device(node.func.value)
        ):
            self._emit(
                node,
                SEV_ERROR,
                f".{node.func.attr}() forces a host-device sync on the "
                "hot path; hoist it off-path or keep the value on device",
            )
            return

        # int()/float()/bool()/np.asarray() on device values
        is_coercion = fname in COERCIONS
        is_np_pull = (
            fchain is not None
            and len(fchain) == 2
            and fchain[0] in self.imports.np
            and fchain[1] in NP_SYNC_FUNCS
        )
        if not (is_coercion or is_np_pull) or not node.args:
            return
        arg = node.args[0]
        if self._mentions_device(arg):
            what = fname if is_coercion else fname
            self._emit(
                node,
                SEV_ERROR,
                f"{what}() on a value that flows from jnp/jit — this "
                "blocks on the device (implicit transfer) inside a hot "
                "module; hoist the coercion off the hot path",
            )
            return
        # reduction-coercion on a parameter-derived array: int(x.max())
        if is_coercion and isinstance(arg, ast.Call):
            f = arg.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in REDUCTIONS
                and isinstance(f.value, ast.Name)
                and f.value.id in self.arrayish
            ):
                self._emit(
                    node,
                    SEV_WARNING,
                    f"{fname}({f.value.id}.{f.attr}(...)) in a hot module "
                    "syncs if the array is device-resident; coerce on "
                    "numpy before device_put or hoist off the hot path",
                )

    def _check_callee_pull(self, node: ast.Call) -> None:
        """Inter-procedural TPU001, one call-graph edge deep: a device
        value handed to a resolved callee whose body host-pulls that
        parameter. The sync is exactly as real as a local ``int(x)`` —
        it just happens one frame down, often in another module."""
        if self.graph is None:
            return
        callee = self.graph.resolved_callee(node)
        if callee is None or not callee.pull_params:
            return
        bindings = []
        for i, arg in enumerate(node.args):
            if isinstance(arg, ast.Starred):
                break  # positions after *args are unknowable
            if i < len(callee.params):
                bindings.append((callee.params[i], arg))
        for kw in node.keywords:
            if kw.arg is not None:
                bindings.append((kw.arg, kw.value))
        for pname, arg in bindings:
            pull = callee.pull_params.get(pname)
            if pull is None or not self._mentions_device(arg):
                continue
            line, how = pull
            self._emit(
                node,
                SEV_ERROR,
                f"passes a device value to {callee.display}() which "
                f"host-pulls it ({how} on '{pname}' at "
                f"{callee.mod.relpath}:{line}) — the sync happens one "
                "call away; pull once at the intended host boundary or "
                "keep the helper on device",
            )
            return  # one finding per call site is enough signal

    def _emit(self, node: ast.AST, severity: str, message: str) -> None:
        self.findings.append(
            self.mod.finding("TPU001", severity, node.lineno, message)
        )


# ---------------------------------------------------------------------------


def _is_refresh_marked(mod: ModuleSource, func: ast.AST) -> bool:
    """True when a ``# policyd: refresh-path`` comment sits in the
    comment block immediately above ``func`` (above its first decorator
    when decorated — the marker reads as documentation of the def, so
    it goes where a docstring reader would look)."""
    start = func.lineno
    if func.decorator_list:
        start = min(start, min(d.lineno for d in func.decorator_list))
    i = start - 2  # 0-based index of the line above the def/decorator
    while i >= 0:
        text = mod.lines[i].strip()
        if not text.startswith("#"):
            return False
        if _REFRESH_RE.search(text):
            return True
        i -= 1
    return False


class _RefreshPull:
    """TPU005 walk: synchronous host pulls inside a refresh-marked
    function.

    Unlike TPU001 (which needs the value to *flow from* a jnp op in
    the same function), the refresh path mostly pulls pre-existing
    device state — ``np.asarray(self._device.sel_match)`` never touches
    a jnp chain, so TPU001's taint can't see it. Here "device-resident"
    means: a jnp/jax chain, a name/attr mentioning the device tables
    (``*device*``, ``sel_match``/``id_bits``/``rule_tab``), or a local
    assigned from one of those (light forward taint).
    """

    def __init__(
        self,
        mod: ModuleSource,
        imports: _Imports,
        func: ast.AST,
        findings: List[Finding],
    ) -> None:
        self.mod = mod
        self.imports = imports
        self.findings = findings
        self.tainted: Set[str] = set()
        for stmt in func.body:
            self._stmt(stmt)

    def _devicey(self, expr: ast.AST) -> bool:
        for n in walk_skipping(expr, (ast.FunctionDef, ast.Lambda)):
            if isinstance(n, ast.Name):
                if n.id in self.tainted or "device" in n.id.lower():
                    return True
            elif isinstance(n, ast.Attribute):
                chain = attr_chain(n)
                if chain is None:
                    continue
                if self.imports.is_device_chain(chain):
                    return True
                if any(
                    part in DEVICE_ATTRS or "device" in part.lower()
                    for part in chain
                ):
                    return True
        return False

    # -- walk (taint through plain Assigns; recurse into control flow) --
    def _stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are separate scopes (and unmarked)
        for expr in ast.iter_child_nodes(stmt):
            if isinstance(expr, ast.expr):
                self._check_expr(expr)
        for item in getattr(stmt, "items", []) or []:  # with-blocks
            self._check_expr(item.context_expr)
        if isinstance(stmt, ast.Assign):
            names = [
                n for t in stmt.targets for n in iter_target_names(t)
            ]
            if self._devicey(stmt.value):
                self.tainted.update(names)
            else:
                self.tainted.difference_update(names)
        for body in ("body", "orelse", "finalbody"):
            for s in getattr(stmt, body, []) or []:
                self._stmt(s)
        for h in getattr(stmt, "handlers", []) or []:
            for s in h.body:
                self._stmt(s)

    def _check_expr(self, expr: ast.AST) -> None:
        for node in walk_skipping(expr, (ast.FunctionDef, ast.Lambda)):
            if isinstance(node, ast.Call):
                self._check_call(node)

    def _check_call(self, node: ast.Call) -> None:
        fchain = attr_chain(node.func)

        # x.block_until_ready() / jax.block_until_ready(x): an explicit
        # barrier is a pull by definition — no arg analysis needed.
        if fchain and fchain[-1] == "block_until_ready":
            self._emit(node, "block_until_ready()")
            return

        # device.sel_match.item() / .tolist() / .__array__()
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in SYNC_METHODS
            and self._devicey(node.func.value)
        ):
            self._emit(node, f".{node.func.attr}()")
            return

        # np.asarray(device...) / int(device...)-style coercions
        is_coercion = (
            fchain is not None
            and len(fchain) == 1
            and fchain[0] in COERCIONS
        )
        is_np_pull = (
            fchain is not None
            and len(fchain) == 2
            and fchain[0] in self.imports.np
            and fchain[1] in NP_SYNC_FUNCS
        )
        if (is_coercion or is_np_pull) and node.args:
            if self._devicey(node.args[0]):
                self._emit(node, f"{'.'.join(fchain)}()")

    def _emit(self, node: ast.AST, what: str) -> None:
        self.findings.append(
            self.mod.finding(
                "TPU005",
                SEV_ERROR,
                node.lineno,
                f"{what} on device state inside a refresh-path function "
                "— every call is a full host-device RTT, and a churny "
                "tick multiplies it (the policyd-delta failure mode); "
                "coalesce the pull across the batch or keep the patch "
                "on device",
            )
        )


def _check_loops(
    mod: ModuleSource,
    imports: _Imports,
    func: ast.AST,
    findings: List[Finding],
) -> None:
    """TPU002: jnp/lax calls under a Python for/while in a hot module."""
    seen_loops: Set[int] = set()
    for node in walk_skipping(func, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
        if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            continue
        if id(node) in seen_loops:
            continue
        # mark nested loops visited so each offending call reports once
        inner = [
            n
            for n in walk_skipping(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
            if isinstance(n, (ast.For, ast.AsyncFor, ast.While))
        ]
        for lp in inner:
            seen_loops.add(id(lp))
        calls = [
            n
            for n in walk_skipping(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            )
            if isinstance(n, ast.Call)
            and imports.is_device_chain(attr_chain(n.func))
        ]
        if not calls:
            continue
        first = min(calls, key=lambda c: c.lineno)
        cn = call_name(first) or "jnp op"
        findings.append(
            mod.finding(
                "TPU002",
                SEV_WARNING,
                first.lineno,
                f"{cn} inside a Python {type(node).__name__.lower()} loop "
                "in a hot module — each iteration traces/dispatches its "
                "own op (per-flow gather anti-pattern); batch it, or "
                "suppress with a justification if this is a bounded "
                "static unroll",
            )
        )


def _check_jit_globals(
    mod: ModuleSource,
    imports: _Imports,
    tree: ast.Module,
    findings: List[Finding],
) -> None:
    """TPU003: jit functions reading mutable module-level globals."""
    mutable = _collect_mutable_globals(tree)
    if not mutable:
        return
    jit_names = _collect_jit_names(tree, imports)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in jit_names:
            continue
        local: Set[str] = set()
        args = node.args
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        ):
            local.add(a.arg)
        for n in walk_skipping(node, (ast.FunctionDef, ast.Lambda)):
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    n.targets if isinstance(n, ast.Assign) else [n.target]
                )
                for t in targets:
                    local.update(iter_target_names(t))
        for n in walk_skipping(node, (ast.FunctionDef, ast.Lambda)):
            if (
                isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and n.id in mutable
                and n.id not in local
            ):
                findings.append(
                    mod.finding(
                        "TPU003",
                        SEV_ERROR,
                        n.lineno,
                        f"jit function '{node.name}' closes over mutable "
                        f"global '{n.id}' (defined line {mutable[n.id]}): "
                        "jit traces the value once — later mutation is "
                        "silently ignored (or forces recompiles); pass it "
                        "as an argument or make it immutable",
                    )
                )
                break  # one finding per function is enough signal


def _operand_dtypes(imports: _Imports, expr: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in walk_skipping(expr, (ast.FunctionDef, ast.Lambda)):
        if isinstance(n, ast.Attribute) and n.attr in DTYPE_LITERALS:
            chain = attr_chain(n)
            if chain and (
                chain[0] in imports.jnp or chain[0] in imports.np
            ):
                out.add(n.attr)
    return out


def _check_dtype_drift(
    mod: ModuleSource,
    imports: _Imports,
    tree: ast.Module,
    findings: List[Finding],
) -> None:
    """TPU004: matmul with operands cast to different dtype literals."""
    for node in ast.walk(tree):
        pairs: List[Tuple[ast.AST, ast.AST, int]] = []
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
            pairs.append((node.left, node.right, node.lineno))
        elif isinstance(node, ast.Call):
            fchain = attr_chain(node.func)
            if (
                fchain
                and len(node.args) >= 2
                and fchain[-1] in ("matmul", "dot", "dot_general", "einsum")
                and (
                    fchain[0] in imports.jnp
                    or fchain[0] in imports.lax
                    or (len(fchain) >= 2 and fchain[0] in imports.jax)
                )
            ):
                a, b = node.args[0], node.args[1]
                if fchain[-1] == "einsum":
                    if len(node.args) >= 3:
                        a, b = node.args[1], node.args[2]
                    else:
                        continue
                pairs.append((a, b, node.lineno))
        for left, right, line in pairs:
            dl = _operand_dtypes(imports, left)
            dr = _operand_dtypes(imports, right)
            if dl and dr and dl.isdisjoint(dr):
                findings.append(
                    mod.finding(
                        "TPU004",
                        SEV_WARNING,
                        line,
                        "matmul operands carry different dtype literals "
                        f"({'/'.join(sorted(dl))} vs {'/'.join(sorted(dr))})"
                        " — mixed-precision contraction promotes off the "
                        "int8 MXU path; align the operand dtypes",
                    )
                )


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    """True for ``except:``, ``except Exception``, ``except
    BaseException`` (bare name or a tuple containing one)."""
    t = h.type
    if t is None:
        return True
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        chain = attr_chain(e)
        if chain and chain[-1] in ("Exception", "BaseException"):
            return True
    return False


def _handler_is_classified(h: ast.ExceptHandler) -> bool:
    """A broad handler is fine when it re-raises or consults the fault
    taxonomy: any ``raise`` in the body, or a call whose attr chain
    ends in ``classify`` (``faults.classify(e)``/``_faults.classify``).
    Nested defs/lambdas don't count — a raise THERE doesn't run HERE."""
    for n in walk_skipping(
        h, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    ):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Call):
            chain = attr_chain(n.func)
            if chain and chain[-1] == "classify":
                return True
    return False


def _check_broad_except(mod: ModuleSource, findings: List[Finding]) -> None:
    """ROBUST001: swallow-everything except blocks in hot modules."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _is_broad_handler(node) and not _handler_is_classified(node):
            findings.append(
                mod.finding(
                    "ROBUST001",
                    SEV_WARNING,
                    node.lineno,
                    "broad except in a hot module swallows every error "
                    "class — classify through faults.classify() (re-raise "
                    "KIND_ERROR, quarantine/retry the rest) or re-raise",
                )
            )


# ROBUST002: method names whose zero-arg / block=True form waits
# without bound. str.join(iterable) and dict.get(key[, default])
# always carry a non-bool positional, which is how they stay exempt.
BLOCKING_WAIT_METHODS = {"join", "wait", "acquire", "get"}


def _const_bool(node: ast.AST) -> Optional[bool]:
    if isinstance(node, ast.Constant) and isinstance(node.value, bool):
        return node.value
    return None


def _is_unbounded_wait(node: ast.Call) -> bool:
    """True when the call blocks with no timeout:

    - ``x.join()`` / ``x.wait()``: any positional is the timeout (or
      str.join's iterable) → only the zero-arg, no-``timeout``-kwarg
      form is unbounded;
    - ``x.acquire()`` / ``x.acquire(True)``: a second positional is
      the timeout; ``acquire(False)`` / ``blocking=False`` polls;
    - ``x.get()`` / ``x.get(True)`` / ``x.get(block=True)``: a
      non-bool positional means dict-style ``get(key)`` (exempt);
      ``block=False`` raises Empty instead of blocking.
    """
    kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
    if "timeout" in kwargs:
        return False
    meth = node.func.attr
    if meth in ("join", "wait"):
        return not node.args
    if meth == "acquire":
        if len(node.args) >= 2:
            return False  # positional timeout
        if node.args and _const_bool(node.args[0]) is False:
            return False
        if _const_bool(kwargs.get("blocking", ast.Constant(value=True))) is False:
            return False
        return True
    # queue-style get
    if node.args and _const_bool(node.args[0]) is not True:
        return False  # dict-style get(key) / non-blocking get(False)
    if _const_bool(kwargs.get("block", ast.Constant(value=True))) is False:
        return False
    return True


def _check_blocking_waits(mod: ModuleSource, findings: List[Finding]) -> None:
    """ROBUST002: untimed blocking waits in hot modules."""
    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in BLOCKING_WAIT_METHODS
            and _is_unbounded_wait(node)
        ):
            findings.append(
                mod.finding(
                    "ROBUST002",
                    SEV_WARNING,
                    node.lineno,
                    f".{node.func.attr}() without a timeout in a hot "
                    "module blocks the thread forever behind a wedged "
                    "device call (the watchdog cannot unwind an untimed "
                    "C wait) — bound it with timeout= in a retry loop, "
                    "or suppress with a justification",
                )
            )


# ROBUST003: write-capable open() modes. "r+" updates in place, "a"
# appends to the final file, "w"/"x" truncate/create it — all of them
# leave a torn file if the process dies mid-write.
_WRITE_MODE_RE = re.compile(r"[wax+]")


def _path_mentions_tmp(expr: ast.AST) -> bool:
    """True when the path expression visibly routes through a temp
    file: a name/attribute/string containing ``tmp``, or a call to a
    tempfile constructor. This is the atomic-write idiom's signature —
    the final name is only ever produced by ``os.replace``."""
    for n in walk_skipping(expr, (ast.FunctionDef, ast.Lambda)):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            if "tmp" in n.value.lower():
                return True
        elif isinstance(n, ast.Name) and "tmp" in n.id.lower():
            return True
        elif isinstance(n, ast.Attribute) and "tmp" in n.attr.lower():
            return True
        elif isinstance(n, ast.Call):
            chain = attr_chain(n.func)
            if chain and chain[-1] in (
                "mkstemp", "mktemp", "NamedTemporaryFile", "TemporaryFile"
            ):
                return True
    return False


def _check_state_writes(mod: ModuleSource, findings: List[Finding]) -> None:
    """ROBUST003: in-place state-file writes in hot modules."""
    for node in ast.walk(mod.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "open"
        ):
            continue
        mode = node.args[1] if len(node.args) >= 2 else next(
            (kw.value for kw in node.keywords if kw.arg == "mode"), None
        )
        if not (
            isinstance(mode, ast.Constant) and isinstance(mode.value, str)
        ):
            continue  # open(p) / dynamic mode: default "r" or unknowable
        if not _WRITE_MODE_RE.search(mode.value):
            continue
        if node.args and _path_mentions_tmp(node.args[0]):
            continue
        findings.append(
            mod.finding(
                "ROBUST003",
                SEV_WARNING,
                node.lineno,
                f"open(..., {mode.value!r}) writes the final path in "
                "place in a hot module — a crash mid-write leaves a "
                "torn file for the next restore; write a tmp sibling, "
                "fsync, then os.replace onto the final name",
            )
        )


# ---------------------------------------------------------------------------


def analyze_hotpath(mod: ModuleSource, graph=None) -> List[Finding]:
    """Run Family A over one module. TPU003 applies everywhere (jit
    closures are a correctness bug wherever they live); the rest only
    fire inside hot modules. With a call graph, TPU001 additionally
    follows device values one resolved call deep into helpers that
    host-pull them."""
    findings: List[Finding] = []
    imports = _Imports(mod.tree)
    _check_jit_globals(mod, imports, mod.tree, findings)
    if mod.is_hot():
        jit_names = _collect_jit_names(mod.tree, imports)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FuncTaint(mod, imports, jit_names, node, findings,
                           graph=graph)
                _check_loops(mod, imports, node, findings)
                if _is_refresh_marked(mod, node):
                    _RefreshPull(mod, imports, node, findings)
        _check_dtype_drift(mod, imports, mod.tree, findings)
        _check_broad_except(mod, findings)
        _check_blocking_waits(mod, findings)
        _check_state_writes(mod, findings)
    return findings
