"""Family B: lock-discipline rules.

The agent side of this codebase carries ~70 ``threading.Lock``s
(identity registry, kvstore, watchers, pipeline). Two failure modes
matter at fleet scale: lock-order inversions between modules (deadlock
under concurrent churn) and long blocking operations performed while a
lock is held (every verdict-serving thread convoys behind one disk
write). Both are invisible to the tier-1 tests, which are mostly
single-threaded.

Rules
-----
LOCK001  potential lock-order cycle: lock B is acquired while lock A
         is held on one path, and A while B on another (including
         one level through method calls). Error.
LOCK002  blocking operation (file I/O, subprocess, socket, sleep,
         block_until_ready) while a lock is held. Error.
LOCK003  invoking a stored callback/observer while a lock is held —
         the callee can acquire arbitrary locks or block, turning the
         caller's lock into an ordering hazard it cannot see. Warning.
LOCK004  guard inconsistency: an attribute mutated both under the
         class's lock and outside any lock (outside __init__) — the
         unguarded site races the guarded readers. Warning.

Lock model: ``with self._lock:`` blocks plus ``X.acquire()`` /
``X.release()`` pairs (held until the matching release in the same
suite, else to function end). Locks are recognized by construction
(``threading.Lock()`` etc.) or by name (``*lock*``, ``*mutex*``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
    ModuleSource,
    attr_chain,
    call_name,
    iter_target_names,
    walk_skipping,
)

_LOCKNAME_RE = re.compile(r"(^|_)(lock|mutex|mu)($|_)|lock$", re.IGNORECASE)

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}

# call-name patterns (matched against the dotted call chain) that block
BLOCKING_CALLS: Tuple[Tuple[str, str], ...] = (
    ("open", "file I/O"),
    ("os.open", "file I/O"),
    ("os.fsync", "file I/O"),
    ("os.replace", "file I/O"),
    ("os.rename", "file I/O"),
    ("subprocess.", "subprocess"),
    ("socket.", "socket"),
    ("time.sleep", "sleep"),
    ("requests.", "network I/O"),
    ("urllib.", "network I/O"),
    ("block_until_ready", "device sync"),
    ("jax.device_put", "device transfer"),
    ("shutil.", "file I/O"),
)
# method names on arbitrary receivers that block
BLOCKING_METHODS = {
    "recv": "socket", "recv_into": "socket", "sendall": "socket",
    "accept": "socket", "connect": "socket", "makefile": "socket",
    "block_until_ready": "device sync", "fsync": "file I/O",
    "communicate": "subprocess", "check_call": "subprocess",
    "check_output": "subprocess", "run": None,  # too generic: skip
}

# receiver-attribute name patterns whose *call* is a stored callback
_CALLBACK_ATTR_RE = re.compile(
    r"^(_?on_|.*callback|.*_cb$|.*observer|.*hook|.*handler)", re.IGNORECASE
)

MUTATOR_METHODS = {
    "append", "add", "pop", "popitem", "update", "setdefault", "clear",
    "remove", "extend", "insert", "discard", "appendleft",
}

# ubiquitous method names never resolved across classes (container
# methods would create bogus cross-class edges)
_GENERIC_METHODS = {
    "get", "set", "add", "pop", "items", "keys", "values", "update",
    "append", "remove", "close", "insert", "delete", "acquire",
    "release", "put", "send", "join", "start", "copy", "clear", "wait",
    "drain", "dump", "read", "write", "run", "stop", "next", "count",
}


def blocking_kind(node: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind, dotted call name) when ``node`` is a blocking operation
    per the LOCK002 vocabulary, else None. Shared with the call-graph
    effect summaries so caller-side propagation and direct findings
    can never disagree on what "blocking" means."""
    cn = call_name(node) or ""
    for pat, k in BLOCKING_CALLS:
        if pat.endswith("."):
            if cn.startswith(pat) or ("." + pat) in ("." + cn):
                return (k, cn)
        elif cn == pat or cn.endswith("." + pat):
            return (k, cn)
    if isinstance(node.func, ast.Attribute):
        k = BLOCKING_METHODS.get(node.func.attr)
        if k:
            return (k, cn or node.func.attr)
    return None


def _is_lock_expr(expr: ast.AST) -> Optional[str]:
    """Lock identity for a with-item / acquire receiver, or None.

    ``self.X`` → "self.X"; bare ``Name`` → "<name>"; anything else
    (e.g. ``backend._lock``) → dotted chain.
    """
    chain = attr_chain(expr)
    if not chain:
        return None
    leaf = chain[-1]
    if not _LOCKNAME_RE.search(leaf):
        return None
    return ".".join(chain)


class _ClassInfo:
    def __init__(self, mod: ModuleSource, node: ast.ClassDef) -> None:
        self.mod = mod
        self.node = node
        self.name = node.name
        self.qual = f"{mod.relpath}:{node.name}"
        self.lock_attrs: Set[str] = set()
        self.methods: Dict[str, ast.FunctionDef] = {}
        # method name -> set of lock node ids acquired anywhere in it
        self.method_acquires: Dict[str, Set[str]] = {}
        # callee method name -> [(caller method, locks held at the site)]
        self.call_sites: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
        # methods whose every call site holds a common lock (or named
        # *_locked): method -> locks assumed held on entry
        self.assumed_held: Dict[str, Tuple[str, ...]] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        for n in ast.walk(node):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    chain = attr_chain(t)
                    if (
                        chain
                        and len(chain) == 2
                        and chain[0] == "self"
                        and isinstance(n.value, ast.Call)
                    ):
                        cn = call_name(n.value) or ""
                        if cn.split(".")[-1] in LOCK_FACTORIES:
                            self.lock_attrs.add(chain[1])

    def lock_id(self, expr_id: str) -> str:
        """Canonical graph node for a lock expression in this class."""
        if expr_id.startswith("self."):
            return f"{self.qual}.{expr_id[5:]}"
        return f"{self.qual}.{expr_id}"


class LockIndex:
    """Package-wide view built in pass 1: which class methods acquire
    which locks (for one-level interprocedural edges)."""

    def __init__(self) -> None:
        self.classes: List[_ClassInfo] = []
        # method name -> [(classinfo, lock ids it acquires)]
        self.by_method: Dict[str, List[Tuple[_ClassInfo, Set[str]]]] = {}

    def add_module(self, mod: ModuleSource) -> None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = _ClassInfo(mod, node)
            self.classes.append(ci)
            for mname, mnode in ci.methods.items():
                acquires: Set[str] = set()
                for n in ast.walk(mnode):
                    if isinstance(n, (ast.With, ast.AsyncWith)):
                        for item in n.items:
                            lid = _is_lock_expr(item.context_expr)
                            if lid is not None:
                                acquires.add(ci.lock_id(lid))
                    elif (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "acquire"
                    ):
                        lid = _is_lock_expr(n.func.value)
                        if lid is not None:
                            acquires.add(ci.lock_id(lid))
                ci.method_acquires[mname] = acquires
                if acquires:
                    self.by_method.setdefault(mname, []).append(
                        (ci, acquires)
                    )
            # collect pass: record self.M() call sites with held locks
            # (findings/edges discarded — only call_sites matter here)
            for mname, mnode in ci.methods.items():
                _MethodWalk(mod, ci, self, mnode, [], [], [],
                            call_sites=ci.call_sites)

    def finalize(self) -> None:
        """Held-context fixpoint: a method is *assumed held* when it is
        named ``*_locked`` (and the class declares a lock), or every
        non-``__init__`` call site holds a common lock — directly or via
        an assumed-held caller. Bodies of assumed-held methods are then
        analyzed with that lock as entry context, so helpers invoked
        under the caller's lock neither raise bogus LOCK004s nor hide
        real blocking/callback findings."""
        for ci in self.classes:
            lock_ids = tuple(sorted(
                ci.lock_id(f"self.{a}") for a in ci.lock_attrs
            ))
            assumed = ci.assumed_held
            for mname in ci.methods:
                if mname.endswith("_locked") and lock_ids:
                    assumed[mname] = lock_ids
            changed = True
            while changed:
                changed = False
                for mname in ci.methods:
                    # only private helpers qualify via call sites:
                    # public methods can always be entered bare from
                    # outside the class
                    if (
                        mname in assumed
                        or not mname.startswith("_")
                        or mname.startswith("__")
                    ):
                        continue
                    sites = [
                        s for s in ci.call_sites.get(mname, ())
                        if s[0] != "__init__"
                    ]
                    if not sites:
                        continue
                    common: Optional[Set[str]] = None
                    for caller, held in sites:
                        eff = set(held) | set(assumed.get(caller, ()))
                        common = eff if common is None else common & eff
                        if not common:
                            break
                    if common:
                        assumed[mname] = tuple(sorted(common))
                        changed = True


class _Edge:
    __slots__ = ("src", "dst", "mod", "line", "where")

    def __init__(self, src, dst, mod, line, where):
        self.src, self.dst = src, dst
        self.mod, self.line, self.where = mod, line, where


class _MethodWalk:
    """Held-region walk over one method: emits LOCK002/LOCK003 findings
    and acquisition edges for the LOCK001 graph."""

    def __init__(
        self,
        mod: ModuleSource,
        ci: _ClassInfo,
        index: LockIndex,
        func: ast.FunctionDef,
        findings: List[Finding],
        edges: List[_Edge],
        mutations: List[Tuple[str, int, bool, str]],
        call_sites: Optional[
            Dict[str, List[Tuple[str, Tuple[str, ...]]]]
        ] = None,
        entry_held: Tuple[str, ...] = (),
        graph=None,
    ) -> None:
        self.mod = mod
        self.ci = ci
        self.index = index
        self.graph = graph
        self.func = func
        self.findings = findings
        self.edges = edges
        self.mutations = mutations  # (attr, line, held, method)
        self.call_sites = call_sites
        self.where = f"{ci.name}.{func.name}"
        if entry_held:
            self.where += " [called with lock held]"
        self._suite(func.body, entry_held)

    # ------------------------------------------------------------------
    def _suite(self, stmts: Sequence[ast.stmt], held: Tuple[str, ...]):
        i = 0
        while i < len(stmts):
            stmt = stmts[i]
            acq = self._acquire_stmt(stmt)
            if acq is not None:
                self._on_acquire(acq, held, stmt.lineno)
                # held until the matching release in this suite, else
                # to the end of the suite (coarse but safe)
                rel = self._find_release(stmts, i + 1, acq)
                inner = stmts[i + 1: rel if rel is not None else len(stmts)]
                self._suite(inner, held + (acq,))
                i = rel if rel is not None else len(stmts)
                continue
            self._stmt(stmt, held)
            i += 1

    def _acquire_stmt(self, stmt: ast.stmt) -> Optional[str]:
        """lock id when ``stmt`` is ``X.acquire()`` (expression stmt)."""
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "acquire"
        ):
            lid = _is_lock_expr(stmt.value.func.value)
            if lid is not None:
                return self.ci.lock_id(lid)
        return None

    def _find_release(
        self, stmts: Sequence[ast.stmt], start: int, lock_id: str
    ) -> Optional[int]:
        for j in range(start, len(stmts)):
            s = stmts[j]
            if (
                isinstance(s, ast.Expr)
                and isinstance(s.value, ast.Call)
                and isinstance(s.value.func, ast.Attribute)
                and s.value.func.attr == "release"
            ):
                lid = _is_lock_expr(s.value.func.value)
                if lid is not None and self.ci.lock_id(lid) == lock_id:
                    return j
        return None

    # ------------------------------------------------------------------
    def _stmt(self, stmt: ast.stmt, held: Tuple[str, ...]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs execute later, not under this hold
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new: List[str] = []
            for item in stmt.items:
                lid = _is_lock_expr(item.context_expr)
                if lid is not None:
                    full = self.ci.lock_id(lid)
                    self._on_acquire(full, held + tuple(new), stmt.lineno)
                    if full not in held:  # re-entrant (RLock) re-take
                        new.append(full)
                else:
                    self._expr(item.context_expr, held)
            self._record_mutations(stmt, held)
            for s in stmt.body:
                self._stmt(s, held + tuple(new))
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body + stmt.orelse + stmt.finalbody:
                self._stmt(s, held)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, held)
            self._record_mutation_target(stmt.target, stmt.lineno, held)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, held)
            return
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, held)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, held)
            return
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, held)
            for s in stmt.body + stmt.orelse:
                self._stmt(s, held)
            return
        # leaf statements: record mutations + scan expressions
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                self._record_mutation_target(t, stmt.lineno, held)
            self._expr(stmt.value, held)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            self._record_mutation_target(stmt.target, stmt.lineno, held)
            if stmt.value is not None:
                self._expr(stmt.value, held)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._record_mutation_target(t, stmt.lineno, held)
        elif isinstance(stmt, (ast.Expr, ast.Return)):
            if stmt.value is not None:
                self._expr(stmt.value, held)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._expr(stmt.exc, held)

    def _record_mutations(self, node: ast.AST, held) -> None:
        pass  # placeholder: with-items carry no mutations

    def _record_mutation_target(
        self, target: ast.AST, line: int, held
    ) -> None:
        attr = self._self_attr_of_target(target)
        if attr is not None:
            self.mutations.append(
                (attr, line, bool(held), self.func.name)
            )

    @staticmethod
    def _self_attr_of_target(target: ast.AST) -> Optional[str]:
        """self.A / self.A[...] / self.A.b assignment target → "A"."""
        node = target
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            parent = node
            node = node.value
            if (
                isinstance(node, ast.Name)
                and node.id == "self"
                and isinstance(parent, ast.Attribute)
            ):
                return parent.attr
        return None

    # ------------------------------------------------------------------
    def _on_acquire(
        self, lock_id: str, held: Tuple[str, ...], line: int
    ) -> None:
        for h in held:
            if h != lock_id:
                self.edges.append(
                    _Edge(h, lock_id, self.mod, line, self.where)
                )

    def _expr(self, expr: ast.AST, held: Tuple[str, ...]) -> None:
        for node in walk_skipping(
            expr, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            if not isinstance(node, ast.Call):
                continue
            if (
                self.call_sites is not None
                and isinstance(node.func, ast.Attribute)
                and attr_chain(node.func.value) == ["self"]
            ):
                self.call_sites.setdefault(node.func.attr, []).append(
                    (self.func.name, tuple(held))
                )
            if held:
                self._check_blocking(node, held)
                self._check_callback(node, held)
                self._check_cross_method(node, held)
            # container mutators on self attrs count as mutations
            # regardless of hold state (LOCK004 needs both sides)
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in MUTATOR_METHODS
            ):
                attr = self._self_attr_of_target(f.value)
                if attr is None:
                    chain = attr_chain(f.value)
                    if chain and chain[0] == "self" and len(chain) >= 2:
                        attr = chain[1]
                if attr is not None:
                    self.mutations.append(
                        (attr, node.lineno, bool(held), self.func.name)
                    )

    def _check_blocking(self, node: ast.Call, held) -> None:
        hit = blocking_kind(node)
        if hit is None:
            self._check_blocking_via_callee(node, held)
            return
        kind, cn = hit
        self.findings.append(
            self.mod.finding(
                "LOCK002",
                SEV_ERROR,
                node.lineno,
                f"{kind} call ({cn}) while holding "
                f"{', '.join(held)} in {self.where} — every thread "
                "contending on the lock convoys behind it; move the "
                "blocking work outside the critical section",
            )
        )

    def _check_blocking_via_callee(self, node: ast.Call, held) -> None:
        """Inter-procedural LOCK002, one call-graph edge deep: the call
        itself is innocuous but the resolved callee's body blocks.
        Same-class callees are skipped (the held-context fixpoint
        analyzes those bodies with the lock as entry state, so their
        blocking sites already report directly), as are callees that
        assume a lock held on entry for the same reason."""
        if self.graph is None:
            return
        callee = self.graph.resolved_callee(node)
        if callee is None or not callee.blocking or callee.held_on_entry:
            return
        if (
            callee.mod.path == self.mod.path
            and callee.cls_name == self.ci.name
        ):
            return
        line, kind, cn = callee.blocking[0]
        more = len(callee.blocking) - 1
        self.findings.append(
            self.mod.finding(
                "LOCK002",
                SEV_ERROR,
                node.lineno,
                f"call to {callee.display}() while holding "
                f"{', '.join(held)} in {self.where} — the callee "
                f"performs a {kind} call ({cn}) at "
                f"{callee.mod.relpath}:{line}"
                + (f" (+{more} more)" if more > 0 else "")
                + "; the block happens one call away — move the call "
                "outside the critical section or suppress with the "
                "invariant written out",
            )
        )

    def _check_callback(self, node: ast.Call, held) -> None:
        f = node.func
        name = None
        if isinstance(f, ast.Attribute):
            chain = attr_chain(f)
            if chain and chain[0] == "self" and _CALLBACK_ATTR_RE.match(
                f.attr
            ):
                name = f"self.{f.attr}"
        elif isinstance(f, ast.Name) and _CALLBACK_ATTR_RE.match(f.id):
            name = f.id
        elif isinstance(f, ast.Name):
            # loop variable over a callback-ish container:
            # ``for obs in self._observers: obs(...)``
            for anc in ast.walk(self.func):
                if (
                    isinstance(anc, (ast.For, ast.AsyncFor))
                    and isinstance(anc.target, ast.Name)
                    and anc.target.id == f.id
                ):
                    chain = attr_chain(anc.iter)
                    if chain and chain[0] == "self" and _CALLBACK_ATTR_RE.match(
                        chain[-1]
                    ):
                        name = f"{f.id} (from self.{chain[-1]})"
                        break
        if name is None:
            return
        self.findings.append(
            self.mod.finding(
                "LOCK003",
                SEV_WARNING,
                node.lineno,
                f"callback {name} invoked while holding "
                f"{', '.join(held)} in {self.where} — the callee can "
                "acquire arbitrary locks or block; snapshot under the "
                "lock, invoke after release (or document the ordering "
                "invariant in a suppression)",
            )
        )

    def _check_cross_method(self, node: ast.Call, held) -> None:
        """One-level interprocedural edges: calling a method that is
        known (by name, package-wide) to acquire locks."""
        f = node.func
        if not isinstance(f, ast.Attribute):
            return
        mname = f.attr
        if mname in _GENERIC_METHODS:
            return
        receiver = attr_chain(f.value)
        is_self_call = receiver == ["self"]
        targets: List[Tuple[_ClassInfo, Set[str]]] = []
        if is_self_call:
            acq = self.ci.method_acquires.get(mname)
            if acq:
                targets.append((self.ci, acq))
        else:
            targets = self.index.by_method.get(mname, [])
        for tci, acquires in targets:
            for lock in acquires:
                for h in held:
                    if h != lock:
                        self.edges.append(
                            _Edge(h, lock, self.mod, node.lineno,
                                  f"{self.where} via .{mname}()")
                        )


# ---------------------------------------------------------------------------


def _cycles(edges: List[_Edge]) -> List[List[_Edge]]:
    """Simple lock-order cycles (length 2..4) in the acquisition graph,
    deduped by node set. Returns one representative edge list each."""
    graph: Dict[str, Dict[str, _Edge]] = {}
    for e in edges:
        graph.setdefault(e.src, {}).setdefault(e.dst, e)
    out: List[List[_Edge]] = []
    seen: Set[frozenset] = set()

    def dfs(start: str, node: str, path: List[_Edge], depth: int):
        if depth > 4:
            return
        for dst, edge in graph.get(node, {}).items():
            if dst == start and path:
                key = frozenset(
                    [start] + [p.dst for p in path]
                )
                if key not in seen:
                    seen.add(key)
                    out.append(path + [edge])
            elif all(p.dst != dst for p in path) and dst != start:
                dfs(start, dst, path + [edge], depth + 1)

    for start in sorted(graph):
        dfs(start, start, [], 0)
    return out


def analyze_locks_module(
    mod: ModuleSource, index: LockIndex, graph=None
) -> Tuple[List[Finding], List[_Edge]]:
    """LOCK002/003/004 findings + acquisition edges for one module.
    With a call graph, LOCK002 additionally propagates one edge deep
    (a held-lock call into a callee whose body blocks)."""
    findings: List[Finding] = []
    edges: List[_Edge] = []
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        ci = next(
            (c for c in index.classes
             if c.mod.path == mod.path and c.node is cls),
            None,
        )
        if ci is None:
            ci = _ClassInfo(mod, cls)
        mutations: List[Tuple[str, int, bool, str]] = []
        for mname, mnode in ci.methods.items():
            _MethodWalk(
                mod, ci, index, mnode, findings, edges, mutations,
                entry_held=ci.assumed_held.get(mname, ()),
                graph=graph,
            )
        if ci.lock_attrs:
            _guard_inconsistency(mod, ci, mutations, findings)
    return findings, edges


def _guard_inconsistency(
    mod: ModuleSource,
    ci: _ClassInfo,
    mutations: List[Tuple[str, int, bool, str]],
    findings: List[Finding],
) -> None:
    """LOCK004: attr mutated both under a lock and bare."""
    by_attr: Dict[str, List[Tuple[int, bool, str]]] = {}
    for attr, line, held, method in mutations:
        if method == "__init__" or attr in ci.lock_attrs:
            continue
        by_attr.setdefault(attr, []).append((line, held, method))
    for attr, sites in sorted(by_attr.items()):
        guarded = [s for s in sites if s[1]]
        bare = [s for s in sites if not s[1]]
        if not guarded or not bare:
            continue
        line, _, method = min(bare)
        findings.append(
            mod.finding(
                "LOCK004",
                SEV_WARNING,
                line,
                f"{ci.name}.{attr} is mutated under a lock elsewhere "
                f"(e.g. {guarded[0][2]}:{guarded[0][0]}) but bare in "
                f"{method} — the unguarded write races guarded "
                "readers; take the lock or document why it's safe",
            )
        )


def cycle_findings(edges: List[_Edge]) -> List[Finding]:
    """LOCK001 findings from the package-wide acquisition graph. The
    finding anchors at the first edge's acquisition site (suppressing
    any edge site suppresses the cycle)."""
    out: List[Finding] = []
    for cyc in _cycles(edges):
        path = " -> ".join([cyc[0].src] + [e.dst for e in cyc])
        sites = "; ".join(
            f"{e.mod.relpath}:{e.line} ({e.where})" for e in cyc
        )
        first = cyc[0]
        f = first.mod.finding(
            "LOCK001",
            SEV_ERROR,
            first.line,
            f"potential lock-order cycle: {path} — acquisition sites: "
            f"{sites}; pick one order and enforce it (or suppress with "
            "the ordering invariant written out)",
        )
        # a suppression on ANY edge site kills the cycle finding
        if any(
            e.mod.is_suppressed("LOCK001", e.line) for e in cyc
        ):
            continue
        out.append(f)
    return out
