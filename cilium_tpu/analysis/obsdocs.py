"""OBS001: metric families must be documented in observe/README.md.

The observability surface (metrics.py families, /metrics exposition,
the observe/README.md catalogue operators read) drifts silently: a PR
adds ``registry.counter("cilium_tpu_new_total", ...)``, the dashboards
pick it up, and the README that explains what the family MEANS — and
what its cost model is — never learns the name. This rule pins the two
together: every family registered at module level must have its full
exposition name appear in the ``observe/README.md`` that lives next to
the registering module (for ``cilium_tpu/metrics.py`` that is
``cilium_tpu/observe/README.md``).

Rule
----
OBS001  a module-level ``registry.counter/gauge/histogram("name", ...)``
        call whose string-literal family name does not appear anywhere
        in the sibling ``observe/README.md`` (warning). A module that
        registers families but has no ``observe/README.md`` beside it
        flags every registration — the catalogue is part of shipping a
        family.

Only literal first arguments are checked: a computed name can't be
matched against prose, and the repo's registry idiom is literal-only.
Suppress a justified exception with ``# policyd-lint: disable=OBS001``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional

from .core import (
    SEV_WARNING,
    Finding,
    ModuleSource,
    call_name,
    walk_skipping,
)

_REGISTRY_METHODS = ("counter", "gauge", "histogram")

# per-analysis README text cache: every registering module in a
# directory shares one catalogue read
_readme_cache: Dict[str, Optional[str]] = {}


def _readme_text(module_path: str) -> Optional[str]:
    """Contents of the observe/README.md sibling to ``module_path``
    (None when absent). A module inside observe/ itself reads its own
    directory's README."""
    d = os.path.dirname(os.path.abspath(module_path))
    candidates = (
        os.path.join(d, "observe", "README.md"),
        os.path.join(d, "README.md") if os.path.basename(d) == "observe"
        else None,
    )
    for path in candidates:
        if path is None:
            continue
        if path not in _readme_cache:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    _readme_cache[path] = f.read()
            except OSError:
                _readme_cache[path] = None
        if _readme_cache[path] is not None:
            return _readme_cache[path]
    return None


def _family_name(node: ast.Call) -> Optional[str]:
    """The literal family name of a registry registration call, or
    None when the call is not one (or the name is computed)."""
    name = call_name(node)
    if name is None:
        return None
    parts = name.split(".")
    # registry.counter(...) or metrics.registry.counter(...)
    if parts[-1] not in _REGISTRY_METHODS or "registry" not in parts[:-1]:
        return None
    if not node.args:
        return None
    first = node.args[0]
    if isinstance(first, ast.Constant) and isinstance(first.value, str):
        return first.value
    return None


def analyze_obsdocs(mod: ModuleSource) -> List[Finding]:
    """Run OBS001 over one module's top-level statements. Registrations
    inside functions are runtime-scoped (tests, fixtures) and exempt."""
    regs: List[tuple] = []
    scoped = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    for stmt in mod.tree.body:
        if isinstance(stmt, scoped):
            continue
        for node in walk_skipping(stmt, scoped):
            if isinstance(node, ast.Call):
                fam = _family_name(node)
                if fam is not None:
                    regs.append((node.lineno, fam))
    if not regs:
        return []
    readme = _readme_text(mod.path)
    findings: List[Finding] = []
    for line, fam in regs:
        if readme is None:
            findings.append(mod.finding(
                "OBS001", SEV_WARNING, line,
                f"metric family {fam!r} registered but no "
                "observe/README.md exists beside this module to "
                "document it",
            ))
        elif fam not in readme:
            findings.append(mod.finding(
                "OBS001", SEV_WARNING, line,
                f"metric family {fam!r} is not documented in "
                "observe/README.md (add it to the metrics catalogue "
                "so the exposition and the operator docs can't drift)",
            ))
    return findings
