"""OBS003: journal event kinds are a closed vocabulary.

The lifecycle journal (observe/journal.py) is a fleet-wide wire
surface: per-node journals are merged into one timeline across nodes
running DIFFERENT commits, bugtool ``events.json`` archives are diffed
offline, and bench --chaos asserts against specific kinds. A kind
literal that drifts from ``cilium_tpu.contracts.JOURNAL_KINDS`` is
therefore worse than a typo — ``EventJournal.emit`` raises on it at
runtime, from INSIDE a lifecycle callback (quarantine, drain, watchdog
sweep), which is the worst possible place to discover a misspelling.

The package's emission convention makes the check static: every
journal emission passes ``kind="..."`` as a keyword argument to a
callable named ``emit`` / ``oj`` / ``on_journal`` / ``_journal_emit``
(the four shapes the hub-style one-attribute-read gate produces).

Rules
-----
OBS003  (error) an emission-shaped call — callee's terminal name in
        the convention set — passing a ``kind=`` string literal that
        is not a JOURNAL_KINDS row.
OBS003  (warning, reverse) a JOURNAL_KINDS row that NO emission site
        in the analyzed set references: a stale vocabulary entry
        consumers will wait on forever; remove the row or wire the
        emitter. Anchored at the table definition.

Suppress a justified exception with ``# policyd-lint:
disable=OBS003``.
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set, Tuple

from .contracts import _Canon
from .core import SEV_ERROR, SEV_WARNING, Finding, ModuleSource

# terminal callee names the journal emission convention uses: the
# journal method itself, the daemon's OFF-gated wrapper, and the two
# local-alias shapes hot modules read the hook into
_EMIT_NAMES = ("emit", "oj", "on_journal", "_journal_emit")


def _callee_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _kind_literal(call: ast.Call) -> Tuple[bool, str, int]:
    """(has_literal, value, lineno) of the call's ``kind=`` keyword."""
    for kw in call.keywords:
        if kw.arg == "kind" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return True, kw.value.value, kw.value.lineno
    return False, "", 0


def analyze_obsjournal(modules: Sequence[ModuleSource]) -> List[Finding]:
    """Run OBS003 over the analyzed set. Cross-file: the vocabulary
    resolves through the canonical-table machinery (a fixture package
    defining JOURNAL_KINDS in its own contracts.py stays
    self-contained), and the stale-row direction needs every emission
    site before it can call a row unreferenced."""
    canon = _Canon(modules)
    kinds = canon.get("JOURNAL_KINDS") or ()
    known = frozenset(kinds)
    findings: List[Finding] = []
    emitted: Set[str] = set()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if _callee_name(node) not in _EMIT_NAMES:
                continue
            has, value, line = _kind_literal(node)
            if not has:
                continue
            emitted.add(value)
            if known and value not in known:
                findings.append(mod.finding(
                    "OBS003", SEV_ERROR, line,
                    f"journal kind {value!r} is not in "
                    "contracts.JOURNAL_KINDS — EventJournal.emit "
                    "raises on it at runtime, inside a lifecycle "
                    "callback; fix the literal or add the row to the "
                    "canonical vocabulary",
                ))
    # reverse direction: vocabulary rows no emitter references rot —
    # only when the table is defined inside the analyzed set (same
    # containment rule the OPT001 stale-row check applies)
    if known and "JOURNAL_KINDS" in canon.sources:
        src_mod, src_line = canon.sources["JOURNAL_KINDS"]
        for kind in kinds:
            if kind not in emitted:
                findings.append(src_mod.finding(
                    "OBS003", SEV_WARNING, src_line,
                    f"JOURNAL_KINDS row {kind!r} has no emission site "
                    "(no kind= literal anywhere in the package) — "
                    "stale vocabulary row consumers will wait on "
                    "forever; remove it or wire the emitter",
                ))
    return findings
