"""OBS002: no unbounded runtime data interpolated into metric labels.

Prometheus-style label values are series keys: every distinct value
materializes a new child series that lives for the process lifetime.
A label value built by interpolating runtime data at a HOT call site
(``{"id": f"{identity}"}``, ``{"peer": str(addr)}``) therefore turns
an unbounded domain — identity ids, endpoint ids, addresses, ports —
into unbounded registry growth, and the /metrics exposition walk gets
slower every batch. The classic offenders all share one shape: an
f-string / ``str(...)`` / ``.format(...)`` / ``%`` expression as a
label VALUE in the dict passed to ``.inc/.set/.observe/.dec``.

Some interpolated labels are fine because their domain is bounded *by
construction* (a device ordinal is capped by the mesh complement, a
bucket rung by the ladder). Those label KEYS are declared once, in
``cilium_tpu.contracts.METRIC_BOUNDED_LABEL_KEYS`` — the canonical
allowed-label table — and exempt here. Everything else interpolated
into a label value in a hot module is a finding.

Rule
----
OBS002  in a hot module (``*/ops/*.py``, ``*/engine.py``,
        ``*/datapath/pipeline.py``, or ``# policyd: hot``), a metric
        mutation call (``.inc/.dec/.set/.observe``) passing a labels
        dict where some string-keyed value is an interpolation
        (f-string, ``str(...)``, ``.format(...)``, ``%`` formatting)
        and the key is not in METRIC_BOUNDED_LABEL_KEYS. Warning.

Only dict literals whose keys are all string constants are treated as
labels dicts (that is the repo's registry idiom); a computed labels
dict can't be judged statically. Suppress a justified exception with
``# policyd-lint: disable=OBS002``.
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Tuple

from .contracts import _Canon
from .core import SEV_WARNING, Finding, ModuleSource

_MUTATORS = ("inc", "dec", "set", "observe")


def _is_interpolation(expr: ast.AST) -> bool:
    """True for the value shapes that smuggle runtime data into a
    label: f-strings, str()/repr()/format()/hex() calls, .format()
    method calls, and %-formatting on a string literal."""
    if isinstance(expr, ast.JoinedStr):
        # an f-string with no substitution is just a literal
        return any(
            isinstance(v, ast.FormattedValue) for v in expr.values
        )
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Name) and expr.func.id in (
            "str", "repr", "format", "hex", "oct", "bin",
        ):
            return True
        if isinstance(expr.func, ast.Attribute) and expr.func.attr == "format":
            return True
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mod):
        left = expr.left
        return isinstance(left, ast.Constant) and isinstance(left.value, str)
    return False


def _labels_dict(call: ast.Call) -> Tuple[ast.Dict, ...]:
    """Dict-literal arguments whose keys are all string constants —
    the only shape the registry idiom passes as labels."""
    out = []
    exprs = list(call.args) + [kw.value for kw in call.keywords]
    for a in exprs:
        if isinstance(a, ast.Dict) and a.keys and all(
            isinstance(k, ast.Constant) and isinstance(k.value, str)
            for k in a.keys
        ):
            out.append(a)
    return tuple(out)


def analyze_obslabels(modules: Sequence[ModuleSource]) -> List[Finding]:
    """Run OBS002 over the analyzed set. Cross-file because the
    allowed-key table resolves through the canonical-table machinery
    (a fixture package defining METRIC_BOUNDED_LABEL_KEYS in its own
    contracts.py stays self-contained)."""
    canon = _Canon(modules)
    bounded = frozenset(canon.get("METRIC_BOUNDED_LABEL_KEYS") or ())
    findings: List[Finding] = []
    for mod in modules:
        if not mod.is_hot():
            continue
        for node in ast.walk(mod.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                continue
            for d in _labels_dict(node):
                for k, v in zip(d.keys, d.values):
                    if not _is_interpolation(v):
                        continue
                    key = k.value  # str constant per _labels_dict
                    if key in bounded:
                        continue
                    findings.append(mod.finding(
                        "OBS002", SEV_WARNING, v.lineno,
                        f"label {key!r} gets an interpolated runtime "
                        "value at a hot metric call site — every "
                        "distinct value becomes a permanent series "
                        "(cardinality explosion); use a bounded "
                        "vocabulary, or declare the key in "
                        "contracts.METRIC_BOUNDED_LABEL_KEYS if its "
                        "domain is bounded by construction",
                    ))
    return findings
