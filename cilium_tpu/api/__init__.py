"""REST surface package. Lazy exports: sidecar processes import
api.client / api.unixhttp without dragging in the daemon (and with it
JAX) through APIServer."""

__all__ = ["APIServer", "APIClient", "APIError"]


def __getattr__(name):
    if name == "APIServer":
        from .server import APIServer

        return APIServer
    if name in ("APIClient", "APIError"):
        from . import client

        return getattr(client, name)
    raise AttributeError(name)
