from .server import APIServer
from .client import APIClient, APIError

__all__ = ["APIServer", "APIClient", "APIError"]
