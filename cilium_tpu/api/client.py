"""Client for the unix-socket REST API (pkg/client analog)."""

from __future__ import annotations

import http.client
import json
import socket
from typing import Optional


class APIError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class _UnixConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float = 30.0) -> None:
        super().__init__("localhost", timeout=timeout)
        self._path = path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._path)
        self.sock = s


class APIClient:
    def __init__(self, socket_path: str, timeout: float = 30.0) -> None:
        self.socket_path = socket_path
        self.timeout = timeout

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        conn = _UnixConnection(self.socket_path, self.timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read().decode()
            ctype = resp.headers.get("Content-Type", "")
            data = json.loads(raw) if "json" in ctype else raw
            if resp.status >= 400:
                msg = data.get("error", raw) if isinstance(data, dict) else raw
                raise APIError(resp.status, msg)
            return data
        finally:
            conn.close()

    # -- typed wrappers -------------------------------------------------
    def status(self):
        return self._request("GET", "/status")

    def metrics(self) -> str:
        return self._request("GET", "/metrics")

    def policy_get(self):
        return self._request("GET", "/policy")

    def policy_put(self, rules: list):
        return self._request("PUT", "/policy", {"rules": rules})

    def policy_delete(self, labels: list):
        return self._request("DELETE", "/policy", {"labels": labels})

    def policy_resolve(self, src, dst, dports=(), *, ingress=True, verbose=False):
        return self._request("POST", "/policy/resolve", {
            "src": list(src), "dst": list(dst), "dports": list(dports),
            "ingress": ingress, "verbose": verbose,
        })

    def endpoint_list(self):
        return self._request("GET", "/endpoint")

    def endpoint_put(self, ep_id: int, labels, ipv4=None, ipv6=None):
        return self._request("PUT", f"/endpoint/{ep_id}", {
            "labels": list(labels), "ipv4": ipv4, "ipv6": ipv6,
        })

    def endpoint_delete(self, ep_id: int):
        return self._request("DELETE", f"/endpoint/{ep_id}")

    def policymap_get(self, ep_id: int, *, egress: bool = False):
        d = "egress" if egress else "ingress"
        return self._request("GET", f"/endpoint/{ep_id}/policymap?direction={d}")

    def identity_list(self):
        return self._request("GET", "/identity")

    def identity_get(self, num: int):
        return self._request("GET", f"/identity/{num}")

    def config_get(self):
        return self._request("GET", "/config")

    def config_patch(self, options: dict):
        return self._request("PATCH", "/config", {"options": options})

    def endpoint_config(self, ep_id: int, options: dict):
        return self._request(
            "PATCH", f"/endpoint/{ep_id}/config", {"options": options}
        )

    def map_dump(self, name: str):
        return self._request("GET", f"/map/{name}")

    def ipam_allocate(self, owner: str = ""):
        return self._request("POST", "/ipam", {"owner": owner})

    def ipam_release(self, ip: str):
        return self._request("DELETE", f"/ipam/{ip}")

    def health(self):
        return self._request("GET", "/health")

    def health_probe(self):
        return self._request("POST", "/health/probe")

    def debuginfo(self):
        return self._request("GET", "/debuginfo")

    def traces_get(self, limit: int = 16):
        return self._request("GET", f"/traces?limit={limit}")

    def profile_get(self):
        return self._request("GET", "/profile")

    def flows_get(self, limit: int = 64, *, verdict=None,
                  from_identity=None, reason=None):
        params = [f"limit={limit}"]
        if verdict is not None:
            params.append(f"verdict={verdict}")
        if from_identity is not None:
            params.append(f"from_identity={from_identity}")
        if reason is not None:
            params.append(f"reason={reason}")
        return self._request("GET", "/flows?" + "&".join(params))

    def policy_explain(self, src, dst, dport="", *, ingress=True):
        return self._request("POST", "/policy/explain", {
            "src": list(src), "dst": list(dst), "dport": dport,
            "ingress": ingress,
        })

    def fqdn_poll(self):
        return self._request("POST", "/fqdn/poll")

    def service_list(self):
        return self._request("GET", "/service")

    def service_put(self, frontend: dict, backends: list):
        return self._request(
            "PUT", "/service", {"frontend": frontend, "backends": backends}
        )

    def service_delete(self, frontend: dict):
        return self._request("DELETE", "/service", {"frontend": frontend})

    def prefilter_get(self):
        return self._request("GET", "/prefilter")

    def prefilter_patch(self, cidrs, revision=None):
        body = {"cidrs": list(cidrs)}
        if revision is not None:
            body["revision"] = revision
        return self._request("PATCH", "/prefilter", body)

    def prefilter_delete(self, cidrs, revision=None):
        body = {"cidrs": list(cidrs)}
        if revision is not None:
            body["revision"] = revision
        return self._request("DELETE", "/prefilter", body)

    def endpoint_get(self, ep_id: int):
        return self._request("GET", f"/endpoint/{ep_id}")

    def endpoint_regenerate(self, ep_id: Optional[int] = None):
        path = (f"/endpoint/{ep_id}/regenerate" if ep_id is not None
                else "/endpoint/regenerate")
        return self._request("POST", path)

    def endpoint_log(self, ep_id: int):
        return self._request("GET", f"/endpoint/{ep_id}/log")

    def endpoint_labels(self, ep_id: int, add=(), delete=()):
        return self._request("PATCH", f"/endpoint/{ep_id}/labels",
                             {"add": list(add), "delete": list(delete)})

    def map_list(self):
        return self._request("GET", "/map")

    def ct_flush(self):
        return self._request("POST", "/map/ct/flush")

    def node_list(self):
        return self._request("GET", "/node")

    def cluster_status(self):
        return self._request("GET", "/cluster")

    def fleet_status(self):
        return self._request("GET", "/fleet")

    def fleet_history(self, limit: int = 64):
        return self._request("GET", f"/fleet/history?limit={limit}")

    def fleet_timeline(self, limit: int = 256):
        return self._request("GET", f"/fleet/timeline?limit={limit}")

    def events_get(self, limit: int = 64, *, kind=None, severity=None,
                   since=None):
        params = [f"limit={limit}"]
        if kind is not None:
            params.append(f"kind={kind}")
        if severity is not None:
            params.append(f"severity={severity}")
        if since is not None:
            params.append(f"since={since}")
        return self._request("GET", "/events?" + "&".join(params))
