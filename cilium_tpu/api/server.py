"""REST API over a unix socket — the daemon's wire surface.

Re-design of the reference's swagger REST API
(/root/reference/api/v1/openapi.yaml, handler wiring
/root/reference/daemon/main.go:963-1035): same resource layout
(/healthz /policy /policy/resolve /endpoint /identity /metrics
/prefilter /status), JSON bodies, served over an AF_UNIX socket like
the reference's cilium.sock. Implemented on http.server — the daemon
is the backend, this layer only routes.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import urllib.parse
from typing import Optional

from ..daemon import Daemon
from .unixhttp import UnixHandler, UnixHTTPServer

_UnixHTTPServer = UnixHTTPServer  # serving scaffold shared with sidecars


class _Handler(UnixHandler):
    # -- helpers --------------------------------------------------------
    def _body(self):
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        return json.loads(raw.decode()) if raw else {}

    @property
    def d(self) -> Daemon:
        return self.server.daemon_obj  # type: ignore[attr-defined]

    def _route(self, method: str) -> None:
        parsed = urllib.parse.urlsplit(self.path)
        path = parsed.path.rstrip("/") or "/"
        q = urllib.parse.parse_qs(parsed.query)
        try:
            handled = self._dispatch(method, path, q)
        except (ValueError, KeyError) as e:
            self._json(400, {"error": str(e)})
            return
        except Exception as e:  # surface daemon errors as 500s
            self._json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if not handled:
            self._json(404, {"error": f"no route {method} {path}"})

    def _dispatch(self, method: str, path: str, q) -> bool:
        d = self.d
        if method == "GET" and path == "/healthz":
            self._json(200, d.status())
        elif method == "GET" and path == "/status":
            self._json(200, d.status())
        elif method == "GET" and path == "/metrics":
            self._text(200, d.metrics_text())
        elif path == "/policy" and method == "GET":
            self._json(200, d.policy_get(q.get("labels")))
        elif path == "/policy" and method == "PUT":
            body = self._body()
            self._json(200, d.policy_add(json.dumps(body["rules"])))
        elif path == "/policy" and method == "DELETE":
            body = self._body()
            self._json(200, d.policy_delete(body.get("labels", [])))
        elif path == "/policy/resolve" and method == "POST":
            body = self._body()
            self._json(200, d.policy_resolve(
                body.get("src", []), body.get("dst", []),
                body.get("dports", []),
                ingress=body.get("ingress", True),
                verbose=body.get("verbose", False),
            ))
        elif path == "/endpoint" and method == "GET":
            self._json(200, d.endpoint_list())
        elif (m := re.fullmatch(r"/endpoint/(\d+)", path)):
            ep_id = int(m.group(1))
            if method == "PUT":
                body = self._body()
                self._json(201, d.endpoint_add(
                    ep_id, body.get("labels", []),
                    ipv4=body.get("ipv4"), ipv6=body.get("ipv6"),
                    pod_name=body.get("pod_name", ""),
                ))
            elif method == "GET":
                model = d.endpoint_get(ep_id)
                if model is None:
                    self._json(404, {"error": f"endpoint {ep_id} not found"})
                else:
                    self._json(200, model)
            elif method == "DELETE":
                ok = d.endpoint_delete(ep_id)
                self._json(200 if ok else 404, {"deleted": ok})
            else:
                return False
        elif (m := re.fullmatch(r"/endpoint/(\d+)/regenerate", path)) and method == "POST":
            self._json(200, d.endpoint_regenerate(int(m.group(1))))
        elif path == "/endpoint/regenerate" and method == "POST":
            self._json(200, d.endpoint_regenerate())
        elif (m := re.fullmatch(r"/endpoint/(\d+)/log", path)) and method == "GET":
            ep_id = int(m.group(1))
            if d.endpoint_manager.lookup(ep_id) is None:
                self._json(404, {"error": f"endpoint {ep_id} not found"})
            else:
                self._json(200, d.endpoint_log(ep_id))
        elif (m := re.fullmatch(r"/endpoint/(\d+)/labels", path)) and method == "PATCH":
            body = self._body()
            self._json(200, d.endpoint_labels(
                int(m.group(1)),
                add=body.get("add", []), delete=body.get("delete", []),
            ))
        elif (m := re.fullmatch(r"/endpoint/(\d+)/policymap", path)) and method == "GET":
            ingress = q.get("direction", ["ingress"])[0] != "egress"
            self._json(200, d.policymap_dump(int(m.group(1)), ingress=ingress))
        elif path == "/identity" and method == "GET":
            self._json(200, d.identity_list())
        elif (m := re.fullmatch(r"/identity/(\d+)", path)) and method == "GET":
            ident = d.identity_get(int(m.group(1)))
            if ident is None:
                self._json(404, {"error": "identity not found"})
            else:
                self._json(200, ident)
        elif path == "/config" and method == "GET":
            self._json(200, d.config_get())
        elif path == "/config" and method == "PATCH":
            body = self._body()
            self._json(200, d.config_patch(body.get("options", {})))
        elif (m := re.fullmatch(r"/endpoint/(\d+)/config", path)) and method == "PATCH":
            ep_id = int(m.group(1))
            if d.endpoint_manager.lookup(ep_id) is None:
                self._json(404, {"error": f"endpoint {ep_id} not found"})
            else:
                body = self._body()
                self._json(200, d.endpoint_config(
                    ep_id, body.get("options", {})
                ))
        elif path == "/map" and method == "GET":
            self._json(200, d.map_list())
        elif path == "/map/ct/flush" and method == "POST":
            self._json(200, d.ct_flush())
        elif path == "/node" and method == "GET":
            self._json(200, d.node_list())
        elif path == "/cluster" and method == "GET":
            self._json(200, d.cluster_status())
        elif path == "/fleet" and method == "GET":
            self._json(200, d.fleet_status())
        elif path == "/fleet/history" and method == "GET":
            limit = int(q.get("limit", ["64"])[0])
            self._json(200, d.fleet_history(limit=limit))
        elif path == "/fleet/timeline" and method == "GET":
            limit = int(q.get("limit", ["256"])[0])
            self._json(200, d.fleet_timeline(limit=limit))
        elif path == "/events" and method == "GET":
            since = q.get("since", [None])[0]
            self._json(200, d.events(
                limit=int(q.get("limit", ["64"])[0]),
                kind=q.get("kind", [None])[0],
                severity=q.get("severity", [None])[0],
                since=float(since) if since is not None else None,
            ))
        elif (m := re.fullmatch(r"/map/(\w+)", path)) and method == "GET":
            self._json(200, d.map_dump(m.group(1)))
        elif path == "/ipam" and method == "POST":
            body = self._body() if self.headers.get("Content-Length") else {}
            ip = d.ipam.allocate_next(owner=body.get("owner", ""))
            self._json(201, {"ip": ip, "cidr": str(d.ipam.net)})
        elif (m := re.fullmatch(r"/ipam/(.+)", path)) and method == "DELETE":
            ok = d.ipam.release(m.group(1))
            self._json(200 if ok else 404, {"released": ok})
        elif path == "/health" and method == "GET":
            self._json(200, d.health_report())
        elif path == "/health/probe" and method == "POST":
            self._json(200, d.health_probe_now())
        elif path == "/debuginfo" and method == "GET":
            self._json(200, d.debuginfo())
        elif path == "/traces" and method == "GET":
            limit = int(q.get("limit", ["16"])[0])
            self._json(200, d.traces(limit=limit))
        elif path == "/profile" and method == "GET":
            self._json(200, d.profile())
        elif path == "/flows" and method == "GET":
            def _opt(name):
                return int(q[name][0]) if name in q else None
            self._json(200, d.flows(
                limit=int(q.get("limit", ["64"])[0]),
                verdict=_opt("verdict"),
                from_identity=_opt("from_identity"),
                reason=_opt("reason"),
            ))
        elif path == "/policy/explain" and method == "POST":
            body = self._body()
            self._json(200, d.policy_explain(
                body.get("src", []), body.get("dst", []),
                body.get("dport", ""),
                ingress=body.get("ingress", True),
            ))
        elif path == "/fqdn/poll" and method == "POST":
            self._json(200, d.fqdn_poll())
        elif path == "/service" and method == "GET":
            self._json(200, d.service_list())
        elif path == "/service" and method == "PUT":
            body = self._body()
            self._json(201, d.service_upsert(
                body["frontend"], body.get("backends", [])
            ))
        elif path == "/service" and method == "DELETE":
            body = self._body()
            ok = d.service_delete(body["frontend"])
            self._json(200 if ok else 404, {"deleted": ok})
        elif path == "/prefilter" and method == "GET":
            rev, cidrs = d.prefilter.dump()
            self._json(200, {"revision": rev, "cidrs": cidrs})
        elif path == "/prefilter" and method == "PATCH":
            body = self._body()
            rev = d.prefilter.insert(
                body.get("revision", d.prefilter.revision),
                body.get("cidrs", []),
            )
            self._json(200, {"revision": rev})
        elif path == "/prefilter" and method == "DELETE":
            body = self._body()
            rev = d.prefilter.delete(
                body.get("revision", d.prefilter.revision),
                body.get("cidrs", []),
            )
            self._json(200, {"revision": rev})
        else:
            return False
        return True

    def do_GET(self):
        self._route("GET")

    def do_PUT(self):
        self._route("PUT")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")

    def do_PATCH(self):
        self._route("PATCH")


class APIServer:
    """Serves a Daemon on a unix socket (cilium.sock role)."""

    def __init__(self, daemon: Daemon, socket_path: str) -> None:
        self.daemon = daemon
        self.socket_path = socket_path
        self._server = _UnixHTTPServer(socket_path, _Handler)
        self._server.daemon_obj = daemon  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def serve_forever(self) -> None:
        self._server.serve_forever()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
