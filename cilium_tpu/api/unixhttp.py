"""Shared AF_UNIX HTTP serving scaffold.

One implementation of the unix-socket HTTP server + handler base the
agent's REST API (api/server.py) and the health sidecar's API
(health/standalone.py) both serve on — the cilium.sock /
cilium-health.sock convention of the reference. Kept free of daemon
imports so sidecar processes can use it without pulling in JAX."""

from __future__ import annotations

import json
import os
import socket
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class UnixHTTPServer(ThreadingHTTPServer):
    address_family = socket.AF_UNIX
    daemon_threads = True
    allow_reuse_address = False

    def server_bind(self):
        path = self.server_address
        if isinstance(path, str) and os.path.exists(path):
            os.unlink(path)
        self.socket.bind(path)

    def server_activate(self):
        self.socket.listen(64)


class UnixHandler(BaseHTTPRequestHandler):
    """Handler base: unix-peer address, quiet logs, JSON/text replies."""

    # BaseHTTPRequestHandler assumes AF_INET client addresses
    def address_string(self) -> str:
        return "unix"

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _json(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, code: int, text: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
