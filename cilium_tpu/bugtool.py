"""bugtool / debuginfo: one-shot state capture for support.

Reference: bugtool/ (cilium-bugtool archives `cilium status`, map
dumps, logs, sysctl) and the /debuginfo REST endpoint
(daemon/debuginfo.go). Here the capture walks the daemon object:
status, policy rules, endpoints + realized policymaps, identities,
services, ipcache, prefilter, conntrack summary, health report,
metrics text, and recent L7 access logs — everything an operator
needs to reconstruct verdict behavior offline.
"""

from __future__ import annotations

import io
import json
import tarfile
import time
from typing import Dict

# Every bundled JSON artifact that external tooling diffs offline
# carries a top-level identification header: ``schema`` (the artifact
# vocabulary, versioned independently of the package) + ``generated_at``
# (one capture-wide wall timestamp — all artifacts of an archive stamp
# the SAME instant, so cross-artifact joins don't skew).
ARTIFACT_SCHEMAS: Dict[str, str] = {
    "traces": "cilium-tpu/traces/v1",
    "flows": "cilium-tpu/flows/v1",
    "profile": "cilium-tpu/profile/v1",
    "fleet": "cilium-tpu/fleet/v1",
    "ct": "cilium-tpu/ct/v1",
    "cluster": "cilium-tpu/cluster/v1",
    "events": "cilium-tpu/events/v1",
}


def _stamp(key: str, payload: Dict, ts: float) -> Dict:
    out = {"schema": ARTIFACT_SCHEMAS[key], "generated_at": ts}
    out.update(payload)
    return out


def collect_debuginfo(daemon) -> Dict:
    """The GET /debuginfo payload (daemon/debuginfo.go)."""
    ipcache = {
        cidr: {"identity": e.identity, "source": e.source,
               "host_ip": e.host_ip}
        for cidr, e in daemon.ipcache.items()
    }
    pf_rev, pf_cidrs = daemon.prefilter.dump()
    endpoints = daemon.endpoint_list()
    policymaps = {}
    for em in endpoints:
        eid = em["id"]
        try:
            policymaps[eid] = {
                "ingress": daemon.policymap_dump(eid, ingress=True),
                "egress": daemon.policymap_dump(eid, ingress=False),
            }
        except Exception as e:  # a broken endpoint must not kill capture
            policymaps[eid] = {"error": f"{type(e).__name__}: {e}"}
    ct = daemon.conntrack
    now = time.time()
    return {
        "timestamp": now,
        "status": daemon.status(),
        "policy": daemon.policy_get(),
        "endpoints": endpoints,
        "policymaps": policymaps,
        "identities": daemon.identity_list(),
        "services": daemon.service_list(),
        "ipcache": ipcache,
        "prefilter": {"revision": pf_rev, "cidrs": pf_cidrs},
        "conntrack": {
            "entries": len(ct) if ct is not None else 0,
            "capacity": ct.capacity if ct is not None else 0,
        },
        # policyd-survive → ct.json: continuity evidence — live table
        # summary plus the provenance of the last restart's CT restore
        # (where it loaded from, snapshot age, kept vs flushed), so an
        # operator can tell a warm restart from a forced cold flush
        "ct": _stamp("ct", {
            "entries": len(ct) if ct is not None else 0,
            "capacity": ct.capacity if ct is not None else 0,
            "version": ct.version if ct is not None else 0,
            "sample": daemon.ct_dump()[:32],
            "restore": daemon.ct_restore_info(),
        }, now),
        "fqdn": {
            "names": daemon.fqdn.tracked_names(),
            "failures": daemon.fqdn.failures,
        },
        "health": daemon.health.report(),
        # policyd-fed → cluster.json: federation membership, per-node
        # published policy epochs, and identity-allocator accounting
        "cluster": _stamp("cluster", daemon.cluster_status(), now),
        # policyd-fleetobs → fleet.json: the aggregated telemetry
        # scoreboard ({"enabled": false} when FleetTelemetry is off)
        "fleet": _stamp("fleet", daemon.fleet_status(), now),
        "accesslog": [r.to_dict() for r in daemon.proxy.accesslog.recent(200)],
        # policyd-trace ring (metrics.prom in the archive carries the
        # matching /metrics snapshot via write_archive_from)
        "traces": _stamp("traces", daemon.traces(limit=64), now),
        # policyd-flows ring → flows.json in the archive: the sampled
        # attributed flows an operator replays offline against
        # policy.json to explain each verdict
        "flows": _stamp("flows", daemon.flows(limit=64), now),
        # policyd-prof → profile.json: sampled RTT decomposition +
        # memory/transfer ledgers, so offline bundles carry the full
        # telemetry surface
        "profile": _stamp("profile", daemon.profile(), now),
        # policyd-journal → events.json: the lifecycle event journal
        # tail ({"enabled": false} while LifecycleJournal is off), the
        # causal spine an operator lines the other artifacts up against
        "events": _stamp("events", daemon.events(limit=256), now),
        # raw Prometheus exposition IN the payload: a remote
        # /debuginfo fetch then archives the same metrics.prom a
        # live-daemon capture gets (write_archive_from pops this key)
        "metrics": daemon.metrics_text(),
    }


def write_archive(daemon, path: str) -> str:
    """cilium-bugtool against a live in-process daemon."""
    return write_archive_from(collect_debuginfo(daemon),
                              daemon.metrics_text(), path)


def write_archive_from(info: Dict, metrics_text: str, path: str) -> str:
    """cilium-bugtool: write a tar.gz of per-subsystem JSON files plus
    the raw Prometheus metrics text. Accepts the /debuginfo payload so
    the CLI can archive a REMOTE daemon over REST. Returns the path."""
    info = dict(info)
    # the payload's own exposition text (remote captures) becomes
    # metrics.prom, not a JSON-encoded metrics.json; an explicit
    # metrics_text (live-daemon capture) wins
    payload_metrics = info.pop("metrics", None)
    members = {f"{key}.json": json.dumps(value, indent=1, default=str)
               for key, value in info.items()}
    members["metrics.prom"] = metrics_text or payload_metrics or ""
    with tarfile.open(path, "w:gz") as tar:
        for name, text in sorted(members.items()):
            data = text.encode()
            ti = tarfile.TarInfo(name=f"cilium-tpu-bugtool/{name}")
            ti.size = len(data)
            ti.mtime = int(time.time())
            tar.addfile(ti, io.BytesIO(data))
    return path
