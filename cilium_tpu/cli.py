"""CLI — the `cilium` command surface (reference: /root/reference/
cilium/cmd, 73 cobra commands; this implements the core operational
set: policy import/get/delete/trace, endpoint list/add/delete,
identity get/list, bpf policy get, prefilter, status, metrics, daemon).

Two modes, decided per invocation:

- **daemon mode**: if the API socket exists (``--socket`` /
  ``$CILIUM_TPU_SOCK``), commands go over REST like the reference CLI
  talks to cilium-agent.
- **standalone mode**: otherwise an in-process Daemon is constructed
  over the state dir (``--state`` / ``$CILIUM_TPU_STATE``), so `policy
  trace` works offline against imported policy — the offline-verdict
  flow of cilium/cmd/policy_trace.go.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
from typing import List, Optional

DEFAULT_SOCK = os.environ.get("CILIUM_TPU_SOCK", "/tmp/cilium_tpu.sock")
DEFAULT_STATE = os.environ.get(
    "CILIUM_TPU_STATE", os.path.expanduser("~/.cilium_tpu")
)


class _Surface:
    """Uniform facade over APIClient (daemon mode) or Daemon
    (standalone)."""

    def __init__(self, socket_path: str, state_dir: str) -> None:
        self._client = None
        self._daemon = None
        if os.path.exists(socket_path):
            from .api.client import APIClient

            self._client = APIClient(socket_path)
        else:
            from .daemon import Daemon

            self._daemon = Daemon(state_dir=state_dir)

    def __getattr__(self, name):
        if self._client is not None:
            return getattr(self._client, name)
        return getattr(self, "_d_" + name)

    # -- standalone adapters (mirror APIClient's surface) ---------------
    def _d_status(self):
        return self._daemon.status()

    def _d_metrics(self):
        return self._daemon.metrics_text()

    def _d_policy_get(self):
        return self._daemon.policy_get()

    def _d_policy_put(self, rules):
        return self._daemon.policy_add(json.dumps(rules))

    def _d_policy_delete(self, labels):
        return self._daemon.policy_delete(labels)

    def _d_policy_resolve(self, src, dst, dports=(), *, ingress=True, verbose=False):
        return self._daemon.policy_resolve(
            src, dst, dports, ingress=ingress, verbose=verbose
        )

    def _d_endpoint_list(self):
        return self._daemon.endpoint_list()

    def _d_endpoint_put(self, ep_id, labels, ipv4=None, ipv6=None):
        return self._daemon.endpoint_add(ep_id, labels, ipv4=ipv4, ipv6=ipv6)

    def _d_endpoint_delete(self, ep_id):
        return {"deleted": self._daemon.endpoint_delete(ep_id)}

    def _d_policymap_get(self, ep_id, *, egress=False):
        return self._daemon.policymap_dump(ep_id, ingress=not egress)

    def _d_identity_list(self):
        return self._daemon.identity_list()

    def _d_identity_get(self, num):
        out = self._daemon.identity_get(num)
        if out is None:
            raise SystemExit(f"identity {num} not found")
        return out

    def _d_health(self):
        return self._daemon.health_report()

    def _d_health_probe(self):
        return self._daemon.health_probe_now()

    def _d_debuginfo(self):
        return self._daemon.debuginfo()

    def _d_traces_get(self, limit=16):
        return self._daemon.traces(limit=limit)

    def _d_profile_get(self):
        return self._daemon.profile()

    def _d_flows_get(self, limit=64, *, verdict=None,
                     from_identity=None, reason=None):
        return self._daemon.flows(
            limit=limit, verdict=verdict,
            from_identity=from_identity, reason=reason,
        )

    def _d_policy_explain(self, src, dst, dport="", *, ingress=True):
        return self._daemon.policy_explain(src, dst, dport,
                                           ingress=ingress)

    def _d_config_get(self):
        return self._daemon.config_get()

    def _d_config_patch(self, options):
        return self._daemon.config_patch(options)

    def _d_endpoint_config(self, ep_id, options):
        return self._daemon.endpoint_config(ep_id, options)

    def _d_map_dump(self, name):
        return self._daemon.map_dump(name)

    def _d_service_list(self):
        return self._daemon.service_list()

    def _d_service_put(self, frontend, backends):
        return self._daemon.service_upsert(frontend, backends)

    def _d_service_delete(self, frontend):
        return {"deleted": self._daemon.service_delete(frontend)}

    def _d_prefilter_get(self):
        rev, cidrs = self._daemon.prefilter.dump()
        return {"revision": rev, "cidrs": cidrs}

    def _d_prefilter_patch(self, cidrs, revision=None):
        rev = self._daemon.prefilter.insert(
            revision if revision is not None
            else self._daemon.prefilter.revision,
            cidrs,
        )
        return {"revision": rev}

    def _d_prefilter_delete(self, cidrs, revision=None):
        rev = self._daemon.prefilter.delete(
            revision if revision is not None
            else self._daemon.prefilter.revision,
            cidrs,
        )
        return {"revision": rev}

    def _d_endpoint_get(self, ep_id):
        out = self._daemon.endpoint_get(ep_id)
        if out is None:
            raise SystemExit(f"endpoint {ep_id} not found")
        return out

    def _d_endpoint_regenerate(self, ep_id=None):
        try:
            return self._daemon.endpoint_regenerate(ep_id)
        except ValueError as e:
            raise SystemExit(str(e)) from None

    def _d_endpoint_log(self, ep_id):
        try:
            return self._daemon.endpoint_log(ep_id)
        except ValueError as e:
            raise SystemExit(str(e)) from None

    def _d_endpoint_labels(self, ep_id, add=(), delete=()):
        try:
            return self._daemon.endpoint_labels(ep_id, add=add, delete=delete)
        except ValueError as e:
            raise SystemExit(str(e)) from None

    def _d_map_list(self):
        return self._daemon.map_list()

    def _d_ct_flush(self):
        return self._daemon.ct_flush()

    def _d_node_list(self):
        return self._daemon.node_list()

    def _d_cluster_status(self):
        return self._daemon.cluster_status()

    def _d_fleet_status(self):
        return self._daemon.fleet_status()

    def _d_fleet_history(self, limit=64):
        return self._daemon.fleet_history(limit=limit)

    def _d_fleet_timeline(self, limit=256):
        return self._daemon.fleet_timeline(limit=limit)

    def _d_events_get(self, limit=64, *, kind=None, severity=None,
                      since=None):
        return self._daemon.events(
            limit=limit, kind=kind, severity=severity, since=since
        )


def _parse_frontend(text: str) -> dict:
    """'10.96.0.10:80/TCP' → frontend dict (cilium service update
    --frontend format, cilium/cmd/service_update.go)."""
    from .lb.service import L3n4Addr

    fe = L3n4Addr.from_string(text)
    return {"ip": fe.ip, "port": fe.port, "protocol": fe.protocol}


def _parse_backend(text: str) -> dict:
    """'10.0.0.3:8080[@weight]' → backend dict."""
    weight = 1
    if "@" in text:
        text, w = text.rsplit("@", 1)
        weight = int(w)
    ip, port = text.rsplit(":", 1)
    return {"ip": ip.strip("[]"), "port": int(port), "weight": weight}


def _print(obj) -> None:
    if isinstance(obj, str):
        print(obj, end="" if obj.endswith("\n") else "\n")
    else:
        print(json.dumps(obj, indent=2))


def _print_journal_lines(events, *, with_node=False) -> None:
    """One line per lifecycle event: wall time, severity, kind, attrs
    (`cilium-tpu events` / `fleet timeline` shared renderer)."""
    import datetime as _dt

    for ev in events:
        ts = _dt.datetime.fromtimestamp(ev["wall_ts"])
        node = f"{ev.get('node', '-'):<12} " if with_node else ""
        attrs = ev.get("attrs") or {}
        rest = " ".join(
            f"{k}={json.dumps(attrs[k])}" for k in sorted(attrs)
        )
        print(
            f"{ts:%H:%M:%S}.{ts.microsecond // 1000:03d} "
            f"{ev['severity']:<8} {node}{ev['kind']:<15} {rest}"
        )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cilium-tpu", description="TPU-native policy framework CLI"
    )
    p.add_argument("--socket", default=DEFAULT_SOCK,
                   help="daemon API socket (used when it exists)")
    p.add_argument("--state", default=DEFAULT_STATE,
                   help="state dir for standalone mode")
    sub = p.add_subparsers(dest="cmd", required=True)

    hl = sub.add_parser("health", help="node connectivity status")
    hl.add_argument("--probe", action="store_true",
                    help="run an immediate probe sweep first")
    hl.add_argument("--sidecar", action="store_true",
                    help="query the standalone health-endpoint process "
                         "(<socket>.health — the cilium-health CLI role) "
                         "instead of the agent's in-process prober")

    bt = sub.add_parser("bugtool", help="archive daemon state for support")
    bt.add_argument("--output", default="",
                    help="archive path (default: cilium-tpu-bugtool-<ts>.tar.gz)")

    mon = sub.add_parser("monitor", help="stream datapath/agent events")
    mon.add_argument("--json", action="store_true", help="print raw events")
    mon.add_argument("--type", action="append", default=None,
                     dest="types", metavar="TYPE",
                     choices=["drop", "trace", "agent", "l7", "capture",
                              "trace-summary"],
                     help="only these event types (repeatable; "
                          "cilium monitor --type)")

    trc = sub.add_parser(
        "traces", help="print recent verdict-batch phase waterfalls"
    )
    trc.add_argument("-n", "--last", type=int, default=5,
                     help="how many traces to show (default 5)")
    trc.add_argument("--json", action="store_true",
                     help="raw trace dicts instead of waterfalls")
    mon.add_argument("--timeout", type=float, default=None,
                     help="stop after N idle seconds (default: run forever)")

    top = sub.add_parser(
        "top", help="device-time profile: sampled RTT split, jit cost "
                    "ledger, device memory + transfer ledgers"
    )
    top.add_argument("--json", action="store_true",
                     help="raw profile dict instead of the summary view")

    flw = sub.add_parser(
        "flows", help="print sampled attributed flows (policyd-flows)"
    )
    flw.add_argument("-n", "--last", type=int, default=20,
                     help="how many flows to show (default 20)")
    flw.add_argument("--verdict", default=None,
                     choices=["forwarded", "drop", "drop-policy",
                              "drop-prefilter", "drop-no-service"],
                     help="only flows with this outcome ('drop' = any "
                          "drop reason)")
    flw.add_argument("--from-identity", type=int, default=None,
                     help="only flows whose source is this numeric "
                          "identity")
    flw.add_argument("--json", action="store_true",
                     help="raw flow dicts instead of one-liners")

    # policyd-journal: the causally-ordered lifecycle event journal
    evt = sub.add_parser(
        "events", help="lifecycle event journal (policyd-journal)"
    )
    evt.add_argument("-n", "--last", type=int, default=20,
                     help="how many events to show (default 20)")
    evt.add_argument("--kind", default=None,
                     help="only this event kind (contracts.JOURNAL_KINDS)")
    evt.add_argument("--severity", default=None,
                     choices=["info", "warning", "error"],
                     help="only this severity")
    evt.add_argument("--json", action="store_true",
                     help="raw event dicts instead of one-liners")

    # daemon
    d = sub.add_parser("daemon", help="run the agent + API server")
    d.add_argument("--no-conntrack", action="store_true")
    d.add_argument("--join", default=None, metavar="KVSTORE",
                   help="join a cluster via a shared kvstore: a SQLite "
                        "path (all agents on one host pass the same "
                        "file) or tcp://host:port[,tcp://h2:p2,...] of "
                        "`kvstore serve` servers (first reachable "
                        "endpoint wins; rejoin retries the list)")
    d.add_argument("--node-name", default=None,
                   help="cluster node name (default: hostname)")
    d.add_argument("--node-ip", default=None,
                   help="this node's reachable address (tunnel endpoint)")
    d.add_argument("--cluster", default="default")
    d.add_argument("--pod-cidr", default="10.200.0.0/16")
    d.add_argument("--sync-interval", type=float, default=1.0,
                   help="cluster pump interval in seconds")
    d.add_argument("--launch-proxy", action="store_true",
                   help="spawn + supervise the external L7 proxy "
                        "process (python -m cilium_tpu.proxy)")
    d.add_argument("--launch-health", action="store_true",
                   help="spawn + supervise the per-node health endpoint "
                        "process (python -m cilium_tpu.health, the "
                        "cilium-health sidecar)")
    d.add_argument("--launch-monitor", action="store_true",
                   help="run the node monitor as its own supervised "
                        "process (python -m cilium_tpu.monitor) so event "
                        "streaming survives agent stalls "
                        "(cilium-node-monitor role)")
    d.add_argument("--health-port", type=int, default=0,
                   help="health responder port (0 = ephemeral; the "
                        "reference's fixed port is 4240)")
    d.add_argument("--k8s-api", default=None, metavar="URL",
                   help="apiserver base URL: LIST + WATCH NetworkPolicy/"
                        "CNP/Service/Endpoints/Pod/Namespace and apply "
                        "them (pkg/k8s client + informer loop)")
    d.add_argument("--k8s-token-file", default=None,
                   help="bearer-token file for --k8s-api (the in-cluster "
                        "ServiceAccount pattern)")
    d.add_argument("--cri", default=None, metavar="TARGET",
                   help="CRI runtime endpoint to watch for containers "
                        "(containerd/cri-o socket, e.g. "
                        "unix:///run/containerd/containerd.sock); starts "
                        "the PLEG event loop (pkg/workloads role)")
    d.add_argument("--cri-interval", type=float, default=5.0,
                   help="CRI poll interval in seconds")

    # status / metrics
    st = sub.add_parser("status", help="agent status")
    st.add_argument("--all-controllers", action="store_true",
                    help="show only the background controller table")
    sub.add_parser("metrics", help="Prometheus metrics dump")

    # policy
    pol = sub.add_parser("policy", help="policy operations").add_subparsers(
        dest="sub", required=True
    )
    imp = pol.add_parser("import", help="import rules from a JSON file")
    imp.add_argument("file", help="rules JSON file ('-' = stdin)")
    pol.add_parser("get", help="dump the repository")
    dele = pol.add_parser("delete", help="delete rules by label")
    dele.add_argument("labels", nargs="+", help="labels, e.g. k8s:policy=x")
    val = pol.add_parser("validate", help="sanitize a rules file")
    val.add_argument("file", help="rules JSON ('-' = stdin)")
    pw = pol.add_parser("wait", help="wait until the repository reaches a revision")
    pw.add_argument("revision", type=int)
    pw.add_argument("--timeout", type=float, default=30.0)
    tr = pol.add_parser("trace", help="offline verdict + trace log")
    tr.add_argument("-s", "--src", action="append", default=[],
                    help="source label (repeatable)")
    tr.add_argument("-d", "--dst", action="append", default=[],
                    help="destination label (repeatable)")
    tr.add_argument("--src-identity", type=int, default=None,
                    help="resolve source labels from a numeric identity")
    tr.add_argument("--dst-identity", type=int, default=None)
    tr.add_argument("--src-endpoint", type=int, default=None,
                    help="resolve source labels from an endpoint id")
    tr.add_argument("--dst-endpoint", type=int, default=None)
    tr.add_argument("--dport", action="append", default=[],
                    help="destination port 'port[/proto]' (repeatable)")
    tr.add_argument("--egress", action="store_true",
                    help="trace the egress direction")
    tr.add_argument("-v", "--verbose", action="store_true")
    ex = pol.add_parser(
        "explain",
        help="replay ONE flow through the device verdict kernel and "
             "name the deciding rule + drop reason (policyd-flows)",
    )
    ex.add_argument("-s", "--src", action="append", default=[],
                    help="source label (repeatable)")
    ex.add_argument("-d", "--dst", action="append", default=[],
                    help="destination label (repeatable)")
    ex.add_argument("--dport", default="",
                    help="destination port 'port[/proto]' (omit for an "
                         "L3-only flow)")
    ex.add_argument("--egress", action="store_true",
                    help="explain the egress direction")
    ex.add_argument("--json", action="store_true")

    # endpoint
    ep = sub.add_parser("endpoint", help="endpoint operations").add_subparsers(
        dest="sub", required=True
    )
    ep.add_parser("list", help="list endpoints")
    epa = ep.add_parser("add", help="create an endpoint")
    epa.add_argument("id", type=int)
    epa.add_argument("-l", "--label", action="append", required=True)
    epa.add_argument("--ipv4")
    epa.add_argument("--ipv6")
    epc = ep.add_parser("config", help="per-endpoint runtime options")
    epc.add_argument("id", type=int)
    epc.add_argument("options", nargs="+", help="Option=true|false pairs")
    epd = ep.add_parser("delete", help="remove an endpoint")
    epd.add_argument("id", type=int)
    epg = ep.add_parser("get", help="one endpoint's model")
    epg.add_argument("id", type=int)
    epr = ep.add_parser("regenerate", help="force policy regeneration")
    epr.add_argument("id", type=int, nargs="?", default=None)
    eplog = ep.add_parser("log", help="per-endpoint status log")
    eplog.add_argument("id", type=int)
    epl = ep.add_parser("labels", help="modify labels (new identity)")
    epl.add_argument("id", type=int)
    epl.add_argument("-a", "--add", action="append", default=[])
    epl.add_argument("-d", "--delete", action="append", default=[])

    # identity
    idp = sub.add_parser("identity", help="identity operations").add_subparsers(
        dest="sub", required=True
    )
    idp.add_parser("list", help="list identities")
    idg = idp.add_parser("get", help="get one identity")
    idg.add_argument("id", type=int)

    # bpf policy get (map dump)
    cfg = sub.add_parser("config", help="runtime option map")
    cfg.add_argument("options", nargs="*",
                     help="Option=true|false pairs (empty: show)")

    bpf = sub.add_parser("bpf", help="datapath map access").add_subparsers(
        dest="sub", required=True
    )
    for mname, mhelp in (
        ("ct", "conntrack entries"), ("ipcache", "IP→identity cache"),
        ("tunnel", "tunnel endpoints"), ("proxy", "proxy handoffs"),
        ("metrics", "per-endpoint counters"), ("routes", "route table"),
        ("lxc", "local endpoints (bpf endpoint list)"),
        ("lb", "service tables (bpf lb list)"),
    ):
        mp = bpf.add_parser(mname, help=mhelp).add_subparsers(
            dest="mapop", required=True
        )
        mp.add_parser("list", help=f"dump {mhelp}")
        if mname == "ct":
            mp.add_parser("flush", help="flush all conntrack entries")
    bp = bpf.add_parser("policy", help="policymap ops").add_subparsers(
        dest="op", required=True
    )
    bpg = bp.add_parser("get", help="dump an endpoint's realized policymap")
    bpg.add_argument("endpoint", type=int)
    bpg.add_argument("--egress", action="store_true")

    # prefilter
    svc = sub.add_parser("service", help="LB service operations").add_subparsers(
        dest="sub", required=True
    )
    svc.add_parser("list", help="list services")
    svu = svc.add_parser("update", help="create/update a service")
    svu.add_argument("--frontend", required=True,
                     help="VIP as ip:port[/proto], e.g. 10.96.0.10:80/TCP")
    svu.add_argument("--backends", nargs="*", default=[],
                     help="backends as ip:port[@weight]")
    svd = svc.add_parser("delete", help="delete a service")
    svd.add_argument("--frontend", required=True)

    pf = sub.add_parser("prefilter", help="XDP deny-list").add_subparsers(
        dest="sub", required=True
    )
    pf.add_parser("get", help="dump deny CIDRs")
    pfu = pf.add_parser("update", help="insert deny CIDRs")
    pfu.add_argument("cidrs", nargs="+")
    pfd = pf.add_parser("delete", help="remove deny CIDRs")
    pfd.add_argument("cidrs", nargs="+")

    # node / map inventory / version / cleanup
    nd = sub.add_parser("node", help="cluster nodes").add_subparsers(
        dest="sub", required=True
    )
    nd.add_parser("list", help="known cluster nodes")
    # policyd-fed: the federated policy plane (GET /cluster)
    cf = sub.add_parser(
        "cluster", help="federated policy plane (policyd-fed)"
    ).add_subparsers(dest="sub", required=True)
    cf.add_parser("nodes", help="fleet nodes + published policy epochs")
    cf.add_parser("status", help="full federation membership view")
    # policyd-fleetobs: the aggregated telemetry plane (GET /fleet)
    fl = sub.add_parser(
        "fleet", help="fleet telemetry scoreboard (policyd-fleetobs)"
    ).add_subparsers(dest="sub", required=True)
    fl.add_parser("status", help="aggregated scoreboard (raw JSON)")
    fl.add_parser("top", help="per-node health grid, one line per node")
    flh = fl.add_parser("history", help="local time-series ring samples")
    flh.add_argument("-n", "--last", type=int, default=32,
                     help="how many ring samples to show (default 32)")
    flh.add_argument("--json", action="store_true",
                     help="raw sample dicts instead of one-liners")
    # policyd-journal: per-node journals merged into one HLC order
    flt = fl.add_parser(
        "timeline", help="merged fleet lifecycle timeline (policyd-journal)"
    )
    flt.add_argument("-n", "--last", type=int, default=64,
                     help="how many merged events to show (default 64)")
    flt.add_argument("--json", action="store_true",
                     help="raw merged-timeline dict instead of one-liners")
    mp2 = sub.add_parser("map", help="open-map inventory").add_subparsers(
        dest="sub", required=True
    )
    mp2.add_parser("list", help="map names + entry counts")
    mg = mp2.add_parser("get", help="dump one map by name")
    mg.add_argument("name")
    sub.add_parser("version", help="framework + backend versions")
    cl = sub.add_parser("cleanup", help="remove agent state/sockets")
    cl.add_argument("-f", "--force", action="store_true",
                    help="actually delete (dry run without)")

    # kvstore: serve the cluster fabric / direct key access
    # (cilium kvstore get|set|delete, cilium/cmd/kvstore*.go)
    kv = sub.add_parser("kvstore", help="cluster kvstore").add_subparsers(
        dest="sub", required=True
    )
    kvs = kv.add_parser(
        "serve",
        help="run the TCP kvstore server agents --join (etcd role)",
    )
    kvs.add_argument("--listen", default="127.0.0.1:4240",
                     metavar="HOST:PORT")
    kvs.add_argument("--lease-ttl", type=float, default=15.0)
    kvs.add_argument("--state-file", default=None, metavar="PATH",
                     help="persist non-lease keys across restarts "
                          "(periodic + on-stop atomic snapshots)")
    for opname, ophelp in (
        ("get", "read keys under a prefix"),
        ("set", "write one key"),
        ("delete", "delete a key (or prefix with trailing /)"),
        ("status", "kvstore connectivity status"),
    ):
        op = kv.add_parser(opname, help=ophelp)
        op.add_argument("--kvstore", required=True, metavar="TARGET",
                        help="tcp://host:port or SQLite path")
        if opname in ("get", "set", "delete"):
            op.add_argument("key")
        if opname == "set":
            op.add_argument("value")

    return p


def _install_signal_handlers() -> None:
    """Route SIGTERM (and SIGINT, for symmetry) into KeyboardInterrupt
    so orchestrated stops — `kill`, container runtimes, systemd — take
    the same graceful-drain teardown as ^C. Best-effort: signal
    delivery only works from the main thread, and embedded callers
    (tests driving main() from a worker) simply keep default disposition."""
    import signal

    def _raise_interrupt(signum, frame):
        raise KeyboardInterrupt

    try:
        signal.signal(signal.SIGTERM, _raise_interrupt)
        signal.signal(signal.SIGINT, _raise_interrupt)
    except ValueError:  # not the main thread
        pass


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.cmd == "daemon":
        from .api.server import APIServer
        from .daemon import Daemon
        from .monitor.server import MonitorServer
        from .utils.logging import setup as logging_setup

        logging_setup(os.environ.get("CILIUM_TPU_LOG_LEVEL", "info"))

        daemon = Daemon(
            state_dir=args.state, conntrack=not args.no_conntrack,
            pod_cidr=args.pod_cidr,
        )
        cluster_node = None
        cluster_pump = None
        if args.join:
            if not args.node_ip:
                # a node without an address cannot serve as a tunnel
                # endpoint — peers would learn unroutable announcements
                print("--join requires --node-ip (this node's reachable "
                      "address for tunnels)", file=sys.stderr)
                return 2
            import socket as _socket

            from .cluster import ClusterNode
            from .kvstore.netstore import backend_from_target
            from .nodes.registry import Node as _Node

            name = args.node_name or _socket.gethostname()
            cluster_node = ClusterNode(
                daemon,
                backend_from_target(args.join, name),
                _Node(name=name, ipv4=args.node_ip,
                      ipv4_alloc_cidr=args.pod_cidr),
                cluster=args.cluster,
            )
            cluster_node.export_services()

            # convergence controller: drain cluster subscriptions on
            # an interval (the kvstore watch pump of the reference's
            # controller loops). A dead backend (kvstore outage)
            # triggers a rejoin attempt on a fresh connection; while
            # the server is down the factory raises and the
            # controller's backoff keeps retrying — enforcement keeps
            # running on local state the whole time.
            def _cluster_sync():
                if not cluster_node.joined():
                    cluster_node.rejoin(backend_from_target(args.join, name))
                    # export follows below — rejoin itself doesn't
                cluster_node.pump()
                cluster_node.export_services()

            # registered with the daemon's manager so it shows in
            # `cilium status --all-controllers`
            cluster_pump = daemon.controllers.update_controller(
                "cluster-sync", _cluster_sync,
                run_interval=args.sync_interval,
            )
        server = APIServer(daemon, args.socket)
        monitor = None
        monitor_launcher = None
        monitor_feeder = None
        if args.launch_monitor:
            # external monitor owns the client socket; the agent only
            # FEEDS it — `cilium monitor` streams survive agent stalls
            # (monitor/monitor.go:184 isolation)
            from .monitor.standalone import MonitorFeeder
            from .proxy.launcher import MonitorLauncher

            monitor_launcher = MonitorLauncher(
                args.socket + ".monitor", args.socket + ".monitor-feed"
            ).start()
            monitor_feeder = MonitorFeeder(
                daemon.monitor, args.socket + ".monitor-feed"
            ).start()
        else:
            monitor = MonitorServer(daemon.monitor, args.socket + ".monitor")
            monitor.start()
        from .xds.server import XDSServer

        xds = XDSServer(daemon.xds_cache, args.socket + ".xds")
        xds.start()
        accesslog_rx = None
        proxy_launcher = None
        if args.launch_proxy:
            # external proxy: accesslog receiver + supervised child
            # (pkg/envoy/envoy.go:76-143 + pkg/launcher)
            from .proxy.accesslog import AccessLogSocketServer
            from .proxy.launcher import ProxyLauncher

            accesslog_rx = AccessLogSocketServer(
                daemon.proxy.accesslog, args.socket + ".accesslog"
            ).start()
            proxy_launcher = ProxyLauncher(
                args.socket + ".xds", args.socket + ".accesslog"
            ).start()
        health_launcher = None
        if args.launch_health:
            # per-node health endpoint as its own supervised process
            # (the cilium-health sidecar, daemon/main.go:927-945)
            from .health.standalone import HealthAPIClient
            from .proxy.launcher import HealthLauncher

            health_api = args.socket + ".health"
            health_launcher = HealthLauncher(
                args.socket, health_api,
                listen_ip=args.node_ip or "127.0.0.1",
                port=args.health_port,
                interval=max(1.0, args.sync_interval),
            ).start()

            if cluster_node is not None:
                # port advertisement only matters with peers to tell;
                # a standalone daemon would poll for nothing
                def _health_advertise():
                    """Once the sidecar reports its responder port,
                    advertise it in the node announcement so peers
                    probe the right socket."""
                    st = HealthAPIClient(health_api, timeout=3.0).status()
                    port = int(st.get("port") or 0)
                    if port:
                        import dataclasses as _dc

                        local = cluster_node.nodes.local
                        if local.health_port != port:
                            cluster_node.nodes.announce_local(_dc.replace(
                                local, health_ip=args.node_ip,
                                health_port=port,
                            ))

                daemon.controllers.update_controller(
                    "health-advertise", _health_advertise,
                    run_interval=max(1.0, args.sync_interval),
                )
        informer = None
        if args.k8s_api:
            from .k8s import K8sWatcher
            from .k8s.client import APIServerClient, Informer

            token = None
            if args.k8s_token_file:
                with open(args.k8s_token_file) as f:
                    token = f.read().strip()
            api = APIServerClient(args.k8s_api, token=token)
            watcher = K8sWatcher(daemon)
            # writeback wiring: CNP status acks, Ingress LB status,
            # node CIDR annotations (pkg/k8s/client.go AnnotateNode)
            watcher.status_client = api
            watcher.node_name = args.node_name or ""
            if args.node_ip:
                daemon.services.host_ip = args.node_ip  # Ingress frontends
            try:
                # register the CNP CRD before watching it
                # (pkg/k8s/apis/cilium.io/v2/register.go)
                api.ensure_cnp_crd()
            except Exception as e:
                print(f"WARNING: CNP CRD registration failed: {e}")
            informer = Informer(api, watcher).start()
            # the reference blocks on cache sync before serving
            # (daemon/main.go:843-856); an unsynced start is loudly
            # flagged rather than silently serving empty k8s state
            if not informer.wait_synced(timeout=30.0):
                print("WARNING: k8s cache not synced after 30s — "
                      "serving with partial state; the informer keeps "
                      "retrying in the background")
        pleg = None
        if args.cri:
            # container runtime watcher over the CRI socket
            # (pkg/workloads docker.go role for containerd/cri-o)
            from .runtimes import CRIRuntime, PLEGPoller
            from .workloads import WorkloadWatcher

            cri = CRIRuntime(args.cri)
            pleg = PLEGPoller(
                WorkloadWatcher(daemon, cri), cri,
                interval=args.cri_interval,
            ).start()
        daemon.fqdn_start()  # ToFQDNs DNS poll loop (daemon/main.go:808)
        if daemon.health.nodes is not None:
            # node prober (daemon/main.go:927-945) — only meaningful
            # once a node registry is attached; a standalone daemon
            # has no peers and would spin an empty sweep forever
            daemon.health.start()
        cluster_note = f", cluster: {args.cluster}@{args.join}" if args.join else ""
        print(f"cilium-tpu daemon serving on {args.socket} "
              f"(monitor: {args.socket}.monitor, xds: {args.socket}.xds, "
              f"state: {args.state}{cluster_note})")
        # Graceful drain on SIGTERM (policyd-survive): rolling restarts
        # deliver SIGTERM, not ^C — route both through the one teardown
        # path below so in-flight verdicts drain and state persists.
        _install_signal_handlers()
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            if informer is not None:
                informer.stop()
            if pleg is not None:
                pleg.stop()
            if proxy_launcher is not None:
                proxy_launcher.stop()
            if health_launcher is not None:
                health_launcher.stop()
            if accesslog_rx is not None:
                accesslog_rx.stop()
            xds.stop()
            if monitor is not None:
                monitor.stop()
            if monitor_feeder is not None:
                monitor_feeder.stop()
            if monitor_launcher is not None:
                monitor_launcher.stop()
            server.stop()
            if cluster_pump is not None:
                cluster_pump.stop()  # BEFORE close: no pump mid-teardown
            if cluster_node is not None:
                cluster_node.close()
            daemon.shutdown()
        return 0

    if args.cmd == "monitor":
        import dataclasses

        from .monitor.server import monitor_stream

        path = args.socket + ".monitor"
        if not os.path.exists(path):
            print(f"no monitor socket at {path} (is the daemon running?)",
                  file=sys.stderr)
            return 1
        print(f"Listening for events on {path}...", file=sys.stderr)
        from .monitor.events import (
            EVENT_AGENT,
            EVENT_CAPTURE,
            EVENT_DROP,
            EVENT_L7,
            EVENT_TRACE,
            EVENT_TRACE_SUMMARY,
        )

        _type_names = {EVENT_DROP: "drop", EVENT_TRACE: "trace",
                       EVENT_AGENT: "agent", EVENT_L7: "l7",
                       EVENT_CAPTURE: "capture",
                       EVENT_TRACE_SUMMARY: "trace-summary"}
        try:
            for ev in monitor_stream(path, timeout=args.timeout):
                if args.types and _type_names.get(ev.type) not in args.types:
                    continue
                if args.json:
                    d = dataclasses.asdict(ev)
                    # bytes fields (peer_addr, capture payloads) ride
                    # as hex — json has no bytes type
                    for k, v in d.items():
                        if isinstance(v, bytes):
                            d[k] = v.hex()
                    print(json.dumps(d))
                else:
                    print(ev.summary())
        except KeyboardInterrupt:
            pass
        return 0

    if args.cmd == "version":
        # local by design: version must print even with no daemon
        from . import __version__

        print(f"cilium-tpu {__version__}")
        try:
            import jax

            devs = jax.devices()
            print(f"jax {jax.__version__} ({devs[0].platform}, "
                  f"{len(devs)} device(s))")
        except Exception as e:
            print(f"jax unavailable: {e}")
        return 0

    if args.cmd == "cleanup":
        # cilium cleanup: remove agent state + sockets (the reference
        # removes BPF maps/veths; our datapath state is the state dir)
        import shutil

        targets = [p for p in (
            args.state,
            args.socket, args.socket + ".monitor", args.socket + ".xds",
            args.socket + ".accesslog",
        ) if os.path.exists(p)]
        if not targets:
            print("nothing to clean")
            return 0
        for t in targets:
            print(("removing " if args.force else "would remove ") + t)
            if args.force:
                if os.path.isdir(t):
                    shutil.rmtree(t, ignore_errors=True)
                else:
                    try:
                        os.unlink(t)
                    except OSError:
                        pass
        if not args.force:
            print("dry run — pass --force to delete")
        return 0

    if args.cmd == "kvstore":
        from .kvstore.netstore import KVStoreServer, backend_from_target

        if args.sub == "serve":
            from .kvstore.netstore import parse_hostport

            try:
                host, port = parse_hostport(args.listen)
            except ValueError as e:
                print(f"--listen: {e}", file=sys.stderr)
                return 2
            server = KVStoreServer(
                host or "127.0.0.1", port, lease_ttl=args.lease_ttl,
                state_path=args.state_file,
            ).start()
            print(f"kvstore serving on {server.url}", flush=True)
            try:
                threading.Event().wait()
            except KeyboardInterrupt:
                pass
            server.stop()
            return 0
        # `kvstore status` exists precisely to probe a possibly-down
        # server — a traceback here would be a bug report, not an
        # answer (same for a dying server mid-op, or an unwritable
        # SQLite path)
        import sqlite3

        _kv_errors = (
            OSError, TimeoutError, RuntimeError, ValueError, sqlite3.Error,
        )
        try:
            be = backend_from_target(args.kvstore, "cli")
        except _kv_errors as e:
            print(f"kvstore {args.kvstore}: unreachable ({e})",
                  file=sys.stderr)
            return 1
        try:
            if args.sub == "get":
                for k, v in sorted(be.list_prefix(args.key).items()):
                    print(f"{k} => {v.decode(errors='replace')}")
            elif args.sub == "set":
                be.set(args.key, args.value.encode())
            elif args.sub == "delete":
                if args.key.endswith("/"):
                    be.delete_prefix(args.key)
                else:
                    be.delete(args.key)
            elif args.sub == "status":
                print(be.status())
        except _kv_errors as e:
            print(f"kvstore {args.kvstore}: {args.sub} failed ({e})",
                  file=sys.stderr)
            return 1
        finally:
            be.close()
        return 0

    s = _Surface(args.socket, args.state)

    if args.cmd == "status":
        status = s.status()
        if getattr(args, "all_controllers", False):
            _print(status.get("controllers", []))
        else:
            slo = status.get("slo")
            if slo:
                # one-line health summary (policyd-fleetobs); absent
                # when FleetTelemetry is off so stdout stays pure JSON
                print(f"SLO: worst={slo['worst_objective']} "
                      f"state={slo['state']} burn={slo['ratio']}",
                      file=sys.stderr)
            _print(status)
    elif args.cmd == "metrics":
        _print(s.metrics())
    elif args.cmd == "policy":
        if args.sub == "import":
            text = (sys.stdin.read() if args.file == "-"
                    else open(args.file).read())
            _print(s.policy_put(json.loads(text)))
        elif args.sub == "get":
            _print(s.policy_get())
        elif args.sub == "delete":
            _print(s.policy_delete(args.labels))
        elif args.sub == "validate":
            from .policy.api.serialization import rules_from_json

            text = (sys.stdin.read() if args.file == "-"
                    else open(args.file).read())
            try:
                rules = rules_from_json(text)
            except (ValueError, KeyError) as e:
                print(f"invalid: {e}", file=sys.stderr)
                return 1
            print(f"valid: {len(rules)} rule(s)")
            return 0
        elif args.sub == "wait":
            import time as _time

            deadline = _time.time() + args.timeout
            while _time.time() < deadline:
                rev = s.status()["policy_revision"]
                if rev >= args.revision:
                    print(f"revision {rev} reached")
                    return 0
                _time.sleep(0.2)
            print(f"timeout waiting for revision {args.revision}",
                  file=sys.stderr)
            return 1
        elif args.sub == "trace":
            eps_by_id: dict = {}

            def endpoints_once():
                # one GET /endpoint serves both sides of the trace
                if not eps_by_id:
                    eps_by_id.update(
                        {e["id"]: e for e in s.endpoint_list()}
                    )
                return eps_by_id

            def resolve_side(labels, identity, endpoint, side):
                # --src-identity / --src-endpoint sources mirror
                # cilium/cmd/policy_trace.go (identity → GET
                # /identity/<id>, endpoint → its labels)
                out = list(labels)
                if identity is not None:
                    try:
                        out += s.identity_get(identity)["labels"]
                    except (SystemExit, Exception):
                        raise SystemExit(
                            f"{side} identity {identity} not found"
                        ) from None
                if endpoint is not None:
                    eps = endpoints_once()
                    if endpoint not in eps:
                        raise SystemExit(f"{side} endpoint {endpoint} not found")
                    out += eps[endpoint]["labels"]
                if not out:
                    raise SystemExit(
                        f"no {side}: give -{side[0]}, --{side}-identity "
                        f"or --{side}-endpoint"
                    )
                return out

            out = s.policy_resolve(
                resolve_side(args.src, args.src_identity,
                             args.src_endpoint, "src"),
                resolve_side(args.dst, args.dst_identity,
                             args.dst_endpoint, "dst"),
                args.dport,
                ingress=not args.egress, verbose=args.verbose,
            )
            print(out["trace"], end="")
            print(f"Final verdict: {out['verdict']}")
            if not out["parity"]:
                print("WARNING: device/oracle verdict mismatch "
                      f"(device allowed={out['device_allowed']})",
                      file=sys.stderr)
                return 2
            return 0 if out["allowed"] else 1
        elif args.sub == "explain":
            if not args.src or not args.dst:
                raise SystemExit("give at least one -s and one -d label")
            out = s.policy_explain(args.src, args.dst, args.dport,
                                   ingress=not args.egress)
            if args.json:
                _print(out)
                return 0 if out["allowed"] else 1
            dec = "ALLOWED" if out["allowed"] else "DENIED"
            print(f"{out['direction']} verdict: {dec} [{out['reason']}]")
            r = out.get("rule")
            if r is not None:
                what = (", ".join(r.get("labels", []))
                        or r.get("description")
                        or f"rule {out['rule_index']}")
                print(f"decided by rule #{out['rule_index']}: {what}")
            elif out["rule_index"] >= 0:
                print(f"decided by rule #{out['rule_index']}")
            else:
                print("no rule matched")
            if out.get("l7_redirect"):
                print("L7: redirected to proxy")
            return 0 if out["allowed"] else 1
    elif args.cmd == "endpoint":
        if args.sub == "list":
            _print(s.endpoint_list())
        elif args.sub == "add":
            _print(s.endpoint_put(args.id, args.label,
                                  ipv4=args.ipv4, ipv6=args.ipv6))
        elif args.sub == "config":
            opts = {}
            for pair in args.options:
                name, _, val = pair.partition("=")
                opts[name] = val or "true"
            _print(s.endpoint_config(args.id, opts))
        elif args.sub == "delete":
            _print(s.endpoint_delete(args.id))
        elif args.sub == "get":
            _print(s.endpoint_get(args.id))
        elif args.sub == "regenerate":
            _print(s.endpoint_regenerate(args.id))
        elif args.sub == "log":
            import datetime as _dt

            for rec in s.endpoint_log(args.id):
                ts = _dt.datetime.fromtimestamp(rec["timestamp"])
                print(f"{ts:%H:%M:%S} [{rec['code']}] {rec['message']}")
        elif args.sub == "labels":
            _print(s.endpoint_labels(args.id, add=args.add,
                                     delete=args.delete))
    elif args.cmd == "identity":
        if args.sub == "list":
            _print(s.identity_list())
        else:
            _print(s.identity_get(args.id))
    elif args.cmd == "config":
        if args.options:
            opts = {}
            for pair in args.options:
                name, _, val = pair.partition("=")
                opts[name] = val or "true"
            _print(s.config_patch(opts))
        else:
            _print(s.config_get())
    elif args.cmd == "bpf":
        if args.sub == "ct" and args.mapop == "flush":
            _print(s.ct_flush())
        elif args.sub in ("ct", "ipcache", "tunnel", "proxy", "metrics",
                          "routes", "lxc", "lb"):
            _print(s.map_dump(args.sub))
        else:
            _print(s.policymap_get(args.endpoint, egress=args.egress))
    elif args.cmd == "health":
        if args.sidecar:
            from .health.standalone import HealthAPIClient

            hpath = args.socket + ".health"
            if not os.path.exists(hpath):
                print(f"no health socket at {hpath} (daemon running "
                      "with --launch-health?)", file=sys.stderr)
                return 1
            from .api.client import APIError

            hc = HealthAPIClient(hpath)
            try:
                if args.probe:
                    hc.probe()
                _print(hc.status())
            except (OSError, APIError, ValueError) as e:
                print(f"health sidecar unreachable: {e}", file=sys.stderr)
                return 1
        else:
            _print(s.health_probe() if args.probe else s.health())
    elif args.cmd == "traces":
        out = s.traces_get(limit=args.last)
        if args.json:
            _print(out)
        else:
            from .monitor.dissect import render_waterfall

            if not out.get("enabled") and not out.get("traces"):
                print("phase tracing is disabled (enable with "
                      "`cilium-tpu config PhaseTracing=true`)")
            if "pipeline_depth" in out:
                # overlap context: with depth>1 a trace's host_sync is
                # the residual wait, not the device execution time
                print(
                    f"pipeline depth {out['pipeline_depth']}, "
                    f"{out.get('in_flight', 0)} batch(es) in flight"
                )
                if out.get("flow_attribution"):
                    # attribution widens host_sync (6 pulled arrays,
                    # not 3) — name it so waterfalls read honestly
                    print("flow attribution is ON: host_sync includes "
                          "rule/reason/hit-counter pulls")
                at = out.get("autotune")
                if at:
                    # depth moved between these traces' batches —
                    # waterfalls are NOT like-for-like comparable
                    # without this context (observe/README.md)
                    adj = at.get("adjustments", {})
                    print(
                        f"auto-tune is ON: depth {at.get('depth')} in "
                        f"[{at.get('min_depth')}, {at.get('max_depth')}], "
                        f"{adj.get('up', 0)} up / {adj.get('down', 0)} "
                        f"down step(s)"
                    )
                pl = out.get("placement")
                if pl and pl.get("axes"):
                    # a formed mesh changes what a dispatch span covers
                    # (flow shards, and under 2D an ident-axis reduce)
                    ax = pl["axes"]
                    shape = "×".join(
                        f"{k}={v}" for k, v in sorted(ax.items())
                    )
                    print(
                        f"placement: mesh {{{shape}}} over "
                        f"{len(pl.get('devices', ()))} device(s), "
                        f"generation {pl.get('generation')}"
                        + (
                            ", identity tables SHARDED over ident"
                            if pl.get("ident_sharded")
                            else ""
                        )
                    )
                adm = out.get("admission")
                if adm and adm.get("enabled"):
                    # an active gate means some flows in this window
                    # never produced device spans at all — waterfalls
                    # undercount offered load without this context
                    wd = adm.get("watchdog") or {}
                    line = (
                        f"admission control is ON: limit "
                        f"{adm.get('limit')}/{adm.get('max_depth')}, "
                        f"queue depth {adm.get('queue_depth', 0)}, "
                        f"shed ratio {adm.get('shed_ratio', 0.0)}"
                    )
                    if adm.get("prefilter"):
                        shed = adm.get("shed", {})
                        line += (
                            f", prefilter shed "
                            f"{shed.get('prefilter', 0)} flow(s)"
                        )
                    print(line)
                    if wd.get("last_stall"):
                        ls = wd["last_stall"]
                        print(
                            f"watchdog: {wd.get('stalls', 0)} stall(s), "
                            f"last at site {ls.get('site')!r} after "
                            f"{ls.get('age_ms')}ms"
                        )
                fs = out.get("failsafe")
                if fs and fs.get("degraded"):
                    # a degraded ladder changes what the spans MEAN
                    # (host mode has no device phases at all) — say so
                    # before any waterfall prints
                    print(
                        f"pipeline DEGRADED: mode {fs.get('mode')} "
                        f"(level {fs.get('level')}), "
                        f"{fs.get('quarantined_batches', 0)} batch(es) "
                        f"quarantined, "
                        f"{'fail-open' if fs.get('fail_open') else 'fail-closed'}"
                    )
                pq = out.get("phase_quantiles")
                if pq:
                    # process-lifetime latency context (histogram
                    # interpolation) for the per-batch waterfalls below
                    print("phase quantiles: " + ", ".join(
                        f"{ph} p50={v['p50_ms']}ms/p99={v['p99_ms']}ms"
                        for ph, v in sorted(pq.items())
                    ))
                print()
            for t in out.get("traces", ()):
                print(render_waterfall(
                    t["kind"], t["batch"], t["total_ns"], t["phases"],
                ))
                print()
    elif args.cmd == "top":
        out = s.profile_get()
        if args.json:
            _print(out)
        else:
            if not out.get("enabled"):
                print("device profiling is disabled (enable with "
                      "`cilium-tpu config DeviceProfiling=true`)")
            else:
                print(f"sampling every {out.get('sample_every')} "
                      f"batch(es), {len(out.get('samples', ()))} "
                      "sample(s) retained")
            sites = out.get("sites") or {}
            if sites:
                print()
                print(f"{'site':<10}{'samples':>8}{'h2d_ms':>10}"
                      f"{'compute_ms':>12}{'d2h_ms':>10}")
                for name, st in sorted(
                    sites.items(),
                    key=lambda kv: -kv[1].get("device_compute_ms", 0.0),
                ):
                    print(f"{name:<10}{st.get('samples', 0):>8}"
                          f"{st.get('h2d_ms', 0.0):>10.3f}"
                          f"{st.get('device_compute_ms', 0.0):>12.3f}"
                          f"{st.get('d2h_ms', 0.0):>10.3f}")
            costs = out.get("jit_costs") or {}
            if costs:
                print()
                print("jit sites (XLA cost_analysis per compiled "
                      "program):")
                for key, c in sorted(costs.items()):
                    print(f"  {key}: flops={c.get('flops')} "
                          f"bytes_accessed={c.get('bytes_accessed')}")
            ledger = out.get("device_table_bytes") or {}
            if ledger:
                print()
                print("device table bytes (family/placement, per "
                      "device):")
                for key, val in sorted(ledger.items()):
                    print(f"  {key:<28}{int(val):>14,}")
            xf = out.get("device_transfers") or {}
            if xf.get("counts") or xf.get("bytes"):
                counts = xf.get("counts") or {}
                nbytes = xf.get("bytes") or {}
                print()
                print("device transfers:")
                for k in sorted(set(counts) | set(nbytes)):
                    print(f"  {k:<6} count={counts.get(k, 0):.0f} "
                          f"bytes={nbytes.get(k, 0):.0f}")
    elif args.cmd == "flows":
        import datetime as _dt

        _verdict_codes = {"forwarded": 1, "drop": -1, "drop-policy": 2,
                          "drop-prefilter": 3, "drop-no-service": 4}
        out = s.flows_get(
            limit=args.last,
            verdict=(_verdict_codes[args.verdict]
                     if args.verdict else None),
            from_identity=args.from_identity,
        )
        if args.json:
            _print(out)
        else:
            if not out.get("enabled") and not out.get("flows"):
                print("flow attribution is disabled (enable with "
                      "`cilium-tpu config FlowAttribution=true`)")
            for f in out.get("flows", ()):
                ts = _dt.datetime.fromtimestamp(f["ts"])
                rule = ""
                if f["rule_index"] >= 0:
                    org = f.get("rule_origin") or {}
                    what = (", ".join(org.get("labels", []))
                            or org.get("description", ""))
                    rule = f"  rule #{f['rule_index']}"
                    if what:
                        rule += f" ({what})"
                ip = f["src_ip"] or f["dst_ip"]
                ip = f" {ip}" if ip else ""
                print(
                    f"{ts:%H:%M:%S} {f['direction']:<7} "
                    f"{f['src_identity']}->{f['dst_identity']}{ip} "
                    f"{f['dport']}/{f['proto']} "
                    f"{f['verdict_name']} [{f['reason_name']}]{rule}"
                )
            if out.get("recorded", 0):
                shown = len(out.get("flows", ()))
                print(f"({shown} shown; {out['recorded']} recorded "
                      "since enable; drops sampled first)")
    elif args.cmd == "events":
        out = s.events_get(
            limit=args.last, kind=args.kind, severity=args.severity
        )
        if args.json:
            _print(out)
        elif not out.get("enabled"):
            print("lifecycle journal is disabled (enable with "
                  "`cilium-tpu config LifecycleJournal=true`)")
        else:
            _print_journal_lines(out.get("events", ()))
            if out.get("dropped", 0):
                print(f"({out['dropped']} event(s) dropped to the ring "
                      "bound since enable)")
    elif args.cmd == "bugtool":
        import time as _time

        from .bugtool import write_archive_from

        out = args.output or f"cilium-tpu-bugtool-{int(_time.time())}.tar.gz"
        write_archive_from(s.debuginfo(), s.metrics(), out)
        print(f"archive written: {out}")
    elif args.cmd == "service":
        if args.sub == "list":
            _print(s.service_list())
        elif args.sub == "update":
            _print(s.service_put(
                _parse_frontend(args.frontend),
                [_parse_backend(b) for b in args.backends],
            ))
        elif args.sub == "delete":
            _print(s.service_delete(_parse_frontend(args.frontend)))
    elif args.cmd == "prefilter":
        if args.sub == "get":
            _print(s.prefilter_get())
        elif args.sub == "delete":
            _print(s.prefilter_delete(args.cidrs))
        else:
            _print(s.prefilter_patch(args.cidrs))
    elif args.cmd == "node":
        _print(s.node_list())
    elif args.cmd == "cluster":
        st = s.cluster_status()
        _print(st.get("nodes", []) if args.sub == "nodes" else st)
    elif args.cmd == "fleet":
        if args.sub == "timeline":
            out = s.fleet_timeline(limit=args.last)
            if args.json:
                _print(out)
            elif not out.get("enabled"):
                print("lifecycle journal is disabled (enable with "
                      "`cilium-tpu config LifecycleJournal=true`)")
            else:
                _print_journal_lines(out.get("events", ()),
                                     with_node=True)
                nodes = out.get("nodes", ())
                flag = "" if out.get("consistent", True) else \
                    "  HLC ORDER VIOLATION"
                print(f"({len(nodes)} node(s) merged: "
                      f"{', '.join(nodes)}){flag}")
        elif args.sub == "history":
            out = s.fleet_history(limit=args.last)
            if args.json:
                _print(out)
            elif not out.get("enabled"):
                print("fleet telemetry is disabled (enable with "
                      "`cilium-tpu config FleetTelemetry=true`)")
            else:
                import datetime as _dt

                for rec in out.get("history", ()):
                    ts = _dt.datetime.fromtimestamp(rec["ts"])
                    rest = " ".join(
                        f"{k}={rec[k]}" for k in sorted(rec) if k != "ts"
                    )
                    print(f"{ts:%H:%M:%S} {rest}")
        else:
            out = s.fleet_status()
            if not out.get("enabled"):
                print("fleet telemetry is disabled (enable with "
                      "`cilium-tpu config FleetTelemetry=true`)")
            elif args.sub == "status":
                _print(out)
            else:  # top: per-node health grid, worst burn first
                agg = out
                print(f"{agg.get('nodes_reporting', 0)} node(s) "
                      f"reporting, fleet vps "
                      f"{agg.get('fleet_vps', 0.0):.1f}, epoch skew "
                      f"{agg.get('epoch_skew', 0)}")
                wb = agg.get("worst_burn") or {}
                if wb.get("objective"):
                    print(f"worst burn: {wb['objective']} on "
                          f"{wb.get('node')} ({wb.get('state')}, "
                          f"ratio {wb.get('ratio')})")
                print(f"{'node':<16}{'state':<9}{'vps':>10}"
                      f"{'p99_ms':>9}{'epoch':>7}{'lag':>5}"
                      f"{'age_s':>7}  mode")
                for n in agg.get("nodes", ()):
                    print(f"{n['node']:<16}{n['slo_state'] or '-':<9}"
                          f"{(n['vps'] or 0.0):>10.1f}"
                          f"{(n['verdict_p99_ms'] or 0.0):>9.2f}"
                          f"{(n['policy_epoch'] if n['policy_epoch'] is not None else '-'):>7}"
                          f"{(n['epoch_lag'] if n['epoch_lag'] is not None else '-'):>5}"
                          f"{n['age_s']:>7.1f}  "
                          f"{n['pipeline_mode'] or '-'}")
    elif args.cmd == "map":
        if args.sub == "list":
            _print(s.map_list())
        else:
            _print(s.map_dump(args.name))
    return 0


def run() -> int:
    """Entry point shared by `python -m cilium_tpu` and
    `python -m cilium_tpu.cli`."""
    try:
        return main()
    except BrokenPipeError:
        # `cilium-tpu ... | head` closing the pipe is not an error;
        # devnull swap avoids a second BrokenPipeError at interpreter
        # shutdown when stdout flushes
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(run())
