"""Clustered-agent assembly: one Daemon joined to the kvstore fabric.

The runDaemon wiring of the reference (daemon/main.go:818 →
kvstore.Setup, InitIdentityAllocator, node registration,
InitIPIdentityWatcher, clustermesh) as one composable object: given a
Daemon and a kvstore backend, ClusterNode

- swaps the daemon's identity allocation onto the cluster-wide CAS
  allocator (every node numbers identities identically — which is
  what keeps compiled policy tensor ROWS compatible across nodes),
- registers the node and attaches the registry to the daemon (health
  probing + tunnel/route programming ride the same observer),
- announces local endpoint IPs on the ip→identity prefix and merges
  every other node's announcements into the local ipcache,
- exports the node's services and (optionally) merges remote
  clusters' identities/ipcache/services via clustermesh.

Convergence is pump()-driven (deterministic for tests, a controller
loop in daemons), matching the rest of the kvstore layer.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from .identity.distributed import DistributedIdentityAllocator
from .ipcache.ipcache import SOURCE_AGENT
from .ipcache.kvstore_sync import IPIdentitySync
from .kvstore.backend import BackendOperations
from .kvstore.clustermesh import ClusterMesh
from .nodes.registry import Node, NodeRegistry
from .utils.logging import get_logger

log = get_logger("cluster")

# a backend that died mid-operation (kvstore outage) raises these;
# teardown paths treat them as "the server's lease expiry will finish
# the job"
_KV_DOWN = (ConnectionError, TimeoutError, RuntimeError, OSError)


class ClusterNode:
    def __init__(
        self,
        daemon,
        backend: BackendOperations,
        node: Node,
        *,
        cluster: str = "default",
        probe_interval: float = 60.0,
    ) -> None:
        self.daemon = daemon
        self.backend = backend
        self.cluster = cluster
        self.probe_interval = probe_interval
        self._closed = False
        # name → (backend, factory) for every add_remote_cluster call,
        # so rejoin() can re-establish clustermesh subscriptions
        self._remote_clusters: Dict[str, Tuple] = {}
        # cluster-wide identity numbering (InitIdentityAllocator)
        self.identities = DistributedIdentityAllocator(
            backend, daemon.registry, node.name
        )
        daemon.allocate_identity = self.identities.allocate
        daemon.release_identity = self.identities.release
        # endpoints created BEFORE the join (standalone run, snapshot
        # restore) carry local-cursor identity numbers the cluster
        # never agreed on — re-allocate them through the CAS so their
        # numbers (and the announcements below) are cluster-valid
        self._adopt_existing_endpoints()
        # node membership + health/tunnel/route programming
        self.nodes = NodeRegistry(backend, node)
        daemon.attach_node_registry(self.nodes, probe_interval=probe_interval)
        # ip→identity announcements (InitIPIdentityWatcher)
        self.ipsync = IPIdentitySync(backend, daemon.ipcache, cluster=cluster)
        daemon.ipcache.add_listener(self._on_ipcache_change, replay=True)
        # remote-cluster merge (identities + ipcache + services)
        self.mesh = ClusterMesh(
            daemon.registry, daemon.ipcache, services=daemon.services
        )
        log.info("joined cluster", fields={
            "cluster": cluster, "nodeName": node.name,
        })

    def _adopt_existing_endpoints(self) -> None:
        from collections import defaultdict

        from .identity.model import MIN_USER_IDENTITY

        daemon = self.daemon
        by_ident = defaultdict(list)
        for ep in daemon.endpoint_manager.endpoints():
            if ep.identity is not None:
                by_ident[ep.identity.id].append(ep)
        renumbered = 0
        for _ident_id, eps in by_ident.items():
            old = eps[0].identity
            # reserved (host/world/…) and local CIDR identities keep
            # their fixed/local numbering — only user-range globals
            # need cluster agreement
            if old.id < MIN_USER_IDENTITY or old.is_local:
                continue
            # the local standalone binding must go FIRST: the registry
            # (rightly) refuses the same labels under two numbers
            for _ in eps:
                daemon.registry.release(old)
            new = self.identities.allocate(old.labels)
            for _ in eps[1:]:
                self.identities.allocate(old.labels)  # one ref per ep
            for ep in eps:
                ep.identity = new
                if new.id != old.id:
                    for ip, plen in ((ep.ipv4, 32), (ep.ipv6, 128)):
                        if ip:
                            daemon.ipcache.upsert(
                                f"{ip}/{plen}", new.id, source=SOURCE_AGENT
                            )
            if new.id != old.id:
                renumbered += len(eps)
        if renumbered:
            daemon._sync_pipeline_endpoints()
            daemon._regenerate("cluster join renumbering")
            log.info("renumbered endpoints at cluster join",
                     fields={"count": renumbered})

    # -- local endpoint announcements -----------------------------------
    def _on_ipcache_change(self, cidr, old, new) -> None:
        """Announce ONLY agent-sourced entries (this node's endpoints).
        kvstore-sourced entries are other nodes' announcements echoed
        back — re-announcing them would loop; the ipcache's source
        priority (agent > kvstore) already keeps our local truth from
        being clobbered by our own echo."""
        host = self.nodes.local.ipv4 or self.nodes.local.ipv6
        if new is not None and new.source == SOURCE_AGENT:
            self.ipsync.announce(cidr, new.identity, host_ip=host)
        elif new is None and old is not None and old.source == SOURCE_AGENT:
            self.ipsync.withdraw(cidr)

    # -- services -------------------------------------------------------
    def export_services(self) -> int:
        """Publish this node's service table for remote clusters
        (the clustermesh services export)."""
        return self.daemon.services.export_to_store(self.backend, self.cluster)

    def add_remote_cluster(self, name: str, backend: BackendOperations,
                           factory=None):
        """Subscribe a remote cluster's state (clustermesh). ``factory``
        (→ a fresh BackendOperations) lets rejoin() re-establish the
        subscription after an outage; without one a rejoin re-uses
        ``backend`` if it is still alive and otherwise drops the
        cluster with a warning."""
        self._remote_clusters[name] = (backend, factory)
        return self.mesh.add_cluster(name, backend)

    # -- convergence ----------------------------------------------------
    def pump(self) -> int:
        """Drain every subscription (identities, ipcache, nodes,
        remote clusters); the next pipeline rebuild picks up the new
        state. Returns events applied."""
        n = self.identities.pump()
        n += self.ipsync.pump()
        n += self.nodes.pump()
        n += self.mesh.pump()
        return n

    def close(self) -> None:
        """Leave the cluster SYMMETRICALLY to __init__ (idempotent):
        the daemon keeps serving standalone afterwards — allocation
        falls back to the local registry, this node's announcements
        are WITHDRAWN (not left to lease expiry: peers must stop
        routing here immediately), learned tunnel/route state is
        flushed, and the prober is halted rather than probing a
        frozen node list forever.

        Tolerates a DEAD backend (kvstore outage): the remote
        withdrawals are skipped — the server-side lease expiry is
        already doing that job — while every local teardown still
        runs, so a rejoin can follow."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        daemon = self.daemon
        daemon.allocate_identity = daemon.registry.allocate
        daemon.release_identity = daemon.registry.release
        daemon.ipcache.remove_listener(self._on_ipcache_change)
        daemon.health.stop()
        daemon.health.nodes = None
        try:
            self.ipsync.withdraw_all()
        except _KV_DOWN:
            log.warning("kvstore unreachable; leaving withdrawals to lease expiry")
        # learned state must not outlive the membership: encap tables
        # AND the kvstore-sourced ip→identity entries (with the
        # watcher gone they would never update again — a reused peer
        # IP would keep the departed cluster's identity forever)
        daemon.tunnel.clear()
        daemon.routes.clear()
        from .ipcache.ipcache import SOURCE_KVSTORE

        for cidr, e in daemon.ipcache.items():
            if e.source == SOURCE_KVSTORE:
                daemon.ipcache.delete(cidr, SOURCE_KVSTORE)
        self.mesh.close()
        self.ipsync.close()
        try:
            self.nodes.unregister()
        except _KV_DOWN:
            pass  # lease expiry withdraws the registration
        self.nodes.close()
        self.identities.close()

    # -- failure recovery ------------------------------------------------
    def rejoin(self, backend: BackendOperations) -> "ClusterNode":
        """Recover from a kvstore outage: tear this membership down
        (tolerating the dead backend) and rebuild it on a fresh one.
        Everything __init__ does runs again — identities re-CAS
        (endpoints keep or re-agree their numbers), this node
        re-registers, and every agent-sourced ip→identity entry
        re-announces via the replaying ipcache listener. The reference
        analog: the etcd session-loss → reconnect → re-create path of
        pkg/kvstore/allocator + node store. Returns self."""
        # under the daemon lock (an RLock — the constructors re-enter
        # it): an endpoint PUT landing between close() and the
        # adoption snapshot would otherwise keep a local-cursor
        # identity number the new cluster never CAS-agreed, and two
        # nodes could map one id to different label sets
        remotes = dict(self._remote_clusters)
        # Held across the rebuild on purpose: API calls stall for the
        # duration (bounded by the backend's op timeout per CAS), but
        # an endpoint created mid-rebuild with an un-agreed identity
        # number would poison cross-node enforcement — correctness
        # over availability, and the controller only retries on the
        # backoff schedule. Callers can hand rejoin a backend with a
        # short op_timeout to bound the worst case.
        with self.daemon._lock:
            self.close()
            try:
                self.__init__(
                    self.daemon, backend, self.nodes.local,
                    cluster=self.cluster, probe_interval=self.probe_interval,
                )
            except Exception:
                # the server died AGAIN mid-rebuild: restore the
                # standalone fallbacks a half-run __init__ may have
                # rebound (allocation must keep working locally) and
                # leave the node closed so the next controller tick
                # retries the whole rejoin
                d = self.daemon
                d.allocate_identity = d.registry.allocate
                d.release_identity = d.registry.release
                try:
                    d.ipcache.remove_listener(self._on_ipcache_change)
                except Exception:
                    pass
                d.health.stop()
                d.health.nodes = None
                self._closed = True
                try:
                    backend.close()
                except Exception:
                    pass
                # the partial __init__ reset _remote_clusters: keep the
                # snapshot so the NEXT successful rejoin still re-adds
                # every clustermesh subscription
                self._remote_clusters = remotes
                raise
        # clustermesh subscriptions are per-remote-backend: re-add each
        # (fresh backend from its factory when given; else reuse the
        # old one if it survived the outage)
        for cname, (rbe, factory) in remotes.items():
            try:
                fresh = factory() if factory is not None else rbe
                if not fresh.alive():
                    raise ConnectionError("remote backend not alive")
                self.add_remote_cluster(cname, fresh, factory)
            except Exception as e:
                log.warning("remote cluster dropped at rejoin", fields={
                    "cluster": cname, "err": f"{type(e).__name__}: {e}",
                })
        # no export_services() here: the cluster-sync controller runs
        # one right after every successful rejoin anyway
        return self

    def joined(self) -> bool:
        """True while this membership is live (backend reachable and
        not torn down) — the cluster-sync controller's rejoin gate."""
        return not self._closed and self.backend.alive()
