"""Clustered-agent assembly: one Daemon joined to the kvstore fabric.

The runDaemon wiring of the reference (daemon/main.go:818 →
kvstore.Setup, InitIdentityAllocator, node registration,
InitIPIdentityWatcher, clustermesh) as one composable object: given a
Daemon and a kvstore backend, ClusterNode

- swaps the daemon's identity allocation onto the cluster-wide CAS
  allocator (every node numbers identities identically — which is
  what keeps compiled policy tensor ROWS compatible across nodes),
- registers the node and attaches the registry to the daemon (health
  probing + tunnel/route programming ride the same observer),
- announces local endpoint IPs on the ip→identity prefix and merges
  every other node's announcements into the local ipcache,
- exports the node's services and (optionally) merges remote
  clusters' identities/ipcache/services via clustermesh.

Convergence is pump()-driven (deterministic for tests, a controller
loop in daemons), matching the rest of the kvstore layer.
"""

from __future__ import annotations

from typing import Optional

from .identity.distributed import DistributedIdentityAllocator
from .ipcache.ipcache import SOURCE_AGENT
from .ipcache.kvstore_sync import IPIdentitySync
from .kvstore.backend import BackendOperations
from .kvstore.clustermesh import ClusterMesh
from .nodes.registry import Node, NodeRegistry
from .utils.logging import get_logger

log = get_logger("cluster")

# a backend that died mid-operation (kvstore outage) raises these;
# teardown paths treat them as "the server's lease expiry will finish
# the job"
_KV_DOWN = (ConnectionError, TimeoutError, RuntimeError, OSError)


class ClusterNode:
    def __init__(
        self,
        daemon,
        backend: BackendOperations,
        node: Node,
        *,
        cluster: str = "default",
        probe_interval: float = 60.0,
    ) -> None:
        self.daemon = daemon
        self.backend = backend
        self.cluster = cluster
        self.probe_interval = probe_interval
        self._closed = False
        # cluster-wide identity numbering (InitIdentityAllocator)
        self.identities = DistributedIdentityAllocator(
            backend, daemon.registry, node.name
        )
        daemon.allocate_identity = self.identities.allocate
        daemon.release_identity = self.identities.release
        # endpoints created BEFORE the join (standalone run, snapshot
        # restore) carry local-cursor identity numbers the cluster
        # never agreed on — re-allocate them through the CAS so their
        # numbers (and the announcements below) are cluster-valid
        self._adopt_existing_endpoints()
        # node membership + health/tunnel/route programming
        self.nodes = NodeRegistry(backend, node)
        daemon.attach_node_registry(self.nodes, probe_interval=probe_interval)
        # ip→identity announcements (InitIPIdentityWatcher)
        self.ipsync = IPIdentitySync(backend, daemon.ipcache, cluster=cluster)
        daemon.ipcache.add_listener(self._on_ipcache_change, replay=True)
        # remote-cluster merge (identities + ipcache + services)
        self.mesh = ClusterMesh(
            daemon.registry, daemon.ipcache, services=daemon.services
        )
        log.info("joined cluster", fields={
            "cluster": cluster, "nodeName": node.name,
        })

    def _adopt_existing_endpoints(self) -> None:
        from collections import defaultdict

        from .identity.model import MIN_USER_IDENTITY

        daemon = self.daemon
        by_ident = defaultdict(list)
        for ep in daemon.endpoint_manager.endpoints():
            if ep.identity is not None:
                by_ident[ep.identity.id].append(ep)
        renumbered = 0
        for _ident_id, eps in by_ident.items():
            old = eps[0].identity
            # reserved (host/world/…) and local CIDR identities keep
            # their fixed/local numbering — only user-range globals
            # need cluster agreement
            if old.id < MIN_USER_IDENTITY or old.is_local:
                continue
            # the local standalone binding must go FIRST: the registry
            # (rightly) refuses the same labels under two numbers
            for _ in eps:
                daemon.registry.release(old)
            new = self.identities.allocate(old.labels)
            for _ in eps[1:]:
                self.identities.allocate(old.labels)  # one ref per ep
            for ep in eps:
                ep.identity = new
                if new.id != old.id:
                    for ip, plen in ((ep.ipv4, 32), (ep.ipv6, 128)):
                        if ip:
                            daemon.ipcache.upsert(
                                f"{ip}/{plen}", new.id, source=SOURCE_AGENT
                            )
            if new.id != old.id:
                renumbered += len(eps)
        if renumbered:
            daemon._sync_pipeline_endpoints()
            daemon._regenerate("cluster join renumbering")
            log.info("renumbered endpoints at cluster join",
                     fields={"count": renumbered})

    # -- local endpoint announcements -----------------------------------
    def _on_ipcache_change(self, cidr, old, new) -> None:
        """Announce ONLY agent-sourced entries (this node's endpoints).
        kvstore-sourced entries are other nodes' announcements echoed
        back — re-announcing them would loop; the ipcache's source
        priority (agent > kvstore) already keeps our local truth from
        being clobbered by our own echo."""
        host = self.nodes.local.ipv4 or self.nodes.local.ipv6
        if new is not None and new.source == SOURCE_AGENT:
            self.ipsync.announce(cidr, new.identity, host_ip=host)
        elif new is None and old is not None and old.source == SOURCE_AGENT:
            self.ipsync.withdraw(cidr)

    # -- services -------------------------------------------------------
    def export_services(self) -> int:
        """Publish this node's service table for remote clusters
        (the clustermesh services export)."""
        return self.daemon.services.export_to_store(self.backend, self.cluster)

    def add_remote_cluster(self, name: str, backend: BackendOperations):
        return self.mesh.add_cluster(name, backend)

    # -- convergence ----------------------------------------------------
    def pump(self) -> int:
        """Drain every subscription (identities, ipcache, nodes,
        remote clusters); the next pipeline rebuild picks up the new
        state. Returns events applied."""
        n = self.identities.pump()
        n += self.ipsync.pump()
        n += self.nodes.pump()
        n += self.mesh.pump()
        return n

    def close(self) -> None:
        """Leave the cluster SYMMETRICALLY to __init__ (idempotent):
        the daemon keeps serving standalone afterwards — allocation
        falls back to the local registry, this node's announcements
        are WITHDRAWN (not left to lease expiry: peers must stop
        routing here immediately), learned tunnel/route state is
        flushed, and the prober is halted rather than probing a
        frozen node list forever.

        Tolerates a DEAD backend (kvstore outage): the remote
        withdrawals are skipped — the server-side lease expiry is
        already doing that job — while every local teardown still
        runs, so a rejoin can follow."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        daemon = self.daemon
        daemon.allocate_identity = daemon.registry.allocate
        daemon.release_identity = daemon.registry.release
        daemon.ipcache.remove_listener(self._on_ipcache_change)
        daemon.health.stop()
        daemon.health.nodes = None
        try:
            self.ipsync.withdraw_all()
        except _KV_DOWN:
            log.warning("kvstore unreachable; leaving withdrawals to lease expiry")
        # learned state must not outlive the membership: encap tables
        # AND the kvstore-sourced ip→identity entries (with the
        # watcher gone they would never update again — a reused peer
        # IP would keep the departed cluster's identity forever)
        daemon.tunnel.clear()
        daemon.routes.clear()
        from .ipcache.ipcache import SOURCE_KVSTORE

        for cidr, e in daemon.ipcache.items():
            if e.source == SOURCE_KVSTORE:
                daemon.ipcache.delete(cidr, SOURCE_KVSTORE)
        self.mesh.close()
        self.ipsync.close()
        try:
            self.nodes.unregister()
        except _KV_DOWN:
            pass  # lease expiry withdraws the registration
        self.nodes.close()
        self.identities.close()

    # -- failure recovery ------------------------------------------------
    def rejoin(self, backend: BackendOperations) -> "ClusterNode":
        """Recover from a kvstore outage: tear this membership down
        (tolerating the dead backend) and rebuild it on a fresh one.
        Everything __init__ does runs again — identities re-CAS
        (endpoints keep or re-agree their numbers), this node
        re-registers, and every agent-sourced ip→identity entry
        re-announces via the replaying ipcache listener. The reference
        analog: the etcd session-loss → reconnect → re-create path of
        pkg/kvstore/allocator + node store. Returns self."""
        # under the daemon lock (an RLock — the constructors re-enter
        # it): an endpoint PUT landing between close() and the
        # adoption snapshot would otherwise keep a local-cursor
        # identity number the new cluster never CAS-agreed, and two
        # nodes could map one id to different label sets
        with self.daemon._lock:
            self.close()
            self.__init__(
                self.daemon, backend, self.nodes.local,
                cluster=self.cluster, probe_interval=self.probe_interval,
            )
        self.export_services()
        return self
