"""Policy compiler: Repository + IdentityRegistry → dense device tensors.

This is the TPU-native replacement for the reference's per-endpoint
policy resolution loop (pkg/endpoint/policy.go:317-389, the O(identities
× rules) walk) and the clang/llc datapath compile pipeline
(pkg/datapath/loader/compile.go): instead of compiling C programs per
endpoint, the whole rule repository is lowered once into dense arrays
that a jitted verdict kernel evaluates for *batches* of flows.
"""

from .selectors import SelectorTable
from .program import (
    CompiledPolicy,
    CompileState,
    DirectionPacker,
    DirectionProgram,
    compile_policy,
    compile_policy_state,
    host_selector_matches,
    try_append_rules,
)

__all__ = [
    "SelectorTable",
    "CompiledPolicy",
    "CompileState",
    "DirectionPacker",
    "DirectionProgram",
    "compile_policy",
    "compile_policy_state",
    "host_selector_matches",
    "try_append_rules",
]
