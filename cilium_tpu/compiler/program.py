"""Lower the policy repository into order-independent matmul operands.

The reference evaluates verdicts by walking rules in order
(pkg/policy/repository.go:80-105); the walk is order-independent in
outcome (a DENIED from any selected rule dominates; otherwise any
ALLOWED wins; else UNDECIDED). That lets the whole rule set compile to
relations over the *selector axis* S (distinct selectors dedupe
heavily), evaluated as int8 matmuls on the MXU — per-element gathers
are pathologically slow on TPU, so nothing downstream of the one
packed row-gather per flow is data-dependent. Per direction:

- ``deny_mat [S,S]``: deny_mat[s1,s2]=1 iff some rule has subject
  selector s1 and FromRequires selector s2 (rule.go:323-345). Flow is
  L3-DENIED iff subj∧s1 and ¬(peer∧s2) for some set pair:
  ``deny = any(subj & ((1-peer) @ deny_matᵀ > 0))``. The negation of
  deny is ``req_ok``, the "all collected requirements hold" term that
  repository.go:249-261 folds into explicit L4 peer selectors.
- ``allow_mat [S,S]``: pure-L3 allows (directional rules without
  ToPorts), including entity- and CIDR-derived selectors
  (ingress.go GetSourceEndpointSelectors):
  ``l3_allow = any(subj & (peer @ allow_matᵀ > 0))``.
- **port vocab** ``ports/protos [P4]``: distinct (port, proto) keys
  appearing in any ToPorts (L4PolicyMap's literal "port/proto" keying;
  a ToPorts port 0 only covers a port-0 query). A flow one-hot-encodes
  its (dport, proto) against the vocab; a miss means no L4 coverage.
- **L4 entry relation** over K1 = distinct (subj_sel, port_id) combos:
  ``s1_mat [S,K1]`` and ``p1_mat [P4,K1]`` activate a combo when the
  subject matches and the port matches; ``en_mat/ee_mat [K1,S]`` hold
  the peer selectors reachable from that combo (en = entity/CIDR/
  wildcard peers, ee = explicit FromEndpoints peers which additionally
  require req_ok — the requirements fold of rule.go:198-232). This
  flattens L4Filter creation + merge (l4.go:148, rule.go:46-122) into
  an OR over (combo, peer) pairs.
- **group pre-check** (rule.go:133-138: a directional rule whose peers
  all fail to match the concrete peer contributes no filters):
  ``gpn_mat/gpe_mat [S,G]`` per-group peer selectors (non-explicit /
  explicit) + ``group_no_peers [G]``.
- **L7 presence** over K7 = distinct (subj_sel, port_id) of L7-bearing
  port rules: ``s7_mat [S,K7]``, ``p7_mat [P4,K7]``, ``g7_mat [G,K7]``
  (the combo's pre-check group). A flow's L4 allow is a proxy redirect
  iff some K7 combo activates with its group pre-check passing — i.e.
  the merged L4Filter at that port has an l7_parser (l4.go:82 sets
  parsers only on TCP). This subsumes wildcardL3L4Rules
  (repository.go:128-168) on the *decision* path: extending an L7
  filter's endpoint list by a broader allow never changes a decision
  (the pre-check that admits the filter already implies a matching L4
  entry); it only wildcards which L7 rules apply, which the proxy
  layer derives separately.

Raw entry lists are kept alongside for host-side consumers (policymap
slot discovery, debugging). Protocols are IANA numbers (u8proto.py),
the policymap nexthdr encoding (bpf/lib/common.h:180).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..identity import IdentityRegistry
from ..labels import LabelVocab
from ..policy.api import EndpointSelector, Rule
from ..policy.cidr import cidr_selectors
from ..policy.repository import (
    Repository,
    _egress_peer_selectors,
    _ingress_peer_selectors,
)
from .. import u8proto
from .selectors import SelectorTable, WILDCARD_SELECTOR_ID

PROTO_TCP_N = u8proto.TCP
PROTO_UDP_N = u8proto.UDP

_PROTO_NUM = {"TCP": PROTO_TCP_N, "UDP": PROTO_UDP_N}


def _expand_protos(proto: str) -> Tuple[int, ...]:
    if proto == "ANY":
        return (PROTO_TCP_N, PROTO_UDP_N)
    return (_PROTO_NUM[proto],)


def _bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two ≥ max(n, minimum) — shape-bucketed padding so
    incremental recompiles hit XLA's compile cache."""
    size = minimum
    while size < n:
        size *= 2
    return size


def _pad_bool(values: Sequence[bool], size: int) -> np.ndarray:
    out = np.zeros(size, dtype=bool)
    out[: len(values)] = values
    return out


@dataclasses.dataclass
class DirectionProgram:
    """Matmul operands for one traffic direction (all numpy, padded to
    shape buckets so incremental recompiles hit XLA's compile cache).
    ``s_pad`` is the padded selector-axis size (multiple of 128, =32 ×
    the packed sel_match word count)."""

    s_pad: int
    # L3 relations
    deny_mat: np.ndarray  # [S, S] int8
    allow_mat: np.ndarray  # [S, S] int8
    # port vocabulary
    ports: np.ndarray  # [P4] int32 (-1 padding)
    protos: np.ndarray  # [P4] int32
    # L4 entry relation over K1 combos
    s1_mat: np.ndarray  # [S, K1] int8
    p1_mat: np.ndarray  # [P4, K1] int8
    en_mat: np.ndarray  # [K1, S] int8  entity/CIDR/wildcard peers
    ee_mat: np.ndarray  # [K1, S] int8  explicit peers (req_ok-gated)
    # group pre-check
    gpn_mat: np.ndarray  # [S, G] int8
    gpe_mat: np.ndarray  # [S, G] int8
    group_no_peers: np.ndarray  # [G] bool
    # L7 presence over K7 combos
    s7_mat: np.ndarray  # [S, K7] int8
    p7_mat: np.ndarray  # [P4, K7] int8
    g7_mat: np.ndarray  # [G, K7] int8
    # raw (unpadded) entry lists for host-side consumers
    e_subj: np.ndarray
    e_port: np.ndarray
    e_proto: np.ndarray
    l7_subj: np.ndarray
    l7_port: np.ndarray


@dataclasses.dataclass
class CompiledPolicy:
    """Host-side compiled policy: identity bitmaps + selector conjuncts
    + per-direction tables. ``revision``/``identity_version`` record the
    inputs this was compiled from (the endpoint regeneration protocol's
    revision gate, pkg/endpoint/policy.go:506)."""

    revision: int
    identity_version: int
    vocab_version: int
    num_words: int
    num_selectors: int
    # identities (dense rows)
    id_bits: np.ndarray  # [N, W] uint32
    row_ids: np.ndarray  # [N] int32 numeric identity per row
    row_live: np.ndarray  # [N] bool
    id_to_row: Dict[int, int]
    # selector conjuncts
    conj_req: np.ndarray  # [S, CPS, W] uint32
    conj_forbid: np.ndarray
    conj_valid: np.ndarray  # [S, CPS] bool
    req_count: np.ndarray  # [S, CPS] int32
    ingress: DirectionProgram = None  # type: ignore[assignment]
    egress: DirectionProgram = None  # type: ignore[assignment]

    def rows_for(self, identity_ids: Sequence[int]) -> np.ndarray:
        return np.array([self.id_to_row[i] for i in identity_ids], dtype=np.int32)


@dataclasses.dataclass
class _RawDirection:
    """Intermediate pair/entry lists before matrix packing."""

    deny: List[Tuple[int, int]]
    allow: List[Tuple[int, int]]
    entries: List[Tuple[int, int, int, int, bool, int]]
    group_no_peers: List[bool]
    gp: List[Tuple[int, int, bool]]
    l7_ports: List[Tuple[int, int, int]]


def _extract_direction(
    rules: Sequence[Rule], table: SelectorTable, ingress: bool
) -> _RawDirection:
    deny: List[Tuple[int, int]] = []
    allow: List[Tuple[int, int]] = []
    entries: List[Tuple[int, int, int, int, bool, int]] = []
    group_no_peers: List[bool] = []
    gp: List[Tuple[int, int, bool]] = []
    # L7-bearing (subj_sel, port, group) — parser presence (always TCP)
    l7_ports: List[Tuple[int, int, int]] = []

    for r in rules:
        subj = table.intern(r.endpoint_selector)
        directional = r.ingress if ingress else r.egress
        for dr in directional:
            requires = dr.from_requires if ingress else dr.to_requires
            for q in requires:
                deny.append((subj, table.intern(q)))
            peer_sels = (
                _ingress_peer_selectors(dr) if ingress else _egress_peer_selectors(dr)
            )
            if not dr.to_ports:
                for s in peer_sels:
                    allow.append((subj, table.intern(s)))
                continue

            # Directional rule with ToPorts → one pre-check group.
            explicit_raw = dr.from_endpoints if ingress else dr.to_endpoints
            entity_sels = dr.peer_selectors()[len(explicit_raw):]
            c_sels = (
                cidr_selectors(dr.from_cidr, dr.from_cidr_set)
                if ingress
                else cidr_selectors(dr.to_cidr, dr.to_cidr_set)
            )
            peers: List[Tuple[int, bool]] = (
                [(table.intern(s), True) for s in explicit_raw]
                + [(table.intern(s), False) for s in entity_sels]
                + [(table.intern(s), False) for s in c_sels]
            )
            group = len(group_no_peers)
            group_no_peers.append(not peers)
            for sid, expl in peers:
                gp.append((group, sid, expl))

            for pr in dr.to_ports:
                has_l7 = bool(pr.rules)
                for pp in pr.ports:
                    for proto in _expand_protos(pp.proto):
                        if has_l7 and proto == PROTO_TCP_N:
                            l7_ports.append((subj, pp.port, group))
                        if not peers:
                            entries.append(
                                (subj, WILDCARD_SELECTOR_ID, pp.port, proto, False, group)
                            )
                        else:
                            for sid, expl in peers:
                                entries.append((subj, sid, pp.port, proto, expl, group))

    return _RawDirection(deny, allow, entries, group_no_peers, gp, l7_ports)


def _pack_direction(raw: _RawDirection, s_pad: int) -> DirectionProgram:
    deny_mat = np.zeros((s_pad, s_pad), np.int8)
    for s1, s2 in raw.deny:
        deny_mat[s1, s2] = 1
    allow_mat = np.zeros((s_pad, s_pad), np.int8)
    for s1, s2 in raw.allow:
        allow_mat[s1, s2] = 1

    # Port vocabulary over entries ∪ L7 ports (L7 is always TCP).
    port_id: Dict[Tuple[int, int], int] = {}
    for e in raw.entries:
        port_id.setdefault((e[2], e[3]), len(port_id))
    for l in raw.l7_ports:
        port_id.setdefault((l[1], PROTO_TCP_N), len(port_id))
    p4 = _bucket(len(port_id))
    ports = np.full(p4, -1, np.int32)
    protos = np.full(p4, -1, np.int32)
    for (port, proto), i in port_id.items():
        ports[i], protos[i] = port, proto

    # K1 combos: (subj_sel, port_id) with explicit/other peer matrices.
    combo_id: Dict[Tuple[int, int], int] = {}
    combo_peers: List[List[Tuple[int, bool]]] = []
    for subj, sid, port, proto, expl, _group in raw.entries:
        key = (subj, port_id[(port, proto)])
        k = combo_id.setdefault(key, len(combo_peers))
        if k == len(combo_peers):
            combo_peers.append([])
        combo_peers[k].append((sid, expl))
    k1 = _bucket(len(combo_id))
    s1_mat = np.zeros((s_pad, k1), np.int8)
    p1_mat = np.zeros((p4, k1), np.int8)
    en_mat = np.zeros((k1, s_pad), np.int8)
    ee_mat = np.zeros((k1, s_pad), np.int8)
    for (subj, pid), k in combo_id.items():
        s1_mat[subj, k] = 1
        p1_mat[pid, k] = 1
        for sid, expl in combo_peers[k]:
            (ee_mat if expl else en_mat)[k, sid] = 1

    g = _bucket(len(raw.group_no_peers))
    gpn_mat = np.zeros((s_pad, g), np.int8)
    gpe_mat = np.zeros((s_pad, g), np.int8)
    for group, sid, expl in raw.gp:
        (gpe_mat if expl else gpn_mat)[sid, group] = 1
    no_peers = _pad_bool(raw.group_no_peers, g)

    # K7 combos: (subj_sel, port_id, group) for L7 presence.
    k7_ids: Dict[Tuple[int, int, int], int] = {}
    for subj, port, group in raw.l7_ports:
        k7_ids.setdefault((subj, port_id[(port, PROTO_TCP_N)], group), len(k7_ids))
    k7_keys = list(k7_ids)
    k7 = _bucket(len(k7_keys))
    s7_mat = np.zeros((s_pad, k7), np.int8)
    p7_mat = np.zeros((p4, k7), np.int8)
    g7_mat = np.zeros((g, k7), np.int8)
    for i, (subj, pid, group) in enumerate(k7_keys):
        s7_mat[subj, i] = 1
        p7_mat[pid, i] = 1
        g7_mat[group, i] = 1

    return DirectionProgram(
        s_pad=s_pad,
        deny_mat=deny_mat,
        allow_mat=allow_mat,
        ports=ports,
        protos=protos,
        s1_mat=s1_mat,
        p1_mat=p1_mat,
        en_mat=en_mat,
        ee_mat=ee_mat,
        gpn_mat=gpn_mat,
        gpe_mat=gpe_mat,
        group_no_peers=no_peers,
        s7_mat=s7_mat,
        p7_mat=p7_mat,
        g7_mat=g7_mat,
        e_subj=np.asarray([e[0] for e in raw.entries], np.int32),
        e_port=np.asarray([e[2] for e in raw.entries], np.int32),
        e_proto=np.asarray([e[3] for e in raw.entries], np.int32),
        l7_subj=np.asarray([l[0] for l in raw.l7_ports], np.int32),
        l7_port=np.asarray([l[1] for l in raw.l7_ports], np.int32),
    )


def compile_policy(repo: Repository, registry: IdentityRegistry) -> CompiledPolicy:
    """Lower repository + identities to dense tables.

    Order matters: selectors intern their vocab bits first, then the
    identity dense view interns identity bits (growing the vocab), and
    only then are conjuncts packed against the final word count — so
    identity bitmaps and selector masks share one bit space.
    """
    table = SelectorTable()
    with repo._lock:
        rules = list(repo.rules)
        revision = repo.revision
    raw_ingress = _extract_direction(rules, table, ingress=True)
    raw_egress = _extract_direction(rules, table, ingress=False)

    # Selector axis padded to a multiple of 128 (MXU tile) — the padded
    # tail never matches (no conjuncts) and relation matrices are zero
    # there.
    s_pad = max(128, ((len(table) + 127) // 128) * 128)
    ingress = _pack_direction(raw_ingress, s_pad)
    egress = _pack_direction(raw_egress, s_pad)

    vocab = registry.vocab
    lowered = table.lower_bits(vocab)
    lowered += [[] for _ in range(s_pad - len(lowered))]
    id_bits, row_ids, row_live = registry.dense_view()
    num_words = id_bits.shape[1]
    conj_req, conj_forbid, conj_valid, req_count = table.pack(lowered, vocab, num_words)

    id_to_row = {int(i): r for r, i in enumerate(row_ids) if row_live[r]}
    return CompiledPolicy(
        revision=revision,
        identity_version=registry.version,
        vocab_version=vocab.version,
        num_words=num_words,
        num_selectors=len(table),
        id_bits=id_bits,
        row_ids=row_ids,
        row_live=row_live,
        id_to_row=id_to_row,
        conj_req=conj_req,
        conj_forbid=conj_forbid,
        conj_valid=conj_valid,
        req_count=req_count,
        ingress=ingress,
        egress=egress,
    )
