"""Lower the policy repository into order-independent matmul operands.

The reference evaluates verdicts by walking rules in order
(pkg/policy/repository.go:80-105); the walk is order-independent in
outcome (a DENIED from any selected rule dominates; otherwise any
ALLOWED wins; else UNDECIDED). That lets the whole rule set compile to
relations over the *selector axis* S (distinct selectors dedupe
heavily), evaluated as int8 matmuls on the MXU — per-element gathers
are pathologically slow on TPU, so nothing downstream of the one
packed row-gather per flow is data-dependent. Per direction:

- ``deny_mat [S,S]``: deny_mat[s1,s2]=1 iff some rule has subject
  selector s1 and FromRequires selector s2 (rule.go:323-345). Flow is
  L3-DENIED iff subj∧s1 and ¬(peer∧s2) for some set pair:
  ``deny = any(subj & ((1-peer) @ deny_matᵀ > 0))``. The negation of
  deny is ``req_ok``, the "all collected requirements hold" term that
  repository.go:249-261 folds into explicit L4 peer selectors.
- ``allow_mat [S,S]``: pure-L3 allows (directional rules without
  ToPorts), including entity- and CIDR-derived selectors
  (ingress.go GetSourceEndpointSelectors):
  ``l3_allow = any(subj & (peer @ allow_matᵀ > 0))``.
- **port vocab** ``ports/protos [P4]``: distinct (port, proto) keys
  appearing in any ToPorts (L4PolicyMap's literal "port/proto" keying;
  a ToPorts port 0 only covers a port-0 query). A flow one-hot-encodes
  its (dport, proto) against the vocab; a miss means no L4 coverage.
- **L4 entry relation** over K1 = distinct (subj_sel, port_id) combos:
  ``s1_mat [S,K1]`` and ``p1_mat [P4,K1]`` activate a combo when the
  subject matches and the port matches; ``en_mat/ee_mat [K1,S]`` hold
  the peer selectors reachable from that combo (en = entity/CIDR/
  wildcard peers, ee = explicit FromEndpoints peers which additionally
  require req_ok — the requirements fold of rule.go:198-232). This
  flattens L4Filter creation + merge (l4.go:148, rule.go:46-122) into
  an OR over (combo, peer) pairs.
- **group pre-check** (rule.go:133-138: a directional rule whose peers
  all fail to match the concrete peer contributes no filters):
  ``gpn_mat/gpe_mat [S,G]`` per-group peer selectors (non-explicit /
  explicit) + ``group_no_peers [G]``.
- **L7 presence** over K7 = distinct (subj_sel, port_id) of L7-bearing
  port rules: ``s7_mat [S,K7]``, ``p7_mat [P4,K7]``, ``g7_mat [G,K7]``
  (the combo's pre-check group). A flow's L4 allow is a proxy redirect
  iff some K7 combo activates with its group pre-check passing — i.e.
  the merged L4Filter at that port has an l7_parser (l4.go:82 sets
  parsers only on TCP). This subsumes wildcardL3L4Rules
  (repository.go:128-168) on the *decision* path: extending an L7
  filter's endpoint list by a broader allow never changes a decision
  (the pre-check that admits the filter already implies a matching L4
  entry); it only wildcards which L7 rules apply, which the proxy
  layer derives separately.

Raw entry lists are kept alongside for host-side consumers (policymap
slot discovery, debugging). Protocols are IANA numbers (u8proto.py),
the policymap nexthdr encoding (bpf/lib/common.h:180).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..identity import IdentityRegistry
from ..labels import LabelVocab
from ..policy.api import EndpointSelector, Rule
from ..policy.cidr import cidr_selectors
from ..policy.repository import (
    Repository,
    _egress_peer_selectors,
    _ingress_peer_selectors,
)
from .. import u8proto
from .selectors import SelectorTable, WILDCARD_SELECTOR_ID

PROTO_TCP_N = u8proto.TCP
PROTO_UDP_N = u8proto.UDP

_PROTO_NUM = {"TCP": PROTO_TCP_N, "UDP": PROTO_UDP_N}


def _expand_protos(proto: str) -> Tuple[int, ...]:
    if proto == "ANY":
        return (PROTO_TCP_N, PROTO_UDP_N)
    return (_PROTO_NUM[proto],)


def _bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two ≥ max(n, minimum) — shape-bucketed padding so
    incremental recompiles hit XLA's compile cache."""
    size = minimum
    while size < n:
        size *= 2
    return size


def _bucket_slack(n: int, minimum: int = 8) -> int:
    """Bucket with ≥25% headroom so incremental rule appends usually fit
    without a reshape-forced full recompile (only the cheap non-selector
    axes use this — S² matrices keep exact buckets)."""
    return _bucket(n + max(4, n // 4), minimum)


def _iter_group_sigs(raw: _RawDirection):
    """Yield (signature, local_group_index) per local group of a raw
    extraction; signature = (no_peers, frozenset((sid, explicit)))."""
    peers_of: Dict[int, List[Tuple[int, bool]]] = {}
    for g, sid, expl in raw.gp:
        peers_of.setdefault(g, []).append((sid, expl))
    for i, no_peers in enumerate(raw.group_no_peers):
        yield (no_peers, frozenset(peers_of.get(i, ()))), i


def _remove_occurrences(items: list, removed: list) -> list:
    """Remove each element of ``removed`` once from ``items``
    (multiset subtraction, order-preserving)."""
    if not removed:
        return items
    from collections import Counter

    need = Counter(removed)
    kept = []
    for x in items:
        if need.get(x, 0) > 0:
            need[x] -= 1
        else:
            kept.append(x)
    return kept


def _pad_bool(values: Sequence[bool], size: int) -> np.ndarray:
    out = np.zeros(size, dtype=bool)
    out[: len(values)] = values
    return out


@dataclasses.dataclass
class DirectionProgram:
    """Matmul operands for one traffic direction (all numpy, padded to
    shape buckets so incremental recompiles hit XLA's compile cache).
    ``s_pad`` is the padded selector-axis size (multiple of 128, =32 ×
    the packed sel_match word count)."""

    s_pad: int
    # L3 relations
    deny_mat: np.ndarray  # [S, S] int8
    allow_mat: np.ndarray  # [S, S] int8
    # port vocabulary
    ports: np.ndarray  # [P4] int32 (-1 padding)
    protos: np.ndarray  # [P4] int32
    # L4 entry relation over K1 combos
    s1_mat: np.ndarray  # [S, K1] int8
    p1_mat: np.ndarray  # [P4, K1] int8
    en_mat: np.ndarray  # [K1, S] int8  entity/CIDR/wildcard peers
    ee_mat: np.ndarray  # [K1, S] int8  explicit peers (req_ok-gated)
    # group pre-check
    gpn_mat: np.ndarray  # [S, G] int8
    gpe_mat: np.ndarray  # [S, G] int8
    group_no_peers: np.ndarray  # [G] bool
    # L7 presence over K7 combos
    s7_mat: np.ndarray  # [S, K7] int8
    p7_mat: np.ndarray  # [P4, K7] int8
    g7_mat: np.ndarray  # [G, K7] int8
    # raw (unpadded) entry lists for host-side consumers
    e_subj: np.ndarray
    e_port: np.ndarray
    e_proto: np.ndarray
    l7_subj: np.ndarray
    l7_port: np.ndarray


@dataclasses.dataclass
class CompiledPolicy:
    """Host-side compiled policy: identity bitmaps + selector conjuncts
    + per-direction tables. ``revision``/``identity_version`` record the
    inputs this was compiled from (the endpoint regeneration protocol's
    revision gate, pkg/endpoint/policy.go:506)."""

    revision: int
    identity_version: int
    vocab_version: int
    num_words: int
    num_selectors: int
    # identities (dense rows)
    id_bits: np.ndarray  # [N, W] uint32
    row_ids: np.ndarray  # [N] int32 numeric identity per row
    row_live: np.ndarray  # [N] bool
    id_to_row: Dict[int, int]
    # selector conjuncts
    conj_req: np.ndarray  # [S, CPS, W] uint32
    conj_forbid: np.ndarray
    conj_valid: np.ndarray  # [S, CPS] bool
    req_count: np.ndarray  # [S, CPS] int32
    ingress: DirectionProgram = None  # type: ignore[assignment]
    egress: DirectionProgram = None  # type: ignore[assignment]

    def rows_for(self, identity_ids: Sequence[int]) -> np.ndarray:
        return np.array([self.id_to_row[i] for i in identity_ids], dtype=np.int32)


@dataclasses.dataclass
class _RawDirection:
    """Intermediate pair/entry lists before matrix packing."""

    deny: List[Tuple[int, int]]
    allow: List[Tuple[int, int]]
    entries: List[Tuple[int, int, int, int, bool, int]]
    group_no_peers: List[bool]
    gp: List[Tuple[int, int, bool]]
    l7_ports: List[Tuple[int, int, int]]


def _extract_direction(
    rules: Sequence[Rule], table: SelectorTable, ingress: bool
) -> _RawDirection:
    deny: List[Tuple[int, int]] = []
    allow: List[Tuple[int, int]] = []
    entries: List[Tuple[int, int, int, int, bool, int]] = []
    group_no_peers: List[bool] = []
    gp: List[Tuple[int, int, bool]] = []
    # L7-bearing (subj_sel, port, group) — parser presence (always TCP)
    l7_ports: List[Tuple[int, int, int]] = []

    for r in rules:
        subj = table.intern(r.endpoint_selector)
        directional = r.ingress if ingress else r.egress
        for dr in directional:
            requires = dr.from_requires if ingress else dr.to_requires
            for q in requires:
                deny.append((subj, table.intern(q)))
            peer_sels = (
                _ingress_peer_selectors(dr) if ingress else _egress_peer_selectors(dr)
            )
            if not dr.to_ports:
                for s in peer_sels:
                    allow.append((subj, table.intern(s)))
                continue

            # Directional rule with ToPorts → one pre-check group.
            explicit_raw = dr.from_endpoints if ingress else dr.to_endpoints
            entity_sels = dr.peer_selectors()[len(explicit_raw):]
            c_sels = (
                cidr_selectors(dr.from_cidr, dr.from_cidr_set)
                if ingress
                else cidr_selectors(dr.to_cidr, dr.to_cidr_set)
            )
            peers: List[Tuple[int, bool]] = (
                [(table.intern(s), True) for s in explicit_raw]
                + [(table.intern(s), False) for s in entity_sels]
                + [(table.intern(s), False) for s in c_sels]
            )
            group = len(group_no_peers)
            group_no_peers.append(not peers)
            for sid, expl in peers:
                gp.append((group, sid, expl))

            for pr in dr.to_ports:
                has_l7 = bool(pr.rules)
                for pp in pr.ports:
                    for proto in _expand_protos(pp.proto):
                        if has_l7 and proto == PROTO_TCP_N:
                            l7_ports.append((subj, pp.port, group))
                        if not peers:
                            entries.append(
                                (subj, WILDCARD_SELECTOR_ID, pp.port, proto, False, group)
                            )
                        else:
                            for sid, expl in peers:
                                entries.append((subj, sid, pp.port, proto, expl, group))

    return _RawDirection(deny, allow, entries, group_no_peers, gp, l7_ports)


class DirectionPacker:
    """Stateful matrix packer for one direction: builds the
    DirectionProgram from raw lists and supports **in-place appends**
    of later rule batches, provided every axis stays inside its padded
    bucket. This is the incremental half of the regeneration protocol
    (pkg/endpoint/policy.go:506-552): a single rule import mutates a
    few matrix cells instead of recompiling the world.

    Cells are **reference-counted per contributing rule** so rule
    deletion is also incremental (repository.go DeleteByLabels:286
    deletes in place): ``remove_rule`` decrements each cell the rule
    contributed and clears cells reaching zero, logging value-0 writes
    the engine scatters to the device — no recompile, no reshape.
    Orphaned selector columns / port-vocab ids / combo slots stay
    allocated (they can never activate with their cells cleared) and
    are reclaimed by the next natural full rebuild."""

    def __init__(self, raw: _RawDirection, s_pad: int) -> None:
        self.s_pad = s_pad
        self.n_groups = len(raw.group_no_peers)
        self.entries: List[Tuple[int, int, int, int, bool, int]] = []
        self.l7_list: List[Tuple[int, int, int]] = []
        # cell → number of rule contributions still referencing it
        self.cell_refs: Dict[Tuple[str, int, int], int] = {}
        # per-rule attribution (key = id(rule)): cells (with
        # multiplicity), owned group ids, entry/l7 tuples
        self.rule_cells: Dict[int, List[Tuple[str, int, int]]] = {}
        self.rule_groups: Dict[int, List[int]] = {}
        self.rule_entries: Dict[int, List[tuple]] = {}
        self.rule_l7: Dict[int, List[tuple]] = {}
        self._attr_key: Optional[int] = None

        # Port vocabulary over entries ∪ L7 ports (L7 is always TCP).
        self.port_id: Dict[Tuple[int, int], int] = {}
        for e in raw.entries:
            self.port_id.setdefault((e[2], e[3]), len(self.port_id))
        for l in raw.l7_ports:
            self.port_id.setdefault((l[1], PROTO_TCP_N), len(self.port_id))
        p4 = _bucket_slack(len(self.port_id))
        ports = np.full(p4, -1, np.int32)
        protos = np.full(p4, -1, np.int32)
        for (port, proto), i in self.port_id.items():
            ports[i], protos[i] = port, proto

        # K1 combos: (subj_sel, port_id) with explicit/other peer sets.
        self.combo_id: Dict[Tuple[int, int], int] = {}
        for subj, _sid, port, proto, _expl, _group in raw.entries:
            self.combo_id.setdefault((subj, self.port_id[(port, proto)]), len(self.combo_id))
        k1 = _bucket_slack(len(self.combo_id))

        # Pre-check groups are INTERNED by signature (no_peers flag +
        # peer (sid, explicit) set): two directional rules with the
        # same peer sets share one group column. At rule counts where
        # many rules repeat selector shapes this collapses the G axis
        # by 5-10×, and the [B,S]@[S,G] group matmuls dominate the
        # materialization sweep's FLOPs. Refcounted for deletion.
        self.group_sig: Dict[tuple, int] = {}
        self.group_refs: Dict[int, int] = {}
        sigs = {s for s, _ in _iter_group_sigs(raw)}
        g = _bucket_slack(max(1, len(sigs)))

        # K7 combos: (subj_sel, port_id, group) for L7 presence —
        # sized via the same deterministic intern order _write uses.
        order: Dict[tuple, int] = {}
        local_gid: Dict[int, int] = {}
        for sig, local in _iter_group_sigs(raw):
            local_gid[local] = order.setdefault(sig, len(order))
        k7_keys = {
            (subj, self.port_id[(port, PROTO_TCP_N)], local_gid[grp])
            for subj, port, grp in raw.l7_ports
        }
        self.k7_ids: Dict[Tuple[int, int, int], int] = {}
        k7 = _bucket_slack(len(k7_keys))

        self.prog = DirectionProgram(
            s_pad=s_pad,
            deny_mat=np.zeros((s_pad, s_pad), np.int8),
            allow_mat=np.zeros((s_pad, s_pad), np.int8),
            ports=ports,
            protos=protos,
            s1_mat=np.zeros((s_pad, k1), np.int8),
            p1_mat=np.zeros((p4, k1), np.int8),
            en_mat=np.zeros((k1, s_pad), np.int8),
            ee_mat=np.zeros((k1, s_pad), np.int8),
            gpn_mat=np.zeros((s_pad, g), np.int8),
            gpe_mat=np.zeros((s_pad, g), np.int8),
            group_no_peers=np.zeros(g, bool),
            s7_mat=np.zeros((s_pad, k7), np.int8),
            p7_mat=np.zeros((p4, k7), np.int8),
            g7_mat=np.zeros((g, k7), np.int8),
            e_subj=np.zeros(0, np.int32),
            e_port=np.zeros(0, np.int32),
            e_proto=np.zeros(0, np.int32),
            l7_subj=np.zeros(0, np.int32),
            l7_port=np.zeros(0, np.int32),
        )
        self.n_groups = 0
        # Cell-level write log: (matrix, i, j, value). Appends record
        # their writes here so the engine can patch device tables with
        # tiny scatters instead of re-uploading whole matrices.
        self.writes: List[Tuple[str, int, int, int]] = []

    def take_writes(self) -> List[Tuple[str, int, int, int]]:
        w, self.writes = self.writes, []
        return w

    def _mat_by_name(self, name: str) -> np.ndarray:
        p = self.prog
        return {
            "deny": p.deny_mat, "allow": p.allow_mat,
            "s1": p.s1_mat, "p1": p.p1_mat,
            "en": p.en_mat, "ee": p.ee_mat,
            "gpn": p.gpn_mat, "gpe": p.gpe_mat,
            "s7": p.s7_mat, "p7": p.p7_mat, "g7": p.g7_mat,
        }[name]

    def write_rule(self, rule_key: int, raw: _RawDirection) -> None:
        """Write ONE rule's raw extraction, attributing every cell,
        group ref, and entry to ``rule_key`` for later removal. Callers
        must call refresh_entry_views() after a batch."""
        self._attr_key = rule_key
        self.rule_cells.setdefault(rule_key, [])
        self.rule_groups.setdefault(rule_key, [])
        n_ent, n_l7 = len(self.entries), len(self.l7_list)
        self._write(raw)
        self.rule_entries.setdefault(rule_key, []).extend(self.entries[n_ent:])
        self.rule_l7.setdefault(rule_key, []).extend(self.l7_list[n_l7:])
        self._attr_key = None

    def remove_rule(self, rule_key: int) -> bool:
        """Retract one rule's contributions in place. False when the
        rule is unknown to this packer (caller must full-rebuild).
        Callers must call refresh_entry_views() after a batch."""
        cells = self.rule_cells.pop(rule_key, None)
        if cells is None:
            return False
        for key in cells:
            n = self.cell_refs.get(key, 0) - 1
            if n > 0:
                self.cell_refs[key] = n
            else:
                self.cell_refs.pop(key, None)
                name, i, j = key
                self._mat_by_name(name)[i, j] = 0
                self.writes.append((name, i, j, 0))
        for g in self.rule_groups.pop(rule_key, []):
            # interned groups are shared: only the LAST contributor's
            # removal deactivates the column (its gpn/gpe/g7 cells die
            # via cell_refs; the id stays interned for reuse)
            n = self.group_refs.get(g, 0) - 1
            if n > 0:
                self.group_refs[g] = n
            else:
                self.group_refs.pop(g, None)
                if self.prog.group_no_peers[g]:
                    self.prog.group_no_peers[g] = False
                    self.writes.append(("group_no_peers", g, 0, 0))
        self.entries = _remove_occurrences(
            self.entries, self.rule_entries.pop(rule_key, [])
        )
        self.l7_list = _remove_occurrences(
            self.l7_list, self.rule_l7.pop(rule_key, [])
        )
        return True

    def refresh_entry_views(self) -> None:
        """Rebuild the raw entry arrays host-side consumers read
        (policymap slot discovery) — called once per write/remove
        batch, not per rule, to stay linear."""
        p = self.prog
        p.e_subj = np.asarray([e[0] for e in self.entries], np.int32)
        p.e_port = np.asarray([e[2] for e in self.entries], np.int32)
        p.e_proto = np.asarray([e[3] for e in self.entries], np.int32)
        p.l7_subj = np.asarray([l[0] for l in self.l7_list], np.int32)
        p.l7_port = np.asarray([l[1] for l in self.l7_list], np.int32)

    # ------------------------------------------------------------------
    def can_append(self, raw: _RawDirection) -> bool:
        """True iff ``raw`` fits the existing buckets (no shape change)."""
        p = self.prog
        new_ports = set()
        for e in raw.entries:
            if (e[2], e[3]) not in self.port_id:
                new_ports.add((e[2], e[3]))
        for l in raw.l7_ports:
            if (l[1], PROTO_TCP_N) not in self.port_id:
                new_ports.add((l[1], PROTO_TCP_N))
        if len(self.port_id) + len(new_ports) > p.ports.size:
            return False
        # combos/k7 need port ids; count conservatively with new keys
        pid_probe = dict(self.port_id)
        for key in new_ports:
            pid_probe[key] = len(pid_probe)
        new_combos = {
            (e[0], pid_probe[(e[2], e[3])])
            for e in raw.entries
            if (e[0], pid_probe[(e[2], e[3])]) not in self.combo_id
        }
        if len(self.combo_id) + len(new_combos) > p.s1_mat.shape[1]:
            return False
        # probe group interning the same way _write will (existing
        # signatures reuse their column; only genuinely new sigs grow)
        local_gid: Dict[int, int] = {}
        next_gid = len(self.group_sig)
        probe_new: Dict[tuple, int] = {}
        for sig, local in _iter_group_sigs(raw):
            gid = self.group_sig.get(sig)
            if gid is None:
                gid = probe_new.get(sig)
                if gid is None:
                    gid = next_gid
                    probe_new[sig] = gid
                    next_gid += 1
            local_gid[local] = gid
        if next_gid > p.gpn_mat.shape[1]:
            return False
        new_k7 = {
            key
            for l in raw.l7_ports
            if (key := (l[0], pid_probe[(l[1], PROTO_TCP_N)], local_gid[l[2]]))
            not in self.k7_ids
        }
        if len(self.k7_ids) + len(new_k7) > p.s7_mat.shape[1]:
            return False
        max_sel = -1
        for s1, s2 in raw.deny + raw.allow:
            max_sel = max(max_sel, s1, s2)
        for e in raw.entries:
            max_sel = max(max_sel, e[0], e[1])
        for _g, sid, _x in raw.gp:
            max_sel = max(max_sel, sid)
        return max_sel < self.s_pad

    # ------------------------------------------------------------------
    def _port(self, port: int, proto: int) -> int:
        key = (port, proto)
        pid = self.port_id.get(key)
        if pid is None:
            pid = len(self.port_id)
            self.port_id[key] = pid
            self.prog.ports[pid] = port
            self.prog.protos[pid] = proto
            self.writes.append(("port_vocab", pid, port, proto))
        return pid

    def _set(self, name: str, mat: np.ndarray, i: int, j: int) -> None:
        key = (name, i, j)
        n = self.cell_refs.get(key, 0)
        self.cell_refs[key] = n + 1
        if self._attr_key is not None:
            self.rule_cells[self._attr_key].append(key)
        if n == 0:
            mat[i, j] = 1
            self.writes.append((name, i, j, 1))

    def _write(self, raw: _RawDirection) -> None:
        p = self.prog
        for s1, s2 in raw.deny:
            self._set("deny", p.deny_mat, s1, s2)
        for s1, s2 in raw.allow:
            self._set("allow", p.allow_mat, s1, s2)

        # intern this raw's local groups by signature → global ids
        gmap: Dict[int, int] = {}
        for sig, local in _iter_group_sigs(raw):
            gid = self.group_sig.get(sig)
            if gid is None:
                gid = len(self.group_sig)
                self.group_sig[sig] = gid
            gmap[local] = gid
            self.group_refs[gid] = self.group_refs.get(gid, 0) + 1
            if self._attr_key is not None:
                self.rule_groups[self._attr_key].append(gid)
            no_peers = raw.group_no_peers[local]
            if no_peers and not p.group_no_peers[gid]:
                p.group_no_peers[gid] = True
                self.writes.append(("group_no_peers", gid, 0, 1))
        self.n_groups = len(self.group_sig)

        for subj, sid, port, proto, expl, group in raw.entries:
            pid = self._port(port, proto)
            key = (subj, pid)
            k = self.combo_id.setdefault(key, len(self.combo_id))
            self._set("s1", p.s1_mat, subj, k)
            self._set("p1", p.p1_mat, pid, k)
            if expl:
                self._set("ee", p.ee_mat, k, sid)
            else:
                self._set("en", p.en_mat, k, sid)
            self.entries.append((subj, sid, port, proto, expl, gmap[group]))

        for group, sid, expl in raw.gp:
            name, mat = ("gpe", p.gpe_mat) if expl else ("gpn", p.gpn_mat)
            self._set(name, mat, sid, gmap[group])

        for subj, port, group in raw.l7_ports:
            pid = self._port(port, PROTO_TCP_N)
            gid = gmap[group]
            k = self.k7_ids.setdefault((subj, pid, gid), len(self.k7_ids))
            self._set("s7", p.s7_mat, subj, k)
            self._set("p7", p.p7_mat, pid, k)
            self._set("g7", p.g7_mat, gid, k)
            self.l7_list.append((subj, port, gid))


# Sentinel for "no rule contributes here" in rule-origin arrays
# (min-reduction identity; mirrored by ops.verdict.NO_RULE — program.py
# cannot import ops.verdict, the dependency points the other way).
NO_RULE = 2**31 - 1


def rule_origin_arrays(
    packer: DirectionPacker, rule_keys: Sequence[int]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Term→first-rule origin arrays for verdict attribution
    (policyd-flows): for each deny subject-selector row, pure-L3-allow
    subject-selector row, and K1 combo column, the LOWEST repository
    rule index whose packed cells reference it (``rule_keys`` is
    ``[id(r) for r in rules]`` in repository order — the same keys
    ``write_rule`` attributed cells under). First-contributing-rule-wins
    matches the reference's in-order rule walk; granularity is the
    packed term (selector row / combo column), the same resolution the
    kernel's reductions preserve. Entries no surviving rule references
    hold ``NO_RULE``."""
    p = packer.prog
    deny_rule = np.full(packer.s_pad, NO_RULE, np.int32)
    allow_rule = np.full(packer.s_pad, NO_RULE, np.int32)
    combo_rule = np.full(p.s1_mat.shape[1], NO_RULE, np.int32)
    for ri, key in enumerate(rule_keys):
        for name, i, j in packer.rule_cells.get(key, ()):
            if name == "deny":
                if ri < deny_rule[i]:
                    deny_rule[i] = ri
            elif name == "allow":
                if ri < allow_rule[i]:
                    allow_rule[i] = ri
            elif name == "s1":
                if ri < combo_rule[j]:
                    combo_rule[j] = ri
    return deny_rule, allow_rule, combo_rule


def subject_sids(rules: Sequence[Rule], table: SelectorTable) -> Tuple[int, ...]:
    """Sorted, deduplicated subject-selector ids for a rule batch —
    the delta-log payload bound (policyd-delta): every verdict term a
    compile emits is gated on its rule's subject selector
    (_extract_direction interns ``r.endpoint_selector`` as the ``subj``
    of every deny/allow/entry cell), so these ids bound the policymap
    COLUMNS an incremental append/delete can change, and
    patch_endpoints_state only re-sweeps endpoints whose label sets
    match one of them. Interning here is idempotent for already-compiled
    rules: appends intern the same selector the compile is about to,
    deletes hit selectors the original compile interned."""
    return tuple(sorted({table.intern(r.endpoint_selector) for r in rules}))


def _merge_raws(raws: Sequence[_RawDirection]) -> _RawDirection:
    """Concatenate per-rule raws into one batch raw, renumbering group
    ids globally (the shape the packer sizes its buckets from)."""
    deny: List[Tuple[int, int]] = []
    allow: List[Tuple[int, int]] = []
    entries: List[Tuple[int, int, int, int, bool, int]] = []
    gnp: List[bool] = []
    gp: List[Tuple[int, int, bool]] = []
    l7: List[Tuple[int, int, int]] = []
    off = 0
    for raw in raws:
        deny.extend(raw.deny)
        allow.extend(raw.allow)
        entries.extend(
            (s, sid, p, pr, e, g + off) for (s, sid, p, pr, e, g) in raw.entries
        )
        gp.extend((g + off, sid, e) for (g, sid, e) in raw.gp)
        l7.extend((s, p, g + off) for (s, p, g) in raw.l7_ports)
        gnp.extend(raw.group_no_peers)
        off += len(raw.group_no_peers)
    return _RawDirection(deny, allow, entries, gnp, gp, l7)


@dataclasses.dataclass
class CompileState:
    """Persistent compiler state for incremental appends: the selector
    interner, per-direction packers, and how many selectors have been
    lowered into the conjunct arrays so far."""

    table: SelectorTable
    ingress: DirectionPacker
    egress: DirectionPacker
    lowered_selectors: int


def compile_policy_state(
    repo: Repository, registry: IdentityRegistry
) -> Tuple[CompiledPolicy, CompileState]:
    """Lower repository + identities to dense tables.

    Order matters: selectors intern their vocab bits first, then the
    identity dense view interns identity bits (growing the vocab), and
    only then are conjuncts packed against the final word count — so
    identity bitmaps and selector masks share one bit space.
    """
    table = SelectorTable()
    with repo._lock:
        rules = list(repo.rules)
        revision = repo.revision
    # Per-rule raws (same intern/group order as one batch extraction)
    # so every matrix cell is attributed to its contributing rule —
    # the basis for incremental deletion.
    raws_ingress = [_extract_direction([r], table, ingress=True) for r in rules]
    raws_egress = [_extract_direction([r], table, ingress=False) for r in rules]
    raw_ingress = _merge_raws(raws_ingress)
    raw_egress = _merge_raws(raws_egress)

    # Selector axis padded to a multiple of 128 (MXU tile) — the padded
    # tail never matches (no conjuncts) and relation matrices are zero
    # there.
    s_pad = max(128, ((len(table) + 127) // 128) * 128)
    ing_packer = DirectionPacker(raw_ingress, s_pad)
    eg_packer = DirectionPacker(raw_egress, s_pad)
    for r, raw_i, raw_e in zip(rules, raws_ingress, raws_egress):
        ing_packer.write_rule(id(r), raw_i)
        eg_packer.write_rule(id(r), raw_e)
    ing_packer.refresh_entry_views()
    eg_packer.refresh_entry_views()
    ing_packer.writes.clear()  # initial build uploads wholesale
    eg_packer.writes.clear()

    vocab = registry.vocab
    lowered = table.lower_bits(vocab)
    lowered += [[] for _ in range(s_pad - len(lowered))]
    id_bits, row_ids, row_live = registry.dense_view()
    num_words = id_bits.shape[1]
    conj_req, conj_forbid, conj_valid, req_count = table.pack(lowered, vocab, num_words)

    id_to_row = {int(i): r for r, i in enumerate(row_ids) if row_live[r]}
    compiled = CompiledPolicy(
        revision=revision,
        identity_version=registry.version,
        vocab_version=vocab.version,
        num_words=num_words,
        num_selectors=len(table),
        id_bits=id_bits,
        row_ids=row_ids,
        row_live=row_live,
        id_to_row=id_to_row,
        conj_req=conj_req,
        conj_forbid=conj_forbid,
        conj_valid=conj_valid,
        req_count=req_count,
        ingress=ing_packer.prog,
        egress=eg_packer.prog,
    )
    return compiled, CompileState(
        table=table,
        ingress=ing_packer,
        egress=eg_packer,
        lowered_selectors=len(table),
    )


def compile_policy(repo: Repository, registry: IdentityRegistry) -> CompiledPolicy:
    return compile_policy_state(repo, registry)[0]


def try_append_rules(
    compiled: CompiledPolicy,
    state: CompileState,
    registry: IdentityRegistry,
    rules: Sequence[Rule],
    new_revision: int,
) -> Optional[Tuple[int, int]]:
    """Append ``rules`` into the compiled tables **in place**.

    Returns the (old, new) selector count on success, or None when a
    full rebuild is required (selector/port/combo/group bucket overflow,
    vocab word growth, or conjunct-slot growth). On None the caller
    must recompile from scratch; the partially-grown interner state is
    discarded there, so bailing is always safe.
    """
    table = state.table
    old_len = len(table)
    raws_in = [_extract_direction([r], table, ingress=True) for r in rules]
    raws_eg = [_extract_direction([r], table, ingress=False) for r in rules]
    raw_in = _merge_raws(raws_in)
    raw_eg = _merge_raws(raws_eg)
    if len(table) > compiled.ingress.s_pad:
        return None
    vocab = registry.vocab
    new_lowered = [
        table.selector(sid).conjuncts(vocab) for sid in range(old_len, len(table))
    ]
    if vocab.num_words > compiled.num_words:
        return None
    cps = compiled.conj_req.shape[1]
    if any(len(c) > cps for c in new_lowered):
        return None
    if not (state.ingress.can_append(raw_in) and state.egress.can_append(raw_eg)):
        return None

    for r, ri, re in zip(rules, raws_in, raws_eg):
        state.ingress.write_rule(id(r), ri)
        state.egress.write_rule(id(r), re)
    state.ingress.refresh_entry_views()
    state.egress.refresh_entry_views()
    for i, conjs in enumerate(new_lowered):
        sid = old_len + i
        for j, (require, forbid) in enumerate(conjs):
            compiled.conj_req[sid, j] = vocab.pack(require, compiled.num_words)
            compiled.conj_forbid[sid, j] = vocab.pack(forbid, compiled.num_words)
            compiled.conj_valid[sid, j] = True
            compiled.req_count[sid, j] = len(set(require))
    compiled.num_selectors = len(table)
    compiled.vocab_version = vocab.version
    state.lowered_selectors = len(table)
    compiled.revision = new_revision
    return old_len, len(table)


def unpack_conjuncts(
    conj_req: np.ndarray, conj_forbid: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Pre-unpack conjunct word masks to transposed bit matrices for
    host_selector_matches (cacheable across incremental updates)."""
    s, cps, w = conj_req.shape
    # float32 operands straight from the bit unpack: numpy int32
    # matmul has no BLAS path and is ~50× slower; bit-count sums stay
    # far below f32's exact-integer range (2^24), so float
    # accumulation is exact here
    req = np.unpackbits(
        conj_req.reshape(s * cps, w).view(np.uint8).reshape(s * cps, w * 4),
        axis=1,
        bitorder="little",
    ).astype(np.float32)
    forbid = np.unpackbits(
        conj_forbid.reshape(s * cps, w).view(np.uint8).reshape(s * cps, w * 4),
        axis=1,
        bitorder="little",
    ).astype(np.float32)
    return np.ascontiguousarray(req.T), np.ascontiguousarray(forbid.T)


def host_selector_matches(
    id_bits: np.ndarray,
    conj_req: np.ndarray,
    conj_forbid: np.ndarray,
    conj_valid: np.ndarray,
    req_count: np.ndarray,
    unpacked: Optional[Tuple[np.ndarray, np.ndarray]] = None,
) -> np.ndarray:
    """Numpy mirror of ops.bitmap.compute_selector_matches for small
    selector slices (incremental appends): → [N, S_slice] bool."""
    n, w = id_bits.shape
    s, cps, _ = conj_req.shape
    if s == 0:
        return np.zeros((n, 0), bool)
    bits = np.unpackbits(
        id_bits.view(np.uint8).reshape(n, w * 4), axis=1, bitorder="little"
    ).astype(np.float32)
    req_t, forbid_t = unpacked if unpacked is not None else unpack_conjuncts(
        conj_req, conj_forbid
    )
    hit_req = bits @ req_t
    hit_forbid = bits @ forbid_t
    ok = (
        (hit_req == req_count.reshape(1, s * cps).astype(np.float32))
        & (hit_forbid == 0)
        & conj_valid.reshape(1, s * cps)
    )
    return ok.reshape(n, s, cps).any(axis=2)
