"""Lower the policy repository into order-independent dense tables.

The reference evaluates verdicts by walking rules in order
(pkg/policy/repository.go:80-105); the walk is order-independent in
outcome (a DENIED from any selected rule dominates; otherwise any
ALLOWED wins; else UNDECIDED), which is what makes a data-parallel
tensor formulation possible. Per direction we emit:

- **deny pairs** (subj_sel, req_sel): one per (rule, FromRequires
  selector). Flow is L3-DENIED iff any pair has subject selected and
  requirement unmatched by the peer (rule.go:323-345). The same
  predicate's negation is ``req_ok``, the "all collected requirements
  hold" term that repository.go:249-261 folds into explicit L4 peer
  selectors.
- **allow pairs** (subj_sel, peer_sel): one per (rule, peer selector)
  for directional rules without ToPorts — the pure-L3 allows, including
  entity- and CIDR-derived selectors (ingress.go GetSourceEndpointSelectors).
- **L4 entries** (subj_sel, peer_sel, port, proto, explicit, group):
  flattened L4Filter contributions (l4.go CreateL4IngressFilter + the
  merge in rule.go mergeL4IngressPort collapse to an OR over entries).
  ``explicit`` marks FromEndpoints-derived selectors, which must also
  satisfy ``req_ok`` (the requirements fold); entity/CIDR selectors and
  the no-peer wildcard are exempt. ``group`` identifies the directional
  rule for the peer pre-check (rule.go:133-138: a rule whose peers all
  fail to match the concrete peer contributes no filters).
- **group peer table** (group, peer_sel, explicit) + ``group_no_peers``:
  evaluates that pre-check per flow.
- **L7-presence entries** (subj_sel, port, group): one per L7-bearing
  (rule, port). A flow's allow is a proxy redirect iff some L7 entry's
  subject is selected, the port matches, and its group passes the
  pre-check — i.e. the merged L4Filter at that port has an l7_parser
  (l4.go:82 sets parsers only on TCP). This also subsumes
  wildcardL3L4Rules (repository.go:128-168) on the *decision* path: an
  extension of an L7 filter's endpoint list by a broader allow never
  changes a decision (the pre-check that admits the filter already
  implies a matching L4 entry); it only wildcards which L7 rules apply,
  which the proxy layer derives separately.

Port matching is literal (a ToPorts port 0 only covers a port-0 query)
to match L4PolicyMap.covers_context's exact "port/proto" keying.
Protocols are IANA numbers (u8proto.py), the policymap nexthdr
encoding (bpf/lib/common.h:180).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..identity import IdentityRegistry
from ..labels import LabelVocab
from ..policy.api import EndpointSelector, Rule
from ..policy.cidr import cidr_selectors
from ..policy.repository import (
    Repository,
    _egress_peer_selectors,
    _ingress_peer_selectors,
)
from .. import u8proto
from .selectors import SelectorTable, WILDCARD_SELECTOR_ID

PROTO_TCP_N = u8proto.TCP
PROTO_UDP_N = u8proto.UDP

_PROTO_NUM = {"TCP": PROTO_TCP_N, "UDP": PROTO_UDP_N}


def _expand_protos(proto: str) -> Tuple[int, ...]:
    if proto == "ANY":
        return (PROTO_TCP_N, PROTO_UDP_N)
    return (_PROTO_NUM[proto],)


def _bucket(n: int, minimum: int = 8) -> int:
    """Next power-of-two ≥ max(n, minimum) — shape-bucketed padding so
    incremental recompiles hit XLA's compile cache."""
    size = minimum
    while size < n:
        size *= 2
    return size


def _pad_i32(values: Sequence[int], size: int) -> np.ndarray:
    out = np.zeros(size, dtype=np.int32)
    out[: len(values)] = values
    return out


def _pad_bool(values: Sequence[bool], size: int) -> np.ndarray:
    out = np.zeros(size, dtype=bool)
    out[: len(values)] = values
    return out


@dataclasses.dataclass
class DirectionProgram:
    """Dense tables for one traffic direction (all numpy, padded)."""

    # deny pairs
    deny_subj: np.ndarray
    deny_req: np.ndarray
    deny_valid: np.ndarray
    # L3 allow pairs
    allow_subj: np.ndarray
    allow_peer: np.ndarray
    allow_valid: np.ndarray
    # L4 entries
    e_subj: np.ndarray
    e_peer: np.ndarray
    e_port: np.ndarray
    e_proto: np.ndarray
    e_explicit: np.ndarray
    e_group: np.ndarray
    e_valid: np.ndarray
    # group pre-check
    group_no_peers: np.ndarray  # [G] bool
    gp_group: np.ndarray
    gp_sel: np.ndarray
    gp_explicit: np.ndarray
    gp_valid: np.ndarray
    # L7-parser presence (always TCP, l4.go:82)
    l7_subj: np.ndarray
    l7_port: np.ndarray
    l7_group: np.ndarray
    l7_valid: np.ndarray


@dataclasses.dataclass
class CompiledPolicy:
    """Host-side compiled policy: identity bitmaps + selector conjuncts
    + per-direction tables. ``revision``/``identity_version`` record the
    inputs this was compiled from (the endpoint regeneration protocol's
    revision gate, pkg/endpoint/policy.go:506)."""

    revision: int
    identity_version: int
    vocab_version: int
    num_words: int
    num_selectors: int
    # identities (dense rows)
    id_bits: np.ndarray  # [N, W] uint32
    row_ids: np.ndarray  # [N] int32 numeric identity per row
    row_live: np.ndarray  # [N] bool
    id_to_row: Dict[int, int]
    # selector conjuncts
    conj_req: np.ndarray  # [S, CPS, W] uint32
    conj_forbid: np.ndarray
    conj_valid: np.ndarray  # [S, CPS] bool
    req_count: np.ndarray  # [S, CPS] int32
    ingress: DirectionProgram = None  # type: ignore[assignment]
    egress: DirectionProgram = None  # type: ignore[assignment]

    def rows_for(self, identity_ids: Sequence[int]) -> np.ndarray:
        return np.array([self.id_to_row[i] for i in identity_ids], dtype=np.int32)


def _extract_direction(
    rules: Sequence[Rule], table: SelectorTable, ingress: bool
) -> DirectionProgram:
    deny: List[Tuple[int, int]] = []
    allow: List[Tuple[int, int]] = []
    entries: List[Tuple[int, int, int, int, bool, int]] = []
    group_no_peers: List[bool] = []
    gp: List[Tuple[int, int, bool]] = []
    # L7-bearing (subj_sel, port, group) — parser presence (always TCP)
    l7_ports: List[Tuple[int, int, int]] = []

    for r in rules:
        subj = table.intern(r.endpoint_selector)
        directional = r.ingress if ingress else r.egress
        for dr in directional:
            requires = dr.from_requires if ingress else dr.to_requires
            for q in requires:
                deny.append((subj, table.intern(q)))
            peer_sels = (
                _ingress_peer_selectors(dr) if ingress else _egress_peer_selectors(dr)
            )
            if not dr.to_ports:
                for s in peer_sels:
                    allow.append((subj, table.intern(s)))
                continue

            # Directional rule with ToPorts → one pre-check group.
            explicit_raw = dr.from_endpoints if ingress else dr.to_endpoints
            entity_sels = dr.peer_selectors()[len(explicit_raw):]
            c_sels = (
                cidr_selectors(dr.from_cidr, dr.from_cidr_set)
                if ingress
                else cidr_selectors(dr.to_cidr, dr.to_cidr_set)
            )
            peers: List[Tuple[int, bool]] = (
                [(table.intern(s), True) for s in explicit_raw]
                + [(table.intern(s), False) for s in entity_sels]
                + [(table.intern(s), False) for s in c_sels]
            )
            group = len(group_no_peers)
            group_no_peers.append(not peers)
            for sid, expl in peers:
                gp.append((group, sid, expl))

            for pr in dr.to_ports:
                has_l7 = bool(pr.rules)
                for pp in pr.ports:
                    for proto in _expand_protos(pp.proto):
                        if has_l7 and proto == PROTO_TCP_N:
                            l7_ports.append((subj, pp.port, group))
                        if not peers:
                            entries.append(
                                (subj, WILDCARD_SELECTOR_ID, pp.port, proto, False, group)
                            )
                        else:
                            for sid, expl in peers:
                                entries.append((subj, sid, pp.port, proto, expl, group))

    nd, na, ne = _bucket(len(deny)), _bucket(len(allow)), _bucket(len(entries))
    ng, ngp, nl7 = _bucket(len(group_no_peers)), _bucket(len(gp)), _bucket(len(l7_ports))
    return DirectionProgram(
        deny_subj=_pad_i32([d[0] for d in deny], nd),
        deny_req=_pad_i32([d[1] for d in deny], nd),
        deny_valid=_pad_bool([True] * len(deny), nd),
        allow_subj=_pad_i32([a[0] for a in allow], na),
        allow_peer=_pad_i32([a[1] for a in allow], na),
        allow_valid=_pad_bool([True] * len(allow), na),
        e_subj=_pad_i32([e[0] for e in entries], ne),
        e_peer=_pad_i32([e[1] for e in entries], ne),
        e_port=_pad_i32([e[2] for e in entries], ne),
        e_proto=_pad_i32([e[3] for e in entries], ne),
        e_explicit=_pad_bool([e[4] for e in entries], ne),
        e_group=_pad_i32([e[5] for e in entries], ne),
        e_valid=_pad_bool([True] * len(entries), ne),
        group_no_peers=_pad_bool(group_no_peers, ng),
        gp_group=_pad_i32([g[0] for g in gp], ngp),
        gp_sel=_pad_i32([g[1] for g in gp], ngp),
        gp_explicit=_pad_bool([g[2] for g in gp], ngp),
        gp_valid=_pad_bool([True] * len(gp), ngp),
        l7_subj=_pad_i32([l[0] for l in l7_ports], nl7),
        l7_port=_pad_i32([l[1] for l in l7_ports], nl7),
        l7_group=_pad_i32([l[2] for l in l7_ports], nl7),
        l7_valid=_pad_bool([True] * len(l7_ports), nl7),
    )


def compile_policy(repo: Repository, registry: IdentityRegistry) -> CompiledPolicy:
    """Lower repository + identities to dense tables.

    Order matters: selectors intern their vocab bits first, then the
    identity dense view interns identity bits (growing the vocab), and
    only then are conjuncts packed against the final word count — so
    identity bitmaps and selector masks share one bit space.
    """
    table = SelectorTable()
    with repo._lock:
        rules = list(repo.rules)
        revision = repo.revision
    ingress = _extract_direction(rules, table, ingress=True)
    egress = _extract_direction(rules, table, ingress=False)

    vocab = registry.vocab
    lowered = table.lower_bits(vocab)
    id_bits, row_ids, row_live = registry.dense_view()
    num_words = id_bits.shape[1]
    conj_req, conj_forbid, conj_valid, req_count = table.pack(lowered, vocab, num_words)

    id_to_row = {int(i): r for r, i in enumerate(row_ids) if row_live[r]}
    return CompiledPolicy(
        revision=revision,
        identity_version=registry.version,
        vocab_version=vocab.version,
        num_words=num_words,
        num_selectors=len(table),
        id_bits=id_bits,
        row_ids=row_ids,
        row_live=row_live,
        id_to_row=id_to_row,
        conj_req=conj_req,
        conj_forbid=conj_forbid,
        conj_valid=conj_valid,
        req_count=req_count,
        ingress=ingress,
        egress=egress,
    )
