"""Selector interning + lowering to packed conjunct bitmaps.

Every distinct EndpointSelector that appears anywhere in the rule
repository (subject selectors, peer allows, requires, CIDR-derived,
entity-derived) is interned to a small integer id. Each selector lowers
to a disjunction of conjuncts (require_bits, forbid_bits) over the
LabelVocab (selector.py conjuncts()); the table packs those into

    conj_req    [S, CPS, W] uint32   required-bit words
    conj_forbid [S, CPS, W] uint32   forbidden-bit words
    conj_valid  [S, CPS]    bool     padding mask
    req_count   [S, CPS]    int32    popcount(conj_req) for the matmul test

so the device kernel can evaluate, for identity bitmap b,

    matches(s) = any_c[ conj_valid[s,c]
                        & (popcount(b & req)  == req_count[s,c])
                        & (popcount(b & forbid) == 0) ]

as two int8 matmuls over the unpacked bit axis (ops/bitmap.py).

Selector id 0 is reserved for the wildcard selector (matches every
identity: zero require, zero forbid) so padded table entries can point
at a well-defined id.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..labels import LabelVocab
from ..policy.api import EndpointSelector

WILDCARD_SELECTOR_ID = 0


def selector_word_window(sel_lo: int, sel_hi: int) -> np.ndarray:
    """Packed sel_match word indices covering selector ids
    [sel_lo, sel_hi) — the column window a selector-append delta
    scatters (ops/materialize.py patch_selector_cols). Appends land in
    one or two words for typical batch sizes, so the CSR payload for a
    selector touching k identities is O(k · window) uint32 words."""
    if sel_hi <= sel_lo:
        return np.zeros(0, np.int32)
    return np.arange(sel_lo >> 5, ((sel_hi - 1) >> 5) + 1, dtype=np.int32)


def selector_col_delta(
    sel_match_host: np.ndarray,  # [N, S/32] uint32 host mirror
    ident_rows: np.ndarray,  # [k] touched identity rows
    sel_lo: int,
    sel_hi: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CSR column-delta for a selector append: (rows, word_cols, vals)
    where ``vals[i, j] = sel_match_host[rows[i], word_cols[j]]`` — the
    final-state packed words for exactly the identities the new
    selectors [sel_lo, sel_hi) matched. Feed to patch_selector_cols;
    the payload is O(k · window), never the full matrix."""
    words = selector_word_window(sel_lo, sel_hi)
    rows = np.asarray(ident_rows, np.int32)
    if rows.size == 0 or words.size == 0:
        return rows, words, np.zeros((rows.size, words.size), np.uint32)
    return rows, words, sel_match_host[np.ix_(rows, words)]


class SelectorTable:
    """Grow-only EndpointSelector → id interner with device lowering."""

    def __init__(self) -> None:
        self._ids: Dict[EndpointSelector, int] = {}
        self._sels: List[EndpointSelector] = []
        self.intern(EndpointSelector.wildcard())  # id 0

    def intern(self, sel: EndpointSelector) -> int:
        sid = self._ids.get(sel)
        if sid is None:
            sid = len(self._sels)
            self._ids[sel] = sid
            self._sels.append(sel)
        return sid

    def __len__(self) -> int:
        return len(self._sels)

    def selector(self, sid: int) -> EndpointSelector:
        return self._sels[sid]

    def lower_bits(self, vocab: LabelVocab) -> List[List[Tuple[List[int], List[int]]]]:
        """Intern every selector's bits into the vocab (must run before
        identity packing so the final word count covers everything)."""
        return [sel.conjuncts(vocab) for sel in self._sels]

    def pack(
        self,
        lowered: List[List[Tuple[List[int], List[int]]]],
        vocab: LabelVocab,
        num_words: int,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Pack lowered conjuncts to (conj_req, conj_forbid, conj_valid,
        req_count) with CPS = max conjuncts per selector."""
        cps = max(1, max(len(c) for c in lowered))
        s = len(lowered)
        conj_req = np.zeros((s, cps, num_words), dtype=np.uint32)
        conj_forbid = np.zeros((s, cps, num_words), dtype=np.uint32)
        conj_valid = np.zeros((s, cps), dtype=bool)
        req_count = np.zeros((s, cps), dtype=np.int32)
        for i, conjs in enumerate(lowered):
            for j, (require, forbid) in enumerate(conjs):
                conj_req[i, j] = vocab.pack(require, num_words)
                conj_forbid[i, j] = vocab.pack(forbid, num_words)
                conj_valid[i, j] = True
                req_count[i, j] = len(set(require))
        return conj_req, conj_forbid, conj_valid, req_count
