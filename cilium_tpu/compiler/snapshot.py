"""Compiled-policy + policymap snapshots — the pinned-map persistence
analog.

Reference: the kernel datapath keeps enforcing out of PINNED BPF maps
while the agent restarts (daemon/state.go:53,135 restores endpoints
against maps that never stopped serving). Our device tables die with
the process, so the equivalent is a disk snapshot of the COMPILED
state: the policy compiler's output arrays plus the materialized
policymaps. A restarting daemon re-loads and re-uploads these in
seconds — enforcement is live on last-known-good state long before the
O(identities × rules) recompile would finish; the normal refresh path
then re-derives when (and only when) the inputs actually move.

Format: one ``.npz`` holding every array field (discovered via
dataclass introspection — the schema follows the dataclasses) plus a
JSON metadata entry for scalars and the id→row map.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from .program import CompiledPolicy, DirectionProgram

SNAPSHOT_SCHEMA = 1


def _array_fields(obj) -> Dict[str, np.ndarray]:
    out = {}
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if isinstance(v, np.ndarray):
            out[f.name] = v
    return out


def save_compiled_state(
    path: str,
    compiled: CompiledPolicy,
    sel_match_host: np.ndarray,
    mats: Optional[Dict[int, object]] = None,  # direction → MaterializedState
) -> None:
    """Atomically write the snapshot (tmp + rename, like every other
    state file in this repo)."""
    arrays: Dict[str, np.ndarray] = {}
    meta: Dict[str, object] = {
        "schema": SNAPSHOT_SCHEMA,
        "revision": compiled.revision,
        "identity_version": compiled.identity_version,
        "vocab_version": compiled.vocab_version,
        "num_words": compiled.num_words,
        "num_selectors": compiled.num_selectors,
        "ing_s_pad": compiled.ingress.s_pad,
        "eg_s_pad": compiled.egress.s_pad,
    }
    for k, v in _array_fields(compiled).items():
        arrays[f"cp.{k}"] = v
    for prefix, d in (("ing", compiled.ingress), ("eg", compiled.egress)):
        for k, v in _array_fields(d).items():
            arrays[f"{prefix}.{k}"] = v
    ids = np.fromiter(compiled.id_to_row.keys(), np.int64,
                      len(compiled.id_to_row))
    rows = np.fromiter(compiled.id_to_row.values(), np.int64,
                       len(compiled.id_to_row))
    arrays["map.ids"] = ids
    arrays["map.rows"] = rows
    arrays["sel_match"] = sel_match_host

    mat_meta = {}
    for direction, st in (mats or {}).items():
        p = f"mat{direction}"
        arrays[f"{p}.allow_nc"] = st.allow_nc
        arrays[f"{p}.red_nc"] = st.red_nc
        arrays[f"{p}.ep_rows"] = st.ep_rows
        t = st.tables
        arrays[f"{p}.col_ep"] = np.asarray(t.col_ep)
        arrays[f"{p}.col_port"] = np.asarray(t.col_port)
        arrays[f"{p}.col_proto"] = np.asarray(t.col_proto)
        arrays[f"{p}.col_is_l3"] = np.asarray(t.col_is_l3)
        mat_meta[str(direction)] = {
            "ingress": st.ingress,
            "n_cols": st.n_cols,
            "endpoint_identity_ids": list(st.endpoint_identity_ids),
            "ep_slots": [[list(s) for s in slots] for slots in st.ep_slots],
        }
    meta["mats"] = mat_meta
    arrays["meta"] = np.frombuffer(
        json.dumps(meta).encode(), np.uint8
    ).copy()

    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".compiled.", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def read_snapshot_basis(path: str) -> Optional[Tuple[int, int, int]]:
    """→ (revision, identity_version, vocab_version) of the snapshot on
    disk, or None when absent/corrupt. Reads only the JSON meta member —
    the CT restore path (policyd-survive) compares this against the
    basis stamped into the CT snapshot to decide keep-vs-flush, and must
    not pay for decoding the full array set to do so."""
    import zipfile

    _bad = (OSError, ValueError, KeyError, zipfile.BadZipFile)
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            if meta.get("schema") != SNAPSHOT_SCHEMA:
                return None
            return (
                int(meta["revision"]),
                int(meta["identity_version"]),
                int(meta["vocab_version"]),
            )
    except _bad:
        return None


def load_compiled_state(path: str):
    """→ (CompiledPolicy, sel_match_host, {direction: mat fields dict})
    or None when the file is absent, truncated, corrupt, or from
    another schema — a bad snapshot must degrade to a recompile, never
    to a crash."""
    import zipfile

    _bad = (OSError, ValueError, KeyError, zipfile.BadZipFile)
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            if meta.get("schema") != SNAPSHOT_SCHEMA:
                return None
            return _decode(z, meta)
    except _bad:
        return None


def _decode(z, meta):

    def direction(prefix: str, s_pad: int) -> DirectionProgram:
        kw = {"s_pad": s_pad}
        for f in dataclasses.fields(DirectionProgram):
            key = f"{prefix}.{f.name}"
            if key in z:
                kw[f.name] = z[key]
        return DirectionProgram(**kw)

    cp_kw = {
        "revision": meta["revision"],
        "identity_version": meta["identity_version"],
        "vocab_version": meta["vocab_version"],
        "num_words": meta["num_words"],
        "num_selectors": meta["num_selectors"],
        "id_to_row": dict(
            zip(z["map.ids"].tolist(), z["map.rows"].tolist())
        ),
        "ingress": direction("ing", meta["ing_s_pad"]),
        "egress": direction("eg", meta["eg_s_pad"]),
    }
    for f in dataclasses.fields(CompiledPolicy):
        key = f"cp.{f.name}"
        if key in z:
            cp_kw[f.name] = z[key].copy()  # incremental paths mutate
    compiled = CompiledPolicy(**cp_kw)

    mats: Dict[int, dict] = {}
    for dkey, m in (meta.get("mats") or {}).items():
        p = f"mat{dkey}"
        mats[int(dkey)] = {
            "ingress": m["ingress"],
            "n_cols": m["n_cols"],
            "endpoint_identity_ids": m["endpoint_identity_ids"],
            "ep_slots": [
                [tuple(s) for s in slots] for slots in m["ep_slots"]
            ],
            "allow_nc": z[f"{p}.allow_nc"],
            "red_nc": z[f"{p}.red_nc"],
            "ep_rows": z[f"{p}.ep_rows"],
            "col_ep": z[f"{p}.col_ep"],
            "col_port": z[f"{p}.col_port"],
            "col_proto": z[f"{p}.col_proto"],
            "col_is_l3": z[f"{p}.col_is_l3"],
        }
    return compiled, z["sel_match"].copy(), mats
