"""Canonical tables for the package's STABLE APIs (policyd-contracts).

One importable, pure-stdlib module holding every name/number the
ROADMAP's standing contracts freeze: trace phase names, wire drop
reasons, attribution codes, the bucket ladder, the bench --diff
direction vocabulary, and the option↔DaemonConfig boot-field map.

Two kinds of consumers:

- runtime code imports what it can single-source directly (the
  pipeline's ``BUCKET_LADDER`` and bench's ``--diff`` suffix tuples
  live HERE and only here);
- ``cilium_tpu.analysis.contracts`` (rules API001 / BENCH001 / OPT001)
  machine-checks every *other* literal in the package against these
  tables at lint time, so wire constants that must stay put in their
  defining modules (monitor/events.py, ops/verdict.py) cannot drift
  silently.

Nothing here may import jax, numpy, or anything else from the
package: the analyzers load this in CI contexts with no device and
no heavyweight deps.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# -- trace phases (observe/tracer.py) ---------------------------------
# Phase names are a stable waterfall vocabulary: TRACES_PR*.md files
# and bench --diff's phase comparison key on them across commits, so
# renaming one is a breaking change (observe/README.md). API001 checks
# every ``bt.phase("...")`` literal against this set.
TRACE_PHASES: Tuple[str, ...] = (
    "rebuild",
    "prepare",
    "lb_translate",
    "ct_prepass",
    "dispatch",
    "host_sync",
    "ct_create",
    "counters",
    "emit_events",
)

# -- drop reasons (monitor/events.py REASON_*) ------------------------
# u8 wire codes carried in the flow-event codec's "sub" field. STABLE:
# renumbering breaks stored flow logs and monitor consumers. API001
# checks every int-valued ``REASON_*`` assignment in the package
# against this map (string-valued REASON_* constants — e.g. the
# admission controller's shed-cause labels — are a different namespace
# and exempt).
WIRE_REASONS: Dict[str, int] = {
    "REASON_UNKNOWN": 0,
    "REASON_POLICY": 133,
    "REASON_CT_MAP_FULL": 135,
    "REASON_PREFILTER": 144,
    "REASON_NO_SERVICE": 146,
    "REASON_POLICY_DENY": 151,
    "REASON_POLICY_NO_L3": 152,
    "REASON_POLICY_NO_L4": 153,
    "REASON_PROXY_REDIRECT": 154,
    "REASON_PIPELINE_DEGRADED": 155,
}

# -- attribution codes (ops/verdict.py ATTR_*) ------------------------
# The device kernel's per-flow match-kind output (policyd-flows).
# ATTR_* → REASON_* is the 1→151 / 2→152 / 3→153 / 4→154 mapping the
# event path applies; both ends are frozen here.
ATTR_CODES: Dict[str, int] = {
    "ATTR_ALLOW": 0,
    "ATTR_DENY_RULE": 1,
    "ATTR_NO_L3": 2,
    "ATTR_NO_L4": 3,
    "ATTR_L7": 4,
}

# code → canonical display name (ops/verdict.py ATTR_NAMES must match)
ATTR_CODE_NAMES: Dict[int, str] = {
    0: "allowed",
    1: "deny-rule",
    2: "no-l3-match",
    3: "no-l4-match",
    4: "l7-redirect",
}

# -- dispatch bucket ladder (datapath/pipeline.py) --------------------
# The fixed padded-shape set for chunked CT-miss dispatch. A rung
# joins the jit cache per static-arg combination, so the ladder is a
# compile-count contract: bench compile_s and the ≤ ladder×directions
# program-count assertion both depend on it (policyd-autotune).
BUCKET_LADDER: Tuple[int, ...] = (1024, 2048, 4096, 8192)

# Effective pipeline depth domain: the DispatchAutoTune controller
# moves in [MIN, DaemonConfig.verdict_pipeline_max_depth], and the
# config validator caps the static depth at MAX.
PIPELINE_DEPTH_MIN = 1
PIPELINE_DEPTH_MAX = 64

# -- bench --diff direction vocabulary (bench.py) ---------------------
# A metric key's unit suffix decides which direction is a regression.
# Keys matching neither tuple are NOT compared — BENCH001 flags
# computed measurements that would silently fall out of regression
# coverage, and flags rate-shaped names (``*_per_s``, ``*_ops_s``)
# whose ``_s`` suffix would be mis-read as a duration.
DIFF_HIGHER_SUFFIXES: Tuple[str, ...] = (
    "_vps", "_rps", "_lps", "_qps", "_ratio",
)
DIFF_LOWER_SUFFIXES: Tuple[str, ...] = ("_ms", "_us", "_ns", "_s", "_pct")

# Environment/bookkeeping keys --diff must never fail a round on
# (calib_*-prefixed keys are skipped separately: they ARE the
# normalizers).
DIFF_SKIP_KEYS: Tuple[str, ...] = (
    "value", "vs_baseline", "build_s", "compile_s",
    "host_cpus", "sample_every",
)

# Keys BENCH001 additionally accepts without a direction suffix:
# scenario descriptors and diff-internal fields, not measurements a
# regression gate should compare across rounds.
BENCH_BOOKKEEPING_KEYS: Tuple[str, ...] = DIFF_SKIP_KEYS + (
    # traffic-mix descriptors: they parameterize the scenario (a
    # changed mix invalidates the round, it isn't a regression)
    "allow_fraction", "deny_fraction", "shed_fraction",
    # --diff's own verdict-entry fields
    "prev", "cur", "threshold_pct",
)

# -- metric label cardinality (observe/, analysis OBS002) -------------
# Label keys whose values are allowed to be INTERPOLATED at a metric
# call site in a hot module (f-string/str()/format of runtime data):
# each one is bounded by construction, so it cannot explode series
# cardinality. Everything else interpolated into a label value in a
# hot module is an OBS002 finding — identity ids, endpoint ids and
# addresses are the classic unbounded offenders.
METRIC_BOUNDED_LABEL_KEYS: Tuple[str, ...] = (
    # bounded by the mesh device complement (VerdictSharding per-device
    # verdict series; at most len(jax.devices()) values)
    "device",
    # bounded by the shape-bucket ladder (BUCKET_LADDER rungs)
    "bucket",
    # bounded by the SLO window vocabulary (observe/timeseries.WINDOWS)
    "window",
    # bounded by the IP family domain ("v4"/"v6" — pipeline dispatch
    # pad-lane accounting)
    "family",
    # bounded by the reason-144 producer taxonomy: the host admission
    # gate and the device prefilter kernel are the ONLY two emitters of
    # REASON_PREFILTER drops (observe/README.md "two producers" note)
    "producer",
)

# -- lifecycle journal event kinds (observe/journal.py) ---------------
# The structured lifecycle-event vocabulary (policyd-journal). STABLE:
# fleet timelines are merged across nodes running different commits,
# bugtool events.json archives are diffed offline, and bench --chaos
# asserts against specific kinds — renaming one breaks all three.
# OBS003 checks every ``emit(kind="...")`` literal in the package
# against this table (and flags stale rows no emitter references).
JOURNAL_KINDS: Tuple[str, ...] = (
    # daemon boot completed (attrs: pipeline_mode, policy_epoch)
    "boot",
    # CT snapshot restore verdict (attrs: kept/expired/flushed counts,
    # basis_match, snapshot_age_s)
    "ct_restore",
    # first verdict batch completed after a restart — closes the
    # boot-anchored downtime window (attrs: downtime_ms)
    "restore_done",
    # compiled-policy or CT snapshot written to disk (attrs: what,
    # basis / ct_epoch)
    "snapshot_save",
    # materialization rebuild committed a new served basis (attrs:
    # prev/new _mat_basis, policy_epoch)
    "rebuild",
    # shadow-built table generation installed (attrs: policy_epoch,
    # basis)
    "epoch_swap",
    # degradation-ladder transition (attrs: from/to mode names)
    "ladder_move",
    # device quarantined (attrs: device, ct_epoch, CT rescue outcome)
    "quarantine",
    # edge-triggered admission shed episode opened (attrs: reason)
    "shed_start",
    # shed episode closed (attrs: per-reason shed deltas, duration_s)
    "shed_end",
    # graceful drain entered (attrs: pipeline_mode, policy_epoch)
    "drain_begin",
    # drain finished (attrs: drain_s, verdicts_lost, flushed counts)
    "drain_end",
    # watchdog declared a verdict-path stall (attrs: site, age_ms)
    "watchdog_stall",
    # federation heartbeat found master keys lost to lease expiry and
    # re-asserted them (attrs: repaired count)
    "lease_lost",
    # federation GC reaped orphaned master identities (attrs: reaped
    # ids)
    "identity_reap",
)

# Journal severity domain: bounds the journal_events_total{severity}
# label and the GET /events?severity= filter.
JOURNAL_SEVERITIES: Tuple[str, ...] = ("info", "warning", "error")

# -- runtime options ↔ DaemonConfig boot fields (option.py) -----------
# OPT001: every option registered in OPTION_SPECS needs an entry here.
# The value is the DaemonConfig field that seeds the option at boot,
# or None for options that are structurally boot-only / runtime-only —
# each None carries its reason right here, where the exception is
# reviewed with the table.
OPTION_BOOT_FIELDS: Dict[str, Optional[str]] = {
    # None: wired from the Daemon ctor's ``conntrack`` argument (the
    # CT table object itself), not a bare flag a config field can hold
    "Conntrack": None,
    # None: log-level toggle, boots from the logging config
    "Debug": None,
    # None: boots unconditionally True (reference parity: DropNotify
    # defaults on); runtime-mutable for operators who want quiet
    "DropNotification": None,
    # None: boots off by definition — traces are an opt-in firehose
    "TraceNotification": None,
    # None: enforcement surface parity with the reference endpoint
    # option set; boots True, immutable (not in _MUTABLE_OPTIONS)
    "Policy": None,
    "PolicyVerdictNotification": "policy_verdict_notification",
    "PhaseTracing": "phase_tracing",
    "VerdictSharding": "verdict_sharding",
    "MeshSharding2D": "mesh_sharding_2d",
    "FlowAttribution": "flow_attribution",
    "DispatchAutoTune": "dispatch_autotune",
    "FailOpen": "fail_open",
    "EpochSwap": "policy_epoch_swap",
    "L7DeviceBatch": "l7_device_batch",
    "FaultInjection": "fault_injection",
    "AdmissionControl": "admission_control",
    "DeviceProfiling": "device_profiling",
    "FleetTelemetry": "fleet_telemetry",
    # None: requires an attached federation membership object (kvstore
    # join happens after boot), so there is nothing to enable at
    # DaemonConfig time
    "ClusterFederation": None,
    "Prefilter": "prefilter_shed",
    "SparseDeltas": "sparse_deltas",
    "LifecycleJournal": "lifecycle_journal",
}
