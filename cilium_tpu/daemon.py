"""Daemon core: the per-node agent object every surface talks to.

Re-design of /root/reference/daemon/daemon.go (NewDaemon :1051) for the
TPU framework: owns the policy repository, identity registry, ipcache,
prefilter, conntrack, endpoint manager, and the device pipeline, and
exposes the operations the REST API (/root/reference/api/v1, wiring
daemon/main.go:963-1035) and CLI surface. No kernel writes — the
"datapath" is the device pipeline; regeneration swaps device tables.

State persistence: rules/endpoints/ipcache snapshot to a state dir
(the role of /var/run/cilium endpoint dirs + restore,
/root/reference/daemon/state.go:53,135).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from dataclasses import asdict as dataclasses_asdict
from typing import Dict, List, Optional, Sequence, Tuple

from . import metrics
from .datapath.conntrack import FlowConntrack
from .datapath.pipeline import DatapathPipeline
from .endpoint.endpoint import Endpoint, EndpointState
from .endpoint.manager import EndpointManager
from .fqdn import DNSPoller, system_resolver
from .health import HealthProber, tcp_probe
from .ipam import IPAM
from .maps.lxcmap import LXCMap
from .maps.proxymap import ProxyMap
from .maps.routes import RouteTable
from .maps.tunnel import TunnelMap
from .mtu import MTUConfig
from .observe.flows import FlowRing
from .utils.iputil import prefix_lengths_of
from .utils.logging import get_logger
from .utils.prefix_counter import PrefixLengthCounter
from .xds.cache import ResourceCache
from .xds.npds import (
    delete_endpoint_policy,
    publish_endpoint_policy,
    wire_nphds,
)

log = get_logger("daemon")
from .engine import PolicyEngine
from .identity import IdentityRegistry
from .ipcache.ipcache import IPCache, SOURCE_AGENT
from .ipcache.prefilter import PreFilter
from .labels import parse_label_array
from .lb.service import Backend, L3n4Addr, ServiceManager
from .monitor.events import AgentNotify, L7Notify
from .monitor.hub import MonitorHub
from .ops.materialize import TRAFFIC_EGRESS, TRAFFIC_INGRESS
from .policy.api.serialization import rule_from_dict, rule_to_dict, rules_from_json
from .option import OptionMap, get_config
from .policy.repository import Repository
from .policy.search import Decision, PortContext, SearchContext, Trace
from .proxy.proxy import Proxy
from . import u8proto


def parse_dport(text: str) -> PortContext:
    """'80/tcp' | '53/udp' | '80' → PortContext (cilium policy trace
    --dport format, cilium/cmd/policy_trace.go)."""
    if "/" in text:
        port_s, proto_s = text.split("/", 1)
        return PortContext(int(port_s), proto_s.upper())
    return PortContext(int(text), "ANY")


class Daemon:
    """In-process agent (daemon/daemon.go Daemon struct)."""

    def __init__(
        self,
        state_dir: Optional[str] = None,
        *,
        conntrack: bool = True,
        dns_resolver=None,
        node_registry=None,
        health_probe=None,
        pod_cidr: str = "10.200.0.0/16",
        regen_debounce: float = 0.0,
        ct_gc_interval: float = 60.0,
    ) -> None:
        self.state_dir = state_dir
        self.repo = Repository()
        self.registry = IdentityRegistry()
        self.ipcache = IPCache()
        self.prefilter = PreFilter()
        self.engine = PolicyEngine(self.repo, self.registry)
        self.conntrack = FlowConntrack() if conntrack else None
        self.services = ServiceManager()
        self.monitor = MonitorHub()
        cfg = get_config()
        # placement intent (policyd-mesh): device subset / 2D axes /
        # per-host process index resolve into the pipeline's MeshPlan
        from .datapath.placement import PlacementConfig

        placement = PlacementConfig(
            device_ids=(
                tuple(int(x) for x in cfg.mesh_devices.split(","))
                if cfg.mesh_devices
                else None
            ),
            ident_axis=cfg.mesh_ident_axis,
            process_index=cfg.mesh_process_index,
        )
        self.pipeline = DatapathPipeline(
            self.engine, self.ipcache, self.prefilter,
            conntrack=self.conntrack, lb=self.services,
            monitor=self.monitor,
            pipeline_depth=cfg.verdict_pipeline_depth,
            sharding=cfg.verdict_sharding,
            flow_ring=FlowRing(capacity=cfg.flow_ring_capacity),
            pipeline_max_depth=cfg.verdict_pipeline_max_depth,
            epoch_swap=cfg.policy_epoch_swap,
            placement=placement,
            mesh_2d=cfg.mesh_sharding_2d,
            # policyd-overload: the deadline and stall budgets are boot
            # config; the AdmissionControl/Prefilter gates themselves
            # are runtime options (default off)
            deadline_ms=cfg.verdict_deadline_ms,
            stall_ms=cfg.dispatch_stall_ms,
            # policyd-prof: the sampling period is boot config; the
            # DeviceProfiling gate itself is a runtime option (off)
            profile_sample_every=cfg.profile_sample_every,
        )
        # ONE controller registry for the whole daemon (pkg/controller;
        # `cilium status --all-controllers` reads it) — the endpoint
        # manager registers its loops here too, so nothing hides in a
        # second manager
        from .utils.controller import ControllerManager

        self.controllers = ControllerManager()
        self.endpoint_manager = EndpointManager(controllers=self.controllers)
        self.proxy = Proxy()
        if self.conntrack is not None and ct_gc_interval > 0:
            # periodic CT reaping (endpointmanager.EnableConntrackGC,
            # ctmap.go GC:345)
            self.endpoint_manager.enable_conntrack_gc(
                self.conntrack, interval=ct_gc_interval
            )
        # boot-time capability probes on a daemon thread (the
        # run_probes.sh-at-boot analog; status() peeks, never blocks)
        from . import probes as _probes

        _probes.probe_in_background()
        # datapath state maps (pkg/maps/{lxcmap,tunnel,proxymap})
        self.ipam = IPAM(pod_cidr)
        self.lxcmap = LXCMap()
        self.tunnel = TunnelMap()
        self.routes = RouteTable()
        self.proxymap = ProxyMap()
        self.mtu = MTUConfig()
        # distinct CIDR prefix lengths in force (pkg/counter) — a new
        # length forces a datapath trie rebuild (the compileBase
        # trigger of daemon/policy.go:184-195)
        self.prefix_lengths = PrefixLengthCounter()
        # datapath redirect verdicts → proxymap entries (the
        # cilium_proxy4/6 write of bpf_lxc.c; the L7 front-end reads
        # them back to recover original destination + source identity)
        self.pipeline.on_redirect = self._record_proxy_flow
        # per-endpoint option resolution for event gating (`cilium
        # endpoint config` overrides, layered over the daemon map)
        self.pipeline.endpoint_options = self._endpoint_option
        # policyd-flows: flow records carry label strings, resolved
        # lazily for the sampled subset only (never per-flow-in-batch)
        self.pipeline.identity_labels = self._identity_label_strings
        # xDS distribution (pkg/envoy xDS): NPDS per-endpoint L7
        # policy + NPHDS identity→addresses, served to external
        # proxies by an XDSServer the embedder/CLI attaches
        self.xds_cache = ResourceCache()
        wire_nphds(self.xds_cache, self.ipcache)
        # policyd-fleetobs: the FleetTelemetry sampler slot + its boot
        # knobs exist BEFORE option seeding so a boot-enabled option
        # can start the sampler from the on_change handler; None while
        # the option is off (the fleet plane stays unimported)
        self._fleet_sampler = None
        self._telemetry_sample_s = cfg.telemetry_sample_s
        self._telemetry_ring_rows = cfg.telemetry_ring_rows
        # policyd-journal: the LifecycleJournal slots + boot knobs,
        # same pre-seeding discipline as the sampler above; None while
        # the option is off (the journal plane stays unimported)
        self._journal = None
        self._journal_publisher = None
        self._journal_capacity = cfg.journal_ring_capacity
        self._journal_publish_s = cfg.journal_publish_s
        self._journal_tail_n = cfg.journal_tail_n
        # runtime-mutable option map (pkg/option: PATCH /config /
        # `cilium config`); endpoints inherit it (applyOptsLocked)
        self.options = OptionMap()
        self.options.set("Policy", True)
        self.options.set("Conntrack", conntrack)
        self.options.set("DropNotification", True)
        # boot value rides DaemonConfig; the pipeline already took it
        # via its ctor, so seed the map BEFORE wiring on_change
        self.options.set("VerdictSharding", cfg.verdict_sharding)
        self.options.set("MeshSharding2D", cfg.mesh_sharding_2d)
        self.options.set("EpochSwap", cfg.policy_epoch_swap)
        self.options.on_change(self._on_option_change)
        # the remaining datapath-gated options need their on_change
        # side effect (pipeline setters / shared L7 pipeline / fault
        # hub), so their boot values seed AFTER on_change is wired;
        # contracts.OPTION_BOOT_FIELDS pairs each with its field and
        # rule OPT001 machine-checks the pairing
        for opt_name, boot_on in (
            ("L7DeviceBatch", cfg.l7_device_batch),
            ("PolicyVerdictNotification", cfg.policy_verdict_notification),
            ("PhaseTracing", cfg.phase_tracing),
            ("FlowAttribution", cfg.flow_attribution),
            ("DispatchAutoTune", cfg.dispatch_autotune),
            ("FailOpen", cfg.fail_open),
            ("AdmissionControl", cfg.admission_control),
            ("Prefilter", cfg.prefilter_shed),
            ("SparseDeltas", cfg.sparse_deltas),
            ("DeviceProfiling", cfg.device_profiling),
            ("FaultInjection", cfg.fault_injection),
            ("FleetTelemetry", cfg.fleet_telemetry),
            ("LifecycleJournal", cfg.lifecycle_journal),
        ):
            if boot_on:
                self.options.set(opt_name, True)
        # daemon boot marker: the journal's causal anchor for the
        # restart-downtime window (restore_done closes it). Emitted
        # here — before restore_state — so journal-computed downtime
        # spans the same window as restart_downtime_seconds.
        self._journal_emit(kind="boot", attrs={
            "policy_epoch": self.pipeline.policy_epoch,
        })
        # fleet regeneration is synchronous by default (tests and
        # small deployments observe effects immediately); a busy node
        # sets regen_debounce > 0 to fold bursts of endpoint churn
        # into rate-limited sweeps (pkg/trigger TriggerPolicyUpdates)
        self._regen_trigger = None
        if regen_debounce > 0:
            from .utils.trigger import Trigger

            self._regen_trigger = Trigger(
                lambda reasons: self._regenerate_now(
                    "; ".join(reasons) or "debounced"
                ),
                min_interval=regen_debounce,
                name="fleet-regeneration",
            )
        # serializes snapshot writers: API threads AND the background
        # DNS poller both reach save_state
        self._save_lock = threading.Lock()
        self._compiled_saved_basis = None  # (rev, id_ver, vocab_ver)
        self._compiled_saved_at = float("-inf")
        # policyd-survive: CT snapshot debounce + restore provenance
        # (bugtool ct.json) + restart-downtime stamp
        self._ct_saved_at = float("-inf")
        self._ct_save_suppressed = False  # True while restore_state runs
        self._ct_restore_info: Optional[Dict] = None
        self._restore_started: Optional[float] = None
        # identity allocation is pluggable: clustered deployments
        # (cluster.py ClusterNode) swap in the kvstore CAS allocator
        # so the whole cluster numbers identities identically
        self.allocate_identity = self.registry.allocate
        self.release_identity = self.registry.release
        # policyd-fed: a federation membership (federation/member.py)
        # is attached after the kvstore join; the ClusterFederation
        # runtime option decides whether the identity source routes
        # through it
        self._federation = None
        # node connectivity prober (cilium-health launch,
        # daemon/main.go:927-945); probes the node registry when one
        # is attached, reports empty standalone
        self.health = HealthProber(
            nodes=node_registry, probe=health_probe or tcp_probe
        )
        # ToFQDNs poller (fqdn.StartDNSPoller, daemon/main.go:808 —
        # started lazily via fqdn_start(); tests drive fqdn_poll())
        self.fqdn = DNSPoller(
            self.repo,
            resolver=dns_resolver or system_resolver,
            on_change=lambda rev: (
                self._regenerate("fqdn update"),
                self.save_state(),
            ),
        )
        # L7 access-log records surface on the monitor stream the way
        # the reference forwards proxy logs as monitor agent events
        # (pkg/proxy/logger → monitor).
        self.proxy.accesslog.subscribe(
            lambda r: self.monitor.publish(
                L7Notify(verdict=r.verdict, detail=json.dumps(r.to_dict()))
            )
            if self.monitor.active
            else None
        )
        self._lock = threading.RLock()
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
            self.restore_state()
            if self.conntrack is not None:
                # periodic CT persistence (policyd-survive): verdict
                # batches churn the table without ever touching
                # save_state, so without this sweep a crash restores a
                # CT snapshot frozen at the last policy mutation. The
                # writer itself debounces; the first trigger re-persists
                # whatever restore just placed.
                self.controllers.update_controller(
                    "ct-snapshot-sync",
                    lambda: self._save_ct_snapshot(),
                    run_interval=self.CT_SNAPSHOT_MIN_INTERVAL_S,
                )

    @staticmethod
    def _rule_cidrs(rules) -> List[str]:
        """Every CIDR prefix a rule set installs (pkg/policy/cidr.go
        GetCIDRPrefixes role) — CIDRRule exceptions expand into the
        covering sub-prefixes the datapath actually materializes."""
        from .policy.cidr import compute_resultant_cidr_set

        out: List[str] = []
        for r in rules:
            for ing in r.ingress:
                out.extend(ing.from_cidr)
                out.extend(compute_resultant_cidr_set(ing.from_cidr_set))
            for eg in r.egress:
                out.extend(eg.to_cidr)
                out.extend(compute_resultant_cidr_set(eg.to_cidr_set))
        return out

    # -- policy ---------------------------------------------------------
    def policy_add(self, rules_json: str) -> Dict:
        """PUT /policy (daemon/policy.go PolicyAdd:167)."""
        rules = rules_from_json(rules_json)
        rev = self.repo.add_list(rules)
        self._regenerate("policy import")
        self.save_state()
        log.info("policy imported",
                 fields={"policyRevision": rev, "rules": len(rules)})
        return {"revision": rev, "count": len(rules)}

    def policy_get(self, labels: Optional[Sequence[str]] = None) -> Dict:
        """GET /policy (daemon/policy.go getPolicy)."""
        with self.repo._lock:
            rules = list(self.repo.rules)
        if labels:
            sel = parse_label_array(labels)
            rules = [
                r for r in rules
                if all(any(l == rl for rl in r.labels) for l in sel)
            ]
        return {
            "revision": self.repo.revision,
            "rules": [rule_to_dict(r) for r in rules],
        }

    def policy_replace(self, labels: Sequence[str], rules_json: str) -> Dict:
        """Atomic upsert: swap the rules carrying ``labels`` for the
        given rule set under one repository lock, then regenerate ONCE
        — the MODIFIED-event path (no enforcement gap, no doubled
        regeneration)."""
        rules = rules_from_json(rules_json)
        rev, n_deleted = self.repo.replace_by_labels(
            parse_label_array(labels), rules
        )
        self._regenerate("policy replace")
        self.save_state()
        return {"revision": rev, "count": len(rules), "deleted": n_deleted}

    def policy_delete(self, labels: Sequence[str]) -> Dict:
        """DELETE /policy (daemon/policy.go PolicyDelete:253). A no-op
        delete (nothing matched) skips regeneration and the state
        save — upsert-style callers probe-delete before every add."""
        rev, deleted = self.repo.take_by_labels(parse_label_array(labels))
        if deleted:
            self._regenerate("policy delete")
            self.save_state()
        return {"revision": rev, "deleted": len(deleted)}

    def policy_translate(self, translator) -> Dict:
        """Re-translate imported rules against changed external state
        (k8s service churn; daemon/k8s_watcher.go → TranslateRules)."""
        rev, n = self.repo.translate_rules(translator)
        if n:
            self._regenerate("policy translate")
            self.save_state()
        return {"revision": rev, "changed": n}

    def policy_resolve(
        self,
        src_labels: Sequence[str],
        dst_labels: Sequence[str],
        dports: Sequence[str] = (),
        *,
        ingress: bool = True,
        verbose: bool = False,
    ) -> Dict:
        """GET /policy/resolve — the `cilium policy trace` backend
        (daemon/policy.go getPolicyResolve.Handle:66-126): runs the
        traced host oracle AND the device engine, asserting parity so
        every trace doubles as a device-correctness check."""
        src = parse_label_array(src_labels)
        dst = parse_label_array(dst_labels)
        ports = tuple(parse_dport(p) for p in dports)
        ctx = SearchContext(
            src=src, dst=dst, dports=ports,
            trace=Trace.VERBOSE if verbose else Trace.ENABLED,
        )
        oracle = (
            self.repo.allows_ingress(ctx) if ingress
            else self.repo.allows_egress(ctx)
        )

        # Device parity: identities for both label sets (ref-counted
        # temporaries when not already allocated).
        src_id = self.registry.lookup_by_labels(src)
        dst_id = self.registry.lookup_by_labels(dst)
        tmp = []
        for have, lbls in ((src_id, src), (dst_id, dst)):
            if have is None:
                # the PLUGGABLE allocator: clustered daemons must not
                # mint local-cursor numbers that collide with the
                # cluster's CAS numbering
                tmp.append(self.allocate_identity(lbls))
        src_id = src_id or self.registry.lookup_by_labels(src)
        dst_id = dst_id or self.registry.lookup_by_labels(dst)
        subj, peer = (dst_id, src_id) if ingress else (src_id, dst_id)
        if ports:
            decs = [
                self.engine.verdict_one(
                    subj.id, peer.id, p.port,
                    u8proto.from_name(p.protocol) if p.protocol not in ("ANY", "") else 6,
                    ingress=ingress, l4=True,
                )[0]
                for p in ports
            ]
            device_allowed = all(d == 1 for d in decs)
        else:
            device_allowed = (
                self.engine.verdict_one(
                    subj.id, peer.id, 0, 6, ingress=ingress, l4=False
                )[0] == 1
            )
        for ident in tmp:
            self.release_identity(ident)

        oracle_allowed = oracle == Decision.ALLOWED
        return {
            "verdict": str(oracle),
            "allowed": oracle_allowed,
            "device_allowed": device_allowed,
            "parity": oracle_allowed == device_allowed,
            "trace": ctx.log(),
        }

    def policy_explain(
        self,
        src_labels: Sequence[str],
        dst_labels: Sequence[str],
        dport: str = "",
        *,
        ingress: bool = True,
    ) -> Dict:
        """GET /policy/explain (policyd-flows): replay ONE flow through
        the verdict kernel with attribution on and name the deciding
        repository rule + drop reason — `cilium policy trace` answered
        by the device program instead of the host oracle."""
        src = parse_label_array(src_labels)
        dst = parse_label_array(dst_labels)
        port = parse_dport(dport) if dport else None
        # identity resolution mirrors policy_resolve: ref-counted
        # temporaries for label sets without a live identity
        src_id = self.registry.lookup_by_labels(src)
        dst_id = self.registry.lookup_by_labels(dst)
        tmp = []
        for have, lbls in ((src_id, src), (dst_id, dst)):
            if have is None:
                tmp.append(self.allocate_identity(lbls))
        src_id = src_id or self.registry.lookup_by_labels(src)
        dst_id = dst_id or self.registry.lookup_by_labels(dst)
        subj, peer = (dst_id, src_id) if ingress else (src_id, dst_id)
        try:
            if port is not None:
                proto = (
                    u8proto.from_name(port.protocol)
                    if port.protocol not in ("ANY", "") else 6
                )
                out = self.engine.explain_one(
                    subj.id, peer.id, port.port, proto,
                    ingress=ingress, l4=True,
                )
            else:
                out = self.engine.explain_one(
                    subj.id, peer.id, 0, 6, ingress=ingress, l4=False,
                )
        finally:
            for ident in tmp:
                self.release_identity(ident)
        out["direction"] = "ingress" if ingress else "egress"
        out["src_identity"] = src_id.id
        out["dst_identity"] = dst_id.id
        return out

    # -- endpoints ------------------------------------------------------
    def endpoint_add(
        self,
        endpoint_id: int,
        labels: Sequence[str],
        *,
        ipv4: Optional[str] = None,
        ipv6: Optional[str] = None,
        pod_name: str = "",
    ) -> Dict:
        """PUT /endpoint/{id} (daemon/endpoint.go putEndpointID →
        endpointmanager.Insert + AllocateIdentity + ipcache upsert +
        regenerate)."""
        with self._lock:
            if self.endpoint_manager.lookup(endpoint_id) is not None:
                raise ValueError(f"endpoint {endpoint_id} exists")
            lbls = parse_label_array(labels)
            ep = Endpoint(endpoint_id, lbls, ipv4=ipv4, ipv6=ipv6,
                          pod_name=pod_name, parent_options=self.options)
            # CREATING → WAITING_FOR_IDENTITY → READY (endpoint.go
            # lifecycle) so the first regeneration is legal.
            ep.set_state(EndpointState.WAITING_FOR_IDENTITY)
            ep.identity = self.allocate_identity(lbls)
            ep.set_state(EndpointState.READY)
            self.endpoint_manager.insert(ep)
            if ipv4:
                self.ipcache.upsert(f"{ipv4}/32", ep.identity.id,
                                    source=SOURCE_AGENT)
            if ipv6:
                self.ipcache.upsert(f"{ipv6}/128", ep.identity.id,
                                    source=SOURCE_AGENT)
            self._sync_pipeline_endpoints()
            # a fresh identity changes what OTHER endpoints' L7
            # identity scopes must allow — regenerate the fleet (the
            # identity-watcher → TriggerPolicyUpdates path; it covers
            # the new endpoint too)
            self._regenerate("endpoint created")
        self.save_state()
        self.notify_agent("endpoint-created", f"endpoint {endpoint_id}")
        log.info("endpoint created", fields={
            "endpointID": endpoint_id,
            "identity": ep.identity.id if ep.identity else 0,
            "ipAddr": ipv4 or ipv6 or "",
        })
        return self._endpoint_model(ep)

    def endpoint_delete(self, endpoint_id: int) -> bool:
        with self._lock:
            ep = self.endpoint_manager.lookup(endpoint_id)
            if ep is None:
                return False
            self.endpoint_manager.remove(ep)
            if ep.ipv4:
                self.ipcache.delete(f"{ep.ipv4}/32", SOURCE_AGENT)
                # REST/CLI deletes must return the address to the pool
                # or the pod CIDR leaks dry. release() is a no-op False
                # for addresses IPAM never allocated (static IPs).
                self.ipam.release(ep.ipv4)
            if ep.ipv6:
                self.ipcache.delete(f"{ep.ipv6}/128", SOURCE_AGENT)
            if ep.identity is not None:
                self.release_identity(ep.identity)
            self._sync_pipeline_endpoints()
            # release the endpoint's L7 redirects (and their proxy
            # ports) BEFORE the fleet regen republishes NPDS
            self.proxy.remove_endpoint(endpoint_id)
            # the released identity must drop out of every OTHER
            # endpoint's L7 scope + published NPDS (symmetric to the
            # create-path fleet regen) — a re-allocated identity id
            # must not inherit stale allows
            self._regenerate("endpoint deleted")
        delete_endpoint_policy(self.xds_cache, endpoint_id)
        self.save_state()
        self.notify_agent("endpoint-deleted", f"endpoint {endpoint_id}")
        log.info("endpoint deleted", fields={"endpointID": endpoint_id})
        return True

    def endpoint_list(self) -> List[Dict]:
        return [self._endpoint_model(ep)
                for ep in self.endpoint_manager.endpoints()]

    def endpoint_get(self, endpoint_id: int) -> Optional[Dict]:
        """GET /endpoint/{id} (cilium endpoint get)."""
        ep = self.endpoint_manager.lookup(endpoint_id)
        return self._endpoint_model(ep) if ep is not None else None

    def endpoint_regenerate(self, endpoint_id: Optional[int] = None) -> Dict:
        """Force regeneration (cilium endpoint regenerate; endpoint.go
        regenerate REST modifier). One endpoint given an id, else all —
        the device tables rebuild either way (regeneration is
        whole-engine here, not per-endpoint program compiles)."""
        if endpoint_id is not None and (
            self.endpoint_manager.lookup(endpoint_id) is None
        ):
            raise ValueError(f"endpoint {endpoint_id} not found")
        self._regenerate_now("manual regeneration")
        return {"regenerated": (
            1 if endpoint_id is not None else len(self.endpoint_manager)
        )}

    def endpoint_labels(
        self,
        endpoint_id: int,
        add: Sequence[str] = (),
        delete: Sequence[str] = (),
    ) -> Dict:
        """Modify an endpoint's labels → new identity → regenerate
        (cilium endpoint labels -a/-d; the reference resolves the new
        identity exactly like a fresh endpoint,
        daemon/endpoint.go modifyEndpointIdentityLabelsFromAPI)."""
        from .labels.label import parse_label

        with self._lock:
            ep = self.endpoint_manager.lookup(endpoint_id)
            if ep is None:
                raise ValueError(f"endpoint {endpoint_id} not found")
            current = {str(l) for l in ep.labels}
            # canonicalize through the label parser: the user spells
            # 'app=web', the store holds 'unspec:app=web' (or
            # 'k8s:app=web') — raw-string set math would silently
            # no-op the delete and duplicate the add under a second
            # source. Source-less deletes remove the label from ANY
            # source (cilium endpoint labels -d semantics).
            removed = set()
            for spec in delete:
                lab = parse_label(spec)
                if ":" in spec.split("=", 1)[0]:
                    removed.add(str(lab))  # exact source given
                else:
                    removed |= {
                        str(l) for l in ep.labels
                        if l.key == lab.key and l.value == lab.value
                    }
            kv_present = {
                (l.key, l.value) for l in ep.labels
                if str(l) not in removed  # allow delete+add to retag source
            }
            added = {
                str(lab) for lab in (parse_label(s) for s in add)
                # same key=value under another source is already there —
                # adding a second copy would force a spurious identity
                if (lab.key, lab.value) not in kv_present
            }
            wanted = (current - removed) | added
            if wanted == current:
                return self._endpoint_model(ep)
            old_ident = ep.identity
            lbls = parse_label_array(sorted(wanted))
            ep.labels = lbls
            ep.identity = self.allocate_identity(lbls)
            if old_ident is not None:
                self.release_identity(old_ident)
            for ip, plen in ((ep.ipv4, 32), (ep.ipv6, 128)):
                if ip:
                    self.ipcache.upsert(
                        f"{ip}/{plen}", ep.identity.id, source=SOURCE_AGENT
                    )
            self._sync_pipeline_endpoints()
            self._regenerate("endpoint labels changed")
            self.save_state()
        self.notify_agent(
            "endpoint-labels",
            f"endpoint {endpoint_id} identity {ep.identity.id}",
        )
        return self._endpoint_model(ep)

    def endpoint_log(self, endpoint_id: int) -> List[Dict]:
        """Per-endpoint status log (cilium endpoint log): state moves
        + regeneration outcomes, newest last."""
        ep = self.endpoint_manager.lookup(endpoint_id)
        if ep is None:
            raise ValueError(f"endpoint {endpoint_id} not found")
        return [
            {"timestamp": ts, "code": code, "message": msg}
            for ts, code, msg in ep.status_log_snapshot()
        ]

    def ct_flush(self) -> Dict:
        """Flush the connection-tracking table (cilium bpf ct flush)."""
        n = self.conntrack.flush() if self.conntrack is not None else 0
        return {"flushed": n}

    def node_list(self) -> List[Dict]:
        """Known cluster nodes (cilium node list). Standalone daemons
        know no peers."""
        reg = getattr(self.health, "nodes", None)
        if reg is None or not hasattr(reg, "remote_nodes"):
            return []
        out = []
        for n in reg.remote_nodes():
            out.append({
                "name": n.name,
                "ipv4": n.ipv4,
                "ipv4_alloc_cidr": n.ipv4_alloc_cidr,
                "cluster": getattr(n, "cluster", "default"),
                "health_ip": getattr(n, "health_ip", None),
                "health_port": getattr(n, "health_port", None),
            })
        return out

    def map_list(self) -> List[Dict]:
        """Open-map inventory (cilium map list): name + entry count."""
        out = []
        for name in ("ct", "ipcache", "tunnel", "proxy", "metrics",
                     "routes", "lxc", "lb"):
            try:
                out.append({"name": name, "entries": len(self.map_dump(name))})
            except Exception:
                out.append({"name": name, "entries": -1})
        return out

    def _endpoint_model(self, ep: Endpoint) -> Dict:
        return {
            "id": ep.id,
            "labels": list(ep.labels.to_strings()),
            "identity": ep.identity.id if ep.identity else None,
            "ipv4": ep.ipv4,
            "ipv6": ep.ipv6,
            "state": str(ep.state.value),
            "policy_revision": ep.policy_revision,
        }

    def _sync_pipeline_endpoints(self) -> None:
        eps = self.endpoint_manager.endpoints()
        self.pipeline.set_endpoints(
            [(ep.id, ep.identity.id) for ep in eps if ep.identity]
        )
        self.lxcmap.sync_endpoints(eps)  # daemon.go:953 syncLXCMap

    def _endpoint_option(self, ep_id: int, name: str, default: bool) -> bool:
        ep = self.endpoint_manager.lookup(ep_id)
        if ep is None:
            return default
        return ep.options.get(name)  # inherits the daemon map

    def _record_proxy_flow(
        self, peer_addr: bytes, ep_idx: int, sport: int, dport: int,
        proto: int, ingress: bool, family: int,
    ) -> None:
        """bpf_lxc.c proxymap insert on redirect verdicts: key the
        redirected 5-tuple to its ORIGINAL destination + source
        identity so the L7 front-end (envoy/cilium_bpf_metadata.cc
        read side) knows where the connection was headed and who sent
        it."""
        import ipaddress as _ipa

        from .maps.proxymap import ProxyValue

        ep_id = self.pipeline.endpoint_id_at(ep_idx)
        ep = self.endpoint_manager.lookup(ep_id) if ep_id is not None else None
        ep_ip = (ep.ipv4 if family == 4 else ep.ipv6) if ep else None
        peer_ip = str(_ipa.ip_address(peer_addr))
        entry = self.ipcache.lookup_by_ip(peer_ip)
        if ingress:
            src_ip, src_port = peer_ip, sport
            dst_ip, dst_port = ep_ip or "", dport
            src_identity = entry.identity if entry else 0
        else:
            src_ip, src_port = ep_ip or "", sport
            dst_ip, dst_port = peer_ip, dport
            src_identity = ep.identity.id if ep and ep.identity else 0
        self.proxymap.record(
            src_ip, src_port, dst_ip, dst_port, proto,
            ProxyValue(
                orig_dst_ip=dst_ip,
                orig_dst_port=dst_port,
                src_identity=src_identity,
            ),
        )

    def notify_agent(self, kind: str, message: str) -> None:
        """AgentNotify on the monitor stream (pkg/monitor/agent.go)."""
        if self.monitor.active:
            self.monitor.publish(AgentNotify(kind=kind, message=message))

    def _regenerate(self, reason: str) -> None:
        if self._regen_trigger is not None:
            self._regen_trigger.trigger(reason)
            return
        self._regenerate_now(reason)

    def _regenerate_now(self, reason: str) -> None:
        # authoritative prefix-length recount (pkg/counter role):
        # incremental add/delete pairs drift once translation or the
        # DNS poller rewrites rule CIDRs, so recount from the live set
        with self.repo._lock:
            rules = list(self.repo.rules)
        self.prefix_lengths.resync(prefix_lengths_of(self._rule_cidrs(rules)))
        self.endpoint_manager.regenerate_all(
            self.pipeline, reason, proxy=self.proxy
        )
        # NPDS: republish every endpoint's L7 policy post-regeneration
        # (UpdateNetworkPolicy, pkg/envoy/server.go:535)
        for ep in self.endpoint_manager.endpoints():
            publish_endpoint_policy(self.xds_cache, ep.id, self.proxy)
        self.notify_agent("regenerate", reason)

    # -- map dumps ------------------------------------------------------
    def policymap_dump(self, endpoint_id: int, *, ingress: bool = True) -> List[Dict]:
        """`cilium bpf policy get <ep>` analog: the realized policymap
        rows for one endpoint (pkg/maps/policymap DumpToSlice)."""
        idx = self.pipeline.endpoint_index(endpoint_id)
        if idx is None:
            raise KeyError(f"endpoint {endpoint_id} not in datapath")
        snaps = self.pipeline.snapshots(ingress=ingress)
        out = []
        for key, redirect in sorted(
            snaps[idx].entries.items(),
            key=lambda kv: (kv[0].identity, kv[0].dport, kv[0].nexthdr),
        ):
            out.append({
                "identity": key.identity,
                "dport": key.dport,
                "proto": key.nexthdr,
                "direction": "ingress" if key.direction == TRAFFIC_INGRESS
                             else "egress",
                "redirect": bool(redirect),
            })
        return out

    # -- identities -----------------------------------------------------
    def identity_list(self) -> List[Dict]:
        return [
            {"id": i.id, "labels": list(i.labels.to_strings())}
            for i in sorted(self.registry, key=lambda i: i.id)
        ]

    def identity_get(self, num: int) -> Optional[Dict]:
        ident = self.registry.get(num)
        if ident is None:
            return None
        return {"id": ident.id, "labels": list(ident.labels.to_strings())}

    def _identity_label_strings(self, num: int) -> Tuple[str, ...]:
        """Label strings for a numeric identity, () when unknown —
        the pipeline's flow-record label resolver (sampled flows
        only, so a registry miss is cheap and non-fatal)."""
        ident = self.registry.get(num)
        if ident is None:
            return ()
        return tuple(ident.labels.to_strings())

    # -- runtime config (pkg/option; PATCH /config) ----------------------
    # options whose runtime mutation actually changes behavior; the
    # rest are rejected so the surface never claims changes it cannot
    # deliver (the reference verifies per-option too, option.go)
    _MUTABLE_OPTIONS = frozenset(
        {
            "Conntrack", "TraceNotification", "DropNotification", "Debug",
            "PhaseTracing", "VerdictSharding", "MeshSharding2D",
            "FlowAttribution", "DispatchAutoTune", "FailOpen",
            "FaultInjection", "EpochSwap", "L7DeviceBatch",
            "AdmissionControl", "Prefilter", "SparseDeltas",
            "DeviceProfiling",
            "ClusterFederation", "PolicyVerdictNotification",
            "FleetTelemetry", "LifecycleJournal",
        }
    )

    def _on_option_change(self, name: str, value: bool) -> None:
        if name == "TraceNotification":
            # trace events for forwarded flows are gated per option
            self.pipeline.trace_enabled = value
        elif name == "Conntrack":
            # detach/reattach the CT pre-pass (flows re-verdict on
            # every batch while detached). Reattach FLUSHES: policy
            # may have changed while detached (the detached table
            # skips the pipeline's basis-move flushes), so stale
            # established-flow bypasses must not come back with it.
            if value and self.conntrack is not None:
                self.conntrack.flush()
            self.pipeline.conntrack = self.conntrack if value else None
        elif name == "DropNotification":
            self.pipeline.drop_notifications = value
        elif name == "PolicyVerdictNotification":
            # per-verdict monitor events (pkg/monitor PolicyVerdict
            # notifications): one PolicyVerdictNotify per sampled flow
            # on the event path; OFF keeps the emit loop untouched
            self.pipeline.verdict_notifications = value
        elif name == "PhaseTracing":
            # policyd-trace: span tracing on the verdict path
            if value:
                self.pipeline.tracer.enable()
            else:
                self.pipeline.tracer.disable()
        elif name == "VerdictSharding":
            # flow-sharded dispatch; placement changes on next rebuild
            # (a single-device node accepts the option as a no-op)
            self.pipeline.set_sharding(value)
        elif name == "MeshSharding2D":
            # policyd-mesh: 2D flows×ident mesh with ident-sharded
            # device tables; the placement plan re-resolves on the
            # next rebuild (a node without an even device factor
            # degrades to the 1D plan — accepted as a no-op)
            self.pipeline.set_mesh_2d(value)
        elif name == "FlowAttribution":
            # policyd-flows: per-flow rule attribution + flow-log ring;
            # the verdict program recompiles with the origin tail on
            # the next rebuild, the off path keeps today's program
            self.pipeline.set_attribution(value)
        elif name == "DispatchAutoTune":
            # policyd-autotune: adaptive pipeline depth; off restores
            # the static configured depth
            self.pipeline.set_autotune(value)
        elif name == "FailOpen":
            # policyd-failsafe: what degraded mode returns — forward
            # (fail-open) vs the default deny with reason 155
            self.pipeline.set_fail_open(value)
        elif name == "EpochSwap":
            # policyd-delta: shadow-built full rebuilds swapped in at
            # a batch boundary; off abandons any in-flight shadow
            self.pipeline.set_epoch_swap(value)
        elif name == "L7DeviceBatch":
            # policyd-l7batch: fused, overlapped L7 classification;
            # off drains the L7 pipeline and policies fall back to the
            # exact pre-option per-field programs on the next batch
            from .datapath import l7_pipeline as _l7rt
            from .option import get_config as _get_config

            _l7rt.set_device_batch(
                value,
                tracer=self.pipeline.tracer,
                depth=_get_config().l7_pipeline_depth,
            )
        elif name == "AdmissionControl":
            # policyd-overload: the AIMD admission gate; off keeps the
            # submit path at one attribute read (exact pre-option path)
            self.pipeline.set_admission(value)
        elif name == "Prefilter":
            # policyd-overload: the coarse shed table compiles +
            # publishes on the next rebuild; off publishes None and the
            # shed kernels never trace
            self.pipeline.set_prefilter_shed(value)
        elif name == "SparseDeltas":
            # policyd-sparse: O(k) placed sel_match patching + in-place
            # LPM trie prefix patches; toggling either way drops the
            # caches so the next rebuild establishes the chosen layout
            # (off = exact pre-option dense re-place / classic tries)
            self.pipeline.set_sparse_deltas(value)
        elif name == "DeviceProfiling":
            # policyd-prof: the sampling device profiler; off clears
            # the instance and both dispatch paths return to one
            # attribute read per batch (exact pre-option programs)
            self.pipeline.set_profiling(value)
            from .datapath import l7_pipeline as _l7rt

            _l7rt.set_profiler(self.pipeline.profiler)
        elif name == "ClusterFederation":
            # policyd-fed: swap the identity source onto the attached
            # federation membership (cluster-wide reserve/confirm CAS
            # numbering); off restores the local registry path. No
            # recompile either way — identity NUMBERING is the only
            # difference, so the OFF path's programs stay bit-identical
            fed = self._federation
            if value and fed is not None:
                self.allocate_identity = fed.allocate
                self.release_identity = fed.release
            else:
                self.allocate_identity = self.registry.allocate
                self.release_identity = self.registry.release
        elif name == "FleetTelemetry":
            # policyd-fleetobs: start/stop the cadence sampler thread.
            # The fleet plane is imported lazily HERE and only here —
            # the off path never loads the frame codec and the verdict
            # path never reads anything fleet-related, so off is
            # bit-identical (tripwire-tested)
            if value:
                self._start_fleet_sampler()
            else:
                self._stop_fleet_sampler()
        elif name == "LifecycleJournal":
            # policyd-journal: start/stop the event journal + tail
            # publisher. The journal plane is imported lazily HERE and
            # only here — off resets every hot-module on_journal slot
            # to None (one attribute read per site) and the verdict
            # path is bit-identical (tripwire-tested)
            if value:
                self._start_journal()
            else:
                self._stop_journal()
        elif name == "FaultInjection":
            # policyd-failsafe: arm/disarm the injection hub; off keeps
            # rules queued so a re-enable resumes a chaos scenario
            from . import faults as _faults

            if value:
                _faults.hub.enable()
            else:
                _faults.hub.disable()
        elif name == "Debug":
            import logging as _logging

            _logging.getLogger("cilium_tpu").setLevel(
                _logging.DEBUG if value else _logging.INFO
            )
        log.info("option changed", fields={"option": name, "value": value})

    def _validated_options(self, options: Dict) -> Dict[str, bool]:
        """Validate EVERY entry before any mutation — a bad entry in a
        batch must not leave earlier options silently applied while
        the client sees a 400."""
        from .option import OPTION_SPECS, _parse_bool

        out: Dict[str, bool] = {}
        for name, value in options.items():
            if name not in OPTION_SPECS:
                raise ValueError(f"unknown option {name!r}")
            if name not in self._MUTABLE_OPTIONS:
                raise ValueError(f"option {name!r} is not runtime-mutable")
            if name == "Conntrack" and self.conntrack is None:
                # a daemon started without a CT table cannot deliver
                # this change — reporting it applied would lie
                raise ValueError(
                    "Conntrack cannot be enabled: daemon started "
                    "without a conntrack table"
                )
            if (
                name == "ClusterFederation"
                and (value if isinstance(value, bool) else _parse_bool(value))
                and self._federation is None
            ):
                # enabling with no membership would silently keep the
                # registry path — same never-lie rule as Conntrack
                raise ValueError(
                    "ClusterFederation cannot be enabled: no federation "
                    "membership attached (daemon.attach_federation)"
                )
            out[name] = value if isinstance(value, bool) else _parse_bool(value)
        return out

    def config_get(self) -> Dict:
        """GET /config (daemon/config.go): static config + the mutable
        option snapshot."""
        return {
            "pod_cidr": str(self.ipam.net),
            "options": self.options.snapshot(),
        }

    def config_patch(self, options: Dict) -> Dict:
        """PATCH /config: mutate runtime options atomically (validate
        all, then apply)."""
        validated = self._validated_options(options)
        changed = [
            name for name, b in validated.items() if self.options.set(name, b)
        ]
        return {"changed": changed, "options": self.options.snapshot()}

    def endpoint_config(self, endpoint_id: int, options: Dict) -> Dict:
        """PATCH /endpoint/{id}/config (cilium endpoint config):
        per-endpoint overrides layered over the daemon map."""
        ep = self.endpoint_manager.lookup(endpoint_id)
        if ep is None:
            raise KeyError(f"endpoint {endpoint_id} not found")
        validated = self._validated_options(options)
        for name, b in validated.items():
            ep.options.set(name, b)
        return {"id": endpoint_id, "options": ep.options.snapshot()}

    # -- map dumps (cilium bpf * list) -----------------------------------
    def map_dump(self, name: str) -> List[Dict]:
        """One shared name→dump table for the REST route and the CLI
        (`cilium bpf <map> list`)."""
        dumps = {
            "ct": self.ct_dump,
            "ipcache": self.ipcache_dump,
            "tunnel": self.tunnel_dump,
            "proxy": self.proxymap_dump,
            "metrics": self.metricsmap_dump,
            "routes": lambda: [
                dataclasses_asdict(r) for r in self.routes.items()
            ],
            # cilium bpf endpoint list (lxcmap) / bpf lb list (lbmap)
            "lxc": lambda: [
                {"ip": ip, **dataclasses_asdict(info)}
                for ip, info in self.lxcmap.items()
            ],
            "lb": lambda: [
                {
                    "frontend": str(s.frontend),
                    "backends": [
                        {"ip": b.ip, "port": b.port, "weight": b.weight}
                        for b in s.backends
                    ],
                    "id": s.id,
                }
                for s in self.services.list()
            ],
        }
        fn = dumps.get(name)
        if fn is None:
            raise ValueError(f"unknown map {name!r}")
        return fn()

    def ct_dump(self) -> List[Dict]:
        return self.conntrack.dump() if self.conntrack is not None else []

    def ipcache_dump(self) -> List[Dict]:
        return [
            {"cidr": cidr, "identity": e.identity, "source": e.source,
             "host_ip": e.host_ip}
            for cidr, e in sorted(self.ipcache.items())
        ]

    def tunnel_dump(self) -> List[Dict]:
        return [
            {"prefix": p, "endpoint": ep} for p, ep in self.tunnel.items()
        ]

    def proxymap_dump(self) -> List[Dict]:
        return self.proxymap.items()

    def metricsmap_dump(self) -> List[Dict]:
        """Per-endpoint forwarded/dropped counters (metricsmap role)."""
        out = []
        counters = self.pipeline.counters
        for idx in range(counters.shape[0]):
            ep_id = self.pipeline.endpoint_id_at(idx)
            if ep_id is None:
                continue
            fwd, dpol, dother = (int(x) for x in counters[idx])
            out.append({
                "endpoint": ep_id, "forwarded": fwd,
                "dropped_policy": dpol, "dropped_other": dother,
            })
        return out

    # -- services (daemon/loadbalancer.go PUT/GET/DELETE /service) -------
    @staticmethod
    def _frontend(fe: Dict) -> L3n4Addr:
        return L3n4Addr(fe["ip"], int(fe["port"]),
                        str(fe.get("protocol", "TCP")).upper())

    @staticmethod
    def _service_model(svc) -> Dict:
        return {
            "id": svc.id,
            "frontend": {
                "ip": svc.frontend.ip,
                "port": svc.frontend.port,
                "protocol": svc.frontend.protocol,
            },
            "backends": [
                {"ip": b.ip, "port": b.port, "weight": b.weight}
                for b in svc.backends
            ],
        }

    def service_upsert(self, frontend: Dict, backends: Sequence[Dict]) -> Dict:
        svc = self.services.upsert(
            self._frontend(frontend),
            [
                Backend(b["ip"], int(b["port"]), int(b.get("weight", 1)))
                for b in backends
            ],
        )
        self._regenerate("service upsert")
        self.save_state()
        return self._service_model(svc)

    def service_delete(self, frontend: Dict) -> bool:
        ok = self.services.delete(self._frontend(frontend))
        if ok:
            self._regenerate("service delete")
            self.save_state()
        return ok

    def service_list(self) -> List[Dict]:
        return [self._service_model(s) for s in self.services.list()]

    # -- fqdn -----------------------------------------------------------
    def fqdn_poll(self) -> Dict:
        """One DNS resolution sweep (the 5s tick of dnspoller.go:78)."""
        changed = self.fqdn.poll_once()
        return {
            "names": self.fqdn.tracked_names(),
            "rules_changed": changed,
            "revision": self.repo.revision,
        }

    def fqdn_start(self, interval: float = 5.0) -> None:
        self.fqdn.start(interval)

    # -- health / debuginfo ---------------------------------------------
    def attach_node_registry(self, registry, *, probe_interval: float = 60.0) -> None:
        """Give the health prober a cluster node registry
        (nodes/registry.py) and start probing — clustered deployments
        call this after joining the kvstore; standalone daemons have
        no peers to probe."""
        self.health.nodes = registry
        self.health.start(probe_interval)
        # remote alloc CIDRs → tunnel endpoints (node/manager.go);
        # registries without an observer feed (tests, static lists)
        # just skip tunnel programming
        if hasattr(registry, "observe"):
            self.tunnel.observe_nodes(registry)
            self.routes.observe_nodes(
                registry, route_mtu=self.mtu.route_mtu
            )

    # -- federation (policyd-fed) ----------------------------------------
    def attach_federation(self, member) -> None:
        """Attach a federation membership (federation/member.py) after
        the kvstore join; the ClusterFederation runtime option decides
        whether the identity source actually routes through it (and
        re-applies immediately if it was already on)."""
        self._federation = member
        if self.options.get("ClusterFederation"):
            self.allocate_identity = member.allocate
            self.release_identity = member.release
        # policyd-fleetobs: a running sampler gains the telemetry
        # exchange the moment a membership exists — frames publish
        # beside the member's epoch-exchange node descriptor
        sampler = self._fleet_sampler
        if sampler is not None and sampler.exchange is None:
            from .observe.fleet import TelemetryExchange

            sampler.attach_exchange(
                TelemetryExchange(
                    member.backend, member.node_name, cluster=member.cluster
                )
            )
        # policyd-journal: a running journal gains the tail exchange,
        # the member's node identity, and the member's lease/reap
        # emission slot the same way
        pub = self._journal_publisher
        if pub is not None and pub.exchange is None:
            from .observe.journal import JournalExchange

            self._journal.node = member.node_name
            pub.attach_exchange(
                JournalExchange(
                    member.backend, member.node_name, cluster=member.cluster
                )
            )
            member.on_journal = self._journal.emit

    def detach_federation(self) -> None:
        """Drop the membership and restore the local identity source
        (the member itself is closed by its owner)."""
        if self._federation is not None:
            self._federation.on_journal = None
        if self.options.get("ClusterFederation"):
            self.options.set("ClusterFederation", False)
        self._federation = None
        self.allocate_identity = self.registry.allocate
        self.release_identity = self.registry.release
        # the telemetry exchange rode the member's backend: close it;
        # the sampler keeps ticking locally (single-node scoreboard)
        sampler = self._fleet_sampler
        if sampler is not None and sampler.exchange is not None:
            exchange, sampler.exchange = sampler.exchange, None
            try:
                exchange.close()
            except (ConnectionError, TimeoutError, OSError, RuntimeError):
                pass
        # ... and so did the journal exchange; the journal itself keeps
        # recording locally (single-node timeline)
        pub = self._journal_publisher
        if pub is not None and pub.exchange is not None:
            exchange, pub.exchange = pub.exchange, None
            try:
                exchange.close()
            except (ConnectionError, TimeoutError, OSError, RuntimeError):
                pass

    def cluster_status(self) -> Dict:
        """GET /cluster (policyd-fed): federation membership view —
        fleet nodes with their published policy epochs, the cluster
        convergence floor, and identity-allocator accounting."""
        out: Dict = {
            "enabled": self.options.get("ClusterFederation"),
            "attached": self._federation is not None,
        }
        if self._federation is not None:
            out.update(self._federation.status())
        else:
            out.update({"node": None, "node_count": 0, "nodes": []})
        return out

    # -- fleet telemetry (policyd-fleetobs) ------------------------------
    def _start_fleet_sampler(self) -> None:
        if self._fleet_sampler is not None:
            return
        # lazy import: the FleetTelemetry OFF path never loads the
        # fleet plane or the frame codec (tripwire-tested)
        from .observe import fleet as _fleet

        sampler = _fleet.FleetSampler(
            interval_s=self._telemetry_sample_s,
            capacity=self._telemetry_ring_rows,
            epoch_source=lambda: self.pipeline.policy_epoch,
        )
        member = getattr(self, "_federation", None)
        if member is not None:
            sampler.attach_exchange(
                _fleet.TelemetryExchange(
                    member.backend, member.node_name, cluster=member.cluster
                )
            )
        sampler.start()
        self._fleet_sampler = sampler

    def _stop_fleet_sampler(self) -> None:
        sampler, self._fleet_sampler = self._fleet_sampler, None
        if sampler is not None:
            sampler.stop()

    def fleet_status(self) -> Dict:
        """GET /fleet: the aggregated scoreboard — fleet-wide when a
        telemetry exchange is attached (federated), a single-node fold
        of the local sampler otherwise — plus local sampler state."""
        sampler = self._fleet_sampler
        if sampler is None:
            return {"enabled": False}
        from .observe import fleet as _fleet  # already loaded: sampler runs

        if sampler.exchange is not None:
            try:
                sampler.exchange.pump()
            except (ConnectionError, TimeoutError, OSError, RuntimeError):
                pass  # partition: serve the last applied view
            frames = sampler.exchange.frames()
            node = sampler.exchange.node_name
        else:
            node = "local"
            frames = {
                node: _fleet.encode_frame(
                    node, sampler.ring.appended, sampler.frame_body()
                )
            }
        out = _fleet.aggregate(frames)
        out["enabled"] = True
        out["node"] = node
        out["local"] = sampler.local_status()
        return out

    def fleet_history(self, limit: int = 64) -> Dict:
        """GET /fleet/history: newest-last local sampler rows (the
        ``cilium-tpu fleet history`` payload)."""
        sampler = self._fleet_sampler
        if sampler is None:
            return {"enabled": False, "history": []}
        return {
            "enabled": True,
            "fields": list(sampler.ring.fields),
            "interval_s": sampler.interval_s,
            "history": sampler.ring.history(limit),
        }

    def _slo_summary(self):
        """One-line SLO block for /status, None while FleetTelemetry
        is off (status must not wake the fleet plane)."""
        sampler = self._fleet_sampler
        if sampler is None:
            return None
        return sampler.slo_summary()

    # -- lifecycle journal (policyd-journal) -----------------------------
    def _start_journal(self) -> None:
        if self._journal is not None:
            return
        # lazy import: the LifecycleJournal OFF path never loads the
        # journal plane or the frame codec (tripwire-tested)
        from .observe import journal as _journal

        member = getattr(self, "_federation", None)
        node = member.node_name if member is not None else "local"
        j = _journal.EventJournal(node=node, capacity=self._journal_capacity)
        pub = _journal.JournalPublisher(
            j, interval_s=self._journal_publish_s, tail_n=self._journal_tail_n
        )
        if member is not None:
            pub.attach_exchange(
                _journal.JournalExchange(
                    member.backend, member.node_name, cluster=member.cluster
                )
            )
            member.on_journal = j.emit
        # hot modules reach the journal through one None-guarded
        # attribute read per site; installing the bound emit arms them
        self.pipeline.on_journal = j.emit
        adm = self.pipeline._admission
        if adm is not None:
            adm.on_journal = j.emit
        pub.start()
        self._journal = j
        self._journal_publisher = pub
        # shed episodes are edge-triggered with a hold: the poller
        # closes an episode once the hold expires without new shed
        # activity (note_shed itself only sees the next storm's edge)
        self.controllers.update_controller(
            "journal-shed-poll", self._journal_shed_poll, run_interval=1.0
        )

    def _journal_shed_poll(self) -> None:
        adm = self.pipeline._admission
        if adm is not None:
            adm.episode_poll()

    def _stop_journal(self) -> None:
        j, self._journal = self._journal, None
        pub, self._journal_publisher = self._journal_publisher, None
        if j is None:
            return
        self.controllers.remove_controller("journal-shed-poll")
        # disarm every hot-module slot before tearing the plane down
        self.pipeline.on_journal = None
        adm = self.pipeline._admission
        if adm is not None:
            adm.on_journal = None
        member = getattr(self, "_federation", None)
        if member is not None:
            member.on_journal = None
        if pub is not None:
            try:
                pub.publish_once()  # final tail (drain events) for peers
            except Exception:
                pass  # kvstore down: peers age our frame out
            pub.stop()

    def _journal_emit(self, **kw) -> None:
        """Emit one lifecycle event when the journal is on; the OFF
        path is a single attribute read (daemon-side sites only — hot
        modules carry their own on_journal slots)."""
        j = self._journal
        if j is not None:
            j.emit(**kw)

    def events(
        self,
        limit: int = 64,
        *,
        kind: Optional[str] = None,
        severity: Optional[str] = None,
        since: Optional[float] = None,
    ) -> Dict:
        """GET /events: the local journal tail + ring accounting."""
        j = self._journal
        if j is None:
            return {"enabled": False, "events": []}
        out = j.snapshot()
        out["enabled"] = True
        out["events"] = j.events(
            limit, kind=kind, severity=severity, since=since
        )
        return out

    def fleet_timeline(self, limit: int = 256) -> Dict:
        """GET /fleet/timeline: local tail + every live peer tail,
        merged into one HLC-total-ordered fleet timeline."""
        pub = self._journal_publisher
        if pub is None:
            return {"enabled": False, "events": []}
        from .observe import journal as _journal  # already loaded

        evs = pub.merged_timeline(limit)
        return {
            "enabled": True,
            "node": pub.journal.node,
            "nodes": sorted({e.get("node") for e in evs}),
            "consistent": _journal.timeline_consistent(evs),
            "events": evs,
        }

    def health_report(self) -> Dict:
        """GET /health (the cilium-health status surface)."""
        return self.health.report()

    def health_probe_now(self) -> Dict:
        """POST /health/probe — one immediate sweep (cilium-health
        `--probe`)."""
        self.health.probe_once()
        return self.health.report()

    def debuginfo(self) -> Dict:
        """GET /debuginfo (daemon/debuginfo.go)."""
        from . import bugtool

        return bugtool.collect_debuginfo(self)

    def traces(self, limit: int = 16) -> Dict:
        """GET /traces (policyd-trace ring buffer)."""
        tr = self.pipeline.tracer
        return {
            "enabled": tr.active,
            "capacity": tr.capacity,
            "pipeline_depth": self.pipeline.pipeline_depth,
            "in_flight": self.pipeline.inflight_depth,
            # policyd-flows: attribution changes what the host_sync
            # phase pulls (6 arrays, not 3) — trace readers should know
            "flow_attribution": self.pipeline.flow_ring.active,
            # policyd-autotune: None while DispatchAutoTune is off;
            # otherwise the tuner snapshot (bounds, per-depth EWMA
            # stats, adjustment counts) — waterfalls read under a
            # moving depth need this context (observe/README.md)
            "autotune": self.pipeline.autotune_state(),
            # policyd-failsafe: ladder level, breaker counters, and the
            # fault-hub snapshot — a trace read during a chaos round or
            # a real degradation needs to say WHICH path produced the
            # spans (device phases vanish at host level)
            "failsafe": self.pipeline.failsafe_state(),
            # policyd-mesh: the placement plan (mesh axes, generation,
            # device set) — sharded vs replicated tables change what a
            # dispatch span covers (per-device bytes, ident reduce)
            "placement": self.pipeline.placement_state(),
            # policyd-overload: gate limit, shed accounting, watchdog —
            # spans read during an overload spike need to say which
            # flows never reached the device path at all
            "admission": self.pipeline.admission_state(),
            # policyd-prof: per-phase p50/p99 from the registry's
            # bucket counts — callers stop eyeballing raw buckets
            "phase_quantiles": self._phase_quantiles(),
            "traces": tr.traces(limit),
        }

    def _phase_quantiles(self) -> Dict:
        """{phase: {n, p50_ms, p99_ms}} interpolated from the
        pipeline_phase_seconds histogram (metrics.Histogram.quantile)."""
        h = metrics.pipeline_phase_seconds
        out: Dict = {}
        for lbl in h.series_labels():
            phase = lbl.get("phase")
            if phase is None:
                continue
            n = h.get_count(lbl)
            if not n:
                continue
            p50 = h.quantile(0.5, lbl)
            p99 = h.quantile(0.99, lbl)
            out[phase] = {
                "n": n,
                "p50_ms": round(p50 * 1e3, 4),
                "p99_ms": round(p99 * 1e3, 4),
            }
        return out

    def profile(self) -> Dict:
        """GET /profile (policyd-prof): sampled RTT decomposition +
        per-site aggregates, the jit cost ledger, and the device
        memory/transfer ledgers."""
        snap = self.pipeline.profile_state()
        snap["device_table_bytes"] = {
            "/".join(v for _, v in key): val
            for key, val in metrics.device_table_bytes.series().items()
        }
        snap["device_transfers"] = {
            "counts": {
                "/".join(v for _, v in key) or "all": val
                for key, val in metrics.device_transfers_total.series().items()
            },
            "bytes": {
                "/".join(v for _, v in key) or "all": val
                for key, val
                in metrics.device_transfer_bytes_total.series().items()
            },
        }
        return snap

    def flows(
        self,
        limit: int = 64,
        *,
        verdict: Optional[int] = None,
        from_identity: Optional[int] = None,
        reason: Optional[int] = None,
    ) -> Dict:
        """GET /flows (policyd-flows ring buffer; the Hubble
        `cilium monitor`/flow-API analog for attributed verdicts)."""
        ring = self.pipeline.flow_ring
        return {
            "enabled": ring.active,
            "capacity": ring.capacity,
            "recorded": ring.recorded,
            "flows": ring.query(
                limit, verdict=verdict,
                from_identity=from_identity, reason=reason,
            ),
        }

    # -- status ---------------------------------------------------------
    def status(self) -> Dict:
        return {
            "policy_revision": self.repo.revision,
            "rules": len(self.repo.rules),
            "identities": len(self.registry),
            "endpoints": len(self.endpoint_manager),
            "ipcache_entries": len(self.ipcache),
            "conntrack_entries": (
                len(self.conntrack) if self.conntrack is not None else 0
            ),
            "prefilter_revision": self.prefilter.revision,
            "services": len(self.services.list()),
            "ipam_allocated": len(self.ipam),
            "lxcmap_entries": len(self.lxcmap),
            "tunnel_entries": len(self.tunnel),
            # node capability probe summary (run_probes.sh role):
            # subsystems running degraded are named, not crashed-on.
            # Non-blocking: the probe set runs on a boot thread (the
            # first native probe can pay a g++ compile), so status
            # answers "still probing" instead of stalling the RPC.
            "features_degraded": (
                peeked.get("degraded", [])
                if (peeked := self._peek_features()) is not None
                else ["probing"]
            ),
            # controller.go:282 status surfacing (`cilium status
            # --all-controllers`)
            "controllers": self.controllers.statuses(),
            # policyd-failsafe: /healthz must answer "are verdicts
            # degraded" without a second RPC — level 0 is healthy,
            # 1/2 names the mode (sharded|single-device|host)
            "pipeline_mode": self.pipeline.pipeline_mode,
            "pipeline_degraded": self.pipeline.pipeline_mode != "sharded",
            # policyd-overload: /healthz answers "is the gate shedding"
            # (queue depth, shed ratio, last stall) without a second RPC
            "admission": self.pipeline.admission_state(),
            # policyd-fed: is this node federated, and is its policy
            # epoch converged with the fleet (GET /cluster for the
            # full per-node view)
            "cluster": {
                "enabled": self.options.get("ClusterFederation"),
                "attached": self._federation is not None,
                "epoch_lag": (
                    self._federation.epochs.epoch_lag()
                    if self._federation is not None
                    else 0
                ),
            },
            # policyd-fleetobs: the one-line SLO summary (worst
            # objective + state) so health is visible without the
            # fleet CLI; None while FleetTelemetry is off. /healthz
            # keys on the plain bool.
            "slo": (slo := self._slo_summary()),
            "slo_burning": bool(slo and slo["burning"]),
        }

    def _peek_features(self):
        from . import probes

        return probes.peek_features()

    def features(self) -> Dict:
        """Node capability probes (probes.py; bpf/run_probes.sh role).
        Blocks until the probe set completes (explicit callers want
        the answer; status() uses the non-blocking peek)."""
        from . import probes

        return probes.probe_features()

    def metrics_text(self) -> str:
        return metrics.registry.expose()

    # -- state persistence (daemon/state.go role) ------------------------
    def save_state(self) -> None:
        if not self.state_dir:
            return
        with self.repo._lock:
            rules = [rule_to_dict(r) for r in self.repo.rules]
        eps = self.endpoint_list()
        # unique tmp per call + a writer lock: the fqdn poller thread
        # and API threads may snapshot concurrently, and two writers
        # sharing one tmp path would interleave into invalid JSON
        with self._save_lock:
            fd, tmp = tempfile.mkstemp(
                dir=self.state_dir, prefix=".state.", suffix=".tmp"
            )
            try:
                with os.fdopen(fd, "w") as f:
                    from .state_migrate import SCHEMA_VERSION

                    body = {
                        "schema": SCHEMA_VERSION,
                        "rules": rules,
                        "endpoints": eps,
                        "services": self.service_list(),
                        # v3: where the CT snapshot lives (its basis
                        # stamp is authoritative inside the npz meta)
                        "ct": {
                            "snapshot": (
                                "ct.npz" if self.conntrack is not None
                                else None
                            ),
                        },
                    }
                    json.dump(body, f, indent=1)
                # _save_lock is a single-purpose snapshot-serialization
                # lock (CLI save vs shutdown poller); holding it across
                # the atomic tmp+rename IS its job — no verdict-path
                # thread ever contends on it
                os.replace(tmp, os.path.join(self.state_dir, "state.json"))  # policyd-lint: disable=LOCK002
                metrics.state_snapshot_bytes.set(
                    float(os.path.getsize(
                        os.path.join(self.state_dir, "state.json")
                    )),
                    {"kind": "state_json"},
                )
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        # compiled-state snapshot beside the JSON (pinned-map
        # persistence analog): a restart serves these tables while the
        # re-imported rules drive the recompile. Debounced — save_state
        # runs on every mutation, but the npz is heavy at scale, so it
        # is rewritten only when the compiled basis moved and at most
        # every few seconds (shutdown() forces the tail write).
        # Materialized policymaps are NOT included: across a restart
        # identity numbering may differ, so the daemon path could not
        # soundly adopt them (the engine-level API still takes them for
        # same-process restores, e.g. the bench restart measurement).
        self._save_compiled_snapshot()
        # CT snapshot beside it (policyd-survive): same debounce shape
        self._save_ct_snapshot()

    COMPILED_SNAPSHOT_MIN_INTERVAL_S = 5.0

    def _save_compiled_snapshot(self, force: bool = False) -> None:
        if not self.state_dir:
            return
        c = self.engine._compiled
        if c is None:
            return
        if c.revision < 0:
            # snapshot-restored state with re-stamped counters: writing
            # it back would overwrite the on-disk snapshot with the
            # same arrays under sentinel metadata — pure cost
            return
        basis = (c.revision, c.identity_version, c.vocab_version)
        now = time.monotonic()
        saved = False
        with self._save_lock:
            if not force:
                if basis == self._compiled_saved_basis:
                    return
                if (
                    now - self._compiled_saved_at
                    < self.COMPILED_SNAPSHOT_MIN_INTERVAL_S
                ):
                    return
            try:
                cpath = os.path.join(self.state_dir, "compiled.npz")
                self.engine.save_snapshot(cpath)
                self._compiled_saved_basis = basis
                self._compiled_saved_at = now
                metrics.state_snapshot_bytes.set(
                    float(os.path.getsize(cpath)), {"kind": "compiled"}
                )
                saved = True
            except Exception as e:
                log.warning("compiled snapshot save failed", fields={
                    "err": f"{type(e).__name__}: {e}",
                })
        if saved:
            # outside _save_lock: the journal must never extend the
            # snapshot writers' critical section
            self._journal_emit(kind="snapshot_save", attrs={
                "what": "compiled", "basis": list(basis),
            })

    CT_SNAPSHOT_MIN_INTERVAL_S = 5.0

    def _save_ct_snapshot(self, force: bool = False) -> None:
        """Write ct.npz beside compiled.npz (policyd-survive), stamped
        with the basis + CT epoch the live entries were VERDICTED
        under — the pipeline's served basis, not the engine's newest
        compile: between a recompile and the next rebuild the table
        still holds previous-basis entries, and stamping those with
        the new revision would let a raced rule change restore as a
        false match. Debounced like the compiled snapshot (CT churn is
        continuous); shutdown() forces the tail write."""
        if not self.state_dir or self.conntrack is None:
            return
        if self._ct_save_suppressed:
            return  # mid-restore: the disk pair is still authoritative
        basis = self.pipeline._mat_basis
        if basis is None or basis[0] < 0:
            return  # nothing served yet / restored sentinel counters
        if self.pipeline._ct_flush_pending:
            return  # table is condemned — the next rebuild flushes it
        # pair coherence: the basis we stamp must also be the one in
        # compiled.npz, or the restore-side match can never succeed. A
        # landed BACKGROUND recompile moves the served basis without
        # any save_state trigger (the endpoint_add save ran while the
        # compile was still in flight), so re-save compiled first.
        # Outside _save_lock — the compiled saver takes it too.
        if basis != self._compiled_saved_basis:
            self._save_compiled_snapshot(force=True)
        now = time.monotonic()
        ct_epoch = getattr(self.pipeline, "_ct_epoch", 0)
        saved = False
        with self._save_lock:
            if not force and (
                now - self._ct_saved_at < self.CT_SNAPSHOT_MIN_INTERVAL_S
            ):
                return
            from .datapath.ct_snapshot import save_ct_state

            try:
                # same _save_lock invariant as save_state above: the
                # callee's tmp+fsync+rename is exactly what the lock
                # serializes (snapshot writers), so the one-call-away
                # file I/O is the design, not a convoy
                nbytes = save_ct_state(  # policyd-lint: disable=LOCK002
                    os.path.join(self.state_dir, "ct.npz"),
                    self.conntrack,
                    basis=basis,
                    ct_epoch=ct_epoch,
                )
                self._ct_saved_at = now
                metrics.state_snapshot_bytes.set(
                    float(nbytes), {"kind": "ct"}
                )
                saved = True
            except Exception as e:
                # a failed CT save (including an injected torn write)
                # must never fail the caller's mutation path — the next
                # save retries; restore tolerates whatever is on disk
                log.warning("ct snapshot save failed", fields={
                    "err": f"{type(e).__name__}: {e}",
                })
        if saved:
            self._journal_emit(kind="snapshot_save", attrs={
                "what": "ct", "basis": list(basis), "ct_epoch": ct_epoch,
            })

    def restore_state(self) -> int:
        """Parse the snapshot and rebuild live state (restoreOldEndpoints
        + regenerateRestoredEndpoints, daemon/state.go:53,135)."""
        path = os.path.join(self.state_dir or "", "state.json")
        if not self.state_dir or not os.path.exists(path):
            return 0
        # restart-downtime clock (policyd-survive): starts at state
        # load, stops at the first completed verdict batch — the span
        # during which a restarted daemon cannot answer.
        self._restore_started = time.monotonic()
        self.pipeline.on_first_batch = self._note_restart_downtime
        # Enforcement continuity (the pinned-map property): load the
        # compiled device tables from the last save FIRST, so verdicts
        # serve last-known-good state while the re-imported rules and
        # endpoints below drive the (slow) recompile when they differ.
        cpath = os.path.join(self.state_dir, "compiled.npz")
        if os.path.exists(cpath):
            try:
                self.engine.restore_snapshot(cpath)
            except Exception as e:
                log.warning("compiled snapshot restore failed", fields={
                    "err": f"{type(e).__name__}: {e}",
                })
        # Capture the CT snapshot (and the basis of the compiled file
        # it rode beside) NOW, same early-read pattern as compiled.npz
        # above: every endpoint_add below runs save_state, whose
        # debounced snapshot writes would otherwise clobber the very
        # files we restore from.
        from .compiler.snapshot import read_snapshot_basis
        from .datapath.ct_snapshot import load_ct_state

        ct_snap = load_ct_state(os.path.join(self.state_dir, "ct.npz"))
        ct_disk_basis = read_snapshot_basis(cpath)
        # ... and the early read is not enough: a boot that dies after
        # the re-add loop but before the first CT sync would leave that
        # clobbered (empty, mid-re-add-basis) ct.npz as the ONLY copy.
        # Suppress CT snapshot writes entirely until the restore has
        # refilled the table — the on-disk pair stays exactly as the
        # dead process left it.
        self._ct_save_suppressed = True
        try:
            with open(path) as f:
                snap = json.load(f)
            # upgrade older snapshots in memory (cilium-map-migrate role)
            from .state_migrate import migrate

            snap = migrate(snap)
            rules = [rule_from_dict(d) for d in snap.get("rules", [])]
            if rules:
                self.repo.add_list(rules)
            for sm in snap.get("services", []):
                self.services.restore(
                    self._frontend(sm["frontend"]),
                    [
                        Backend(
                            b["ip"], int(b["port"]),
                            int(b.get("weight", 1)),
                        )
                        for b in sm.get("backends", [])
                    ],
                    int(sm["id"]),
                )
            n = 0
            for em in snap.get("endpoints", []):
                try:
                    self.endpoint_add(
                        em["id"], em["labels"], ipv4=em.get("ipv4"),
                        ipv6=em.get("ipv6"),
                    )
                    n += 1
                except ValueError:
                    continue
                # re-register restored IPs with IPAM so allocate_next
                # cannot hand them out again (pkg/ipam restore path)
                ip = em.get("ipv4")
                if ip:
                    try:
                        self.ipam.allocate(ip, owner=f"endpoint-{em['id']}")
                    except ValueError:
                        pass  # outside the pool (static IP) or pre-claimed
            # Established-flow continuity: restore the CT snapshot LAST —
            # every endpoint_add above ran set_endpoints, which flushes
            # the host table (CT keys embed endpoint indices, and the
            # restore loop reproduces the saved index order).
            self._restore_ct_snapshot(ct_snap, ct_disk_basis)
        finally:
            self._ct_save_suppressed = False
        # kept-vs-cold restore verdict on the journal: warning when the
        # basis mismatched (the fleet timeline shows which restarts
        # came up cold)
        info = self._ct_restore_info
        if info is not None:
            self._journal_emit(
                kind="ct_restore",
                severity="info" if info.get("basis_match") else "warning",
                attrs=dict(info),
            )
        return n

    def _restore_ct_snapshot(self, snap, basis) -> None:
        """Refill the host conntrack from the captured ct.npz payload
        when its recorded policy basis matches the compiled snapshot we
        just restored. Any mismatch — raced rule change between the two
        writes, torn file, missing compiled.npz — degrades to the
        pre-PR behaviour: a cold (flushed) table. Never raises."""
        if not self.state_dir or self.conntrack is None:
            return
        info: Dict = {
            "restored_from": os.path.join(self.state_dir, "ct.npz"),
            "kept": 0, "expired": 0, "flushed": 0,
            "basis_match": False, "snapshot_age_s": None,
        }
        if snap is None:  # missing / torn / foreign-schema file
            self._ct_restore_info = info
            return
        info["snapshot_age_s"] = max(0.0, time.time() - snap["saved_at"])
        if basis is None or basis != snap["basis"]:
            # the entries were admitted under a policy world we did not
            # restore — keeping them would enforce stale verdicts
            info["flushed"] = int(snap["entries"])
            metrics.ct_restored_entries_total.inc(
                {"result": "flushed"}, float(snap["entries"])
            )
            self._ct_restore_info = info
            return
        kept, expired = self.conntrack.restore_arrays(
            snap["ka"], snap["kb"], snap["kc"], snap["ttl"],
            packets=snap["packets"], revnat=snap["revnat"],
        )
        # the first rebuild materializes from exactly these restored
        # tables — hold its flush triggers so the refill survives it;
        # pinned to the revision current NOW, so any policy mutation
        # landing before that rebuild voids the hold and flushes
        c = self.engine._compiled
        self.pipeline._ct_restore_hold = (
            c.revision if c is not None else None
        )
        info.update(kept=kept, expired=expired, basis_match=True)
        if kept:
            metrics.ct_restored_entries_total.inc(
                {"result": "kept"}, float(kept))
        if expired:
            metrics.ct_restored_entries_total.inc(
                {"result": "expired"}, float(expired))
        self._ct_restore_info = info

    def _note_restart_downtime(self) -> None:
        """One-shot pipeline callback: first verdict batch after a
        restore closes the downtime window."""
        started = self._restore_started
        if started is None:
            return
        self._restore_started = None
        downtime = time.monotonic() - started
        metrics.restart_downtime_seconds.set(downtime)
        self._journal_emit(kind="restore_done", attrs={
            "downtime_ms": round(downtime * 1e3, 3),
        })

    def ct_restore_info(self) -> Optional[Dict]:
        """Provenance of the last CT restore attempt (bugtool)."""
        return self._ct_restore_info

    def drain(self, deadline_s: float = 5.0) -> Dict:
        """Graceful drain (policyd-survive): shed new admissions, let
        in-flight verdict batches complete FIFO under the deadline,
        persist CT + compiled + state.json, and report. Every batch is
        resolved — completed normally or degraded — so callers observe
        verdicts_lost == 0 structurally."""
        t0 = time.monotonic()
        self._journal_emit(kind="drain_begin", attrs={
            "policy_epoch": self.pipeline.policy_epoch,
            "deadline_s": float(deadline_s),
        })
        # stop the stall watchdog FIRST: the bounded wait below
        # legitimately blocks on slow completions and must not race an
        # abandonment sweep
        self.pipeline.set_stall_ms(0)
        self.pipeline.begin_drain()
        report = self.pipeline.drain(deadline_s=deadline_s)
        # flush the shared L7 inspection pipeline too — its in-flight
        # batches carry verdicts the same callers are waiting on
        try:
            from .datapath import l7_pipeline as _l7rt

            l7 = _l7rt.shared_pipeline()
            if l7 is not None:
                l7.drain()
        except Exception as e:
            log.warning("l7 drain failed", fields={
                "err": f"{type(e).__name__}: {e}",
            })
        # tail persistence while the tables are quiescent
        self._save_compiled_snapshot(force=True)
        self._save_ct_snapshot(force=True)
        self.save_state()
        elapsed = time.monotonic() - t0
        metrics.drain_seconds.observe(elapsed)
        report = dict(report)
        report.update(drain_s=elapsed, verdicts_lost=0)
        self._journal_emit(kind="drain_end", attrs={
            "drain_s": round(elapsed, 6),
            "verdicts_lost": 0,
            "completed": report.get("completed", 0),
            "abandoned": report.get("abandoned", 0),
        })
        return report

    def shutdown(self, deadline_s: float = 5.0) -> None:
        # bounded graceful drain: sheds new work, completes (or
        # degrades) everything in flight, persists CT + compiled +
        # state.json under the deadline
        self.drain(deadline_s=deadline_s)
        self._stop_journal()
        self._stop_fleet_sampler()
        self.controllers.remove_all()
        self.health.stop()
        self.fqdn.stop()
        self.endpoint_manager.shutdown()
