"""Datapath: the flow-processing pipeline (reference: bpf/ + pkg/datapath).

The kernel-eBPF role collapses here into a batched device pipeline:
prefilter deny tries → ipcache LPM identity derivation → policymap
lookup — the XDP hook (bpf/bpf_xdp.c), netdev identity resolution
(bpf/bpf_netdev.c:376), and per-endpoint policy program
(bpf/lib/policy.h) as one jitted program over flow batches.
"""

from .fastpath import VerdictFastpath
from .pipeline import DatapathPipeline, DatapathTables, DROP_PREFILTER, DROP_POLICY, FORWARD

__all__ = [
    "DatapathPipeline",
    "DatapathTables",
    "VerdictFastpath",
    "DROP_PREFILTER",
    "DROP_POLICY",
    "FORWARD",
]
