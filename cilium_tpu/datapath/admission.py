# policyd: hot
"""Deadline-aware admission control + stuck-dispatch watchdog
(policyd-overload).

PR 6's failsafe heals *faults*; this module handles *overload* and
*hangs* — the two failure classes a policy plane serving millions of
users meets long before a poisoned device program:

- ``AdmissionController``: an AIMD limit on the submit queue, keyed on
  queue wait + an EWMA of completion latency. Every submitted batch
  can carry a deadline (``DaemonConfig.verdict_deadline_ms``); over
  budget, the pipeline routes flows through the prefilter shed stage
  (``compile_shed_table`` + the ``shed_flows*`` kernels in
  pipeline.py) or defers them bounded — never an unbounded queue,
  never a silent drop.

- ``compile_shed_table``: the host compile of the coarse
  ``[identity, proto/port-class]`` drop table (PAPER.md layer 1's XDP
  prefilter role, drop reason 144). Sound by construction: a cell is
  markable only when NO realized policymap column of ANY local
  endpoint could allow ANY flow in it, so a shed verdict is always a
  verdict the full path would also have denied.

- ``Watchdog``: a monitor thread that bounds how long the daemon can
  block on a wedged dispatch (r05's bench round died to exactly this).
  A batch whose completion pull exceeds ``dispatch_stall_ms`` is
  abandoned THROUGH the PR 6 quarantine — degraded result, CT-epoch
  bump, breaker accounting — and ``result()`` unblocks with a verdict
  per flow. Registered external waits (attach, compile) ride the same
  sweep via ``watching()``.

Both halves are deterministically injectable: ``SITE_QUEUE_FULL``
forces the gate over budget, ``SITE_STALL`` fires a synthetic stall
through the same classify → breaker path a real one takes.

Stdlib + numpy only: the controller and watchdog must be importable
(and testable) without jax; the device kernels live in pipeline.py.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults as _faults
from .. import metrics as _metrics

# -- the proto/port class law ----------------------------------------------
# 3 proto rows (tcp / udp / other) × 3 dport buckets (well-known <1024,
# registered <32768, ephemeral) = 9 classes. Coarse on purpose: the
# table must stay a single cheap gather, and DoS mixes concentrate in
# few cells (a SYN flood is one (tcp, bucket) column).
PROTO_TCP = 6
PROTO_UDP = 17
N_PROTO_CLASSES = 3
N_PORT_BUCKETS = 3
N_SHED_CLASSES = N_PROTO_CLASSES * N_PORT_BUCKETS

REASON_SHED_PREFILTER = "prefilter"  # drop reason 144
REASON_SHED_DEADLINE = "deadline"    # resolved via 155 / FailOpen


def flow_class(dport, proto):
    """[B] proto, [B] dport → [B] class index in [0, 9). Operator-only
    math so the SAME law runs on host numpy (table compile, tests) and
    inside the jitted shed walk (jnp arrays)."""
    pi = 2 - 2 * (proto == PROTO_TCP) - 1 * (proto == PROTO_UDP)
    bucket = (dport >= 1024) * 1 + (dport >= 32768) * 1
    return pi * N_PORT_BUCKETS + bucket


def _port_bucket(port: int) -> int:
    return (1 if port >= 1024 else 0) + (1 if port >= 32768 else 0)


def compile_shed_table(
    allow_nc: np.ndarray,  # [N, C_pad] bool host policymap mirror
    ep_slots: Sequence[Sequence[Tuple[int, int]]],
) -> np.ndarray:
    """Realized policymap → ``[N, 9]`` uint8 drop table (1 = every flow
    in this (identity row, class) cell is deny-for-sure).

    A cell stays 0 ("don't shed") whenever any column of any local
    endpooint could cover it: the L3-only column covers every class,
    a (0, proto) slot covers the proto's three buckets, a (port, proto)
    slot covers its exact (proto, bucket) cell. Unknown protos map to
    the "other" row (coverage within the class is a superset of the
    column's true match set, which only ever clears shed bits — the
    sound direction). Merged over endpoints: shed only when NO endpoint
    allows, so the table is valid for any ep_idx in the batch."""
    n = allow_nc.shape[0]
    if not len(ep_slots):
        # no endpoints → nothing can be proven deny-heavy; shed nothing
        return np.zeros((n, N_SHED_CLASSES), np.uint8)
    covered = np.zeros((n, N_SHED_CLASSES), bool)
    col = 0
    for slots in ep_slots:
        l3 = allow_nc[:, col]
        col += 1
        covered |= l3[:, None]
        for port, proto in slots:
            a = allow_nc[:, col]
            col += 1
            if proto == PROTO_TCP:
                pis = (0,)
            elif proto == PROTO_UDP:
                pis = (1,)
            elif proto == 0:  # wildcard proto covers every row
                pis = (0, 1, 2)
            else:
                pis = (2,)
            buckets = (
                range(N_PORT_BUCKETS) if port == 0 else (_port_bucket(port),)
            )
            for pi in pis:
                for bk in buckets:
                    covered[:, pi * N_PORT_BUCKETS + bk] |= a
    return (~covered).astype(np.uint8)


class AdmissionController:
    """AIMD submit-queue limit, keyed on EWMA completion latency.

    The limit moves in ``[1, max_depth]``: additive increase on every
    in-deadline completion, multiplicative (halving) decrease on a
    deadline overrun or an injected queue-full. ``over_budget`` is the
    gate decision: depth at the limit, OR — with a deadline configured
    — the Little's-law projection ``(depth + 1) × ewma`` past the
    budget (admitting one more batch behind ``depth`` waiters can't
    finish in time, so shed it NOW instead of queueing it to die).

    ``shedding()`` is the tuner armistice: while the gate shed
    recently, the depth controller must not probe the queue UP — two
    controllers pushing the same knob in opposite directions is a
    classic oscillation."""

    SHED_HOLD_S = 1.0
    EWMA_ALPHA = 0.2

    def __init__(self, max_depth: int, deadline_ms: float = 0.0) -> None:
        self.max_depth = max(1, int(max_depth))
        self.deadline_s = max(0.0, float(deadline_ms)) / 1000.0
        self._lock = threading.Lock()
        self._limit = float(self.max_depth)
        self._ewma_s = 0.0
        self._last_shed = 0.0  # time.monotonic of the last shed
        self.shed = {REASON_SHED_PREFILTER: 0, REASON_SHED_DEADLINE: 0}
        self.admitted = 0  # flows that entered the full verdict path
        # lifecycle-journal hook (policyd-journal): daemon sets this to
        # EventJournal.emit while LifecycleJournal is on; None keeps
        # the hot path at one attribute read. Shed episodes are EDGE
        # TRIGGERED — one shed_start when shedding begins, one shed_end
        # after SHED_HOLD_S of quiet — never one event per shed batch.
        self.on_journal = None
        self._episode = None  # {"t0": monotonic, "shed0": counts}

    @property
    def limit(self) -> float:
        return self._limit

    def over_budget(self, depth: int) -> bool:
        with self._lock:
            if depth + 1 > self._limit:
                return True
            if self.deadline_s and self._ewma_s:
                return (depth + 1) * self._ewma_s > self.deadline_s
            return False

    def observe_completion(self, latency_s: float) -> None:
        """One batch finished ``latency_s`` after submit: fold into the
        EWMA and move the AIMD limit."""
        in_deadline = (
            not self.deadline_s or latency_s <= self.deadline_s
        )
        with self._lock:
            self._ewma_s = (
                latency_s
                if self._ewma_s == 0.0
                else (1 - self.EWMA_ALPHA) * self._ewma_s
                + self.EWMA_ALPHA * latency_s
            )
            if in_deadline:
                # additive increase, slower near the ceiling (the
                # classic 1/w growth keeps the probe gentle)
                self._limit = min(
                    float(self.max_depth), self._limit + 1.0 / self._limit
                )
            else:
                self._limit = max(1.0, self._limit / 2.0)

    def note_queue_full(self) -> None:
        """Injected (or observed) queue-full: multiplicative decrease
        without waiting for a completion to prove the overrun."""
        with self._lock:
            self._limit = max(1.0, self._limit / 2.0)

    def note_shed(self, reason: str, n: int) -> None:
        end_attrs = start_attrs = None
        with self._lock:
            now = time.monotonic()
            # a burst arriving after the hold window first closes the
            # PREVIOUS episode (its deltas must not include this burst)
            if (
                self._episode is not None
                and now - self._last_shed >= self.SHED_HOLD_S
            ):
                end_attrs = self._close_episode_locked(now)
            if self._episode is None:
                self._episode = {"t0": now, "shed0": dict(self.shed)}
                start_attrs = {"reason": reason}
            self.shed[reason] = self.shed.get(reason, 0) + int(n)
            self._last_shed = now
        _metrics.admission_shed_total.inc({"reason": reason}, float(n))
        oj = self.on_journal
        if oj is not None:
            if end_attrs is not None:
                oj(kind="shed_end", attrs=end_attrs)
            if start_attrs is not None:
                oj(kind="shed_start", severity="warning", attrs=start_attrs)

    def _close_episode_locked(self, now: float) -> Dict:
        """Retire the open episode; returns the shed_end attrs (the
        caller emits OUTSIDE the lock). Deltas are per-reason counts
        shed since the episode opened — the journal carries episode
        totals, never per-flow records."""
        ep = self._episode
        self._episode = None
        deltas = {
            r: self.shed.get(r, 0) - ep["shed0"].get(r, 0)
            for r in self.shed
            if self.shed.get(r, 0) - ep["shed0"].get(r, 0)
        }
        return {
            "shed": deltas,
            "duration_s": round(self._last_shed - ep["t0"], 6),
        }

    def episode_poll(self) -> None:
        """Close an episode that went quiet (called on the daemon's
        journal-shed-poll controller): without this, the FINAL shed_end
        of a load spike would wait for the next overload to surface."""
        end_attrs = None
        with self._lock:
            now = time.monotonic()
            if (
                self._episode is not None
                and now - self._last_shed >= self.SHED_HOLD_S
            ):
                end_attrs = self._close_episode_locked(now)
        oj = self.on_journal
        if oj is not None and end_attrs is not None:
            oj(kind="shed_end", attrs=end_attrs)

    def note_admitted(self, n: int) -> None:
        with self._lock:
            self.admitted += int(n)

    def shedding(self) -> bool:
        return time.monotonic() - self._last_shed < self.SHED_HOLD_S

    def snapshot(self) -> Dict:
        with self._lock:
            shed_n = sum(self.shed.values())
            total = shed_n + self.admitted
            return {
                "limit": round(self._limit, 3),
                "max_depth": self.max_depth,
                "deadline_ms": self.deadline_s * 1000.0,
                "ewma_completion_ms": round(self._ewma_s * 1000.0, 3),
                "shed": dict(self.shed),
                "admitted_flows": self.admitted,
                "shed_ratio": round(shed_n / total, 6) if total else 0.0,
                "shedding": time.monotonic() - self._last_shed
                < self.SHED_HOLD_S,
            }


class Watchdog:
    """Stuck-operation monitor (the bound on how long the daemon can
    hang). Three watch sources per sweep:

    - the pipeline's ACTIVELY COMPLETING batch (``pipe._completing``,
      set around the finish closure): a completion pull older than the
      stall budget is abandoned through ``pipe._quarantine`` — the
      waiter's ``result()`` unblocks with a degraded verdict per flow
      while the wedged XLA pull is left to die on its own thread.
      In-flight batches nobody is pulling are NOT stalls — lazy
      completion is the pipeline's normal shape.
    - registered external waits (``watching(site)``): attach and
      compile stalls ride the same sweep; one metric + breaker note
      per stalled op.
    - ``SITE_STALL`` injection: with the hub armed, every sweep probes
      the site, so a chaos round drives the whole detect → classify →
      quarantine path without a real wedge.

    The sweep interval is stall/4 (clamped to [1ms, 250ms]), so a
    stall is detected at most 1.25× the budget after it began —
    comfortably under the 2× acceptance bound."""

    def __init__(self, pipe, stall_ms: float) -> None:
        self._pipe = pipe
        self.stall_s = float(stall_ms) / 1000.0
        self._poll_s = min(0.25, max(0.001, self.stall_s / 4.0))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._external: Dict[int, List] = {}  # token → [site, t0, fired]
        self._next_token = 0
        self.last_stall: Optional[Dict] = None
        self.stalls = 0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="policyd-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=max(1.0, 8 * self._poll_s))
        self._thread = None

    # -- external waits ------------------------------------------------
    @contextmanager
    def watching(self, site: str):
        """Register an external operation (attach handshake, policy
        compile) for the sweep: if it outlives the stall budget it is
        counted and classified like a stuck dispatch. The operation
        itself is not interrupted — the point is that the stall becomes
        VISIBLE (metric + breaker) instead of a silent hang."""
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._external[token] = [site, time.monotonic(), False]
        try:
            yield
        finally:
            with self._lock:
                self._external.pop(token, None)

    # -- the sweep -----------------------------------------------------
    def _note_stall(self, site: str, age_s: float, exc: BaseException) -> None:
        self.stalls += 1
        self.last_stall = {
            "site": site,
            "age_ms": round(age_s * 1000.0, 3),
            "at": time.time(),
        }
        _metrics.watchdog_stalls_total.inc({"site": site})
        oj = getattr(self._pipe, "on_journal", None)
        if oj is not None:
            oj(
                kind="watchdog_stall",
                severity="error",
                attrs={"site": site, "age_ms": round(age_s * 1000.0, 3)},
            )
        kind = _faults.classify(exc)
        # a stall is never a programmer error; classify() maps the
        # TimeoutError we synthesize (and injected FaultErrors) to
        # transient/poisoned — both feed the breaker
        if kind == _faults.KIND_ERROR:
            kind = _faults.KIND_TRANSIENT
        self._pipe._note_fault(exc, kind)

    def _sweep(self) -> None:
        now = time.monotonic()
        pipe = self._pipe
        # injected stalls: deterministic chaos without a real wedge
        if _faults.hub.active:
            try:
                _faults.hub.check(_faults.SITE_STALL)
            except _faults.FaultError as e:
                self._note_stall(_faults.SITE_STALL, 0.0, e)
        # the actively-completing batch
        completing = pipe._completing
        if completing is not None:
            inf, t0 = completing
            if now - t0 > self.stall_s:
                abandoned = False
                with pipe._queue_lock:
                    if not inf.abandoned and not inf.pending.done:
                        inf.abandoned = True
                        abandoned = True
                if abandoned:
                    exc = TimeoutError(
                        f"dispatch completion stalled > "
                        f"{self.stall_s * 1000.0:.0f}ms"
                    )
                    self._note_stall(_faults.SITE_DISPATCH, now - t0, exc)
                    # quarantine THROUGH the failsafe path: CT epoch
                    # bump + degraded result, then unblock the waiter
                    value = pipe._quarantine(inf)
                    inf.pending._value = value
                    inf.pending._event.set()
        # registered external waits (attach / compile)
        with self._lock:
            stuck = [
                e for e in self._external.values()
                if not e[2] and now - e[1] > self.stall_s
            ]
            for e in stuck:
                e[2] = True  # one note per op
        for site, t0, _f in stuck:
            self._note_stall(
                site, now - t0,
                TimeoutError(
                    f"{site} stalled > {self.stall_s * 1000.0:.0f}ms"
                ),
            )

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            try:
                self._sweep()
            # the watchdog must never die to a racing teardown (the
            # pipe it probes is being mutated by shutdown); a broken
            # sweep carries no pipeline state to corrupt — it simply
            # retries next tick, so classification has nothing to do
            except Exception:  # policyd-lint: disable=ROBUST001
                continue

    def snapshot(self) -> Dict:
        with self._lock:
            watching = [e[0] for e in self._external.values()]
        return {
            "stall_ms": self.stall_s * 1000.0,
            "stalls": self.stalls,
            "last_stall": self.last_stall,
            "watching": watching,
            "alive": self._thread is not None,
        }
