"""Vectorized flow conntrack for the batched datapath.

The reference consults its conntrack tables on every packet before the
policy stage (bpf/bpf_lxc.c:477 ct_lookup4 / bpf/lib/conntrack.h:103-205):
an established or reply hit forwards without a policy verdict — that's
what lets reply traffic flow without explicit rules and keeps the
per-packet cost at one hash probe.

TPU-first redesign: the table is a numpy open-addressing hash table
probed with fully vectorized batch lookups, sitting IN FRONT of the
device dispatch. Established-heavy batches shrink (often to zero) the
flow set that pays the device round trip — the same economics as the
kernel's CT fast path, moved to the batch level. Keys are three packed
uint64 words so IPv4 and IPv6 share one table.

Direction/reply semantics (conntrack.h tuple flip): an entry created
for (peer, ep, sport, dport, dir) matches

- the exact tuple again              → ESTABLISHED
- (peer, ep, dport, sport, 1-dir)    → REPLY

mirroring the kernel's forward/reverse tuple pair.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

import numpy as np

from ..maps.ctmap import DEFAULT_LIFETIME_OTHER, DEFAULT_LIFETIME_TCP

CT_NEW = 0
CT_ESTABLISHED = 1
CT_REPLY = 2

_EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — vectorized uint64 avalanche."""
    with np.errstate(over="ignore"):
        x = x.astype(np.uint64, copy=True)
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x


def pack_keys(
    peer_hi: np.ndarray,  # [B] uint64 — high 64 bits of peer IP (0 for v4)
    peer_lo: np.ndarray,  # [B] uint64 — low 64 bits (v4 address for v4)
    ep_idx: np.ndarray,
    sport: np.ndarray,
    dport: np.ndarray,
    proto: np.ndarray,
    direction: np.ndarray,  # [B] 0 ingress / 1 egress
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """→ (ka, kb, kc) uint64 key words for the forward tuple."""
    # bit layout of kc: ep[41..63] sport[25..40] dport[9..24]
    # proto[1..8] dir[0]
    ka = peer_hi.astype(np.uint64)
    kb = peer_lo.astype(np.uint64)
    kc = (
        (ep_idx.astype(np.uint64) << np.uint64(41))
        | (sport.astype(np.uint64) << np.uint64(25))
        | (dport.astype(np.uint64) << np.uint64(9))
        | (proto.astype(np.uint64) << np.uint64(1))
        | direction.astype(np.uint64)
    )
    return ka, kb, kc


def unpack_proto(kc: np.ndarray) -> np.ndarray:
    return (kc >> np.uint64(1)) & np.uint64(0xFF)


def flip_kc(kc: np.ndarray) -> np.ndarray:
    """Reply tuple: swap sport/dport, flip direction, keep ep/proto."""
    ep = kc >> np.uint64(41)
    sport = (kc >> np.uint64(25)) & np.uint64(0xFFFF)
    dport = (kc >> np.uint64(9)) & np.uint64(0xFFFF)
    proto = unpack_proto(kc)
    direction = kc & np.uint64(0x1)
    return (
        (ep << np.uint64(41))
        | (dport << np.uint64(25))
        | (sport << np.uint64(9))
        | (proto << np.uint64(1))
        | (np.uint64(1) - direction)
    )


class FlowConntrack:
    """Open-addressing CT table with vectorized batch ops."""

    def __init__(
        self,
        capacity_bits: int = 18,
        # 16 linear probes: zero insert drops at load ≤0.25 (measured);
        # drops only degrade to per-batch re-verdicts, but each CT miss
        # tail costs a device dispatch, so placement robustness pays.
        probes: int = 16,
        tcp_lifetime: float = DEFAULT_LIFETIME_TCP,
        other_lifetime: float = DEFAULT_LIFETIME_OTHER,
    ) -> None:
        self.capacity = 1 << capacity_bits
        self.mask = np.uint64(self.capacity - 1)
        self.probes = probes
        self.tcp_lifetime = tcp_lifetime
        self.other_lifetime = other_lifetime
        self._lock = threading.Lock()
        c = self.capacity
        self.ka = np.full(c, _EMPTY, np.uint64)
        self.kb = np.zeros(c, np.uint64)
        self.kc = np.zeros(c, np.uint64)
        self.valid = np.zeros(c, bool)
        self.expires = np.zeros(c, np.float64)
        self.packets = np.zeros(c, np.int64)
        # revNAT id recorded at creation (ct_entry.rev_nat_index,
        # bpf/lib/common.h ct_entry) — lets reply traffic restore the
        # original VIP after backend→client translation.
        self.revnat = np.zeros(c, np.uint16)
        self.version = 0

    # ------------------------------------------------------------------
    def _hash(self, ka, kb, kc) -> np.ndarray:
        with np.errstate(over="ignore"):
            h = _mix64(ka ^ _mix64(kb ^ _mix64(kc)))
        return h

    def _probe_slots(self, ka, kb, kc) -> np.ndarray:
        """[B, P] candidate slot indices (linear probing)."""
        h = self._hash(ka, kb, kc)
        with np.errstate(over="ignore"):
            return (
                (h[:, None] + np.arange(self.probes, dtype=np.uint64)[None, :])
                & self.mask
            ).astype(np.int64)

    def _find(self, ka, kb, kc, now: float) -> np.ndarray:
        """[B] slot of a live exact match, or -1.

        Progressive narrowing: probe round p touches only flows still
        unresolved after round p-1 (an EMPTY slot terminates a probe
        chain — miss; a key match terminates it — hit). At load ≤0.25
        almost everything resolves in round 0, so the memory traffic is
        ~1.1 gathers per flow instead of P=16 — materializing the full
        [B, P] probe matrix made the CT pre-pass cost more than the
        device dispatch it was meant to save."""
        n = len(ka)
        h = self._hash(ka, kb, kc)
        out = np.full(n, -1, np.int64)
        pending = np.arange(n)
        for p in range(self.probes):
            with np.errstate(over="ignore"):
                s = ((h[pending] + np.uint64(p)) & self.mask).astype(np.int64)
            kas = self.ka[s]
            key_eq = (
                (kas == ka[pending])
                & (self.kb[s] == kb[pending])
                & (self.kc[s] == kc[pending])
            )
            hit = key_eq & self.valid[s] & (self.expires[s] > now)
            out[pending[hit]] = s[hit]
            # chain continues only past live non-matching slots; an
            # EMPTY ka ends it (same termination rule the insert path
            # guarantees: entries never skip an empty slot)
            cont = ~hit & (kas != _EMPTY)
            pending = pending[cont]
            if pending.size == 0:
                break
        return out

    # ------------------------------------------------------------------
    def lookup_batch(
        self, ka, kb, kc, *, refresh: bool = True, want_revnat: bool = False
    ):
        """→ (state [B] uint8 CT_*, slot [B] int64)[, revnat [B] u16].
        Established hits optionally refresh lifetimes (the kernel
        updates ct lifetime on every packet). ``want_revnat`` reads
        each hit's revNAT id UNDER THE SAME LOCK HOLD as the find — a
        slot index used after the lock drops can be tombstoned, reused,
        or moved by a concurrent gc()/compact, so post-hoc revnat reads
        would return another flow's id."""
        now = time.monotonic()
        with self._lock:
            slot = self._find(ka, kb, kc, now)
            state = np.where(slot >= 0, CT_ESTABLISHED, CT_NEW).astype(np.uint8)
            miss = slot < 0
            if miss.any():
                rslot = self._find(ka[miss], kb[miss], flip_kc(kc[miss]), now)
                rhit = rslot >= 0
                midx = np.nonzero(miss)[0]
                state[midx[rhit]] = CT_REPLY
                slot[midx] = np.where(rhit, rslot, -1)
            live = slot >= 0
            if refresh and live.any():
                s = slot[live]
                proto = unpack_proto(self.kc[s])
                life = np.where(
                    proto == 6, self.tcp_lifetime, self.other_lifetime
                )
                self.expires[s] = now + life
                np.add.at(self.packets, s, 1)
            if want_revnat:
                rev = np.zeros(slot.shape, np.uint16)
                rev[live] = self.revnat[slot[live]]
                return state, slot, rev
            return state, slot

    def dump(self, limit: int = 4096) -> list:
        """Readable live entries (cilium bpf ct list). Addresses with a
        zero high word render as IPv4."""
        import ipaddress

        now = time.monotonic()
        out = []
        with self._lock:
            live = np.nonzero(self.valid & (self.expires > now))[0][:limit]
            for s in live:
                kc = self.kc[s]
                hi, lo = int(self.ka[s]), int(self.kb[s])
                if hi == 0 and lo <= 0xFFFFFFFF:
                    peer = str(ipaddress.ip_address(lo))
                else:
                    peer = str(ipaddress.ip_address((hi << 64) | lo))
                out.append({
                    "peer": peer,
                    "endpoint_index": int(kc >> np.uint64(41)),
                    "sport": int((kc >> np.uint64(25)) & np.uint64(0xFFFF)),
                    "dport": int((kc >> np.uint64(9)) & np.uint64(0xFFFF)),
                    "proto": int(unpack_proto(np.uint64(kc))),
                    "direction": "ingress" if int(kc) & 1 == 0 else "egress",
                    "packets": int(self.packets[s]),
                    "revnat": int(self.revnat[s]),
                    "expires_in_s": round(float(self.expires[s]) - now, 1),
                })
        return out

    def create_batch(self, ka, kb, kc, revnat: Optional[np.ndarray] = None) -> int:
        """Insert forward-tuple entries (vectorized claim, P rounds of
        first-writer-wins per slot). Duplicate keys in the batch are
        deduped; full neighborhoods drop the insert (the kernel map
        fails inserts when full — flow retries next batch). Returns the
        number inserted."""
        if len(ka) == 0:
            return 0
        now = time.monotonic()
        if revnat is None:
            revnat = np.zeros(len(ka), np.uint16)
        with self._lock:
            # dedupe within the batch
            u, uidx = np.unique(
                np.stack([ka, kb, kc], axis=1), axis=0, return_index=True
            )
            ka, kb, kc, revnat = ka[uidx], kb[uidx], kc[uidx], revnat[uidx]
            # skip keys already present (established)
            have = self._find(ka, kb, kc, now) >= 0
            ka, kb, kc, revnat = ka[~have], kb[~have], kc[~have], revnat[~have]
            if len(ka) == 0:
                return 0
            slots = self._probe_slots(ka, kb, kc)  # [B, P]
            proto = unpack_proto(kc)
            life = np.where(proto == 6, self.tcp_lifetime, self.other_lifetime)
            placed = np.zeros(len(ka), bool)
            inserted = 0
            for p in range(self.probes):
                cand = slots[:, p]
                free = (~self.valid[cand]) | (self.expires[cand] <= now)
                want = (~placed) & free
                if not want.any():
                    continue
                idx = np.nonzero(want)[0]
                # first writer wins per slot within this round
                _, first = np.unique(cand[idx], return_index=True)
                win = idx[first]
                s = cand[win]
                self.ka[s] = ka[win]
                self.kb[s] = kb[win]
                self.kc[s] = kc[win]
                self.valid[s] = True
                self.expires[s] = now + life[win]
                self.packets[s] = 1
                self.revnat[s] = revnat[win].astype(np.uint16)
                placed[win] = True
                inserted += len(win)
                if placed.all():
                    break
            self.version += 1
            return inserted

    # -- snapshot / restore (policyd-survive) --------------------------
    def snapshot_arrays(self) -> dict:
        """Packed live entries for the state-dir CT snapshot.

        ``expires`` is monotonic-clock based — meaningless in another
        process — so the snapshot stores REMAINING lifetime (``ttl``)
        and restore_arrays() re-bases it onto the restoring process's
        clock. Arrays are copied under the lock; the caller serializes
        outside it (the save_snapshot discipline in engine.py)."""
        now = time.monotonic()
        with self._lock:
            live = np.nonzero(self.valid & (self.expires > now))[0]
            return {
                "ka": self.ka[live].copy(),
                "kb": self.kb[live].copy(),
                "kc": self.kc[live].copy(),
                "ttl": (self.expires[live] - now).astype(np.float64),
                "packets": self.packets[live].copy(),
                "revnat": self.revnat[live].copy(),
            }

    def restore_arrays(
        self,
        ka: np.ndarray,
        kb: np.ndarray,
        kc: np.ndarray,
        ttl: np.ndarray,
        packets: Optional[np.ndarray] = None,
        revnat: Optional[np.ndarray] = None,
    ) -> Tuple[int, int]:
        """Re-insert snapshotted entries with a TTL-aware expiry sweep.

        → (kept, expired). Entries whose remaining lifetime ran out
        while the process was down are swept; TTLs are clamped to the
        configured lifetimes so a corrupt snapshot cannot install
        immortal entries. Keys already present stay untouched and count
        as kept (the quarantine rescue path restores into a live
        table). Entries that lose a full probe neighborhood are counted
        expired — same drop-not-crash rule as create_batch."""
        ka = np.asarray(ka, np.uint64)
        kb = np.asarray(kb, np.uint64)
        kc = np.asarray(kc, np.uint64)
        ttl = np.asarray(ttl, np.float64)
        n_in = len(ka)
        if packets is None:
            packets = np.ones(n_in, np.int64)
        if revnat is None:
            revnat = np.zeros(n_in, np.uint16)
        packets = np.asarray(packets, np.int64)
        revnat = np.asarray(revnat, np.uint16)
        alive = ttl > 0.0
        expired = n_in - int(alive.sum())
        ka, kb, kc, ttl = ka[alive], kb[alive], kc[alive], ttl[alive]
        packets, revnat = packets[alive], revnat[alive]
        if len(ka) == 0:
            return 0, expired
        now = time.monotonic()
        ttl = np.minimum(ttl, max(self.tcp_lifetime, self.other_lifetime))
        kept = 0
        with self._lock:
            have = self._find(ka, kb, kc, now) >= 0
            kept += int(have.sum())
            ka, kb, kc, ttl = ka[~have], kb[~have], kc[~have], ttl[~have]
            packets, revnat = packets[~have], revnat[~have]
            expires = now + ttl
            slots = self._probe_slots(ka, kb, kc)
            placed = np.zeros(len(ka), bool)
            for p in range(self.probes):
                cand = slots[:, p]
                free = (~self.valid[cand]) | (self.expires[cand] <= now)
                want = (~placed) & free
                if not want.any():
                    continue
                idx = np.nonzero(want)[0]
                _, first = np.unique(cand[idx], return_index=True)
                win = idx[first]
                s = cand[win]
                self.ka[s] = ka[win]
                self.kb[s] = kb[win]
                self.kc[s] = kc[win]
                self.valid[s] = True
                self.expires[s] = expires[win]
                self.packets[s] = packets[win]
                self.revnat[s] = revnat[win]
                placed[win] = True
                if placed.all():
                    break
            kept += int(placed.sum())
            expired += int((~placed).sum())
            self.version += 1
        return kept, expired

    # -- maintenance ----------------------------------------------------
    def gc(self) -> int:
        """Invalidate expired entries (ctmap.go GC:345).

        Tombstones only (valid=False, ka KEPT): _find terminates probe
        chains at an EMPTY ka, so emptying a reclaimed slot would make
        live entries later in the same chain unreachable. Tombstoned
        slots stay reusable — create_batch's free test is
        ``~valid | expired``, not ``ka == EMPTY``."""
        now = time.monotonic()
        with self._lock:
            stale = self.valid & (self.expires <= now)
            n = int(stale.sum())
            if n:
                self.valid[stale] = False
                self.version += 1
            # Tombstones accumulate forever (ka stays) and each one
            # keeps probe chains alive past it — sustained churn would
            # erode the early-termination win back to full-width
            # probing. Past 25% occupancy by tombstones, rehash the
            # live entries into fresh arrays.
            tombstones = int(((self.ka != _EMPTY) & ~self.valid).sum())
            if tombstones > self.capacity // 4:
                self._compact(now)
            return n

    def _compact(self, now: float) -> None:
        """Rebuild the table from its live entries (caller holds the
        lock): tombstoned slots return to EMPTY, restoring ~1-probe
        chains."""
        live = np.nonzero(self.valid & (self.expires > now))[0]
        ka, kb, kc = self.ka[live], self.kb[live], self.kc[live]
        expires = self.expires[live]
        packets = self.packets[live]
        revnat = self.revnat[live]
        self.ka[:] = _EMPTY
        self.valid[:] = False
        # re-place with the same probe discipline as create_batch
        slots = self._probe_slots(ka, kb, kc)
        placed = np.zeros(len(ka), bool)
        for p in range(self.probes):
            cand = slots[:, p]
            want = (~placed) & ~self.valid[cand]
            if not want.any():
                continue
            idx = np.nonzero(want)[0]
            _, first = np.unique(cand[idx], return_index=True)
            win = idx[first]
            s = cand[win]
            free = ~self.valid[s]
            win, s = win[free], s[free]
            self.ka[s] = ka[win]
            self.kb[s] = kb[win]
            self.kc[s] = kc[win]
            self.valid[s] = True
            self.expires[s] = expires[win]
            self.packets[s] = packets[win]
            self.revnat[s] = revnat[win]
            placed[win] = True
            if placed.all():
                break
        self.version += 1

    def flush(self) -> int:
        with self._lock:
            n = int(self.valid.sum())
            self.valid[:] = False
            self.ka[:] = _EMPTY
            self.version += 1
            return n

    def __len__(self) -> int:
        now = time.monotonic()
        return int((self.valid & (self.expires > now)).sum())
