"""Conntrack snapshot — the pinned-CT-map persistence analog.

Reference: the kernel datapath's conntrack maps are PINNED — they keep
admitting established flows while the agent restarts, and
bpf/cilium-map-migrate.c carries them across upgrades. Our host
`FlowConntrack` dies with the process, so the equivalent is a disk
snapshot beside the compiled-policy snapshot: packed key/meta arrays
with REMAINING lifetimes (the table's own clock is monotonic and does
not survive a process), stamped with the policy basis the entries were
verdicted under.

The basis stamp is what keeps established-bypass-survives-revoke
correct across a restart that raced a rule change: the restore path
(daemon.restore_state) KEEPS the entries only when the restored
compiled snapshot carries the same (revision, identity_version,
vocab_version) — otherwise the entries may bypass rules that no longer
allow them, so the table restores cold (flush), exactly what the PR 7
transactional CT flush would have done in-process.

Write path: atomic tmp + fsync + rename like every other state file,
with one injectable fault site (``SITE_STATE_WRITE``). An injected
fault there models the failure the atomic idiom cannot fully rule out
— power loss where the rename persisted but the data blocks did not —
by leaving a TORN file at the final path; the tolerant loader then
classifies it and the caller falls back to a cold flush, never a crash.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
import time
from typing import Optional, Tuple

import numpy as np

from .. import faults as _faults
from .conntrack import FlowConntrack

CT_SNAPSHOT_SCHEMA = 1

# Bounded restore: a snapshot larger than this is somebody else's file
# (or corruption that survived the zip CRC) — cap what one boot will
# re-place rather than stalling first-verdict behind a giant insert.
MAX_RESTORE_ENTRIES = 1 << 20


def save_ct_state(
    path: str,
    ct: FlowConntrack,
    *,
    basis: Tuple[int, int, int],
    ct_epoch: int,
) -> int:
    """Atomically write the CT snapshot; → payload size in bytes.

    ``basis`` is the compiled-policy basis (revision, identity_version,
    vocab_version) the live entries were verdicted under; ``ct_epoch``
    is the pipeline's flush-epoch counter at save time. Both ride in
    the meta blob for the restore-side keep-vs-flush decision and for
    bugtool provenance."""
    arrays = ct.snapshot_arrays()
    meta = {
        "schema": CT_SNAPSHOT_SCHEMA,
        "basis": [int(b) for b in basis],
        "ct_epoch": int(ct_epoch),
        "entries": int(len(arrays["ka"])),
        "saved_at": time.time(),
    }
    arrays["meta"] = np.frombuffer(
        json.dumps(meta).encode(), np.uint8
    ).copy()
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()

    if _faults.hub.active:
        try:
            _faults.hub.check(_faults.SITE_STATE_WRITE)
        except _faults.FaultError:
            # Torn-write injection: leave a truncated file at the FINAL
            # path (the post-rename-pre-data power-loss shape the
            # tmp+rename idiom cannot prevent) so chaos rounds exercise
            # the loader's tolerance, then surface the fault.
            with open(path, "wb") as f:
                f.write(payload[: max(1, len(payload) // 2)])
            raise

    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".ct.", suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return len(payload)


def load_ct_state(path: str) -> Optional[dict]:
    """→ {ka, kb, kc, ttl, packets, revnat, basis, ct_epoch, entries,
    saved_at} or None when the file is absent, truncated, torn, corrupt,
    or from another schema — a bad CT snapshot must degrade to a cold
    flush, never to a crash (same contract as load_compiled_state)."""
    import zipfile

    _bad = (OSError, ValueError, KeyError, zipfile.BadZipFile)
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["meta"]).decode())
            if meta.get("schema") != CT_SNAPSHOT_SCHEMA:
                return None
            n = min(int(meta["entries"]), MAX_RESTORE_ENTRIES)
            out = {
                k: z[k][:n].copy()
                for k in ("ka", "kb", "kc", "ttl", "packets", "revnat")
            }
            out["basis"] = tuple(int(b) for b in meta["basis"])
            out["ct_epoch"] = int(meta["ct_epoch"])
            out["entries"] = int(meta["entries"])
            out["saved_at"] = float(meta.get("saved_at", 0.0))
            return out
    except _bad:
        return None
