"""Device-resident conntrack: the CT table lives in HBM and is probed,
refreshed, and inserted INSIDE the verdict dispatch.

The host FlowConntrack (conntrack.py) fronts the device as a batch
pre-pass — correct, but at millions of flows per batch the host pays
gather-bound hash probing per packet while the device idles. The
kernel keeps its CT next to the datapath for exactly this reason
(bpf/lib/conntrack.h: per-CPU maps probed in the same program as the
policy lookup). TPU-first redesign: the table is six uint32 arrays
(full 192-bit tuple keys — no fingerprint collisions) plus an expiry
word, carried through the jitted step functionally: every dispatch
returns the updated arrays and the pipeline threads them into the next
call (donated buffers make the update in-place on device). One batch =
ONE device program: CT probe (forward + flipped reply tuple) → deny
LPM → identity LPM → policymap lookup → CT insert for newly-allowed
flows.

Semantics mirrored from FlowConntrack / conntrack.h:
- forward-tuple hit → ESTABLISHED (refresh lifetime)
- flipped-tuple hit (sport/dport swapped, direction inverted) → REPLY
- policy-allowed, non-redirect misses insert a forward entry
- redirect (proxy) flows never enter CT
- expiry: TCP 21600s / other 60s, wall clock passed per call
- flush = zero the arrays (verdict-basis moves, same as the host CT)

Insert conflicts (two new flows hashing to one free slot in one batch)
resolve last-writer-wins; the loser re-verdicts next batch — the same
degradation as a full kernel CT neighborhood.

MEASURED RESULT (TPU v5e-1, 4M-slot table, 2M-flow batches): the fused
step sustains ~0.6M flows/s — the [B, P] probe gathers against a
multi-MB table are random-access, and TPUs execute scattered gathers
essentially serially (the same reason the verdict kernel is formulated
as one-hot matmuls). The host numpy CT reaches ~7M lookups/s and the
native C++ front-end ~13M established flows/s end-to-end on one core.
CONCLUSION, recorded here deliberately: a hash-table conntrack belongs
next to the CPU — mirroring the reference, whose CT lives in per-CPU
kernel maps, not on an accelerator. This module stays as a correct,
tested engine for fully-device-resident deployments (no host in the
loop at all), and as the measured justification for the framework's
layering: device = dense policy math, host/native = per-flow state.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

CT_PROBES = 8

LIFE_TCP_S = 21600
LIFE_OTHER_S = 60


class DeviceCTState(NamedTuple):
    """CT table as device arrays ([C] each). A slot is live iff
    exp > now. Key words: peer address (hi/lo 64 bits as 2×u32 each)
    and the packed kc word (ep/sport/dport/proto/dir, conntrack.py
    pack_keys layout) split into 2×u32."""

    ka_hi: jnp.ndarray  # peer_hi >> 32
    ka_lo: jnp.ndarray  # peer_hi & 0xffffffff
    kb_hi: jnp.ndarray  # peer_lo >> 32
    kb_lo: jnp.ndarray
    kc_hi: jnp.ndarray  # kc >> 32
    kc_lo: jnp.ndarray
    exp: jnp.ndarray  # [C] int32 expiry (seconds, monotonic clock)


def make_state(capacity_bits: int = 20) -> DeviceCTState:
    # distinct buffers per field: the step donates the whole state, and
    # aliasing one zeros array across fields would donate it six times
    c = 1 << capacity_bits
    return DeviceCTState(
        *(jnp.zeros(c, jnp.uint32) for _ in range(6)),
        jnp.zeros(c, jnp.int32),
    )


def _mix32(x: jnp.ndarray) -> jnp.ndarray:
    """murmur3 fmix32 over uint32 lanes."""
    x = x.astype(jnp.uint32)
    x ^= x >> 16
    x *= jnp.uint32(0x85EBCA6B)
    x ^= x >> 13
    x *= jnp.uint32(0xC2B2AE35)
    x ^= x >> 16
    return x


def _hash_tuple(ka_hi, ka_lo, kb_hi, kb_lo, kc_hi, kc_lo) -> jnp.ndarray:
    h = _mix32(ka_hi)
    h = _mix32(h ^ ka_lo)
    h = _mix32(h ^ kb_hi)
    h = _mix32(h ^ kb_lo)
    h = _mix32(h ^ kc_hi)
    h = _mix32(h ^ kc_lo)
    return h


def pack_kc_words(
    ep_idx: jnp.ndarray, sport: jnp.ndarray, dport: jnp.ndarray,
    proto: jnp.ndarray, direction: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The pack_keys kc layout (ep[41:] sport[25:41] dport[9:25]
    proto[1:9] dir[0]) built in 32-bit halves (no uint64 on device):
        kc_lo = sport[25:32←7 bits] | dport<<9 | proto<<1 | dir
        kc_hi = ep<<9 | sport>>7
    """
    ep = ep_idx.astype(jnp.uint32)
    sp = sport.astype(jnp.uint32)
    dp = dport.astype(jnp.uint32)
    pr = proto.astype(jnp.uint32)
    dr = direction.astype(jnp.uint32)
    kc_lo = ((sp & jnp.uint32(0x7F)) << 25) | (dp << 9) | (pr << 1) | dr
    kc_hi = (ep << 9) | (sp >> 7)
    return kc_hi, kc_lo


def _flip_kc_words(kc_hi, kc_lo):
    """Reply tuple: swap sport/dport, invert the direction bit."""
    sp = ((kc_hi & jnp.uint32(0x1FF)) << 7) | (kc_lo >> 25)
    dp = (kc_lo >> 9) & jnp.uint32(0xFFFF)
    pr = (kc_lo >> 1) & jnp.uint32(0xFF)
    dr = kc_lo & jnp.uint32(1)
    ep = kc_hi >> 9
    f_lo = ((dp & jnp.uint32(0x7F)) << 25) | (sp << 9) | (pr << 1) | (
        dr ^ jnp.uint32(1)
    )
    f_hi = (ep << 9) | (dp >> 7)
    return f_hi, f_lo


def _probe(state: DeviceCTState, ka_hi, ka_lo, kb_hi, kb_lo, kc_hi, kc_lo,
           now: jnp.ndarray):
    """→ (hit [B] bool, slot [B] int32 of the hit or -1). Dense P-way
    probe: the [B, P] gathers stay on device where they belong."""
    c_mask = jnp.uint32(state.exp.shape[0] - 1)
    h = _hash_tuple(ka_hi, ka_lo, kb_hi, kb_lo, kc_hi, kc_lo)
    offs = jnp.arange(CT_PROBES, dtype=jnp.uint32)
    slots = ((h[:, None] + offs[None, :]) & c_mask).astype(jnp.int32)  # [B,P]
    match = (
        (state.ka_hi[slots] == ka_hi[:, None])
        & (state.ka_lo[slots] == ka_lo[:, None])
        & (state.kb_hi[slots] == kb_hi[:, None])
        & (state.kb_lo[slots] == kb_lo[:, None])
        & (state.kc_hi[slots] == kc_hi[:, None])
        & (state.kc_lo[slots] == kc_lo[:, None])
        & (state.exp[slots] > now)
    )
    hit = match.any(axis=1)
    first = jnp.argmax(match, axis=1)
    slot = jnp.where(hit, jnp.take_along_axis(slots, first[:, None], 1)[:, 0], -1)
    return hit, slot


def _life(proto: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(proto == 6, jnp.int32(LIFE_TCP_S), jnp.int32(LIFE_OTHER_S))


@functools.partial(jax.jit, donate_argnums=(0,))
def ct_step(
    state: DeviceCTState,
    peer_hi_w: Tuple[jnp.ndarray, jnp.ndarray],  # (hi32, lo32) of peer_hi
    peer_lo_w: Tuple[jnp.ndarray, jnp.ndarray],  # (hi32, lo32) of peer_lo
    kc_w: Tuple[jnp.ndarray, jnp.ndarray],  # (kc_hi, kc_lo)
    proto: jnp.ndarray,  # [B] int32
    now: jnp.ndarray,  # [] int32 seconds
    allow_new: jnp.ndarray,  # [B] bool — policy-allowed non-redirect misses
) -> Tuple[DeviceCTState, jnp.ndarray]:
    """Probe (fwd + reply), refresh hits, insert allowed misses →
    (new_state, established [B] bool). Designed to be CALLED FROM
    WITHIN a fused dispatch (pipeline process_flows_ct) — standalone
    jit here is for tests."""
    return _ct_step_impl(state, peer_hi_w, peer_lo_w, kc_w, proto, now, allow_new)


def _ct_step_impl(state, peer_hi_w, peer_lo_w, kc_w, proto, now, allow_new):
    ka_hi, ka_lo = peer_hi_w
    kb_hi, kb_lo = peer_lo_w
    kc_hi, kc_lo = kc_w

    fwd_hit, fwd_slot = _probe(state, ka_hi, ka_lo, kb_hi, kb_lo, kc_hi, kc_lo, now)
    f_hi, f_lo = _flip_kc_words(kc_hi, kc_lo)
    rep_hit, _rep_slot = _probe(state, ka_hi, ka_lo, kb_hi, kb_lo, f_hi, f_lo, now)
    established = fwd_hit | rep_hit

    life = _life(proto)
    # refresh forward hits (reply hits refresh their stored entry too —
    # via the reply slot; both scatters drop out-of-range -1 slots)
    exp = state.exp
    exp = exp.at[jnp.where(fwd_hit, fwd_slot, -1)].set(
        now + life, mode="drop"
    )
    exp = exp.at[jnp.where(rep_hit, _rep_slot, -1)].set(
        now + life, mode="drop"
    )

    # ── insert allowed new flows: first probe slot that is FREE
    # (expired) — scatter conflicts within the batch resolve last-wins
    c_mask = jnp.uint32(exp.shape[0] - 1)
    h = _hash_tuple(ka_hi, ka_lo, kb_hi, kb_lo, kc_hi, kc_lo)
    offs = jnp.arange(CT_PROBES, dtype=jnp.uint32)
    slots = ((h[:, None] + offs[None, :]) & c_mask).astype(jnp.int32)
    free = exp[slots] <= now  # [B, P]
    has_free = free.any(axis=1)
    pick = jnp.argmax(free, axis=1)
    ins_slot = jnp.take_along_axis(slots, pick[:, None], 1)[:, 0]
    do_ins = allow_new & ~established & has_free
    tgt = jnp.where(do_ins, ins_slot, -1)
    new_state = DeviceCTState(
        ka_hi=state.ka_hi.at[tgt].set(ka_hi, mode="drop"),
        ka_lo=state.ka_lo.at[tgt].set(ka_lo, mode="drop"),
        kb_hi=state.kb_hi.at[tgt].set(kb_hi, mode="drop"),
        kb_lo=state.kb_lo.at[tgt].set(kb_lo, mode="drop"),
        kc_hi=state.kc_hi.at[tgt].set(kc_hi, mode="drop"),
        kc_lo=state.kc_lo.at[tgt].set(kc_lo, mode="drop"),
        exp=exp.at[tgt].set(now + life, mode="drop"),
    )
    return new_state, established


def split_u64(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """uint64 host words → (hi32, lo32) uint32 arrays."""
    x = np.asarray(x, np.uint64)
    return (
        (x >> np.uint64(32)).astype(np.uint32),
        (x & np.uint64(0xFFFFFFFF)).astype(np.uint32),
    )


# ---------------------------------------------------------------------------
# host-side pull / seed (policyd-survive)
#
# The word split is lossless against the host layout: pack_kc_words
# builds exactly the low/high 32-bit halves of pack_keys' uint64 kc
# (sp>>7 lands in kc_hi bits [0:9] == kc bits [32:41]), so
# (hi<<32)|lo reconstructs the FlowConntrack key words verbatim.
# ---------------------------------------------------------------------------


def pull_live_entries(state: DeviceCTState, now_s: int,
                      limit: int = 1 << 16) -> dict:
    """Pull the live device entries to host → {ka, kb, kc (uint64),
    ttl (float64 remaining seconds)}, bounded at ``limit``.

    This is the quarantine CT rescue: called right before the failsafe
    zeroes device-CT, so degraded/host-mode keeps serving established
    flows out of FlowConntrack. The device may be the very thing being
    quarantined — callers wrap this in the classified-fault discipline
    and treat any failure as "rescue skipped, cold"."""
    exp = np.asarray(state.exp)
    live = np.nonzero(exp > now_s)[0][:limit]

    def join(hi, lo):
        return (
            (np.asarray(hi)[live].astype(np.uint64) << np.uint64(32))
            | np.asarray(lo)[live].astype(np.uint64)
        )

    return {
        "ka": join(state.ka_hi, state.ka_lo),
        "kb": join(state.kb_hi, state.kb_lo),
        "kc": join(state.kc_hi, state.kc_lo),
        "ttl": (exp[live] - now_s).astype(np.float64),
    }


def _mix32_np(x: np.ndarray) -> np.ndarray:
    """Numpy twin of _mix32 — bit-identical murmur3 fmix32, so host
    placement lands entries where the device probe will find them."""
    with np.errstate(over="ignore"):
        x = x.astype(np.uint32, copy=True)
        x ^= x >> np.uint32(16)
        x *= np.uint32(0x85EBCA6B)
        x ^= x >> np.uint32(13)
        x *= np.uint32(0xC2B2AE35)
        x ^= x >> np.uint32(16)
    return x


def _hash_tuple_np(ka_hi, ka_lo, kb_hi, kb_lo, kc_hi, kc_lo) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = _mix32_np(ka_hi)
        h = _mix32_np(h ^ ka_lo)
        h = _mix32_np(h ^ kb_hi)
        h = _mix32_np(h ^ kb_lo)
        h = _mix32_np(h ^ kc_hi)
        h = _mix32_np(h ^ kc_lo)
    return h


def seed_state_from_host(
    ka: np.ndarray,  # [N] uint64 host key words (conntrack.py layout)
    kb: np.ndarray,
    kc: np.ndarray,
    ttl: np.ndarray,  # [N] remaining seconds
    capacity_bits: int,
    now_s: int,
    limit: int = 1 << 16,
) -> DeviceCTState:
    """Build a DeviceCTState pre-populated from host CT entries — the
    re-upload half of the quarantine rescue: when the failsafe ladder
    re-promotes back onto the fused device-CT path, the fresh table
    starts with the flows the rescue preserved instead of forgetting
    them a second time.

    Placement runs host-side with the numpy murmur twin (bit-identical
    hashing), so every seeded entry sits on its device probe chain.
    Entries past ``limit`` or losing a full neighborhood are dropped —
    they re-verdict and re-insert on their next batch, the normal
    device-CT degradation."""
    c = 1 << capacity_bits
    mask = np.uint32(c - 1)
    n = min(len(ka), limit)
    ka = np.asarray(ka, np.uint64)[:n]
    kb = np.asarray(kb, np.uint64)[:n]
    kc = np.asarray(kc, np.uint64)[:n]
    exp_in = now_s + np.maximum(
        np.asarray(ttl, np.float64)[:n], 1.0
    ).astype(np.int64)
    ka_hi, ka_lo = split_u64(ka)
    kb_hi, kb_lo = split_u64(kb)
    kc_hi, kc_lo = split_u64(kc)

    t = {f: np.zeros(c, np.uint32) for f in
         ("ka_hi", "ka_lo", "kb_hi", "kb_lo", "kc_hi", "kc_lo")}
    exp = np.zeros(c, np.int32)
    h = _hash_tuple_np(ka_hi, ka_lo, kb_hi, kb_lo, kc_hi, kc_lo)
    placed = np.zeros(n, bool)
    for p in range(CT_PROBES):
        with np.errstate(over="ignore"):
            cand = ((h + np.uint32(p)) & mask).astype(np.int64)
        want = (~placed) & (exp[cand] <= now_s)
        if not want.any():
            continue
        idx = np.nonzero(want)[0]
        _, first = np.unique(cand[idx], return_index=True)
        win = idx[first]
        s = cand[win]
        t["ka_hi"][s], t["ka_lo"][s] = ka_hi[win], ka_lo[win]
        t["kb_hi"][s], t["kb_lo"][s] = kb_hi[win], kb_lo[win]
        t["kc_hi"][s], t["kc_lo"][s] = kc_hi[win], kc_lo[win]
        exp[s] = exp_in[win].astype(np.int32)
        placed[win] = True
        if placed.all():
            break
    return DeviceCTState(
        ka_hi=jnp.asarray(t["ka_hi"]),
        ka_lo=jnp.asarray(t["ka_lo"]),
        kb_hi=jnp.asarray(t["kb_hi"]),
        kb_lo=jnp.asarray(t["kb_lo"]),
        kc_hi=jnp.asarray(t["kc_hi"]),
        kc_lo=jnp.asarray(t["kc_lo"]),
        exp=jnp.asarray(exp),
    )
