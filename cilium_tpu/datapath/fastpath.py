"""Per-flow verdict fast path — the enforcement front-end's hot loop.

The reference enforces per-packet verdicts with ≤3 hash lookups against
the per-endpoint BPF policymap the control plane wrote
(bpf/lib/policy.h:46-110: exact {id,dport,proto} → L3-only {id} →
L4-only). Here the TPU-materialized policymap snapshots
(ops/materialize.py) play the role of the pinned BPF map, and this
cache answers single-flow queries with the same probe order — two dict
probes, no device round trip. Batch/cold traffic takes the device
pipeline (datapath/pipeline.py) instead; this path is what keeps p99
per-flow latency inside the BASELINE.md budget (<50µs) the way
established-flow conntrack hits keep the reference's datapath cheap.

Snapshot dicts are shared by reference with the pipeline's materialized
state, so incremental row patches (identity churn) are visible here
without rebuilding the cache.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from ..ops.materialize import EndpointPolicySnapshot, PolicyKey, TRAFFIC_INGRESS

ALLOW = 1
DENY = 2


class VerdictFastpath:
    """Wraps realized per-endpoint policymaps for O(1) per-flow checks."""

    def __init__(
        self,
        snapshots: Sequence[EndpointPolicySnapshot],
        direction: int = TRAFFIC_INGRESS,
    ) -> None:
        self._entries: List[dict] = [s.entries for s in snapshots]
        self._direction = direction

    def lookup(
        self, ep_idx: int, identity: int, dport: int, proto: int
    ) -> Tuple[int, bool]:
        """→ (decision, redirect). Probe order mirrors
        __policy_can_access (bpf/lib/policy.h:46): exact key first so a
        redirecting L4 filter wins over a plain L3 allow."""
        entries = self._entries[ep_idx]
        e = entries.get(PolicyKey(identity, dport, proto, self._direction))
        if e is not None:
            return ALLOW, bool(e)
        if PolicyKey(identity, 0, 0, self._direction) in entries:
            return ALLOW, False
        return DENY, False
