"""Overlapped L7 batch classification (policyd-l7batch).

The L7 analogue of DatapathPipeline's submit()/PendingBatch shape: one
``submit()`` packs a request batch's field strings (host work), pushes
the fused DFA walk onto the device asynchronously, and returns a
handle; ``result()`` completes in FIFO order. Host prep of batch N+1
therefore overlaps device execution of batch N — the same overlap
discipline PR 3 gave the verdict path.

Packing follows the PR 5 ladder rules: the walk length is bucketed to
a FIXED rung set (ops.dfa.L7_LEN_LADDER) and the lane (row) dimension
to L7_LANE_RUNGS, so jit keys only on rung shapes — a live batch never
compiles a new program once the rungs are warm. Pad rows are marked
length -1 (the kernels mask them to an empty accept mask) and counted
in ``l7_pad_lanes_total``.

The module also owns the ``L7DeviceBatch`` runtime gate: policies read
``device_batch_enabled()`` per batch and fall back to their exact
pre-option code path when it is off (the FlowAttribution /
DispatchAutoTune pinning contract).
"""
# policyd: hot

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import metrics
from ..observe.tracer import NOOP_BATCH, Tracer
from ..ops.dfa import (
    DeviceDFATable,
    L7_LEN_LADDER,
    dfa_match_batch_fused,
    dfa_match_batch_pair,
    len_rung,
    strings_to_batch_u8,
)

# Lane (row-count) rungs: a submit of F fields × B requests dispatches
# ceil(F*B / top) full-rung chunks plus one tail rung. Fixed set —
# same contract as the verdict path's BUCKET_LADDER.
L7_LANE_RUNGS: Tuple[int, ...] = (512, 4096, 16384)


def lane_rung(needed: int) -> int:
    for rung in L7_LANE_RUNGS:
        if needed <= rung:
            return rung
    return L7_LANE_RUNGS[-1]


class PendingL7Batch:
    """Handle for one submitted L7 classification batch. ``result()``
    blocks until this batch (and every earlier one — FIFO) is pulled,
    and returns per-field ``[B] uint64`` accept masks."""

    __slots__ = ("_pipe", "_done", "_value", "_exc")

    def __init__(self, pipe: "L7Pipeline") -> None:
        self._pipe = pipe
        self._done = False
        self._value: Optional[List[np.ndarray]] = None
        self._exc: Optional[BaseException] = None

    def result(self) -> List[np.ndarray]:
        if not self._done:
            self._pipe._complete_until(self)
        if self._exc is not None:
            raise self._exc
        assert self._value is not None
        return self._value


class _InFlight:
    __slots__ = ("pending", "chunks", "n_req", "n_fields", "bt", "t0", "ps")

    def __init__(self, pending, chunks, n_req, n_fields, bt, t0,
                 ps=None) -> None:
        self.pending = pending
        # [(lo_dev, hi_dev, rows_live)] — device handles; pulled at
        # completion time, not submit time (that's the overlap)
        self.chunks = chunks
        self.n_req = n_req
        self.n_fields = n_fields
        self.bt = bt
        self.t0 = t0
        # policyd-prof: live _DispatchSample on the profiler's Nth
        # batch (None otherwise); _finish times the mask pull into it
        self.ps = ps


class L7Pipeline:
    """Bounded in-flight queue of fused-DFA dispatches.

    Depth semantics mirror DatapathPipeline: ``submit()`` retires the
    oldest batch first once ``depth`` batches are on device, so at
    most ``depth`` device programs are outstanding while the host
    packs the next batch.
    """

    def __init__(self, depth: int = 2, tracer: Optional[Tracer] = None) -> None:
        self.depth = max(1, int(depth))
        self.tracer = tracer
        # policyd-prof: None (off) keeps submit()/_finish() at one
        # attribute read per batch; the daemon installs the shared
        # DeviceProfiler through set_profiler() below
        self.profiler = None
        self._lock = threading.Lock()
        self._inflight: "deque[_InFlight]" = deque()
        # jit program identity for the walk is (kernel, Q, lanes, rung):
        # tracked so first-use compiles are visible in /metrics and the
        # prewarm pass can claim its rungs
        self._seen_shapes: set = set()

    # -- shape accounting ------------------------------------------------
    def _note_shape(self, kind: str, n_states: int, lanes: int, rung: int,
                    warm: bool = False) -> None:
        key = (kind, n_states, lanes, rung)
        with self._lock:
            fresh = key not in self._seen_shapes
            if fresh:
                self._seen_shapes.add(key)
        if fresh:
            result = "warm" if warm else "miss"
        else:
            result = "hit"
        metrics.jit_shape_buckets_total.inc({"site": "l7", "result": result})

    def prewarm(self, table: DeviceDFATable, caps: Sequence[int]) -> int:
        """Compile the walk for every (lane, length) rung this table
        can be dispatched at — at policy compile() time, so no request
        batch ever eats a first-use jit compile mid-request. → number
        of programs warmed (counted under
        ``jit_shape_buckets_total{site="l7",result="warm"}``)."""
        cap_max = max(caps)
        rungs = [r for r in L7_LEN_LADDER if r <= cap_max]
        if cap_max not in rungs:
            rungs.append(cap_max)
        warmed = 0
        for rung in rungs:
            for lanes in L7_LANE_RUNGS:
                key_kind = "pair" if table.has_pair else "fused"
                key = (key_kind, table.n_states, lanes, rung)
                with self._lock:
                    if key in self._seen_shapes:
                        continue
                sb = np.zeros((lanes, rung), np.uint8)
                lens = np.full(lanes, -1, np.int32)
                starts = np.zeros(lanes, np.int32)
                lo, hi = self._walk(table, sb, lens, starts, rung)
                lo.block_until_ready()
                self._note_shape(key_kind, table.n_states, lanes, rung, warm=True)
                warmed += 1
                del hi
        return warmed

    # -- dispatch --------------------------------------------------------
    def _walk(self, table: DeviceDFATable, sb: np.ndarray, lens: np.ndarray,
              starts: np.ndarray, rung: int):
        if table.has_pair:
            return dfa_match_batch_pair(
                table.pair, table.accept_lo, table.accept_hi,
                jnp.asarray(starts), jnp.asarray(sb), jnp.asarray(lens), rung,
            )
        return dfa_match_batch_fused(
            table.trans, table.accept_lo, table.accept_hi,
            jnp.asarray(starts), jnp.asarray(sb), jnp.asarray(lens), rung,
        )

    def submit(
        self,
        table: DeviceDFATable,
        fields: Sequence[Tuple[Sequence[bytes], int]],
        parser: str = "http",
    ) -> PendingL7Batch:
        """Classify one request batch against ``table``.

        ``fields`` pairs each fused field slot (in table order) with
        (encoded values, field length cap). Values longer than their
        field cap come back with mask 0 — the CALLER host-walks those
        rows, exactly as the unfused path does. → PendingL7Batch whose
        ``result()`` is per-field ``[B] uint64`` masks."""
        if len(fields) != table.n_fields:
            raise ValueError(
                f"table fuses {table.n_fields} fields, got {len(fields)}"
            )
        t0 = time.perf_counter()
        tr = self.tracer
        bt = tr.begin("l7", len(fields[0][0])) if (tr is not None and tr.active) else NOOP_BATCH

        with bt.phase("prepare"):
            n_req = len(fields[0][0])
            caps = [cap for _, cap in fields]
            flat: List[bytes] = []
            for values, _cap in fields:
                if len(values) != n_req:
                    raise ValueError("field batches must be the same length")
                flat.extend(values)
            # one rung covers every field; per-field caps re-mark
            # overlong rows below
            needed = 1
            for s in flat:
                if len(s) > needed:
                    needed = len(s)
            cap_max = max(caps)
            rung = len_rung(min(needed, cap_max), cap_max)
            sb, lens = strings_to_batch_u8(flat, rung)
            for f, cap in enumerate(caps):
                if cap < rung:
                    seg = lens[f * n_req : (f + 1) * n_req]
                    seg[seg > cap] = -1
            starts = np.repeat(table.starts_host, n_req)
            live = int(lens.size)
            live_bytes = int(np.maximum(lens, 0).sum())

        # policyd-prof: one attribute read while off; the sampled
        # batch pays the explicit-upload / ready sandwiches below
        prof = self.profiler
        ps = prof.begin_dispatch("l7", n_req) if prof is not None else None

        with bt.phase("dispatch"):
            chunks = []
            top = L7_LANE_RUNGS[-1]
            pad_rows = 0
            off = 0
            n_chunks = 0
            _pl_t0 = time.perf_counter() if ps is not None else 0.0
            while off < live:
                take = min(top, live - off)
                lanes = lane_rung(take)
                if take < lanes:
                    csb = np.zeros((lanes, rung), np.uint8)
                    csb[:take] = sb[off : off + take]
                    clens = np.full(lanes, -1, np.int32)
                    clens[:take] = lens[off : off + take]
                    cstarts = np.zeros(lanes, np.int32)
                    cstarts[:take] = starts[off : off + take]
                    pad_rows += lanes - take
                else:
                    csb = sb[off : off + take]
                    clens = lens[off : off + take]
                    cstarts = starts[off : off + take]
                if ps is not None:
                    # sampled h2d edge: upload explicitly and wait so
                    # the walk below starts from device-resident inputs
                    # (jnp.asarray in _walk passes jax arrays through —
                    # same avals, same compiled program). The per-chunk
                    # sync IS the measurement, 1-in-N batches only:
                    _t0 = time.perf_counter()
                    csb, clens, cstarts = jax.block_until_ready(  # policyd-lint: disable=TPU002
                        jax.device_put((csb, clens, cstarts))
                    )
                    ps.add_h2d(time.perf_counter() - _t0)
                kind = "pair" if table.has_pair else "fused"
                self._note_shape(kind, table.n_states, lanes, rung)
                lo, hi = self._walk(table, csb, clens, cstarts, rung)
                chunks.append((lo, hi, take))
                off += take
                n_chunks += 1
            if ps is not None:
                # sampled compute edge: h2d already completed above, so
                # the rest of the chunk loop (lane padding, per-rung jit
                # dispatch) plus the residual wait here is the fused DFA
                # walk side of the split
                jax.block_until_ready([(c[0], c[1]) for c in chunks])
                ps.add_compute(
                    time.perf_counter() - _pl_t0 - ps.h2d_s
                )
                ps.mark(
                    rungs=[lane_rung(min(top, c[2])) for c in chunks],
                    len_rung=int(rung),
                    lanes=int(live),
                    pad_lanes=int(pad_rows),
                    chunks=n_chunks,
                    parser=parser,
                )
            metrics.l7_pad_lanes_total.inc({"kind": "lane"}, pad_rows)
            metrics.l7_pad_lanes_total.inc({"kind": "lane_live"}, live)
            metrics.l7_pad_lanes_total.inc(
                {"kind": "len_bytes"}, live * rung - live_bytes
            )
            metrics.l7_pad_lanes_total.inc({"kind": "len_bytes_live"}, live_bytes)
            metrics.l7_batches_total.inc({"parser": parser})

        pending = PendingL7Batch(self)
        entry = _InFlight(pending, chunks, n_req, table.n_fields, bt, t0, ps)
        if bt is not NOOP_BATCH:
            tr.detach(bt)
        overflow: List[_InFlight] = []
        with self._lock:
            self._inflight.append(entry)
            while len(self._inflight) > self.depth:
                overflow.append(self._inflight.popleft())
        for e in overflow:
            self._finish(e)
        return pending

    # -- completion ------------------------------------------------------
    def _complete_until(self, pending: PendingL7Batch) -> None:
        while not pending._done:
            with self._lock:
                if not self._inflight:
                    break
                entry = self._inflight.popleft()
            self._finish(entry)

    def _finish(self, entry: _InFlight) -> None:
        bt = entry.bt
        ps = entry.ps
        _pt0 = time.perf_counter() if ps is not None else 0.0
        try:
            with bt.phase("host_sync"):
                parts = []
                for ch in entry.chunks:
                    lo64 = np.asarray(ch[0]).astype(np.uint64)
                    hi64 = np.asarray(ch[1]).astype(np.uint64)
                    parts.append((lo64 | (hi64 << np.uint64(32)))[: ch[2]])
                if not parts:
                    masks = np.zeros(0, np.uint64)
                elif len(parts) == 1:
                    masks = parts[0]
                else:
                    masks = np.concatenate(parts)
            b = entry.n_req
            entry.pending._value = [
                masks[f * b : (f + 1) * b] for f in range(entry.n_fields)
            ]
        # not swallowed: the error is stored and re-raised by the
        # caller's result() — completion must still mark the batch done
        # or FIFO draining would deadlock behind it
        except Exception as exc:  # policyd-lint: disable=ROBUST001
            entry.pending._exc = exc
        if ps is not None:
            ps.add_d2h(time.perf_counter() - _pt0)
            prof = self.profiler
            if prof is not None:
                prof.complete(ps)
            entry.ps = None
        entry.pending._done = True
        metrics.l7_batch_seconds.observe(time.perf_counter() - entry.t0)
        bt.end()

    def drain(self) -> None:
        while True:
            with self._lock:
                if not self._inflight:
                    return
                entry = self._inflight.popleft()
            self._finish(entry)


# ---------------------------------------------------------------------------
# L7DeviceBatch runtime gate
# ---------------------------------------------------------------------------

_rt_lock = threading.Lock()
_enabled = False
_pipeline: Optional[L7Pipeline] = None
# shared DeviceProfiler (policyd-prof): installed by the daemon while
# DeviceProfiling is on; carried onto any pipeline set_device_batch
# creates later so toggle order doesn't matter
_profiler = None


def set_device_batch(on: bool, tracer: Optional[Tracer] = None,
                     depth: int = 2) -> None:
    """Flip the L7DeviceBatch runtime option. Turning it OFF drains
    outstanding batches and drops the shared pipeline — the next check
    runs the pre-option code path with the pre-option programs."""
    global _enabled, _pipeline
    with _rt_lock:
        if on:
            if _pipeline is None or (tracer is not None and _pipeline.tracer is not tracer):
                _pipeline = L7Pipeline(depth=depth, tracer=tracer)
            _pipeline.profiler = _profiler
            _enabled = True
            return
        _enabled = False
        pipe, _pipeline = _pipeline, None
    if pipe is not None:
        pipe.drain()


def set_profiler(prof) -> None:
    """Install (or clear, with None) the shared DeviceProfiler on the
    L7 pipeline — the DeviceProfiling half of the L7DeviceBatch gate."""
    global _profiler
    with _rt_lock:
        _profiler = prof
        if _pipeline is not None:
            _pipeline.profiler = prof


def device_batch_enabled() -> bool:
    # one unlocked read on the request path (same cost model as
    # tracer.active)
    return _enabled


def shared_pipeline() -> Optional[L7Pipeline]:
    with _rt_lock:
        return _pipeline


def _reset_for_tests() -> None:
    set_device_batch(False)
    set_profiler(None)
