"""Standalone LB datapath mode.

Reference: /root/reference/bpf/bpf_lb.c — a datapath program that ONLY
load-balances (VIP→backend translate + forward, DSR-style), attached
on nodes acting as dedicated load balancers with no policy
enforcement. Same stance here: a pipeline that owns service tables and
a conntrack for flow affinity + revNAT, with no policy engine in the
loop — batches translate on device (lb/device.py lb_translate) and
non-service traffic passes through untouched (bpf_lb.c forwards
unmatched traffic).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..lb.device import flow_hash32, lb_translate
from ..ops.lpm import ipv4_to_bytes
from .conntrack import CT_REPLY, FlowConntrack, pack_keys

FORWARD = 1
DROP_NO_SERVICE = 4


class LBOnlyDatapath:
    """VIP→backend translation with per-flow affinity, no policy."""

    def __init__(self, manager, conntrack: Optional[FlowConntrack] = None):
        self.lb = manager
        self.conntrack = conntrack
        self._lock = threading.Lock()
        self._tables: Dict[int, object] = {}
        self._version = -1

    def _refresh(self) -> None:
        with self._lock:
            if self.lb.version != self._version:
                self._tables = self.lb.build_device()
                self._version = self.lb.version
                if self.conntrack is not None:
                    # translated CT keys change with the tables
                    self.conntrack.flush()

    def process(
        self,
        dst_ips: np.ndarray,  # [B] uint32 destination addresses
        dports: np.ndarray,
        protos: np.ndarray,
        sports: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """→ (new_dst [B] uint32, new_port [B] int32, verdict [B] int8,
        revnat [B] uint16). Frontend hit with zero backends drops
        (lb4_local slave-lookup failure → DROP_NO_SERVICE); unmatched
        traffic forwards untranslated."""
        self._refresh()
        dst = np.asarray(dst_ips, np.uint32)
        dports = np.asarray(dports, np.int32)
        protos = np.asarray(protos, np.int32)
        b = dst.shape[0]
        t = self._tables.get(4)
        if t is None:
            return dst, dports, np.full(b, FORWARD, np.int8), np.zeros(b, np.uint16)
        peer_bytes = ipv4_to_bytes(dst)
        fh = flow_hash32(
            peer_bytes, sports, dports, protos, np.zeros(b, np.int64)
        )
        nb, npo, rv, ok, nobk = lb_translate(
            t, jnp.asarray(peer_bytes), jnp.asarray(dports),
            jnp.asarray(protos), jnp.asarray(fh),
        )
        nb = np.asarray(nb).astype(np.uint32)
        new_dst = (
            (nb[:, 0] << 24) | (nb[:, 1] << 16) | (nb[:, 2] << 8) | nb[:, 3]
        )
        new_port = np.asarray(npo, np.int32)
        revnat = np.asarray(rv).astype(np.uint16)
        nobk = np.asarray(nobk)
        verdict = np.where(nobk, np.int8(DROP_NO_SERVICE), np.int8(FORWARD))
        revnat = np.where(np.asarray(ok), revnat, 0).astype(np.uint16)

        if self.conntrack is not None and sports is not None:
            # record forward entries for SERVICE-TRANSLATED flows only
            # (affinity + revNAT restore). Pass-through traffic is not
            # tracked — on a dedicated LB node it dwarfs the service
            # flows and would evict/fill the table, starving revNAT
            # entries (bpf_lb.c tracks only service flows too).
            translated = np.asarray(ok)
            if translated.any():
                sp = np.asarray(sports, np.int64)
                ka, kb, kc = pack_keys(
                    np.zeros(b, np.uint64), new_dst.astype(np.uint64),
                    np.zeros(b, np.uint64), sp.astype(np.uint64),
                    new_port.astype(np.uint64), protos.astype(np.uint64),
                    np.ones(b, np.uint64),
                )
                self.conntrack.create_batch(
                    ka[translated], kb[translated], kc[translated],
                    revnat=revnat[translated],
                )
        return new_dst, new_port, verdict, revnat

    def rev_nat(
        self,
        src_ips: np.ndarray,  # [B] uint32 reply SOURCE (backend) addrs
        sports: np.ndarray,  # [B] backend ports
        dports: np.ndarray,  # [B] client ports
        protos: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Reply-direction revNAT: restore the VIP on reply sources
        whose CT entry carries a revNAT id (lb4_rev_nat) →
        (new_src [B] uint32, new_sport [B] int32)."""
        src = np.asarray(src_ips, np.uint32)
        sports = np.asarray(sports, np.int64)
        dports = np.asarray(dports, np.int64)
        protos = np.asarray(protos, np.int64)
        b = src.shape[0]
        new_src = src.copy()
        new_sport = sports.astype(np.int32).copy()
        if self.conntrack is None:
            return new_src, new_sport
        # the reply packet's own tuple: sport = backend port, dport =
        # client port, ingress; lookup_batch's flip matches it against
        # the stored forward (egress) entry
        ka, kb, kc = pack_keys(
            np.zeros(b, np.uint64), src.astype(np.uint64),
            np.zeros(b, np.uint64), sports.astype(np.uint64),
            dports.astype(np.uint64), protos.astype(np.uint64),
            np.zeros(b, np.uint64),
        )
        state, _slot, rev = self.conntrack.lookup_batch(
            ka, kb, kc, refresh=False, want_revnat=True
        )
        rev[state != CT_REPLY] = 0
        for i in np.nonzero(rev)[0]:
            fe = self.lb.rev_nat(int(rev[i]))
            if fe is not None and ":" not in fe.ip:
                parts = [int(x) for x in fe.ip.split(".")]
                new_src[i] = (
                    (parts[0] << 24) | (parts[1] << 16)
                    | (parts[2] << 8) | parts[3]
                )
                new_sport[i] = fe.port
        return new_src, new_sport
