"""Batched flow pipeline: prefilter → identity → policy verdict.

Mirrors the per-packet path of the reference, hoisted to batches:

    bpf_xdp.c check_filters (:158)    → deny-trie LPM on src address
    bpf_netdev.c secctx from ipcache  → identity-trie LPM (world if miss)
    bpf_lxc.c tail_ipv4_policy (:931) → policymap lookup (ops/lookup.py)

plus per-endpoint forwarded/dropped counters (the metricsmap role,
pkg/maps/metricsmap). One jitted dispatch per batch; all state tensors
are rebuilt by the host ``DatapathPipeline`` when any source version
moves (ipcache, prefilter, policy revision, identity registry).
"""

from __future__ import annotations

import functools
import threading
from typing import List, Optional, Sequence, Tuple

import chex
import jax
import jax.numpy as jnp
import numpy as np

from ..engine import PolicyEngine
from ..identity.model import ID_WORLD
from ..ipcache.ipcache import IPCache
from ..ipcache.prefilter import PreFilter
from ..ops.lookup import PolicymapTables, lookup_batch
from ..ops.lpm import lpm_lookup, ipv4_to_bytes
from ..ops.materialize import (
    EndpointPolicySnapshot,
    MaterializedState,
    materialize_endpoints_state,
    patch_identity_rows,
)

FORWARD = 1
DROP_POLICY = 2
DROP_PREFILTER = 3


@chex.dataclass(frozen=True)
class DatapathTables:
    pf_child4: jnp.ndarray
    pf_info4: jnp.ndarray
    ip_child4: jnp.ndarray
    ip_info4: jnp.ndarray
    world_row: jnp.ndarray  # [] int32
    policymap: PolicymapTables


@functools.partial(jax.jit, static_argnames=("ep_count", "block"))
def process_ipv4(
    t: DatapathTables,
    src_bytes: jnp.ndarray,  # [B, 4] int32
    ep_idx: jnp.ndarray,  # [B] int32
    dport: jnp.ndarray,  # [B] int32
    proto: jnp.ndarray,  # [B] int32
    ep_count: int = 1,
    block: int = 65536,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """→ (verdict[B] int8, redirect[B] bool, counters [EP, 3] int32).

    counters[e] = (forwarded, dropped_policy, dropped_prefilter) — the
    metricsmap accumulation, computed with a one-hot matmul so the
    scatter stays on the MXU.
    """
    denied_pf = lpm_lookup(t.pf_child4, t.pf_info4, src_bytes, levels=4) > 0
    hit = lpm_lookup(t.ip_child4, t.ip_info4, src_bytes, levels=4)
    src_row = jnp.where(hit > 0, hit - 1, t.world_row)
    dec, red = lookup_batch(t.policymap, ep_idx, src_row, dport, proto, block=block)
    verdict = jnp.where(denied_pf, jnp.int8(DROP_PREFILTER), dec)
    redirect = red & ~denied_pf

    # counters via one-hot matmul [B, EP]ᵀ @ [B, 3]
    ep_oh = (ep_idx[:, None] == jnp.arange(ep_count)[None, :]).astype(jnp.int8)
    cls = jnp.stack(
        [verdict == FORWARD, verdict == DROP_POLICY, verdict == DROP_PREFILTER],
        axis=1,
    ).astype(jnp.int8)
    counters = jax.lax.dot_general(
        ep_oh, cls, (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    return verdict, redirect, counters


class DatapathPipeline:
    """Host orchestrator: owns the device snapshot of prefilter +
    ipcache + materialized policymaps for a set of local endpoints, and
    re-materializes when any input version moves (the regeneration
    trigger role of pkg/endpoint/policy.go:812)."""

    def __init__(
        self,
        engine: PolicyEngine,
        ipcache: IPCache,
        prefilter: Optional[PreFilter] = None,
    ) -> None:
        self.engine = engine
        self.ipcache = ipcache
        self.prefilter = prefilter or PreFilter()
        self._lock = threading.Lock()
        self._endpoints: List[int] = []  # identity ids of local endpoints
        self._endpoint_ids: List[int] = []  # endpoint ids (same order)
        self._tables: Optional[DatapathTables] = None
        self._mat: Optional[MaterializedState] = None
        self._mat_sig: Tuple = ()  # endpoint list the policymap was built for
        self._last_delta_seq = 0  # engine delta cursor
        self._trie_versions: Tuple = ()  # (ipcache.version, prefilter.revision)
        self._tries: Optional[Tuple] = None  # (pf_child4, pf_info4, ip_child4, ip_info4, world_row)
        self.counters = np.zeros((0, 3), np.int64)

    def set_endpoints(self, endpoints: Sequence) -> None:
        """Accepts identity ids (endpoint id == identity id) or
        (endpoint_id, identity_id) pairs; order defines the datapath
        endpoint index."""
        with self._lock:
            pairs = [
                e if isinstance(e, tuple) else (int(e), int(e)) for e in endpoints
            ]
            self._endpoint_ids = [p[0] for p in pairs]
            self._endpoints = [p[1] for p in pairs]
            self._mat = None  # column layout changes with the endpoint set

    def endpoint_index(self, endpoint_id: int) -> Optional[int]:
        try:
            return self._endpoint_ids.index(endpoint_id)
        except ValueError:
            return None

    # ------------------------------------------------------------------
    def rebuild(self, force: bool = False) -> DatapathTables:
        """Bring device state up to date. Incremental where possible:

        - identity churn ("rows" engine deltas) → policymap row patches
          (n_seg × k verdicts instead of the full sweep)
        - rule appends / full recompiles → warm re-materialization
        - ipcache/prefilter moves → trie rebuild only (policymap kept)
        """
        with self._lock:
            # Capture versions BEFORE reading the sources: a concurrent
            # mutation mid-build then triggers one extra rebuild rather
            # than being silently marked materialized.
            trie_versions = (self.ipcache.version, self.prefilter.revision)
            delta_target = self.engine.delta_seq
            compiled, device = self.engine.snapshot()
            delta_target = max(delta_target, self.engine.delta_seq)
            ep_sig = tuple(self._endpoints)

            mat_fresh = False
            saw_row_event = False
            if force or self._mat is None or self._mat_sig != ep_sig:
                self._mat = materialize_endpoints_state(
                    compiled, device, self._endpoints
                )
                mat_fresh = True
            else:
                deltas = self.engine.deltas_since(self._last_delta_seq)
                if deltas is None or any(k != "rows" for _, k, _ in deltas):
                    # rule appends or full recompiles invalidate column
                    # layout / verdict basis → re-materialize (warm jit,
                    # shape-bucketed, so this is the fast full path)
                    self._mat = materialize_endpoints_state(
                        compiled, device, self._endpoints
                    )
                    mat_fresh = True
                else:
                    for _seq, _kind, events in deltas:
                        patch_identity_rows(self._mat, compiled, device, events)
                        # Any row event (add OR release) can change what an
                        # ipcache entry resolves to — e.g. a released id
                        # being re-allocated onto a tombstoned row, or an
                        # add resolving a previously-unmapped entry — so
                        # the tries must follow every row move.
                        saw_row_event |= bool(events)
            self._mat_sig = ep_sig
            self._last_delta_seq = delta_target

            # Tries: rebuilt when their sources move, when the row basis
            # was re-established, or when any row event could have
            # changed an ipcache row mapping (identity release).
            if (
                force
                or self._tries is None
                or trie_versions != self._trie_versions
                or mat_fresh
                or saw_row_event  # any row move can re-point trie targets
                or self._tables is None
            ):
                pf_child4, pf_info4 = self.prefilter.build_device()[0]
                ip4, _ip6 = self.ipcache.build_device(
                    lambda ident: compiled.id_to_row.get(ident)
                )
                ip_child4, ip_info4 = ip4
                world_row = compiled.id_to_row.get(ID_WORLD)
                if world_row is None:
                    raise RuntimeError("reserved:world identity has no device row")
                self._tries = (
                    jnp.asarray(pf_child4),
                    jnp.asarray(pf_info4),
                    jnp.asarray(ip_child4),
                    jnp.asarray(ip_info4),
                    jnp.asarray(np.int32(world_row)),
                )
                self._trie_versions = trie_versions

            assert self._tries is not None and self._mat is not None
            self._tables = DatapathTables(
                pf_child4=self._tries[0],
                pf_info4=self._tries[1],
                ip_child4=self._tries[2],
                ip_info4=self._tries[3],
                world_row=self._tries[4],
                policymap=self._mat.tables,
            )
            if self.counters.shape[0] != len(self._endpoints):
                self.counters = np.zeros((len(self._endpoints), 3), np.int64)
            return self._tables

    def snapshots(self) -> List[EndpointPolicySnapshot]:
        self.rebuild()
        assert self._mat is not None
        return self._mat.snapshots

    def fastpath(self):
        """Per-flow verdict cache over the current realized policymaps
        (datapath/fastpath.py). Row patches from identity churn are
        visible through the shared snapshot dicts; re-fetch after rule
        changes (re-materialization swaps the snapshot objects)."""
        from .fastpath import VerdictFastpath

        self.rebuild()
        assert self._mat is not None
        return VerdictFastpath(self._mat.snapshots)

    # ------------------------------------------------------------------
    def process(
        self,
        src_ips: np.ndarray,  # [B] uint32 IPv4 host-order
        ep_idx: np.ndarray,  # [B] int32 local endpoint index
        dports: np.ndarray,
        protos: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """→ (verdicts [B] int8, redirect [B] bool); accumulates the
        per-endpoint counters."""
        t = self.rebuild()
        v, red, counters = process_ipv4(
            t,
            jnp.asarray(ipv4_to_bytes(np.asarray(src_ips))),
            jnp.asarray(np.asarray(ep_idx, np.int32)),
            jnp.asarray(np.asarray(dports, np.int32)),
            jnp.asarray(np.asarray(protos, np.int32)),
            ep_count=max(1, len(self._endpoints)),
        )
        c = np.asarray(counters)
        with self._lock:
            if self.counters.shape == c.shape:
                self.counters += c
        return np.asarray(v), np.asarray(red)
