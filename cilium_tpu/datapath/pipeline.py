"""Batched flow pipeline: conntrack → prefilter → identity → verdict.

Mirrors the per-packet path of the reference, hoisted to batches:

    bpf_lxc.c lb4_local (:444-455)    → device VIP→backend translate
                                        (lb/device.py, egress only)
    bpf/lib/conntrack.h ct_lookup     → vectorized host CT pre-pass
                                        (established/reply bypass)
    bpf_xdp.c check_filters (:158)    → deny-trie LPM on peer address
    bpf_netdev.c secctx from ipcache  → identity-trie LPM (world if miss)
    bpf_lxc.c tail_ipv4_policy (:931) → ingress policymap lookup
    bpf_lxc.c policy_can_egress4(:505)→ egress policymap lookup

plus per-endpoint forwarded/dropped counters (the metricsmap role,
pkg/maps/metricsmap). Both traffic directions are materialized
(ingress AND egress policymaps — bpf_lxc.c enforces both), IPv4 and
IPv6 tries are live (4- vs 16-level LPM walks), and the conntrack
pre-pass means established-heavy batches dispatch only their CT-miss
tail to the device — the batch-level analog of the kernel's
one-hash-probe fast path for established flows.
"""

from __future__ import annotations

import functools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import chex
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .admission import (
    AdmissionController,
    N_SHED_CLASSES,
    REASON_SHED_DEADLINE,
    REASON_SHED_PREFILTER,
    Watchdog,
    compile_shed_table,
    flow_class,
)
from .placement import EMPTY_PLAN, MeshPlan, PlacementConfig, resolve_plan
from .. import faults as _faults
from .. import metrics as _metrics
from ..engine import PolicyEngine
from ..identity.model import ID_WORLD
from ..observe.flows import SAMPLE_CAP as _FLOW_SAMPLE_CAP, FlowRecord, FlowRing
from ..observe.tracer import NOOP_BATCH as _NOOP_BATCH, Tracer
from ..ipcache.ipcache import IPCache
from ..ipcache.prefilter import PreFilter
from ..ops.lookup import (
    PolicymapTables,
    lookup_batch,
    replicate_tables,
    shard_tables_ident,
)
from ..ops.lpm import (
    DENY_BIT,
    MERGED_VALUE_MASK,
    PatchableElidedTrie,
    build_trie_elided,
    build_wide_trie,
    ipv4_to_bytes,
    lpm_lookup,
    lpm_lookup_wide,
    make_patchable_wide,
    merge_flat_tries,
    merge_trie_entries,
    place_table,
)
from ..compiler.selectors import selector_word_window
from ..ops.materialize import (
    EndpointPolicySnapshot,
    MaterializedState,
    PlacedTables,
    TRAFFIC_EGRESS,
    TRAFFIC_INGRESS,
    materialize_endpoints_state,
    patch_endpoints_state,
    patch_identity_rows,
    patch_selector_cols,
    patch_selector_rows,
)
from ..lb.device import flow_hash32, lb_translate
from ..utils.backoff import Backoff
from .conntrack import CT_NEW, FlowConntrack, pack_keys
from .tuner import DepthTuner

FORWARD = 1
DROP_POLICY = 2
DROP_PREFILTER = 3
DROP_NO_SERVICE = 4  # frontend matched but zero backends (lb4_local)
# policyd-failsafe: the pipeline could not verdict this flow (device
# fault exhausted its bounded retries) and FailOpen is off — the
# fail-closed deny. Maps to monitor drop reason 155 (STABLE taxonomy).
DROP_DEGRADED = 5

# verdict code → metrics outcome label (metricsmap REASON strings)
_OUTCOME_NAMES = (
    (FORWARD, "forwarded"),
    (DROP_POLICY, "dropped_policy"),
    (DROP_PREFILTER, "dropped_prefilter"),
    (DROP_NO_SERVICE, "dropped_no_service"),
    (DROP_DEGRADED, "dropped_degraded"),
)

# degradation-ladder levels (policyd-failsafe): index = ladder level.
# Level 0 is the full device complement (sharded across the verdict
# mesh when VerdictSharding is on), 1 re-forms the mesh down to a
# single healthy device, 2 verdicts on host numpy.
_MODE_NAMES = ("sharded", "single-device", "host")


@chex.dataclass(frozen=True)
class DatapathTables:
    """Device state for one address family + one traffic direction.
    Trie arrays are shared between the two directions' instances.
    ``*_common`` carry each trie's elided shared prefix bytes ([K]
    int32, [0] = no elision) — compared vectorized, not walked.
    ``merged_*`` carry the fused deny+identity trie (one walk, both
    answers — ops/lpm.py merge_trie_entries); presence is signalled by
    the caller's static ``fused`` flag, the placeholders only keep the
    pytree shape stable."""

    pf_child: jnp.ndarray
    pf_info: jnp.ndarray
    pf_common: jnp.ndarray
    ip_child: jnp.ndarray
    ip_info: jnp.ndarray
    ip_common: jnp.ndarray
    merged_child: jnp.ndarray
    merged_info: jnp.ndarray
    merged_common: jnp.ndarray
    world_row: jnp.ndarray  # [] int32
    policymap: PolicymapTables


@chex.dataclass(frozen=True)
class WideDatapathTables:
    """IPv4 device state using the dense-16-bit-first-stride tries
    (ops/lpm.py WideTrieBuilder) — 3 gathers per LPM instead of 4,
    measured ~1.8× on the identity-derivation stage.

    ``merged_*`` carry the FUSED deny+identity flat trie when both
    sides use the dense layout (ops/lpm.py merge_flat_tries): one
    2-gather walk yields the identity row AND the prefilter verdict,
    halving the chain's gather count. A [1,1] merged_sub_info marks
    "no merged table" (shape is static at trace time, so the jit
    routes without a flag)."""

    pf_root_info: jnp.ndarray  # [65536] int32
    pf_root_child: jnp.ndarray
    pf_sub_child: jnp.ndarray  # [M, 256] int32
    pf_sub_info: jnp.ndarray
    ip_root_info: jnp.ndarray
    ip_root_child: jnp.ndarray
    ip_sub_child: jnp.ndarray
    ip_sub_info: jnp.ndarray
    merged_root_info: jnp.ndarray  # [65536] int32 (packed) or [1]
    merged_root_child: jnp.ndarray
    merged_sub_child: jnp.ndarray
    merged_sub_info: jnp.ndarray  # [M, 65536] or [1, 1]
    world_row: jnp.ndarray  # [] int32
    policymap: PolicymapTables


def _elided_lpm(
    child: jnp.ndarray,
    info: jnp.ndarray,
    common: jnp.ndarray,
    addr_bytes: jnp.ndarray,
    levels: int,
) -> jnp.ndarray:
    """LPM walk with the trie's shared prefix compared (one vectorized
    equality, zero gathers) instead of walked — K is static from the
    common array's shape, so each table set compiles its own depth."""
    k = common.shape[0]
    hit = lpm_lookup(child, info, addr_bytes[:, k:], levels=levels - k)
    if k:
        ok = jnp.all(addr_bytes[:, :k] == common[None, :], axis=1)
        hit = jnp.where(ok, hit, 0)
    return hit


def _v4_lpm_stage(t, peer_u32, prefilter: bool):
    """→ (denied_pf [B] bool, identity hit [B] int32 value+1).

    Routes on the (static) merged-table shape: with the fused
    deny+identity flat trie present and the prefilter stage active, ONE
    walk answers both questions (bpf_xdp.c check_filters + the ipcache
    secctx derivation in a single pass); otherwise the two classic
    walks run (and the deny walk only when the stage is active)."""
    fused = t.merged_sub_info.shape[-1] == 65536
    if prefilter and fused:
        packed = lpm_lookup_wide(
            t.merged_root_info, t.merged_root_child, t.merged_sub_child,
            t.merged_sub_info, peer_u32,
        )
        denied_pf = (packed & jnp.int32(DENY_BIT)) != 0
        hit = packed & jnp.int32(MERGED_VALUE_MASK)
        return denied_pf, hit
    if prefilter:
        denied_pf = lpm_lookup_wide(
            t.pf_root_info, t.pf_root_child, t.pf_sub_child, t.pf_sub_info,
            peer_u32,
        ) > 0
    else:
        denied_pf = jnp.zeros(peer_u32.shape[0], jnp.bool_)
    hit = lpm_lookup_wide(
        t.ip_root_info, t.ip_root_child, t.ip_sub_child, t.ip_sub_info,
        peer_u32,
    )
    return denied_pf, hit


def _verdict_tail(
    policymap: PolicymapTables,
    denied_pf: jnp.ndarray,
    peer_row: jnp.ndarray,
    ep_idx: jnp.ndarray,
    dport: jnp.ndarray,
    proto: jnp.ndarray,
    ep_count: int,
    block: int,
    attrib: bool = False,
    rule_tab: Optional[jnp.ndarray] = None,
    n_rules: int = 0,
    ident_gather: bool = False,
):
    """Shared post-LPM tail (policy lookup, prefilter override,
    counter matmul) — traced inside both jitted entry points so the
    v4/v6 paths cannot diverge.

    ``attrib=True`` (static on the jitted callers; the off path keeps
    its exact original program) appends per-flow attribution: the
    deciding-rule index gathered from ``rule_tab`` (-1 = none; masked
    for prefilter drops, which never reached the policymap), whether
    any L4 column covered the flow (the no-L4 vs no-L3 drop
    discriminator), and the on-device [R] rule-hit segment-sum —
    pulled d2h only in the completion half, like the counters."""
    if not attrib:
        dec, red = lookup_batch(
            policymap, ep_idx, peer_row, dport, proto, block=block,
            ident_gather=ident_gather,
        )
    else:
        dec, red, rule, l4x = lookup_batch(
            policymap, ep_idx, peer_row, dport, proto, block=block,
            attrib=True, rule_tab=rule_tab, ident_gather=ident_gather,
        )
    verdict = jnp.where(denied_pf, jnp.int8(DROP_PREFILTER), dec)
    redirect = red & ~denied_pf

    # counters via one-hot matmul [B, EP]ᵀ @ [B, 3]
    ep_oh = (ep_idx[:, None] == jnp.arange(ep_count)[None, :]).astype(jnp.int8)
    cls = jnp.stack(
        [verdict == FORWARD, verdict == DROP_POLICY, verdict == DROP_PREFILTER],
        axis=1,
    ).astype(jnp.int8)
    counters = jax.lax.dot_general(
        ep_oh, cls, (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    if not attrib:
        return verdict, redirect, counters
    rule = jnp.where(denied_pf, jnp.int32(-1), rule)
    idx = jnp.clip(rule, 0, max(n_rules - 1, 0))
    hits = (
        jnp.zeros((max(n_rules, 1),), jnp.int32)
        .at[idx]
        .add((rule >= 0).astype(jnp.int32))
    )
    return verdict, redirect, counters, rule, l4x, hits


def _v6_lpm_stage(t, peer_bytes, levels: int, prefilter: bool, fused: bool):
    """→ (denied_pf, hit) — the v6 twin of _v4_lpm_stage: with the
    fused trie present and the deny stage active, ONE elided stride-8
    walk answers both questions; ``fused`` is a static flag because the
    stride-8 shapes can't disambiguate presence the way the flat
    layout's 65536 width can."""
    if prefilter and fused:
        raw = _elided_lpm(
            t.merged_child, t.merged_info, t.merged_common, peer_bytes,
            levels,
        )
        packed = jnp.where(raw > 0, raw - 1, 0)
        denied_pf = (packed & jnp.int32(DENY_BIT)) != 0
        hit = packed & jnp.int32(MERGED_VALUE_MASK)
        return denied_pf, hit
    if prefilter:
        denied_pf = _elided_lpm(
            t.pf_child, t.pf_info, t.pf_common, peer_bytes, levels
        ) > 0
    else:
        denied_pf = jnp.zeros(peer_bytes.shape[0], jnp.bool_)
    hit = _elided_lpm(t.ip_child, t.ip_info, t.ip_common, peer_bytes, levels)
    return denied_pf, hit


@functools.partial(
    jax.jit,
    static_argnames=(
        "ep_count", "block", "levels", "prefilter", "fused", "attrib",
        "n_rules", "ident_gather",
    ),
)
def process_flows(
    t: DatapathTables,
    peer_bytes: jnp.ndarray,  # [B, levels] int32 address bytes
    ep_idx: jnp.ndarray,  # [B] int32
    dport: jnp.ndarray,  # [B] int32
    proto: jnp.ndarray,  # [B] int32
    ep_count: int = 1,
    block: int = 16384,  # measured-fastest lookup block (ops/lookup.py)
    levels: int = 4,
    prefilter: bool = True,
    fused: bool = False,
    row_override: Optional[jnp.ndarray] = None,  # [B] int32, -1 = LPM
    attrib: bool = False,
    rule_tab: Optional[jnp.ndarray] = None,  # [N, C_pad] int32
    n_rules: int = 0,
    ident_gather: bool = False,
):
    """→ (verdict[B] int8, redirect[B] bool, counters [EP, 3] int32);
    with ``attrib=True`` additionally (rule[B] int32, l4_covered[B]
    bool, hits[R] int32) — see _verdict_tail.

    ``peer_bytes`` is the remote address of each flow: the SOURCE for
    ingress traffic (bpf_netdev.c:376 resolves src identity), the
    DESTINATION for egress (bpf_lxc.c:497 resolves dst identity).
    ``prefilter`` guards the XDP deny-trie stage — the reference runs
    it only on traffic entering the node (bpf_xdp.c), not on egress.
    ``row_override`` carries the overlay path's identity-from-tunnel-
    key (bpf_overlay.c: decap reads the security identity from the
    encap key and trusts it over an ipcache walk): flows with a
    non-negative row skip BOTH the identity LPM and the prefilter (the
    XDP prefilter inspects outer headers, which decap already shed).

    counters[e] = (forwarded, dropped_policy, dropped_prefilter) — the
    metricsmap accumulation, computed with a one-hot matmul so the
    scatter stays on the MXU.
    """
    denied_pf, hit = _v6_lpm_stage(t, peer_bytes, levels, prefilter, fused)
    peer_row = jnp.where(hit > 0, hit - 1, t.world_row)
    if row_override is not None:
        trusted = row_override >= 0
        peer_row = jnp.where(trusted, row_override, peer_row)
        denied_pf = denied_pf & ~trusted
    return _verdict_tail(
        t.policymap, denied_pf, peer_row, ep_idx, dport, proto, ep_count,
        block, attrib=attrib, rule_tab=rule_tab if attrib else None,
        n_rules=n_rules, ident_gather=ident_gather,
    )


# Backwards-compatible alias for the IPv4 path.
process_ipv4 = process_flows


@functools.partial(
    jax.jit, static_argnames=("ep_count", "block", "prefilter", "attrib",
                              "n_rules", "ident_gather")
)
def process_flows_wide(
    t: WideDatapathTables,
    peer_u32: jnp.ndarray,  # [B] uint32 host-order peer addresses
    ep_idx: jnp.ndarray,
    dport: jnp.ndarray,
    proto: jnp.ndarray,
    ep_count: int = 1,
    block: int = 16384,
    prefilter: bool = True,
    row_override: Optional[jnp.ndarray] = None,  # [B] int32, -1 = LPM
    attrib: bool = False,
    rule_tab: Optional[jnp.ndarray] = None,  # [N, C_pad] int32
    n_rules: int = 0,
    ident_gather: bool = False,
):
    """IPv4 fast path over the wide tries — semantics identical to
    process_flows(levels=4), including the overlay row_override and
    the attrib variant."""
    denied_pf, hit = _v4_lpm_stage(t, peer_u32, prefilter)
    peer_row = jnp.where(hit > 0, hit - 1, t.world_row)
    if row_override is not None:
        trusted = row_override >= 0
        peer_row = jnp.where(trusted, row_override, peer_row)
        denied_pf = denied_pf & ~trusted
    return _verdict_tail(
        t.policymap, denied_pf, peer_row, ep_idx, dport, proto, ep_count,
        block, attrib=attrib, rule_tab=rule_tab if attrib else None,
        n_rules=n_rules, ident_gather=ident_gather,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "ep_count", "block", "prefilter", "levels", "family", "fused"
    ),
    donate_argnums=(1,),
)
def process_flows_ct(
    t,  # WideDatapathTables (family 4) | DatapathTables (family 6)
    ct,  # DeviceCTState — DONATED: updated in place on device
    peer: jnp.ndarray,  # family 4: [B] uint32; family 6: [B, 16] int32
    ep_idx: jnp.ndarray,
    dport: jnp.ndarray,
    proto: jnp.ndarray,
    sport: jnp.ndarray,
    direction: jnp.ndarray,  # [] int32 0 ingress / 1 egress
    now: jnp.ndarray,  # [] int32 seconds (monotonic)
    valid: jnp.ndarray,  # [B] bool — False for shape-bucket padding
    ep_count: int = 1,
    block: int = 16384,
    prefilter: bool = True,
    levels: int = 4,
    family: int = 4,
    fused: bool = False,  # v6 merged-trie presence (v4 routes by shape)
):
    """The FUSED datapath step with device-resident conntrack: CT
    probe (fwd + reply) → deny LPM → identity LPM → policymap lookup →
    CT insert, ONE device program per batch (datapath/device_ct.py).
    Established flows take FORWARD regardless of the policy stages —
    the bpf/lib/conntrack.h bypass, computed branch-free (the verdict
    stages run for every lane anyway; SIMD lanes are not saved by
    host-side subsetting).

    → (verdict [B] int8, redirect [B] bool, counters [EP, 3] int32,
    new_ct_state)."""
    from .device_ct import _ct_step_impl, pack_kc_words

    if family == 4:
        denied_pf, hit = _v4_lpm_stage(t, peer, prefilter)
        z = jnp.zeros_like(peer)
        ka_w, kb_w = (z, z), (z, peer)
    else:
        denied_pf, hit = _v6_lpm_stage(t, peer, levels, prefilter, fused)
        b32 = peer.astype(jnp.uint32)

        def word(i):
            return (
                (b32[:, i] << 24) | (b32[:, i + 1] << 16)
                | (b32[:, i + 2] << 8) | b32[:, i + 3]
            )

        ka_w, kb_w = (word(0), word(4)), (word(8), word(12))
    peer_row = jnp.where(hit > 0, hit - 1, t.world_row)
    dec, red = lookup_batch(
        t.policymap, ep_idx, peer_row, dport, proto, block=block
    )
    policy_fwd = dec == jnp.int8(FORWARD)
    # padded lanes must never create CT state (their zero-keys would
    # otherwise become real, long-lived entries)
    allow_new = policy_fwd & ~denied_pf & ~red & valid

    kc_w = pack_kc_words(
        ep_idx, sport, dport, proto, jnp.broadcast_to(direction, ep_idx.shape)
    )
    new_ct, established = _ct_step_impl(
        ct, ka_w, kb_w, kc_w, proto, now, allow_new
    )

    verdict = jnp.where(
        established,
        jnp.int8(FORWARD),
        jnp.where(denied_pf, jnp.int8(DROP_PREFILTER), dec),
    )
    redirect = red & ~denied_pf & ~established

    ep_oh = (ep_idx[:, None] == jnp.arange(ep_count)[None, :]).astype(jnp.int8)
    cls = (
        jnp.stack(
            [
                verdict == FORWARD,
                verdict == DROP_POLICY,
                verdict == DROP_PREFILTER,
            ],
            axis=1,
        )
        & valid[:, None]
    ).astype(jnp.int8)
    counters = jax.lax.dot_general(
        ep_oh, cls, (((0,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )
    return verdict, redirect, counters, new_ct


def _bucket(n: int, floor: int = 1024) -> int:
    """Next power-of-two ≥ n (min ``floor``) — shape buckets so the
    CT-miss tail reuses compiled XLA programs."""
    b = floor
    while b < n:
        b <<= 1
    return b


def _pack_v4_u32(peer_bytes: np.ndarray) -> np.ndarray:
    """[B, 4] address bytes → [B] uint32 host-order (the wide-trie
    query word). One definition for every dispatch path."""
    b = peer_bytes.astype(np.uint32)
    return (b[:, 0] << 24) | (b[:, 1] << 16) | (b[:, 2] << 8) | b[:, 3]


# -- policyd-overload: prefilter shed walk ---------------------------------
# The coarse admission prefilter (PAPER.md layer 1's XDP prefilter
# role, drop reason 144): ONE identity LPM walk + ONE gather from the
# [N, 9] drop table compiled by admission.compile_shed_table. Runs only
# from the admission gate when the queue is over budget — it is not on
# the normal verdict path, so the Prefilter OFF program set is exactly
# the pre-option one. Deliberately skips the deny-trie stage and the
# policymap: cheapness is the point (shed rate must be a multiple of
# full-pipeline rate on deny-heavy mixes), and the table alone is
# deny-for-sure so skipping stages can only shed less, never wrongly.


@jax.jit
def shed_flows_wide(
    t: "WideDatapathTables",
    shed_tab: jnp.ndarray,  # [N, 9] uint8 (admission.compile_shed_table)
    peer_u32: jnp.ndarray,  # [B] uint32 host-order peer addresses
    dport: jnp.ndarray,  # [B] int32
    proto: jnp.ndarray,  # [B] int32
) -> jnp.ndarray:
    """→ shed[B] bool: every flagged flow is deny-for-sure under the
    current realized policy (IPv4)."""
    _, hit = _v4_lpm_stage(t, peer_u32, False)
    row = jnp.where(hit > 0, hit - 1, t.world_row)
    cls = flow_class(dport, proto).astype(jnp.int32)
    return jnp.take(shed_tab.reshape(-1), row * N_SHED_CLASSES + cls) != 0


@functools.partial(jax.jit, static_argnames=("levels",))
def shed_flows(
    t: "DatapathTables",
    shed_tab: jnp.ndarray,
    peer_bytes: jnp.ndarray,  # [B, levels] int32 address bytes
    dport: jnp.ndarray,
    proto: jnp.ndarray,
    levels: int = 16,
) -> jnp.ndarray:
    """IPv6 twin of shed_flows_wide (stride-8 elided identity walk)."""
    _, hit = _v6_lpm_stage(t, peer_bytes, levels, False, False)
    row = jnp.where(hit > 0, hit - 1, t.world_row)
    cls = flow_class(dport, proto).astype(jnp.int32)
    return jnp.take(shed_tab.reshape(-1), row * N_SHED_CLASSES + cls) != 0


def _pad_flows(pad: int, peer_bytes, *arrays, row_override=None):
    """Zero-pad a flow batch's arrays to a shape bucket (row_override
    pads with -1: padded lanes must derive-by-LPM, never trust)."""
    if pad:
        peer_bytes = np.pad(peer_bytes, ((0, pad), (0, 0)))
        arrays = tuple(np.pad(a, (0, pad)) for a in arrays)
        if row_override is not None:
            row_override = np.pad(row_override, (0, pad), constant_values=-1)
    return (peer_bytes, *arrays, row_override)


def _bucket_multiple(n: int, ndev: int, floor: int = 1024) -> int:
    """_bucket(), then rounded up to a multiple of the mesh device
    count so a flow-sharded batch splits evenly (P("flows") shards
    dim 0; an uneven split would compile per-remainder programs)."""
    b = _bucket(n, floor)
    return b + ((-b) % ndev)


# policyd-autotune bucket ladder: the ONLY padded shapes the bucketed
# (CT-miss tail) dispatch path ever compiles. Fixed — not derived from
# traffic — so the jit shape-bucket count is bounded by construction at
# len(BUCKET_LADDER) per static-arg combination, and a rung warmed by
# any batch stays reusable by every later batch. STABLE CONTRACT
# (ROADMAP): the rungs live in cilium_tpu/contracts.py (single source
# of truth, machine-checked by rule API001) because changing them
# invalidates every warm compiled program and the staging-pool sizing.
from ..contracts import BUCKET_LADDER


def _ladder_rungs(ndev: int, ladder: Tuple[int, ...] = BUCKET_LADDER):
    """Ladder rungs rounded up to mesh-device multiples (same reason as
    _bucket_multiple: P("flows") must split each chunk evenly)."""
    if ndev <= 1:
        return ladder
    return tuple(r + ((-r) % ndev) for r in ladder)


@functools.lru_cache(maxsize=512)
def _tail_cover(m: int, rungs: Tuple[int, ...]) -> Tuple[int, int, Tuple[int, ...]]:
    """Minimum-padded-lane rung cover of an m-flow tail (m ≤ top rung
    after full-top-rung stripping): returns (lanes, chunks, plan) with
    the plan sorted largest-first so only the final chunk carries pad.
    Lanes are minimized first, chunk count second (each chunk is one
    h2d + enqueue), and on full ties the larger leading rung wins —
    e.g. an 1100-flow tail covers with one 2048 chunk, not 1024+1024,
    and a 3000-flow tail with 2048+1024 (3072 lanes) instead of one
    4096 chunk (the single-warm-bucket scheme's ~37% extra pad).
    Exact, not greedy: ndev-rounded rungs are not mutual multiples, so
    greedy largest-fit can strand a worse tail. Depth is bounded by
    top/floor (≤ 8 recursions)."""
    best = None
    for r in reversed(rungs):  # largest first → wins full ties
        if r >= m:
            cand = (r, 1, (r,))
        else:
            lanes, chunks, plan = _tail_cover(m - r, rungs)
            cand = (
                r + lanes,
                chunks + 1,
                tuple(sorted((r,) + plan, reverse=True)),
            )
        if best is None or (cand[0], cand[1]) < (best[0], best[1]):
            best = cand
    return best


class PendingBatch:
    """Handle for one batch accepted by ``DatapathPipeline.submit()``.
    Batches complete strictly FIFO; ``result()`` blocks until this
    batch's host pull + accounting have run (completing any older
    in-flight batches first, preserving event/conntrack order)."""

    __slots__ = ("_pipe", "_event", "_value", "_exc")

    def __init__(self, pipe: "DatapathPipeline") -> None:
        self._pipe = pipe
        self._event = threading.Event()
        self._value = None
        self._exc: Optional[BaseException] = None

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def result(self):
        if not self._event.is_set():
            self._pipe._complete_until(self)
            # Timed loop, not a bare wait: if the completing thread
            # wedges on a stuck device pull, the watchdog resolves this
            # batch degraded and sets the event — but a daemon run
            # without a watchdog must still never park a caller
            # unwakeably on a lost completion (policyd-overload
            # ROBUST002 discipline: no untimed blocking waits on the
            # hot path).
            while not self._event.wait(0.5):
                pass
        if self._exc is not None:
            raise self._exc
        return self._value


class _InFlight:
    """One submitted batch: its handle, the completion closure (host
    pull + counters + CT create + events), and the trace that must end
    when the batch COMPLETES. ``finish=None`` marks a batch that ran
    synchronously (the donated-state device-CT path)."""

    __slots__ = (
        "pending", "finish", "bt", "enq_ns", "occ", "b", "rev", "t0",
        "abandoned",
    )

    def __init__(
        self, pending: PendingBatch, finish, bt,
        b: int = 0, rev: bool = False,
    ) -> None:
        self.pending = pending
        self.finish = finish
        self.bt = bt
        # depth-tuner observations (populated only while DispatchAutoTune
        # is on): enqueue-half wall ns, queue occupancy at admission.
        # enq_ns == 0 marks "not observed".
        self.enq_ns = 0
        self.occ = 0
        # batch size + rev-NAT flag: always populated — the failsafe
        # quarantine path synthesizes a shape-correct degraded result
        # from these when the finish closure is unrecoverable
        self.b = b
        self.rev = rev
        # policyd-overload: submit time (monotonic; 0 = not tracked —
        # set only while admission control or the watchdog is on) and
        # the watchdog's abandonment mark. Once abandoned, the batch is
        # already resolved degraded — a late-returning finish must not
        # overwrite the published result.
        self.t0 = 0.0
        self.abandoned = False


class _GatedPending(PendingBatch):
    """Admission-gate handle for a PARTIALLY shed batch: the deny-for-
    sure flows were resolved at the gate (reason 144), the kept flows
    ride an inner PendingBatch through the unchanged submit path.
    result() merges the two back into the caller's original [B] order —
    from the outside the batch is indistinguishable from an ungated
    one, just with some lanes pre-verdicted."""

    __slots__ = ("_inner", "_keep", "_shed_v", "_b", "_rev", "_merge")

    def __init__(
        self,
        pipe: "DatapathPipeline",
        inner: PendingBatch,
        keep_idx: np.ndarray,  # [K] indices of kept flows in the batch
        shed_verdict: np.ndarray,  # [B] int8, shed lanes pre-filled
        b: int,
        rev: bool,
    ) -> None:
        super().__init__(pipe)
        self._inner = inner
        self._keep = keep_idx
        self._shed_v = shed_verdict
        self._b = b
        self._rev = rev
        self._merge = threading.Lock()

    @property
    def done(self) -> bool:
        return self._event.is_set() or self._inner.done

    def result(self):
        with self._merge:
            if not self._event.is_set():
                try:
                    out = self._inner.result()
                except BaseException as e:
                    self._exc = e
                    self._event.set()
                    raise
                v = self._shed_v
                red = np.zeros(self._b, bool)
                v[self._keep] = out[0]
                red[self._keep] = out[1]
                if self._rev:
                    rev = np.zeros(self._b, np.uint16)
                    rev[self._keep] = out[2]
                    self._value = (v, red, rev)
                else:
                    self._value = (v, red)
                self._event.set()
        if self._exc is not None:
            raise self._exc
        return self._value


class _Enqueued:
    """Un-pulled device results of one dispatch: per-chunk (verdict,
    redirect, counters) device arrays plus the spans that produced
    them — (…, rule, l4_covered, hits) 6-tuples when ``attrib``.
    ``exact`` marks device counters (and rule-hit sums) usable as-is
    (no padded lanes polluted them)."""

    __slots__ = (
        "chunks", "spans", "b", "exact", "ndev", "attrib", "staging", "host",
        "psample",
    )

    def __init__(
        self, chunks, spans, b, exact, ndev, attrib=False, staging=(),
        host=None, psample=None,
    ) -> None:
        self.chunks = chunks
        self.spans = spans
        self.b = b
        self.exact = exact
        self.ndev = ndev
        self.attrib = attrib
        # staging tuples pinned under this dispatch's device inputs;
        # released back to the pipeline's pool at the host pull
        self.staging = staging
        # ladder level 2 (host fallback): (verdict, redirect) computed
        # synchronously on host numpy — no device arrays to pull
        self.host = host
        # policyd-prof: the live _DispatchSample when this dispatch was
        # the profiler's Nth batch (None otherwise) — the completion
        # half times the d2h pull into it and retires it
        self.psample = psample


class DatapathPipeline:
    """Host orchestrator: owns the device snapshot of prefilter +
    ipcache + materialized policymaps for a set of local endpoints, and
    re-materializes when any input version moves (the regeneration
    trigger role of pkg/endpoint/policy.go:812)."""

    def __init__(
        self,
        engine: PolicyEngine,
        ipcache: IPCache,
        prefilter: Optional[PreFilter] = None,
        conntrack: Optional[FlowConntrack] = None,
        lb=None,  # Optional[lb.service.ServiceManager]
        monitor=None,  # Optional[monitor.hub.MonitorHub]
        device_ct_bits: Optional[int] = None,
        tracer: Optional[Tracer] = None,
        pipeline_depth: int = 2,
        sharding: bool = False,
        flow_ring: Optional[FlowRing] = None,
        pipeline_max_depth: int = 4,
        autotune: bool = False,
        epoch_swap: bool = False,
        placement: Optional[PlacementConfig] = None,
        mesh_2d: bool = False,
        admission: bool = False,
        prefilter_shed: bool = False,
        sparse_deltas: bool = False,
        deadline_ms: float = 0.0,
        stall_ms: float = 0.0,
        profiling: bool = False,
        profile_sample_every: int = 64,
    ) -> None:
        self.engine = engine
        self.ipcache = ipcache
        self.prefilter = prefilter or PreFilter()
        self.conntrack = conntrack
        # Device-resident conntrack (datapath/device_ct.py): the CT
        # table lives in HBM and the whole batch runs as ONE fused
        # device program. Takes precedence over the host CT for flows
        # it can serve; falls back to the host path when an LB table
        # is active for the batch's family+direction (VIP translation
        # precedes CT and is host-fused today).
        self._device_ct_bits = device_ct_bits
        self._device_ct = None  # lazily-created DeviceCTState
        if device_ct_bits is not None and self.conntrack is None:
            # Batches the device CT cannot serve (active LB tables,
            # overlay tunnel identities) fall back to the host CT
            # domain; without one they would silently lose conntrack
            self.conntrack = FlowConntrack(capacity_bits=max(10, device_ct_bits))
        self.lb = lb
        self.monitor = monitor
        # policyd-trace span tracer (observe/): off by default — the
        # verdict path pays one `tracer.active` attribute read per
        # batch (the hub's `active` pattern) until enabled
        self.tracer = tracer if tracer is not None else Tracer()
        # policyd-flows ring (observe/flows.py): sampled FlowRecords
        # from the completion half while FlowAttribution is on. Same
        # cost model as the tracer: one `ring.active` read per batch.
        self.flow_ring = flow_ring if flow_ring is not None else FlowRing()
        # optional identity → labels resolver for flow records:
        # fn(identity_id) -> tuple of label strings (the daemon points
        # this at its IdentityRegistry)
        self.identity_labels = None
        # jit-cache key shapes already dispatched (tracing telemetry:
        # a new member ≈ one XLA recompile)
        self._seen_shapes: set = set()
        # called for every redirect verdict with a known 5-tuple:
        # fn(peer_addr_bytes, ep_idx, sport, dport, proto, ingress,
        # family) — the cilium_proxy4/6 write hook (bpf_lxc.c inserts
        # a proxymap entry when the verdict is a proxy port)
        self.on_redirect = None
        # TraceNotify for forwarded flows is opt-in (the reference
        # gates trace events behind the TraceNotify endpoint option);
        # DropNotify defaults on while a listener is attached, gated
        # by the DropNotification runtime option.
        self.trace_enabled = False
        self.drop_notifications = True
        # PolicyVerdictNotify for EVERY flow (allowed included) is
        # opt-in (PolicyVerdictNotification runtime option) — it walks
        # the whole batch, so it stays off unless asked for
        self.verdict_notifications = False
        # optional per-endpoint option resolver:
        # fn(endpoint_id, option_name, default) -> bool. The daemon
        # points this at each endpoint's OptionMap so `cilium endpoint
        # config` overrides actually gate that endpoint's events
        # (applyOptsLocked inheritance, pkg/option).
        self.endpoint_options = None
        self._lb_tables: Dict[int, object] = {}
        self._lb_version = -1
        self._lock = threading.Lock()
        self._endpoints: List[int] = []  # identity ids of local endpoints
        self._endpoint_ids: List[int] = []  # endpoint ids (same order)
        self._tables: Dict[Tuple[int, int], DatapathTables] = {}
        # direction → MaterializedState (TRAFFIC_INGRESS / TRAFFIC_EGRESS)
        self._mat: Dict[int, MaterializedState] = {}
        self._mat_sig: Tuple = ()  # endpoint list the policymap was built for
        self._last_delta_seq = 0  # engine delta cursor
        self._trie_versions: Tuple = ()  # (ipcache.version, prefilter.revision)
        # (v4_empty, v6_empty) for the COMPILED prefilter tries: an
        # empty deny set skips the whole deny-LPM walk (which would
        # otherwise cost as much as the identity walk — half the
        # end-to-end pipeline), matching the reference's no-op empty
        # XDP maps. Updated together with self._tables.
        # REBUILD-INTERNAL: these two feed the _dp_state snapshot below
        # and are only safe to read directly in single-threaded contexts
        # (tests, bench setup). Dispatch paths MUST read _dp_state — a
        # separate-attribute read can pair a fresh flag with old tables.
        self._pf_empty: Tuple[bool, bool] = (True, True)
        self._v6_fused = False  # v6 merged deny+identity trie present
        # ATOMIC read snapshot for the lock-free dispatch paths:
        # (tables, pf_empty, v6_fused, flow_sharding, ndev, attrib,
        # ident2d, shed) swap together — reading them as separate attributes
        # could pair a new flag with old tables (e.g. fused=True against
        # placeholder merged arrays, which would resolve every v6 flow
        # to world with no denies, or a flow sharding against tables
        # placed for a different mesh, or a rule table from an older
        # rule set against newer policymaps). ``attrib`` is None (off)
        # or ({direction: rule_tab [N, C_pad]}, n_rules). ``ident2d``
        # selects the ident-sharded gather program — it must pair with
        # tables actually placed under P("ident"), never cross-read.
        # ``ndev`` is the FLOWS-axis size, not the total device count:
        # on a 2D mesh a batch splits over flows only.
        # (policyd-overload widened the tuple with the placed prefilter
        # shed table — None while the Prefilter option is off.)
        self._dp_state: Tuple = (
            {}, (True, True), False, None, 1, None, False, None,
        )
        self._tries: Optional[Tuple] = None  # ((pf4, ip4), (pf6, ip6), world_row)
        self.counters = np.zeros((0, 3), np.int64)
        # -- bounded in-flight dispatch queue -------------------------
        # submit() enqueues the device program and defers the host pull
        # (+ counters/ct_create/events) until completion; depth bounds
        # how many batches sit un-pulled so host prep of batch N+1
        # overlaps device execution of batch N. Depth 1 = synchronous.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._inflight: deque = deque()  # FIFO of _InFlight
        self._queue_lock = threading.Lock()  # guards _inflight only
        # conntrack basis epoch: bumped on every CT flush so a batch
        # completing AFTER a basis move (policy/ipcache change raced
        # its in-flight window) cannot create entries verdicted under
        # the old basis
        self._ct_epoch = 0
        # set when a basis move is DETECTED, cleared only after the
        # flush+epoch-advance completes: table versions commit above
        # this block, so a fault between commit and flush must not let
        # a retried rebuild skip the flush (policyd-failsafe)
        self._ct_flush_pending = False
        # basis (revision, identity_version, vocab_version) of the
        # generation the SERVED conntrack entries were verdicted under
        # (policyd-survive): committed by rebuild, read by the CT
        # snapshot writer — between a recompile and the next rebuild
        # the live entries still belong to THIS basis, not the
        # engine's newest compile
        self._mat_basis: Optional[Tuple[int, int, int]] = None
        # ladder rungs already dispatched (telemetry: the chunker's
        # shape set is the fixed BUCKET_LADDER; a rung joins this set
        # the first time a batch actually compiles/warms it)
        self._warm_buckets: set = set()
        # -- policyd-autotune: pre-pinned staging + depth tuner --------
        # (rung, peer_width) → free-list of rung-sized int32 host
        # staging tuples (peer_bytes, ep_idx, dports, protos, row_ov).
        # The bucketed pad half writes into these instead of np.pad
        # allocations per chunk. A tuple leaves the list at enqueue and
        # returns at the batch's host pull — never earlier: JAX CPU can
        # alias aligned numpy memory zero-copy, so reuse before
        # completion could race the device program's reads.
        self._staging: Dict[Tuple[int, int], list] = {}
        self._staging_lock = threading.Lock()
        # depth auto-tuner (DispatchAutoTune): OFF by default — the
        # dispatch path then pays one `self._tuner is None` read per
        # batch and pipeline_depth never moves (static-depth behavior
        # preserved exactly). _static_depth is what set_autotune(False)
        # restores.
        self._static_depth = self.pipeline_depth
        self.pipeline_max_depth = max(self.pipeline_depth, int(pipeline_max_depth))
        self._tuner: Optional[DepthTuner] = None
        if autotune:
            self.set_autotune(True)
        _metrics.pipeline_depth_current.set(float(self.pipeline_depth))
        # -- multi-device flow sharding (VerdictSharding) -------------
        # active mesh → tables replicated, flow batches split over the
        # "flows" axis. The dispatch-visible sharding rides _dp_state
        # so it can never pair with tables placed for a different mesh.
        self._sharding_requested = bool(sharding)
        # -- placement subsystem (datapath/placement.py) --------------
        # the resolved MeshPlan owns mesh construction, axis shardings
        # and the generation counter; _mesh/_flow_sharding/
        # _table_sharding are kept as synced mirrors (tests and older
        # call sites read them directly).
        self._placement = placement
        self._mesh2d_requested = bool(mesh_2d)
        self._plan: MeshPlan = EMPTY_PLAN
        self._mesh: Optional[Mesh] = None
        self._flow_sharding: Optional[NamedSharding] = None
        self._table_sharding: Optional[NamedSharding] = None
        # direction → (plan generation, source policymap, placed copy):
        # re-place when materialization swaps the source object OR the
        # plan generation moved (a ladder demotion / placement change
        # must never serve tables placed on a stale mesh)
        self._placed_pm: Dict[int, Tuple[int, object, object]] = {}
        # source sel_match → (generation, ident-placed copy): the 2D
        # plan row-shards the [N, S/32] selector-match bitmaps the
        # materializer sweeps gather from
        self._placed_sel: Tuple[int, object, object] = (0, None, None)
        # -- verdict attribution (FlowAttribution) --------------------
        # requested state; takes effect on the next rebuild (the sweep
        # must re-run with the attribution kernel variant to populate
        # the per-(row, column) rule table)
        self._attrib_requested = False
        self._attrib_n_rules = 0
        # rule index → origin label (repo.origin_names()), refreshed
        # with the rule tables; read lock-free in the completion half
        self._attrib_names: List[str] = []
        # direction → (plan generation, source rule_tab, placed copy) —
        # the _placed_pm pattern for the attribution gather table
        self._placed_rt: Dict[int, Tuple[int, object, object]] = {}
        # -- policyd-failsafe: self-healing / degradation ladder ------
        # ladder level (index into _MODE_NAMES): 0 = full device
        # complement, 1 = single-device, 2 = host fallback. Transitions
        # take self._lock; dispatch paths read the int lock-free
        # (GIL-atomic, same rule as pipeline_depth).
        self._ladder_level = 0
        # FailOpen runtime option: what an UNRESOLVABLE batch returns.
        # Off (default) = fail-closed: DROP_DEGRADED verdicts, monitor
        # reason 155. On = forward unverdicted traffic.
        self._fail_open = False
        # device ids the mesh must exclude (populated on a sharded →
        # single-device descent; consulted by _refresh_mesh_locked)
        self._excluded_devices: set = set()
        # circuit breaker: quarantines increment _breaker_faults and a
        # clean-batch streak clears them; at the threshold the ladder
        # descends one level. recover_after_clean clean batches at a
        # degraded level probe one level back up. Both knobs are plain
        # attributes so tests/bench shrink the windows.
        self.breaker_threshold = 3
        self.recover_after_clean = 32
        self._breaker_faults = 0
        self._clean_batches = 0
        # bounded retry of classified-transient failures (completion
        # pull / enqueue): retry_limit attempts spaced by a fresh
        # Backoff(retry_min_s → retry_max_s) per failure
        self.retry_limit = 2
        self.retry_min_s = 0.005
        self.retry_max_s = 0.1
        self._quarantined = 0  # batches resolved degraded (lifetime)
        # direction → (source policymap, host numpy copy) for the
        # ladder-level-2 fallback — pulled once per materialization,
        # not per batch
        self._host_pm: Dict[int, Tuple[object, Tuple]] = {}
        # -- policyd-delta: epoch-swapped device tables ---------------
        # Opt-in (EpochSwap runtime option): a full re-materialization
        # demanded by the delta log builds its policymaps on a SHADOW
        # thread while dispatches keep serving the current generation;
        # the finished generation installs under self._lock and becomes
        # dispatch-visible through the NEXT rebuild's single _dp_state
        # publish — the atomic batch-boundary swap, riding the same
        # transactional _ct_flush_pending block (and SITE_CT_EPOCH
        # fault site) as every other basis move. _swap_gen is the
        # abandonment guard: any event that invalidates the basis the
        # shadow bound to (quarantine, ladder move, endpoint/sharding/
        # attribution change, swap-off) bumps it, and a finishing
        # shadow whose generation no longer matches is discarded — a
        # swap mid-quarantine must not resurrect the abandoned epoch.
        self._epoch_swap = bool(epoch_swap)
        self._policy_epoch = 0  # generations actually swapped in
        self._swap_gen = 0  # basis generation a shadow build binds to
        self._shadow_thread: Optional[threading.Thread] = None
        self._shadow_exc: Optional[BaseException] = None
        # -- policyd-overload: admission control + watchdog -----------
        # AdmissionControl runtime option: None (off) keeps _submit at
        # one `self._admission is None` read per batch — the exact
        # pre-option path. The deadline is boot config, consulted only
        # while the controller exists.
        self.deadline_ms = max(0.0, float(deadline_ms))
        # policyd-journal: lifecycle-event emission slot. None while
        # the LifecycleJournal option is off (every site pays one
        # attribute read); the daemon installs the journal's bound
        # emit — called as ``oj(kind=..., severity=..., attrs=...)``
        # with OBS003-checked kind literals, always OUTSIDE this
        # pipeline's locks. Initialized before the admission boot
        # toggle below, which forwards it to the controller.
        self.on_journal = None
        self._admission: Optional[AdmissionController] = None
        if admission:
            self.set_admission(True)
        # Prefilter runtime option: when on, rebuild() compiles the
        # coarse [identity, proto/port-class] drop table from the
        # ingress policymap mirror and publishes it THROUGH _dp_state
        # (placed with the same table sharding as the tries, so it
        # rides the MeshPlan). Off publishes None and no shed kernel
        # ever traces.
        self._shed_requested = bool(prefilter_shed)
        # (plan generation, placed device table) — recompiled when the
        # policymap mirror or the placement moved
        self._shed_cache: Optional[Tuple[int, object]] = None
        # -- policyd-sparse: O(k) sparse device-table deltas ----------
        # SparseDeltas runtime option: when on, (a) the ident-placed
        # sel_match copy is PATCHED from the engine's delta log (row +
        # column scatters, O(delta) per device) instead of re-placed
        # whole, and (b) ipcache prefix churn patches the device LPM
        # trie tensors in place (ops/lpm.py Patchable* — O(delta) node
        # writes) instead of re-merging whole tries. Off keeps the
        # exact pre-option paths: full device_put on sel_match source
        # change, full trie rebuild on any ipcache version move (the
        # patchable builders are never constructed).
        self._sparse_deltas = bool(sparse_deltas)
        # family → patchable trie builder (4: PatchableFlatTrie or
        # None, 6: PatchableElidedTrie or None), rebuilt alongside
        # self._tries; None until a sparse-enabled full rebuild runs
        self._trie_patch: Optional[Dict[int, object]] = None
        # stuck-dispatch watchdog (dispatch_stall_ms > 0): monitors the
        # actively-completing batch + registered external waits and
        # drives the quarantine/breaker path instead of hanging
        self._watchdog: Optional[Watchdog] = None
        # (inf, t0) while a completion pull is running; the watchdog's
        # only view into "actively stuck" (set/cleared only while the
        # watchdog exists — the off path never writes it per batch)
        self._completing: Optional[Tuple] = None
        if stall_ms > 0:
            self.set_stall_ms(stall_ms)
        # -- policyd-prof: device-time sampling profiler --------------
        # DeviceProfiling runtime option: None (off) keeps the dispatch
        # halves at one `self.profiler is None` read per batch — the
        # exact pre-option programs (observe/profiler.py is not even
        # imported). sample_every is boot config; set_profiling builds
        # the profiler with it.
        self.profile_sample_every = max(1, int(profile_sample_every))
        self.profiler = None
        if profiling:
            self.set_profiling(True)
        # -- policyd-survive: restart/drain continuity ----------------
        # Drain shed: begin_drain() flips this and _submit resolves new
        # batches degraded immediately while drain() FIFO-completes the
        # in-flight queue. The not-draining path pays one GIL-atomic
        # bool read per batch (the hub `active` pattern).
        self._draining = False
        # One-shot CT restore hold: the daemon sets this to the engine
        # revision current when it restored a CT snapshot whose basis
        # it verified against the restored compiled snapshot. The NEXT
        # rebuild's flush triggers consume it — but only if they
        # materialize that SAME revision (this process's first
        # materialization then builds from exactly the restored
        # tables, so the basis that admitted the entries still holds).
        # A policy mutation racing in before the first rebuild bumps
        # the revision, invalidates the hold, and flushes as always.
        self._ct_restore_hold: Optional[int] = None
        # one-shot completion hook (restart_downtime measurement): the
        # daemon points this at its downtime stamp after restore; fired
        # once after the first completed batch, then cleared
        self.on_first_batch = None
        # quarantine CT rescue: set after live device-CT entries were
        # pulled into the host table, so the next fresh device table
        # seeds from the host CT (re-upload on ladder re-promotion)
        # instead of zeros — established flows survive the round trip
        self._device_ct_seed = False
        self.device_ct_rescue_limit = 1 << 16
        _metrics.pipeline_mode.set(0.0)

    def set_endpoints(self, endpoints: Sequence) -> None:
        """Accepts identity ids (endpoint id == identity id) or
        (endpoint_id, identity_id) pairs; order defines the datapath
        endpoint index."""
        with self._lock:
            pairs = [
                e if isinstance(e, tuple) else (int(e), int(e)) for e in endpoints
            ]
            self._endpoint_ids = [p[0] for p in pairs]
            self._endpoints = [p[1] for p in pairs]
            self._mat.clear()  # column layout changes with the endpoint set
            # CT keys embed the endpoint INDEX; a changed endpoint list
            # would let a new occupant of an index inherit the previous
            # endpoint's established-flow bypass entries.
            if self.conntrack is not None:
                self.conntrack.flush()
            self._ct_epoch += 1
            self._device_ct = None
            self._swap_gen += 1  # column layout moved: abandon shadows

    def endpoint_index(self, endpoint_id: int) -> Optional[int]:
        try:
            return self._endpoint_ids.index(endpoint_id)
        except ValueError:
            return None

    def endpoint_id_at(self, idx: int) -> Optional[int]:
        with self._lock:
            if 0 <= idx < len(self._endpoint_ids):
                return self._endpoint_ids[idx]
        return None

    def set_sharding(self, on: bool) -> None:
        """Toggle multi-device flow sharding (the VerdictSharding
        runtime option). Takes effect on the next rebuild; a mesh only
        forms with >1 visible device. Clears placed tables and the
        shape/warm caches — sharded and unsharded dispatches compile
        different programs."""
        with self._lock:
            if bool(on) == self._sharding_requested:
                return
            self._sharding_requested = bool(on)
            self._tables = {}
            self._tries = None
            self._placed_pm.clear()
            self._placed_rt.clear()
            self._placed_sel = (0, None, None)
            self._swap_gen += 1  # placement basis moved: abandon shadows
        # telemetry/warm caches: best-effort sets the lock-free dispatch
        # paths also mutate bare (GIL-atomic; a racing add only costs
        # one redundant compile or a miscounted cache-hit metric)
        self._seen_shapes.clear()
        self._warm_buckets.clear()

    def set_mesh_2d(self, on: bool) -> None:
        """Toggle 2D flows×ident mesh sharding (the MeshSharding2D
        runtime option). Takes effect on the next rebuild through the
        placement plan: the device grid splits into flows×ident axes
        and the identity dimension of the policymaps / rule tables /
        sel_match bitmaps shards over ``ident``. OFF compiles the exact
        pre-option 1D/replicated programs (the ident-gather variant is
        unreachable — pinned spy-style like FlowAttribution). Clears
        placed tables and the shape/warm caches, same discipline as
        set_sharding."""
        with self._lock:
            if bool(on) == self._mesh2d_requested:
                return
            self._mesh2d_requested = bool(on)
            self._tables = {}
            self._tries = None
            self._placed_pm.clear()
            self._placed_rt.clear()
            self._placed_sel = (0, None, None)
            self._swap_gen += 1  # placement basis moved: abandon shadows
        self._seen_shapes.clear()
        self._warm_buckets.clear()

    def set_attribution(self, on: bool) -> None:
        """Toggle per-flow verdict attribution (the FlowAttribution
        runtime option). Takes effect on the next rebuild: the
        materializer sweep re-runs with the attribution kernel variant
        to populate the per-(identity row, column) deciding-rule table,
        and dispatches switch to the attrib program variant (rule
        gather + on-device rule-hit segment-sum; d2h pulls stay in the
        completion half). Off keeps the exact pre-attribution programs
        — the rule table contributes no leaves to the off-path trace.
        The device-CT fused path is NOT attributed; its drops keep the
        generic policy reason. Clears the shape/warm caches —
        attributed and plain dispatches compile different programs."""
        with self._lock:
            if bool(on) == self._attrib_requested:
                return
            self._attrib_requested = bool(on)
            # force re-materialization: the rule table only exists when
            # the sweep ran with attribution (and is dropped when off)
            self._mat.clear()
            self._mat_sig = ()
            self._placed_rt.clear()
            self._swap_gen += 1  # sweep variant moved: abandon shadows
        self.flow_ring.active = bool(on)
        self._seen_shapes.clear()
        self._warm_buckets.clear()

    # -- policyd-autotune: depth controller ----------------------------
    def set_autotune(
        self,
        on: bool,
        *,
        max_depth: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> None:
        """Toggle the dispatch depth auto-tuner (the DispatchAutoTune
        runtime option). ON installs a fresh DepthTuner stepping
        pipeline_depth in [1, pipeline_max_depth] from per-batch
        enqueue/complete timings; OFF restores the configured static
        depth and drops the tuner (the per-batch cost returns to one
        ``self._tuner is None`` read). ``epoch`` shrinks the decision
        interval for tests/bench convergence runs."""
        if max_depth is not None:
            self.pipeline_max_depth = max(1, int(max_depth))
        if not on:
            if self._tuner is not None:
                self._tuner = None
                self._apply_depth(self._static_depth)
            return
        kw = {} if epoch is None else {"epoch": int(epoch)}
        self._tuner = DepthTuner(1, self.pipeline_max_depth, **kw)
        _metrics.pipeline_depth_current.set(float(self.pipeline_depth))

    def _apply_depth(self, depth: int) -> None:
        """Move the effective pipeline depth (tuner decisions and
        autotune-off restore). Reads of pipeline_depth on the admission
        path are GIL-atomic, so a step takes effect on the very next
        submit — a deeper queue admits immediately, a shallower one
        drains through the existing over-depth completion loop."""
        depth = max(
            1, min(int(depth), max(self.pipeline_max_depth, self._static_depth))
        )
        cur = self.pipeline_depth
        if depth == cur:
            return
        self.pipeline_depth = depth
        _metrics.pipeline_depth_current.set(float(depth))
        _metrics.autotune_adjustments_total.inc(
            {"direction": "up" if depth > cur else "down"}
        )

    def autotune_state(self) -> Optional[Dict]:
        """Tuner snapshot for GET /traces (None while autotune is
        off)."""
        t = self._tuner
        if t is None:
            return None
        snap = t.snapshot()
        snap["depth"] = self.pipeline_depth
        snap["static_depth"] = self._static_depth
        return snap

    # -- policyd-autotune: pre-pinned staging --------------------------
    # free-list bound per (rung, width): deeper queues keep more tuples
    # in flight, but depth × chunks stays small — beyond this the
    # allocations were a burst, not steady state, so let them collect
    _STAGING_FREE_CAP = 8

    def _staging_acquire(self, rung: int, width: int):
        """One rung-sized staging tuple (peer[rung, width], ep, dp, pr,
        row_override — all int32, matching what prepare() coerces), off
        the free-list or freshly allocated on first use of a rung."""
        key = (rung, width)
        with self._staging_lock:
            free = self._staging.get(key)
            if free:
                return free.pop()
        return (
            np.empty((rung, width), np.int32),
            np.empty(rung, np.int32),
            np.empty(rung, np.int32),
            np.empty(rung, np.int32),
            np.empty(rung, np.int32),
        )

    def _staging_release(self, bufs_list) -> None:
        """Return a completed batch's staging tuples to their
        free-lists (called from the host-pull half only — see the
        aliasing note at _staging)."""
        for bufs in bufs_list:
            key = (bufs[0].shape[0], bufs[0].shape[1])
            with self._staging_lock:
                free = self._staging.setdefault(key, [])
                if len(free) < self._STAGING_FREE_CAP:
                    free.append(bufs)

    def _refresh_mesh_locked(self) -> None:
        """Resolve the placement plan to match the sharding/2D requests
        (held-lock helper for rebuild). Devices in _excluded_devices
        (a degradation-ladder descent) never join the mesh; with an
        empty exclusion set and no PlacementConfig this is exactly the
        pre-placement behavior — one 1D mesh over all visible devices,
        formed once (resolve_plan returns the previous plan unchanged
        when nothing moved, so mesh identity is stable). The legacy
        _mesh/_flow_sharding/_table_sharding attributes are mirrors of
        the plan, kept for tests and older call sites."""
        plan = resolve_plan(
            self._placement,
            sharding=self._sharding_requested,
            mesh_2d=self._mesh2d_requested,
            excluded=frozenset(self._excluded_devices),
            prev=self._plan,
        )
        if plan is not self._plan:
            self._plan = plan
            _metrics.mesh_axis_size.set(
                float(plan.axes.get("flows", 0)), {"axis": "flows"}
            )
            _metrics.mesh_axis_size.set(
                float(plan.axes.get("ident", 0)), {"axis": "ident"}
            )
        self._mesh = plan.mesh
        self._flow_sharding = plan.flow_sharding
        self._table_sharding = plan.table_sharding

    # -- policyd-failsafe: ladder + classified error handling ----------
    def set_fail_open(self, on: bool) -> None:
        """Toggle the FailOpen runtime option: what a batch that
        exhausted its retries returns. Off (default) is fail-closed —
        DROP_DEGRADED verdicts carrying monitor reason 155; on forwards
        unverdicted traffic (availability over enforcement)."""
        self._fail_open = bool(on)

    @property
    def pipeline_mode(self) -> str:
        return _MODE_NAMES[self._ladder_level]

    def failsafe_state(self) -> Dict:
        """Degraded-state snapshot for GET /healthz, GET /traces, and
        the CLI traces header."""
        return {
            "mode": self.pipeline_mode,
            "level": self._ladder_level,
            "degraded": self._ladder_level > 0,
            "fail_open": self._fail_open,
            "breaker_faults": self._breaker_faults,
            "clean_batches": self._clean_batches,
            "quarantined_batches": self._quarantined,
            "excluded_devices": sorted(self._excluded_devices),
            "fault_injection": _faults.hub.active,
        }

    def placement_state(self) -> Dict:
        """Placement snapshot for GET /traces and the CLI traces
        header: the resolved plan's generation, axes, and device set
        plus the operator's requests. Resolves the plan first so a
        just-patched option reports the mesh it WILL run on, not the
        one the last dispatch used."""
        with self._lock:
            self._refresh_mesh_locked()
        plan = self._plan
        return {
            "generation": plan.generation,
            "axes": dict(plan.axes),
            "devices": list(plan.device_ids),
            "flows_size": plan.flows_size,
            "mesh_2d_requested": self._mesh2d_requested,
            "sharding_requested": self._sharding_requested,
            "ident_sharded": plan.is_2d,
            "excluded_devices": sorted(self._excluded_devices),
        }

    # -- policyd-overload: admission control + watchdog ----------------
    def set_admission(self, on: bool) -> None:
        """Toggle the AdmissionControl runtime option. Off (default)
        keeps the submit path at ONE attribute read per batch
        (``self._admission is None``) — the exact pre-option programs;
        on installs the AIMD gate bounded by pipeline_max_depth and
        keyed on the boot verdict deadline."""
        if on:
            if self._admission is None:
                self._admission = AdmissionController(
                    max_depth=max(
                        self.pipeline_depth, self.pipeline_max_depth
                    ),
                    deadline_ms=self.deadline_ms,
                )
                # a journal armed before the controller existed still
                # sees its shed episodes
                self._admission.on_journal = self.on_journal
        else:
            self._admission = None

    def set_prefilter_shed(self, on: bool) -> None:
        """Toggle the Prefilter runtime option: whether rebuild()
        compiles + publishes the coarse [identity, class] shed table.
        The next rebuild's single _dp_state publish makes the change
        dispatch-visible; off publishes None and the shed kernels never
        trace."""
        self._shed_requested = bool(on)

    def set_stall_ms(self, stall_ms: float) -> None:
        """(Re)arm the stuck-dispatch watchdog; 0 stops it."""
        wd = self._watchdog
        if wd is not None:
            wd.stop()
            self._watchdog = None
        if stall_ms and stall_ms > 0:
            self._watchdog = Watchdog(self, float(stall_ms))
            self._watchdog.start()

    def admission_state(self) -> Dict:
        """Overload snapshot for GET /healthz, GET /traces, and the CLI
        traces header: gate limit + shed accounting, queue depth, and
        the watchdog's last stall."""
        adm = self._admission
        wd = self._watchdog
        out: Dict = {
            "enabled": adm is not None,
            "prefilter": self._shed_requested,
            "queue_depth": len(self._inflight),
            "deadline_ms": self.deadline_ms,
        }
        if adm is not None:
            out.update(adm.snapshot())
        else:
            out["shed_ratio"] = 0.0
        out["watchdog"] = wd.snapshot() if wd is not None else None
        return out

    # -- policyd-prof: device-time sampling profiler -------------------
    def set_profiling(
        self, on: bool, *, sample_every: Optional[int] = None
    ) -> None:
        """Toggle the DeviceProfiling runtime option. Off (default)
        keeps both dispatch halves at ONE attribute read per batch
        (``self.profiler is None``) — the exact pre-option programs;
        on installs a fresh DeviceProfiler whose every
        ``sample_every``-th batch pays the block_until_ready
        sandwiches that decompose dispatch RTT (observe/profiler.py)."""
        if sample_every is not None:
            self.profile_sample_every = max(1, int(sample_every))
        if not on:
            self.profiler = None
            return
        if self.profiler is None:
            from ..observe.profiler import DeviceProfiler

            self.profiler = DeviceProfiler(
                sample_every=self.profile_sample_every
            )
        elif self.profiler.sample_every != self.profile_sample_every:
            # re-enable with a new rate retunes the live instance (the
            # ring and ledgers are kept — only the cadence moves)
            self.profiler.sample_every = self.profile_sample_every

    def profile_state(self) -> Dict:
        """Profiler snapshot for GET /profile and ``cilium-tpu top``
        (enabled flag + samples/aggregates/jit-cost ledger when on)."""
        prof = self.profiler
        if prof is None:
            return {
                "enabled": False,
                "sample_every": self.profile_sample_every,
            }
        return prof.snapshot()

    def _shed_walk(
        self, peer_bytes: np.ndarray, dports, protos, *, family: int
    ) -> Optional[np.ndarray]:
        """[B] bool deny-for-sure mask from the published shed table
        (one device gather + the LPM identity walk), or None when no
        table is live (Prefilter off, pre-first-rebuild, host-mode
        ladder). Reflects the policy as of the LAST rebuild — the same
        one-batch staleness window every in-flight dispatch has. The
        jit keys on the raw batch shape (no bucketing): the gate is
        only reached over budget, where a recompile-per-new-size is
        noise next to the queue it is shedding."""
        state = self._dp_state
        shed_tab = state[7]
        if shed_tab is None:
            return None
        t = state[0].get((TRAFFIC_INGRESS, family))
        if t is None:
            return None
        dp = jnp.asarray(np.asarray(dports, np.int32))
        pr = jnp.asarray(np.asarray(protos, np.int32))
        if family == 4:
            peer_u32 = _pack_v4_u32(np.asarray(peer_bytes, np.int32))
            mask = shed_flows_wide(t, shed_tab, jnp.asarray(peer_u32), dp, pr)
        else:
            mask = shed_flows(
                t, shed_tab, jnp.asarray(np.asarray(peer_bytes, np.int32)),
                dp, pr, levels=16,
            )
        # intended host boundary: the gate partitions the batch on the
        # host, so the [B] bool mask is pulled once per shed decision —
        # the same one-batched-pull contract the verdict path carries
        return np.asarray(mask)  # policyd-lint: disable=TPU001

    def _resolve_at_gate(
        self,
        peer_bytes: np.ndarray,
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        idx: np.ndarray,
        *,
        verdict_code: int,
        ingress: bool,
        family: int,
    ) -> None:
        """Account + emit for flows resolved AT the admission gate
        (shed or deadline-degraded) — the same per-endpoint counters,
        verdicts_total series, drop-reason series, and DropNotify
        events the device path would have produced, so a shed flow is
        observable everywhere a dropped one is (never a silent drop)."""
        if idx.size == 0:
            return
        v = np.full(idx.size, verdict_code, np.int8)
        with self._lock:
            if self.counters.shape[0] == max(1, len(self._endpoints)):
                cls = 0 if verdict_code == FORWARD else 2
                np.add.at(self.counters, (ep_idx[idx], cls), 1)
        if verdict_code == DROP_PREFILTER:
            # reason 144 has two producers; this is the host admission
            # gate, not the device prefilter kernel (observe/README.md)
            _metrics.drop_reasons_total.inc(
                {"reason": "prefilter", "producer": "admission"},
                float(idx.size),
            )
        elif verdict_code == DROP_DEGRADED:
            _metrics.drop_reasons_total.inc(
                {"reason": "pipeline-degraded"}, float(idx.size)
            )
        self._account_batch(v)
        self._emit_flow_events(
            peer_bytes[idx], ep_idx[idx], dports[idx], protos[idx], v,
            ingress=ingress, family=family, producer="admission",
        )

    def _admission_gate(
        self,
        peer_bytes: np.ndarray,
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        sports: Optional[np.ndarray],
        *,
        ingress: bool,
        family: int,
        peer_words,
        want_rev_nat: bool,
        tunnel_identities,
    ) -> Optional[PendingBatch]:
        """The over-budget path of the admission gate. Returns None
        when the batch is admitted UNCHANGED (the caller proceeds down
        the exact ungated submit path), else a fully- or
        partially-resolved PendingBatch:

        1. deny-for-sure flows (shed-table match) resolve NOW with
           DROP_PREFILTER (monitor reason 144) — no queue, no device
           round-trip beyond the one cheap gather;
        2. the remainder DEFERS bounded: this thread drains its own
           in-flight queue until the gate opens or the deadline budget
           is spent (an empty queue always admits — nothing left to
           wait on);
        3. a spent deadline resolves the remainder through the
           failsafe semantics — FORWARD under FailOpen, else
           DROP_DEGRADED (155). Never an unbounded queue, never a
           silent drop."""
        adm = self._admission
        t_gate = time.monotonic()
        forced = False
        if _faults.hub.active:
            try:
                _faults.hub.check(_faults.SITE_QUEUE_FULL)
            except _faults.FaultError:
                # an overload signal, not a device fault: halve the
                # limit and force THIS batch through the shed path (the
                # breaker/ladder stays out of it — shedding load must
                # not also degrade the mesh)
                adm.note_queue_full()
                forced = True
        depth = len(self._inflight)
        _metrics.admission_queue_depth.set(float(depth))
        if not forced and not adm.over_budget(depth):
            adm.note_admitted(peer_bytes.shape[0])
            return None
        b = peer_bytes.shape[0]
        ep_idx = np.asarray(ep_idx, np.int32)
        dports = np.asarray(dports, np.int32)
        protos = np.asarray(protos, np.int32)
        # 1) prefilter shed — ingress only (the table is compiled from
        # the ingress policymaps, like the device pf stage) and never
        # for overlay flows whose tunnel identity overrides the LPM row
        shed_mask = None
        if ingress and tunnel_identities is None and b:
            try:
                shed_mask = self._shed_walk(
                    peer_bytes, dports, protos, family=family
                )
            except BaseException as e:
                kind = _faults.classify(e)
                if kind == _faults.KIND_ERROR:
                    raise
                # the shed walk is an optimization: a faulted gather
                # must never fail the submission itself
                self._note_fault(e, kind)
                shed_mask = None
        if shed_mask is not None and shed_mask.any():
            shed_idx = np.nonzero(shed_mask)[0]
            keep_idx = np.nonzero(~shed_mask)[0]
            self._resolve_at_gate(
                peer_bytes, ep_idx, dports, protos, shed_idx,
                verdict_code=DROP_PREFILTER, ingress=ingress, family=family,
            )
            adm.note_shed(REASON_SHED_PREFILTER, int(shed_idx.size))
        else:
            shed_idx = np.empty(0, np.int64)
            keep_idx = np.arange(b)
        # 2) bounded deferral for the remainder
        admitted = keep_idx.size > 0
        if admitted:
            budget_s = adm.deadline_s or None
            while adm.over_budget(len(self._inflight)):
                if (
                    budget_s is not None
                    and time.monotonic() - t_gate >= budget_s
                ):
                    admitted = False
                    break
                if not self._complete_oldest():
                    break
        _metrics.queue_wait_seconds.observe(time.monotonic() - t_gate)
        if keep_idx.size == 0:
            # whole batch shed: resolved handle, nothing ever queued
            pending = PendingBatch(self)
            v = np.full(b, DROP_PREFILTER, np.int8)
            red = np.zeros(b, bool)
            pending._value = (
                (v, red, np.zeros(b, np.uint16)) if want_rev_nat
                else (v, red)
            )
            pending._event.set()
            return pending
        if not admitted:
            # 3) deadline spent: failsafe resolution for the remainder
            code = FORWARD if self._fail_open else DROP_DEGRADED
            self._resolve_at_gate(
                peer_bytes, ep_idx, dports, protos, keep_idx,
                verdict_code=code, ingress=ingress, family=family,
            )
            adm.note_shed(REASON_SHED_DEADLINE, int(keep_idx.size))
            v = np.empty(b, np.int8)
            v[shed_idx] = DROP_PREFILTER
            v[keep_idx] = code
            red = np.zeros(b, bool)
            pending = PendingBatch(self)
            pending._value = (
                (v, red, np.zeros(b, np.uint16)) if want_rev_nat
                else (v, red)
            )
            pending._event.set()
            return pending
        adm.note_admitted(int(keep_idx.size))
        if keep_idx.size == b:
            # nothing shed and the gate opened: the caller proceeds
            # down the UNCHANGED submit path (bit-identical programs)
            return None
        inner = self._submit(
            peer_bytes[keep_idx], ep_idx[keep_idx], dports[keep_idx],
            protos[keep_idx],
            None if sports is None else np.asarray(sports)[keep_idx],
            ingress=ingress, family=family,
            peer_words=(
                None if peer_words is None
                else (peer_words[0][keep_idx], peer_words[1][keep_idx])
            ),
            want_rev_nat=want_rev_nat,
            tunnel_identities=None,
            gate=False,
        )
        shed_v = np.zeros(b, np.int8)
        shed_v[shed_idx] = DROP_PREFILTER
        return _GatedPending(self, inner, keep_idx, shed_v, b, want_rev_nat)

    def _set_level(self, level: int) -> None:
        """Move the degradation ladder (descent on a tripped breaker,
        re-promotion probe on a clean streak). Clears placed tables and
        the shape/warm caches — the next rebuild re-forms the mesh over
        the healthy device set and re-places tables through the
        identity-cached placement, exactly like a sharding toggle."""
        with self._lock:
            cur = self._ladder_level
            level = max(0, min(len(_MODE_NAMES) - 1, int(level)))
            if level == cur:
                return
            frm, to = _MODE_NAMES[cur], _MODE_NAMES[level]
            self._ladder_level = level
            if level == 0:
                # full re-promotion: all devices eligible again
                self._excluded_devices.clear()
            elif cur == 0:
                # sharded → single-device: keep ONE healthy device.
                # Which chip faulted is not attributable host-side (the
                # pull fails for the whole mesh program), so keep the
                # first and exclude the rest — the recovery probe
                # re-admits them after a clean streak. The excluded set
                # derives from the ACTIVE plan's device ids, not
                # jax.devices(): a placement-restricted daemon must
                # never demote onto a device it was configured not to
                # use (the plan's first device stays; everything else
                # the plan was using leaves the mesh).
                plan_ids = self._plan.device_ids or tuple(
                    d.id for d in jax.devices()
                )
                self._excluded_devices.update(plan_ids[1:])
            self._tables = {}
            self._tries = None
            self._placed_pm.clear()
            self._placed_rt.clear()
            self._placed_sel = (0, None, None)
            self._breaker_faults = 0
            self._clean_batches = 0
            # a ladder move re-forms the mesh: a shadow generation
            # built for the old device set must never install
            self._swap_gen += 1
        self._seen_shapes.clear()
        self._warm_buckets.clear()
        _metrics.degradations_total.inc({"from": frm, "to": to})
        _metrics.pipeline_mode.set(float(level))
        oj = self.on_journal
        if oj is not None:
            oj(
                kind="ladder_move",
                severity="warning" if level > cur else "info",
                attrs={"from": frm, "to": to, "level": level},
            )

    def _note_fault(self, exc: BaseException, kind: str) -> None:
        """Account one classified fault and trip the breaker when due.
        Injected FaultErrors were already counted at the injection site
        (faults.hub.check) — only real errors add a metric here."""
        if not isinstance(exc, _faults.FaultError):
            _metrics.pipeline_faults_total.inc(
                {"site": getattr(exc, "site", "pipeline"), "kind": kind}
            )
        with self._lock:
            self._clean_batches = 0
            self._breaker_faults += 1
            trip = self._breaker_faults >= self.breaker_threshold
            lvl = self._ladder_level
        if trip and lvl < len(_MODE_NAMES) - 1:
            self._set_level(lvl + 1)

    def _note_clean_batch(self) -> None:
        """One healthy completion: clear the breaker after a short
        streak; at a degraded level a long-enough streak is the
        recovery probe — re-promote ONE level and keep watching."""
        if self._ladder_level == 0 and self._breaker_faults == 0:
            return  # steady state: one int read, no lock
        with self._lock:
            self._clean_batches += 1
            if self._clean_batches >= self.breaker_threshold:
                self._breaker_faults = 0
            lvl = self._ladder_level
            promote = lvl > 0 and self._clean_batches >= self.recover_after_clean
        if promote:
            self._set_level(lvl - 1)

    def _degraded_result(self, inf: "_InFlight"):
        """Shape-correct result for an unresolvable batch. NEVER an
        exception: every submitted flow gets a verdict (verdicts_lost
        stays 0) — FORWARD under FailOpen, DROP_DEGRADED (monitor
        reason 155) fail-closed. Flow tuples are no longer reachable
        (they live in the abandoned closure), so per-endpoint counters
        and DropNotify events are skipped; the batch still lands in
        verdicts_total{dropped_degraded} and drop_reasons_total."""
        b = max(0, inf.b)
        if self._fail_open:
            v = np.full(b, FORWARD, np.int8)
        else:
            v = np.full(b, DROP_DEGRADED, np.int8)
            if b:
                _metrics.drop_reasons_total.inc(
                    {"reason": "pipeline-degraded"}, float(b)
                )
        self._account_batch(v)
        red = np.zeros(b, bool)
        if inf.rev:
            return v, red, np.zeros(b, np.uint16)
        return v, red

    def _quarantine(self, inf: "_InFlight"):
        """Give up on a poisoned batch: advance the CT epoch under the
        lock so any sibling completing after us cannot create CT
        entries verdicted under the possibly-poisoned basis, drop the
        device-CT state, and resolve the handle with a degraded RESULT.
        The batch's pinned staging buffers are abandoned (NOT returned
        to the free-list — the wedged device program may still read
        them; the pool only ever re-issues buffers it owns, so the
        free-lists stay consistent and the GC reclaims the orphans once
        the program dies)."""
        with self._lock:
            dct = self._device_ct
            self._ct_epoch += 1
            ct_epoch = self._ct_epoch
            self._device_ct = None
            self._quarantined += 1
            quarantined = self._quarantined
            # the epoch the shadow bound to may be the poisoned one —
            # a swap mid-quarantine must not resurrect it
            self._swap_gen += 1
        # policyd-survive: before the zeroed device-CT is forgotten,
        # best-effort pull its established entries into the host table
        # (outside the lock — the pull can be slow or fail outright on
        # a quarantined device)
        rescue = None
        if dct is not None and self.conntrack is not None:
            rescue = self._rescue_device_ct(dct)
        oj = self.on_journal
        if oj is not None:
            oj(kind="quarantine", severity="error", attrs={
                "ct_epoch": ct_epoch,
                "quarantined": quarantined,
                "ct_rescue": "skipped" if rescue is None else rescue,
            })
        return self._degraded_result(inf)

    def _rescue_device_ct(self, state) -> Optional[Dict]:
        """Quarantine CT rescue (policyd-survive): pull the live
        device-CT entries into the host FlowConntrack so degraded/
        host-mode keeps serving established flows, and mark the next
        fresh device table to seed from the host CT (the re-upload half
        — re-promotion must not forget the flows a second time).

        Bounded (device_ct_rescue_limit) and classified: the device is
        the very thing being quarantined, so ANY failure — including an
        injected fault at the completion-pull site — means "rescue
        skipped, cold" (returns None), never a second escalation.
        Programmer errors still surface raw. Returns the
        {kept, expired} outcome for the quarantine journal event."""
        from .device_ct import pull_live_entries

        try:
            if _faults.hub.active:
                _faults.hub.check(_faults.SITE_COMPLETE)
            pulled = pull_live_entries(
                state, int(time.monotonic()),
                limit=self.device_ct_rescue_limit,
            )
            kept, expired = self.conntrack.restore_arrays(
                pulled["ka"], pulled["kb"], pulled["kc"], pulled["ttl"]
            )
        except BaseException as e:
            if _faults.classify(e) == _faults.KIND_ERROR:
                raise
            return None  # rescue skipped — quarantine proceeds cold
        if kept:
            _metrics.ct_restored_entries_total.inc(
                {"result": "kept"}, float(kept)
            )
            with self._lock:  # published to _process_device_ct readers
                self._device_ct_seed = True
        if expired:
            _metrics.ct_restored_entries_total.inc(
                {"result": "expired"}, float(expired)
            )
        return {"kept": int(kept), "expired": int(expired)}

    def _seed_device_ct(self):
        """Fresh device-CT state pre-populated from the host table (the
        re-upload half of the quarantine rescue; caller holds
        self._lock). Falls back to a zeros table on any classified
        failure — seeding is an optimization, never a correctness
        dependency."""
        from .device_ct import make_state, seed_state_from_host

        try:
            snap = self.conntrack.snapshot_arrays()
            return seed_state_from_host(
                snap["ka"], snap["kb"], snap["kc"], snap["ttl"],
                self._device_ct_bits, int(time.monotonic()),
                limit=self.device_ct_rescue_limit,
            )
        except BaseException as e:
            if _faults.classify(e) == _faults.KIND_ERROR:
                raise
            return make_state(self._device_ct_bits)

    def _finish_guarded(self, inf: "_InFlight"):
        """Run a batch's finish closure with classified error handling:

        - transient → bounded retry (retry_limit attempts, fresh
          Backoff sleeps). Sound because the closure's externally
          visible mutations (counters, CT create, events) all happen
          AFTER the host pull — the only device interaction that can
          fail transiently — so re-running from the top cannot
          double-account.
        - poisoned (or retries exhausted) → quarantine: degraded
          result, CT-epoch rollback, FIFO order preserved.
        - error (programmer/control) → returned as the exception for
          the caller to surface raw through PendingBatch.result(),
          exactly the pre-failsafe contract.

        Returns (value, exc) — exactly one is non-None."""
        attempt = 0
        bo: Optional[Backoff] = None
        while True:
            try:
                out = inf.finish()
            except BaseException as e:
                kind = _faults.classify(e)
                if kind == _faults.KIND_ERROR:
                    return None, e
                self._note_fault(e, kind)
                if (
                    kind == _faults.KIND_TRANSIENT
                    and attempt < self.retry_limit
                ):
                    attempt += 1
                    if bo is None:
                        bo = Backoff(
                            min_s=self.retry_min_s, max_s=self.retry_max_s,
                            jitter=False,
                        )
                    time.sleep(bo.duration())
                    continue
                return self._quarantine(inf), None
            self._note_clean_batch()
            return out, None

    # ------------------------------------------------------------------
    def rebuild(self, force: bool = False) -> Dict[Tuple[int, int], DatapathTables]:
        """Bring device state up to date. Incremental where possible:

        - identity churn ("rows" engine deltas) → policymap row patches
          in BOTH directions (n_seg × k verdicts instead of full sweeps)
        - rule appends / full recompiles → warm re-materialization
        - ipcache/prefilter moves → trie rebuild only (policymap kept)

        Returns {(direction, family): DatapathTables}.
        """
        oj = self.on_journal
        prev_basis = self._mat_basis if oj is not None else None
        tables = self._rebuild_locked(force)
        # served-basis move → one journal event, AFTER the lock is
        # released (the journal must never extend the rebuild critical
        # section the dispatch path competes with)
        if oj is not None and self._mat_basis != prev_basis:
            basis = self._mat_basis
            oj(kind="rebuild", attrs={
                "prev_basis": None if prev_basis is None else list(prev_basis),
                "basis": None if basis is None else list(basis),
                "policy_epoch": self._policy_epoch,
                "generation": self._plan.generation,
            })
        return tables

    def _rebuild_locked(
        self, force: bool = False
    ) -> Dict[Tuple[int, int], DatapathTables]:
        with self._lock:
            self._refresh_mesh_locked()
            # Capture versions BEFORE reading the sources: a concurrent
            # mutation mid-build then triggers one extra rebuild rather
            # than being silently marked materialized.
            trie_versions = (self.ipcache.version, self.prefilter.revision)
            delta_target = self.engine.delta_seq
            compiled, device = self.engine.snapshot()
            # one delta fetch per rebuild, shared by the placed-copy
            # patcher and the materialized-state router — both replay
            # FINAL-state values, so re-application across rebuilds
            # (e.g. a non-advancing cursor under a pending epoch swap)
            # is idempotent
            pending_deltas = self.engine.deltas_since(self._last_delta_seq)
            # 2D plan: the materializer sweeps/patches read an ident-
            # sharded sel_match (generation-cached; the engine's own
            # copy is untouched)
            device = self._ident_placed_device(device, pending_deltas)
            delta_target = max(delta_target, self.engine.delta_seq)
            ep_sig = tuple(self._endpoints)
            # captured before the trie block updates _trie_versions;
            # feeds the conntrack invalidation below
            basis_moved = trie_versions != self._trie_versions

            mat_fresh = False
            saw_row_event = False
            saw_rule_delta = False
            swap_pending = False
            if force or not self._mat or self._mat_sig != ep_sig:
                self._materialize_both(compiled, device)
                mat_fresh = True
            else:
                routed = self._route_deltas(compiled, device, pending_deltas)
                if routed is None:
                    # full rebuild needed (log truncation, a "full"
                    # recompile event, or a rule delta the column patch
                    # cannot express). With EpochSwap on, build it on
                    # the shadow thread and KEEP SERVING the current
                    # generation — the install advances the delta
                    # cursor itself, so nothing here commits.
                    if self._epoch_swap and self._kick_shadow_build(
                        compiled, device, ep_sig, delta_target
                    ):
                        swap_pending = True
                    else:
                        # warm jit, shape-bucketed — the fast full path
                        self._materialize_both(compiled, device)
                        mat_fresh = True
                else:
                    saw_row_event, saw_rule_delta = routed
            if not swap_pending:
                self._mat_sig = ep_sig
                self._last_delta_seq = delta_target

            # policyd-sparse: when the ONLY trie trigger is ipcache
            # churn (prefilter untouched, row basis stable), patch the
            # placed trie tensors in place from the ipcache delta ring
            # instead of rebuilding — O(delta) node rows / dense spans
            # uploaded. Success commits _trie_versions, so the full
            # rebuild below sees a clean basis and skips; any failure
            # (ring truncation, pool exhaustion, live deny trie,
            # elision violation) leaves the versions stale and falls
            # through to the classic rebuild.
            if (
                self._sparse_deltas
                and self._trie_patch is not None
                and not force
                and not mat_fresh
                and not saw_row_event
                and self._tries is not None
                and self._tables
                and len(self._trie_versions) == 2
                and trie_versions != self._trie_versions
                and trie_versions[1] == self._trie_versions[1]
            ):
                self._patch_tries_locked(compiled, trie_versions)

            # Tries: rebuilt when their sources move, when the row basis
            # was re-established, or when any row event could have
            # changed an ipcache row mapping.
            if (
                force
                or self._tries is None
                or trie_versions != self._trie_versions
                or mat_fresh
                or saw_row_event  # any row move can re-point trie targets
                or not self._tables
            ):
                _, pf_cidrs = self.prefilter.dump()
                # empty-set flags first: both families' fusion gates
                # read them (an empty deny set skips the walk entirely)
                self._pf_empty = (
                    not any(":" not in c for c in pf_cidrs),
                    not any(":" in c for c in pf_cidrs),
                )
                # IPv6: stride-8 tries with the shared prefix elided
                # (pod allocations live under one /48-/64 — compare
                # those bytes once instead of walking them)
                pf6_list = [(c, 0) for c in pf_cidrs if ":" in c]
                ip6_list = [
                    (cidr, row)
                    for cidr, e in self.ipcache.items()
                    if ":" in cidr
                    and (row := compiled.id_to_row.get(e.identity))
                    is not None
                ]
                # policyd-sparse: with no live v6 deny trie, build the
                # identity trie through a patchable host mirror (pow2
                # node-pool headroom) so ipcache churn can patch it in
                # place. The OFF path — and any fused build — compiles
                # the exact classic layout.
                p6_patch = (
                    PatchableElidedTrie(ip6_list, ipv6=True)
                    if self._sparse_deltas and self._pf_empty[1]
                    else None
                )
                ip6 = (
                    p6_patch.arrays()
                    if p6_patch is not None
                    else build_trie_elided(ip6_list, ipv6=True)
                )
                # fused deny+identity v6 walk (one elided pass, both
                # answers) — built only while the deny stage is live
                merged6_list = (
                    merge_trie_entries(ip6_list, pf6_list, ipv6=True)
                    if not self._pf_empty[1]
                    else None
                )
                placeholder6 = (
                    np.zeros((1, 256), np.int32),
                    np.zeros((1, 256), np.int32),
                    np.zeros(0, np.int32),
                )
                if merged6_list is not None:
                    merged6 = build_trie_elided(merged6_list, ipv6=True)
                    self._v6_fused = True
                    # the fused trie fully covers the deny stage (same
                    # reasoning as the v4 pf_wide elision below): don't
                    # build/upload the standalone deny trie
                    pf6 = placeholder6
                else:
                    pf6 = build_trie_elided(pf6_list, ipv6=True)
                    merged6 = placeholder6
                    self._v6_fused = False
                # IPv4 rides the wide (dense-16-bit-first) tries
                pf_wide = build_wide_trie(
                    (c, 0) for c in pf_cidrs if ":" not in c
                )
                ip4_list = [
                    (cidr, row)
                    for cidr, e in self.ipcache.items()
                    if ":" not in cidr
                    and (row := compiled.id_to_row.get(e.identity)) is not None
                ]
                # v4 mirror only when the flat 16+16 layout holds (the
                # 16-8-8 pointer layout is not patched → None)
                p4_patch = (
                    make_patchable_wide(ip4_list)
                    if self._sparse_deltas and self._pf_empty[0]
                    else None
                )
                ip_wide = (
                    p4_patch.arrays()
                    if p4_patch is not None
                    else build_wide_trie(ip4_list)
                )
                # fused deny+identity walk: only worth building when
                # the deny stage is live and both layouts are flat
                merged = (
                    merge_flat_tries(ip_wide, pf_wide)
                    if not self._pf_empty[0]
                    else None
                )
                if merged is None:
                    merged = (
                        np.zeros(1, np.int32),
                        np.zeros(1, np.int32),
                        np.zeros((1, 1), np.int32),
                        np.zeros((1, 1), np.int32),
                    )
                else:
                    # the fused table fully covers the deny stage, so
                    # the standalone deny trie would never be read —
                    # don't upload it (placeholders keep the pytree
                    # shape-stable for the jit cache)
                    pf_wide = (
                        np.zeros(1, np.int32),
                        np.zeros(1, np.int32),
                        np.zeros((1, 1), np.int32),
                        np.zeros((1, 1), np.int32),
                    )
                world_row = compiled.id_to_row.get(ID_WORLD)
                if world_row is None:
                    raise RuntimeError("reserved:world identity has no device row")
                # sharding-aware upload (ops/lpm.py place_table):
                # tries are replicated across the verdict mesh — every
                # flow shard walks the whole trie. The device_put runs
                # under _lock BY DESIGN: rebuild() is the control
                # plane's table swap, and publishing a trie ref before
                # its device buffers exist would hand the verdict path
                # a half-placed table (EpochSwap is the stall-free
                # alternative; this is the non-shadow path).
                tsh = self._table_sharding
                self._tries = (
                    tuple(
                        place_table(a, tsh)  # policyd-lint: disable=LOCK002
                        for a in (*pf_wide, *ip_wide, *merged)
                    ),
                    tuple(place_table(a, tsh) for a in (*pf6, *ip6, *merged6)),  # policyd-lint: disable=LOCK002
                    place_table(np.int32(world_row), tsh),  # policyd-lint: disable=LOCK002
                )
                self._trie_versions = trie_versions
                self._trie_patch = (
                    {4: p4_patch, 6: p6_patch} if self._sparse_deltas else None
                )

            # Conntrack invalidation: established-flow bypass is only
            # sound while the verdict basis that admitted the flow still
            # holds. ANY basis move — policy re-materialization (rule
            # changes, endpoint set), identity row churn, ipcache remap,
            # prefilter revision — flushes the table, so revoked rules,
            # remapped peer IPs, and new deny prefixes apply to
            # established flows on their next packet (the reference
            # scrubs CT after regeneration / ipcache changes; we take
            # the conservative whole-table flush — one re-verdict per
            # flow is a single batched dispatch). Uses the versions
            # captured BEFORE the reads so a mutation landing mid-build
            # flushes again on the next rebuild rather than slipping by.
            # saw_rule_delta: a column patch is still a rule change —
            # a revoked rule must not keep admitting its established
            # flows just because the policymap was patched in place
            # rather than re-materialized.
            if mat_fresh or saw_row_event or saw_rule_delta or basis_moved:
                # policyd-survive restore hold (one-shot): on the first
                # rebuild after a verified CT restore, the fresh
                # materialization builds from the restored tables — the
                # basis that admitted the restored entries still holds,
                # so greeting it with the usual flush would cold-flush
                # exactly what restore just placed. Revision-pinned: a
                # policy mutation racing in before this rebuild bumps
                # the compiled revision and voids the hold. Consumed
                # below; every later trigger flushes as always.
                if self._ct_restore_hold != compiled.revision:
                    self._ct_flush_pending = True
            if self._ct_flush_pending:
                if _faults.hub.active:
                    # before the flush: a retried rebuild re-runs this
                    # whole block (pending stays set), so nothing is
                    # half-advanced
                    _faults.hub.check(_faults.SITE_CT_EPOCH)
                if self.conntrack is not None:
                    self.conntrack.flush()
                # a basis move while batches are in flight: their
                # completion halves must not create CT entries
                # verdicted under the old basis
                self._ct_epoch += 1
                self._device_ct = None  # zeroed on next use
                self._ct_flush_pending = False

            # LB tables: deterministic per-flow backend selection means
            # backend churn changes the translated CT key (natural
            # miss), but entries created while a flow was NOT
            # translated (pre-service, or post-delete) would bypass the
            # new service table — so any LB move also flushes CT.
            if self.lb is not None and self.lb.version != self._lb_version:
                lb_ver = self.lb.version
                self._lb_tables = self.lb.build_device()
                self._lb_version = lb_ver
                # restore hold covers this trigger too: restored
                # services come from the SAME state.json snapshot the
                # CT entries were saved with, so the restored entries
                # were translated under exactly these service tables
                if self._ct_restore_hold != compiled.revision:
                    if self.conntrack is not None:
                        self.conntrack.flush()
                    self._ct_epoch += 1
                    self._device_ct = None
            # the one-shot hold is spent once both flush triggers above
            # have seen it
            self._ct_restore_hold = None
            # Served-basis commit (policyd-survive): AFTER the flush
            # blocks above, so a concurrent CT-snapshot writer can
            # never pair surviving old-basis entries with the new
            # stamp. A pending shadow swap keeps serving the old
            # generation — its basis stays until the install's flush
            # publishes through here.
            if not swap_pending:
                self._mat_basis = (
                    compiled.revision, compiled.identity_version,
                    compiled.vocab_version,
                )

            assert self._tries is not None and self._mat
            v4, v6, world = self._tries
            # Build complete, then assign once: _dispatch reads
            # self._tables without the lock and must never observe a
            # partially-populated dict.
            tables: Dict[Tuple[int, int], object] = {}
            for direction, mat in self._mat.items():
                pm = self._replicated_policymap(direction, mat.tables)
                tables[(direction, 4)] = WideDatapathTables(
                    pf_root_info=v4[0],
                    pf_root_child=v4[1],
                    pf_sub_child=v4[2],
                    pf_sub_info=v4[3],
                    ip_root_info=v4[4],
                    ip_root_child=v4[5],
                    ip_sub_child=v4[6],
                    ip_sub_info=v4[7],
                    merged_root_info=v4[8],
                    merged_root_child=v4[9],
                    merged_sub_child=v4[10],
                    merged_sub_info=v4[11],
                    world_row=world,
                    policymap=pm,
                )
                tables[(direction, 6)] = DatapathTables(
                    pf_child=v6[0],
                    pf_info=v6[1],
                    pf_common=v6[2],
                    ip_child=v6[3],
                    ip_info=v6[4],
                    ip_common=v6[5],
                    merged_child=v6[6],
                    merged_info=v6[7],
                    merged_common=v6[8],
                    world_row=world,
                    policymap=pm,
                )
            self._tables = tables
            # flows-axis size, NOT total device count: bucket-ladder
            # rung rounding and chunk spans split over "flows" only
            # (1D: the two are equal; 2D: ndev = devices / ident)
            ndev = self._plan.flows_size
            # attribution element: present only when EVERY direction's
            # state carries a rule table (a race with a rule mutation
            # can leave one direction plain for a cycle — the racing
            # delta re-materializes on the next rebuild)
            attrib_el = None
            if self._attrib_requested:
                rtabs = {}
                for direction, mat in self._mat.items():
                    if mat.rule_tab is None:
                        rtabs = None
                        break
                    rtabs[direction] = self._replicated_rule_tab(
                        direction, mat.rule_tab
                    )
                if rtabs:
                    attrib_el = (rtabs, self._attrib_n_rules)
            # prefilter shed element (policyd-overload): the coarse
            # [identity, proto/port-class] deny-for-sure table, compiled
            # from the ingress policymap host mirror and placed with the
            # same table sharding as the tries so it rides the MeshPlan.
            # Cached across rebuilds that change neither the policymap
            # basis nor the placement; Prefilter off publishes None and
            # the shed kernels never trace.
            shed_el = None
            if self._shed_requested:
                mat_in = self._mat.get(TRAFFIC_INGRESS)
                if mat_in is not None:
                    gen = self._plan.generation
                    if (
                        self._shed_cache is None
                        or self._shed_cache[0] != gen
                        or mat_fresh
                        or saw_row_event
                        or saw_rule_delta
                    ):
                        shed_tab = compile_shed_table(
                            mat_in.allow_nc, mat_in.ep_slots
                        )
                        # placed under _lock by design: same publish-
                        # whole-tables invariant as the trie upload
                        self._shed_cache = (
                            gen,
                            place_table(shed_tab, self._table_sharding),  # policyd-lint: disable=LOCK002
                        )
                    shed_el = self._shed_cache[1]
            else:
                self._shed_cache = None
            self._dp_state = (
                tables, self._pf_empty, self._v6_fused,
                self._flow_sharding, ndev, attrib_el, self._plan.is_2d,
                shed_el,
            )
            # per-device table-bytes telemetry: under a 2D plan the
            # identity tables split by the ident factor (within the
            # last shard's padding); replicated/1D reports full bytes
            ident = self._plan.ident_size if self._plan.is_2d else 1
            pm_bytes = sum(
                int(np.prod(m.tables.id_bits.shape)) * 4
                for m in self._mat.values()
            )
            rt_bytes = sum(
                int(np.prod(m.rule_tab.shape)) * 4
                for m in self._mat.values()
                if m.rule_tab is not None
            )
            _metrics.sharded_table_bytes.set(
                float(pm_bytes // ident), {"family": "policymap"}
            )
            _metrics.sharded_table_bytes.set(
                float(rt_bytes // ident), {"family": "rule_tab"}
            )
            # policyd-prof memory ledger: every device-resident table
            # family under its placement (same per-device convention as
            # sharded_table_bytes; the tries are always replicated —
            # every flow shard walks the whole trie)
            ident_placement = (
                "ident-sharded" if self._plan.is_2d else "replicated"
            )
            _metrics.device_table_bytes.set(
                float(pm_bytes // ident),
                {"family": "policymap", "placement": ident_placement},
            )
            _metrics.device_table_bytes.set(
                float(rt_bytes // ident),
                {"family": "rule_tab", "placement": ident_placement},
            )
            sel = getattr(device, "sel_match", None)
            if sel is not None:
                _metrics.device_table_bytes.set(
                    float(int(getattr(sel, "nbytes", 0)) // ident),
                    {"family": "sel_match", "placement": ident_placement},
                )
            if self._tries is not None:
                trie_bytes = sum(
                    int(getattr(a, "nbytes", 0))
                    for leaves in self._tries[:2]
                    for a in leaves
                )
                _metrics.device_table_bytes.set(
                    float(trie_bytes),
                    {"family": "lpm_trie", "placement": "replicated"},
                )
            if self.counters.shape[0] != len(self._endpoints):
                self.counters = np.zeros((len(self._endpoints), 3), np.int64)
            return self._tables

    def _route_deltas(
        self, compiled, device, deltas
    ) -> Optional[Tuple[bool, bool]]:
        """Apply the engine delta log to the materialized state IN
        PLACE (the O(delta) refresh path). Held-lock helper for
        rebuild. Returns ``(saw_row_event, saw_rule_delta)`` on
        success, or None when the
        log demands a full re-materialization: a truncated ring, a
        "full" recompile event, or a rule delta the column patch cannot
        express (slot growth, row-bucket crossing, attribution deletes
        — every later rule's index shifts, so the per-cell rule table
        cannot be patched).

        Ordering note: row patches rewrite whole identity ROWS and
        column patches whole endpoint COLUMNS, and both sweep against
        the FINAL (compiled, device) snapshot — so replaying rows
        first and the coalesced rule-column union second lands every
        touched cell on its final value regardless of how the log
        interleaved them."""
        if deltas is None:
            return None
        if any(k == "full" for _, k, _ in deltas):
            return None
        if self._attrib_requested and any(
            k == "rules" and p and p[0] == "del" for _, k, p in deltas
        ):
            if any(m.rule_nc is not None for m in self._mat.values()):
                return None
        t0 = time.perf_counter()
        ao, nr = self._attrib_origins(compiled)
        saw_row_event = False
        touched_sids: set = set()
        row_events: list = []
        for _seq, kind, payload in deltas:
            if kind == "rows":
                # Coalesce across log entries, one patch per direction
                # below — the engine-side _set_rows2 discipline applied
                # at the pipeline layer. The stale-snapshot scan and
                # the verdict re-sweep are per-CALL costs, so a churny
                # tick (many row deltas between rebuilds) must replay
                # as one patch, not one per log entry; last event per
                # row wins inside patch_identity_rows, which preserves
                # log order.
                row_events.extend(payload)
                # Any row event (add OR release) can change what an
                # ipcache entry resolves to — e.g. a released id being
                # re-allocated onto a tombstoned row, or an add
                # resolving a previously-unmapped entry — so the tries
                # must follow every row move.
                saw_row_event |= bool(payload)
            elif kind == "cols":
                # (sel_lo, sel_hi, touched rows): sel_match column
                # scatter already applied by the engine (and replayed
                # onto the ident-placed copy by _ident_placed_device).
                # The materialized policymap consumes the selector
                # change through the PAIRED "rules" event's column
                # re-sweep, so there is nothing to route here.
                pass
            else:  # "rules": ("add"|"del", (subject_sid, ...))
                touched_sids.update(payload[1])
        if row_events:
            for direction, mat in self._mat.items():
                # patch the mesh-placed copies through the SAME scatter
                # (PlacedTables holder) so 2D/replicated placement
                # survives the O(delta) path without a re-place
                placed = self._placed_holder(direction, mat)
                patch_identity_rows(
                    mat, compiled, device, row_events,
                    attrib_origin=ao[direction == TRAFFIC_INGRESS],
                    n_rules=nr, placed=placed,
                )
                self._rekey_placed(direction, mat, placed)
        if touched_sids:
            for direction, mat in self._mat.items():
                placed = self._placed_holder(direction, mat)
                if not patch_endpoints_state(
                    mat, compiled, device, sorted(touched_sids),
                    attrib_origin=ao[direction == TRAFFIC_INGRESS],
                    n_rules=nr, placed=placed,
                ):
                    # partial patches are harmless: every cell they
                    # wrote already holds its final value, and the
                    # full rebuild replaces the state wholesale
                    return None
                self._rekey_placed(direction, mat, placed)
            # appends grow the rule set: keep the completion half's
            # rule-index → origin map in step with the patched tables
            if nr:
                self._attrib_n_rules = nr
                self._attrib_names = self.engine.repo.origin_names()
        if saw_row_event or touched_sids:
            _metrics.engine_refresh_seconds.observe(
                time.perf_counter() - t0, {"kind": "delta"}
            )
        return saw_row_event, bool(touched_sids)

    def _replicated_policymap(self, direction: int, pm: PolicymapTables):
        """Mesh-placed copy of one direction's policymap, cached on the
        source object AND the plan generation: row patches (which swap
        the arrays) and placement changes (ladder demotion/re-promotion,
        runtime 2D toggles) re-place, while steady-state rebuilds reuse
        the committed copy. Under a 2D plan the identity axis shards
        (shard_tables_ident); 1D replicates, exactly as before."""
        plan = self._plan
        if plan.table_sharding is None:
            return pm
        gen, src, placed = self._placed_pm.get(direction, (-1, None, None))
        if src is pm and gen == plan.generation:
            return placed
        # identity-cached: the callee's device_put fires only when a
        # rebuild swapped the policymap (same publish-whole-tables
        # invariant and same _lock as the trie upload in rebuild)
        if plan.is_2d:
            placed = shard_tables_ident(  # policyd-lint: disable=LOCK002
                pm, plan.ident_sharding, plan.table_sharding
            )
        else:
            placed = replicate_tables(pm, plan.table_sharding)  # policyd-lint: disable=LOCK002
        self._placed_pm[direction] = (plan.generation, pm, placed)
        return placed

    def _replicated_rule_tab(self, direction: int, rt):
        """Mesh-placed copy of one direction's attribution rule table —
        the _replicated_policymap pattern (generation-keyed). 1D keeps
        it whole on every device the flow shards land on; the 2D plan
        row-shards it like id_bits (the rule gather becomes the same
        ident-axis one-hot contraction)."""
        plan = self._plan
        if plan.table_sharding is None:
            return rt
        gen, src, placed = self._placed_rt.get(direction, (-1, None, None))
        if src is rt and gen == plan.generation:
            return placed
        # identity-cached: the transfer fires only when a rebuild
        # swapped the rule table (same cadence + same _lock as the
        # sibling _replicated_policymap's replicate_tables placement)
        sh = plan.ident_sharding if plan.is_2d else plan.table_sharding
        placed = jax.device_put(rt, sh)  # policyd-lint: disable=LOCK002
        self._placed_rt[direction] = (plan.generation, rt, placed)
        return placed

    def _ident_placed_device(self, device, deltas=None):
        """DevicePolicy view with sel_match re-placed under the 2D
        plan's ident sharding (generation-cached on the source array).
        Non-2D plans return the snapshot untouched. The engine's own
        device object is never mutated — the pipeline's sweeps just
        read through a sharded copy so the [N, S/32] selector-match
        matrix also stops replicating at scale.

        With SparseDeltas on, a source change whose gap is covered by
        the engine delta log (``deltas``) PATCHES the cached placed
        copy — O(delta) row/column scatters that preserve the ident
        sharding (GSPMD propagates the operand's sharding through
        ``.at[].set``) — instead of re-placing the full matrix; the
        placed jit caches survive because the placement never moves."""
        plan = self._plan
        if not plan.is_2d:
            return device
        gen, src, placed = self._placed_sel
        if src is not device.sel_match or gen != plan.generation:
            patched = (
                self._patch_placed_sel(device, deltas)
                if self._sparse_deltas
                else None
            )
            if patched is None:
                placed = jax.device_put(  # policyd-lint: disable=LOCK002
                    device.sel_match, plan.ident_sharding
                )
            else:
                placed = patched
            self._placed_sel = (plan.generation, device.sel_match, placed)
        return device.replace(sel_match=placed)

    def _patch_placed_sel(self, device, deltas):
        """Replay the delta window onto the cached ident-placed
        sel_match copy (policyd-sparse). Returns the patched placed
        array, or None when the gap is not patchable — no cached copy,
        plan generation moved, truncated/absent log, a "full" recompile
        in the window, a shape move (row bucket or selector word
        growth), or a mirror-bounds miss — and the caller re-places
        wholesale. Values are FINAL-state reads from the engine's host
        mirror (sel_match_rows), so replay is idempotent and ordering
        against concurrent engine mutation self-heals on the next
        rebuild, exactly like the in-place compiled snapshot."""
        plan = self._plan
        gen, _src, placed = self._placed_sel
        if placed is None or gen != plan.generation:
            return None
        if not deltas:  # None (truncated) or an un-logged source move
            return None
        if getattr(placed, "shape", None) != device.sel_match.shape:
            return None
        row_set: set = set()
        col_events: list = []
        for _seq, kind, payload in deltas:
            if kind == "rows":
                row_set.update(int(r) for r, _ident, _live in payload)
            elif kind == "cols":
                col_events.append(payload)
            elif kind != "rules":  # "full" (or unknown): re-place
                return None
        if not row_set and not col_events:
            # source object moved with no sel_match event in the
            # window — the gap is not explained by the log; re-place
            return None
        nbytes = 0
        nscat = 0
        if row_set:
            rows = sorted(row_set)
            vals = self.engine.sel_match_rows(rows)
            if vals is None or vals.shape[1] != placed.shape[1]:
                return None
            placed = patch_selector_rows(placed, rows, vals)
            nbytes += len(rows) * 4 + int(vals.nbytes)
            nscat += 1
        for sel_lo, sel_hi, touched in col_events:
            # rows already rewritten whole by the row patch above carry
            # their final column bits — skip them here
            rows = [int(r) for r in touched if int(r) not in row_set]
            if not rows:
                continue
            words = selector_word_window(int(sel_lo), int(sel_hi))
            if words.size == 0 or int(words.max()) >= placed.shape[1]:
                return None
            vals = self.engine.sel_match_rows(rows, words)
            if vals is None:
                return None
            placed = patch_selector_cols(placed, rows, words, vals)
            nbytes += len(rows) * 4 + int(vals.nbytes) + int(words.nbytes)
            nscat += 1
        if nscat:
            # transfer-ledger attribution for the column/row patches:
            # O(k) logical bytes where the dense re-place moved the
            # full [N, S/32] matrix (control-plane cadence, counted
            # unconditionally — rebuilds are rare and the delta is the
            # number the stretch bench diffs)
            _metrics.device_transfer_bytes_total.inc(
                {"direction": "h2d"}, float(nbytes)
            )
            _metrics.device_transfers_total.inc(
                {"direction": "h2d"}, float(nscat)
            )
        return placed

    def _patch_tries_locked(self, compiled, trie_versions) -> bool:
        """Apply the ipcache delta window to the placed identity-trie
        tensors in place (policyd-sparse). On success commits
        ``_trie_versions`` (the full-rebuild trigger then sees a clean
        basis) and returns True; any non-patchable condition — ring
        truncation, a live deny trie for a touched family, an
        unsupported layout, pool exhaustion, an elision violation, a
        device/mirror shape mismatch — returns False with the versions
        left stale, and the classic full rebuild runs. Host mirrors
        mutated before a mid-window failure are discarded by that
        rebuild, so partial application never leaks."""
        deltas = self.ipcache.deltas_since(self._trie_versions[0])
        if not deltas:  # None (truncated) or un-logged version move
            return False
        patch = self._trie_patch or {}
        ops = []  # staged (mirror, family, cidr, row|None)
        for _ver, cidr, _old_ident, new_ident in deltas:
            fam = 6 if ":" in cidr else 4
            if not self._pf_empty[0 if fam == 4 else 1]:
                # the fused deny+identity trie is live for this family;
                # it is never patched — rebuild keeps it coherent
                return False
            mirror = patch.get(fam)
            if mirror is None:
                return False  # unsupported layout (16-8-8 wide v4)
            row = (
                compiled.id_to_row.get(new_ident)
                if new_ident is not None
                else None
            )
            # identity without a device row == absent from the trie
            ops.append((mirror, cidr, row))
        napplied = {4: 0, 6: 0}
        for mirror, cidr, row in ops:
            ok = (
                mirror.insert(cidr, row)
                if row is not None
                else mirror.delete(cidr)
            )
            if not ok:
                return False
            napplied[6 if ":" in cidr else 4] += 1
        v4, v6, world = self._tries
        nbytes = 0
        p4 = patch.get(4)
        if p4 is not None and p4.dirty:
            out = p4.flush(v4[4], v4[5], v4[6], v4[7])
            if out is None:
                return False
            (ri, rc, sc, si), nb = out
            v4 = (*v4[:4], ri, rc, sc, si, *v4[8:])
            nbytes += nb
        p6 = patch.get(6)
        if p6 is not None and p6.dirty:
            out = p6.flush(v6[3], v6[4])
            if out is None:
                return False
            (child, info), nb = out
            v6 = (*v6[:3], child, info, v6[5], *v6[6:])
            nbytes += nb
        self._tries = (v4, v6, world)
        self._trie_versions = trie_versions
        for fam in (4, 6):
            if napplied[fam]:
                _metrics.lpm_trie_patches_total.inc(
                    {"family": str(fam)}, float(napplied[fam])
                )
        if nbytes:
            _metrics.device_transfer_bytes_total.inc(
                {"direction": "h2d"}, float(nbytes)
            )
            _metrics.device_transfers_total.inc({"direction": "h2d"}, 1.0)
        return True

    def _placed_holder(self, direction: int, mat) -> Optional[PlacedTables]:
        """PlacedTables view of the direction's CURRENT placed-table
        cache entries, for the O(delta) patch paths to scatter into.
        None when nothing valid is cached (unplaced pipeline, source
        swap, or plan-generation move) — the next rebuild re-places
        wholesale instead."""
        plan = self._plan
        if plan.table_sharding is None:
            return None
        gen, src, ppm = self._placed_pm.get(direction, (-1, None, None))
        if src is not mat.tables or gen != plan.generation:
            return None
        holder = PlacedTables(tables=ppm)
        rgen, rsrc, prt = self._placed_rt.get(direction, (-1, None, None))
        if (
            mat.rule_tab is not None
            and rsrc is mat.rule_tab
            and rgen == plan.generation
        ):
            holder.rule_tab = prt
        return holder

    def _rekey_placed(self, direction: int, mat, holder) -> None:
        """Re-key the placed caches after an in-place patch: the patch
        swapped both the host-materialized arrays AND the placed copies
        (same scatter), so the cache entries move to the new source
        objects without any re-place transfer."""
        if holder is None:
            return
        plan = self._plan
        self._placed_pm[direction] = (
            plan.generation, mat.tables, holder.tables
        )
        if holder.rule_tab is not None and mat.rule_tab is not None:
            self._placed_rt[direction] = (
                plan.generation, mat.rule_tab, holder.rule_tab
            )

    def _attrib_origins(self, compiled):
        """({ingress_bool: AttribTables|None}, n_rules) for the current
        rebuild — all-None when attribution is off, the engine carries
        no compile state (snapshot-restored), or a rule mutation raced
        the (compiled, device) snapshot (the racing delta forces
        re-materialization on the next rebuild, which self-heals)."""
        off = {True: None, False: None}
        if not self._attrib_requested:
            return off, 0
        ai = self.engine.attribution(True, expect_revision=compiled.revision)
        ae = self.engine.attribution(False, expect_revision=compiled.revision)
        if ai is None or ae is None:
            return off, 0
        return {True: ai[0], False: ae[0]}, ai[1]

    def _materialize_both(self, compiled, device) -> None:
        ao, nr = self._attrib_origins(compiled)
        self._attrib_n_rules = nr
        self._attrib_names = (
            self.engine.repo.origin_names() if nr else []
        )
        # a full sweep is the slowest thing rebuild() can do — with the
        # watchdog armed, register it so a wedged device compile shows
        # up as a classified stall instead of a silent hang
        wd = self._watchdog
        if wd is not None:
            with wd.watching("compile"):
                self._mat = self._build_mats(
                    compiled, device, self._endpoints, ao, nr
                )
        else:
            self._mat = self._build_mats(
                compiled, device, self._endpoints, ao, nr
            )

    @staticmethod
    def _build_mats(compiled, device, endpoints, ao, nr):
        """Both directions' full sweeps from one frozen (compiled,
        device) snapshot. Static and self-free on purpose: the
        epoch-swap shadow thread runs this OFF the pipeline lock, so
        it must not read mutable pipeline state."""
        return {
            TRAFFIC_INGRESS: materialize_endpoints_state(
                compiled, device, endpoints, ingress=True,
                attrib_origin=ao[True], n_rules=nr,
            ),
            TRAFFIC_EGRESS: materialize_endpoints_state(
                compiled, device, endpoints, ingress=False,
                attrib_origin=ao[False], n_rules=nr,
            ),
        }

    # -- policyd-delta: epoch-swapped shadow rebuilds ------------------
    def set_epoch_swap(self, on: bool) -> None:
        """Toggle epoch-swapped full rebuilds (the EpochSwap runtime
        option). Turning it off also abandons any in-flight shadow
        build — the next rebuild that needs a full sweep runs it
        synchronously again."""
        with self._lock:
            on = bool(on)
            if on == self._epoch_swap:
                return
            self._epoch_swap = on
            if not on:
                self._swap_gen += 1

    def set_sparse_deltas(self, on: bool) -> None:
        """Toggle O(k) sparse device-table deltas (the SparseDeltas
        runtime option). ON takes effect on the next rebuild: the
        patchable trie builders are constructed alongside the full trie
        compile, and subsequent ipcache / selector deltas patch device
        tensors in place. OFF drops the patch state and the placed
        sel_match cache so the next rebuild re-places and re-merges
        from scratch — the exact pre-option arrays and programs (the
        patch kernels are never traced)."""
        with self._lock:
            on = bool(on)
            if on == self._sparse_deltas:
                return
            self._sparse_deltas = on
            self._trie_patch = None
            self._placed_sel = (0, None, None)
            # drop the trie tensors on BOTH transitions: ON must
            # construct the patchable mirrors alongside a fresh full
            # compile (they mirror the device arrays row for row), OFF
            # must shed the ON path's pow2 node-pool headroom and
            # rebuild exact-sized pre-option tries
            self._tries = None

    def wait_epoch_swap(self, timeout: float = 60.0) -> bool:
        """Block until no shadow build is in flight (tests/bench
        convergence helper; the daemon never calls this). Returns False
        on timeout. The installed generation becomes dispatch-visible
        on the NEXT rebuild() — call it after this returns."""
        t = self._shadow_thread
        if t is not None and t.is_alive():
            t.join(timeout)
            return not t.is_alive()
        return True

    @property
    def policy_epoch(self) -> int:
        """Shadow-built generations swapped in since start (telemetry:
        rides /healthz next to the failsafe state)."""
        return self._policy_epoch

    def _kick_shadow_build(
        self, compiled, device, ep_sig, delta_target
    ) -> bool:
        """Start (or keep watching) a shadow materialization bound to
        the current basis generation. Held-lock helper for rebuild.
        Returns True while a shadow is (now) running — the caller keeps
        serving the old generation — or False when it must fall back to
        the synchronous full path (a previous shadow died on a
        transient/poisoned fault; programmer errors re-raise here)."""
        exc = self._shadow_exc
        if exc is not None:
            self._shadow_exc = None
            if _faults.classify(exc) == _faults.KIND_ERROR:
                raise exc
            return False
        t = self._shadow_thread
        if t is not None and t.is_alive():
            return True  # one shadow at a time; converge via the log
        gen = self._swap_gen
        ao, nr = self._attrib_origins(compiled)
        names = self.engine.repo.origin_names() if nr else []
        t = threading.Thread(
            target=self._shadow_build,
            args=(
                compiled, device, list(self._endpoints), ep_sig,
                delta_target, gen, ao, nr, names,
            ),
            name="policyd-shadow-mat",
            daemon=True,
        )
        self._shadow_thread = t
        t.start()
        return True

    def _shadow_build(
        self, compiled, device, endpoints, ep_sig, delta_target, gen,
        ao, nr, names,
    ) -> None:
        """Shadow-thread body: the expensive sweeps run OFF the
        pipeline lock (dispatches and O(delta) rebuilds keep going
        against the old generation), then the finished generation
        installs under it. Deltas that landed while the sweep ran are
        NOT lost: the install rewinds the cursor to the kick-time
        target, so the next rebuild replays them against the new
        generation (row/column patches compute from the then-current
        snapshot — eventually consistent, same contract as any
        in-flight window)."""
        try:
            mats = self._build_mats(compiled, device, endpoints, ao, nr)
        # The broad catch is the point: ANY shadow failure must park in
        # _shadow_exc so the next kick can route it through
        # faults.classify (KIND_ERROR re-raises there, transients fall
        # back to a synchronous build) — a raise on this daemon thread
        # would vanish.  # policyd-lint: disable=ROBUST001
        except BaseException as e:
            with self._lock:
                if self._swap_gen == gen:
                    self._shadow_exc = e
            return
        with self._lock:
            if self._swap_gen != gen:
                return  # basis moved under us: abandon this epoch
            self._mat = mats
            self._mat_sig = ep_sig
            self._last_delta_seq = delta_target
            self._attrib_n_rules = nr
            self._attrib_names = names
            # rows may have moved with the rebuild: tries must follow
            self._tries = None
            # The generation becomes dispatch-visible ONLY through the
            # next rebuild's single _dp_state publish (the atomic
            # batch-boundary swap). Its CT flush rides the
            # transactional pending block there — fault-injectable at
            # SITE_CT_EPOCH like every other basis move.
            self._ct_flush_pending = True
            self._policy_epoch += 1
            epoch = self._policy_epoch
        _metrics.engine_epoch_swaps_total.inc()
        oj = self.on_journal
        if oj is not None:
            oj(kind="epoch_swap", attrs={
                "policy_epoch": epoch,
                "basis": [
                    compiled.revision, compiled.identity_version,
                    compiled.vocab_version,
                ],
            })

    def snapshots(self, ingress: bool = True) -> List[EndpointPolicySnapshot]:
        self.rebuild()
        return self._mat[TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS].snapshots

    def fastpath(self, ingress: bool = True):
        """Per-flow verdict cache over the current realized policymaps
        (datapath/fastpath.py). Row patches from identity churn are
        visible through the shared snapshot dicts; re-fetch after rule
        changes (re-materialization swaps the snapshot objects)."""
        from .fastpath import VerdictFastpath

        self.rebuild()
        direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS
        return VerdictFastpath(
            self._mat[direction].snapshots, direction=direction
        )

    # ------------------------------------------------------------------
    def _emit_flow_events(
        self,
        peer_bytes: np.ndarray,
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        verdict: np.ndarray,
        *,
        ingress: bool,
        family: int,
        redirect: Optional[np.ndarray] = None,
        rule: Optional[np.ndarray] = None,
        l4_covered: Optional[np.ndarray] = None,
        producer: str = "prefilter",
    ) -> None:
        """DropNotify per dropped flow (+ TraceNotify per forwarded
        flow when trace_enabled). Cold path: runs only while a monitor
        listener is attached (hub.active), and drops are normally the
        small tail of a batch. Peer identity is resolved host-side via
        the ipcache (the event consumer wants labels/identity, the
        datapath only knows rows).

        ``producer`` disambiguates reason-144's two emitters on the
        DropNotify record: the device path defaults to "prefilter" (the
        shed kernel), the host admission gate passes "admission". Only
        REASON_PREFILTER drops carry it — other reasons have one
        producer.

        With attribution arrays (``rule``/``l4_covered``, FlowAttribution
        on) policy drops carry the REAL reason from the policyd-flows
        taxonomy — deny-rule vs no-L3-match vs no-L4-match — instead of
        the generic REASON_POLICY."""
        hub = self.monitor
        if hub is None or not hub.active:
            return
        from ..monitor.events import (
            REASON_NO_SERVICE,
            REASON_PIPELINE_DEGRADED,
            REASON_POLICY,
            REASON_POLICY_DENY,
            REASON_POLICY_NO_L3,
            REASON_POLICY_NO_L4,
            REASON_PREFILTER,
            REASON_PROXY_REDIRECT,
            REASON_UNKNOWN,
            TRACE_TO_ENDPOINT,
            TRACE_TO_PROXY,
            DropNotify,
            PolicyVerdictNotify,
            TraceNotify,
        )
        import ipaddress as _ipa

        reason_of = {
            DROP_POLICY: REASON_POLICY,
            DROP_PREFILTER: REASON_PREFILTER,
            DROP_NO_SERVICE: REASON_NO_SERVICE,
            DROP_DEGRADED: REASON_PIPELINE_DEGRADED,
        }

        def _reason(i: int) -> int:
            code = int(verdict[i])
            if code == DROP_POLICY and rule is not None:
                if int(rule[i]) >= 0:
                    return REASON_POLICY_DENY
                if l4_covered is not None and bool(l4_covered[i]):
                    return REASON_POLICY_NO_L4
                return REASON_POLICY_NO_L3
            return reason_of.get(code, 0)

        events = []

        def _identity(addr: bytes) -> int:
            e = self.ipcache.lookup_by_ip(str(_ipa.ip_address(addr)))
            return 0 if e is None else e.identity

        def _ep(i: int) -> int:
            idx = int(ep_idx[i])
            return (
                self._endpoint_ids[idx]
                if 0 <= idx < len(self._endpoint_ids)
                else idx
            )

        def _opt(ep_id: int, name: str, default: bool) -> bool:
            if self.endpoint_options is None:
                return default
            try:
                return bool(self.endpoint_options(ep_id, name, default))
            except Exception as e:
                # classified (policyd-failsafe): a transient/poisoned
                # resolver fault degrades to the default — but a
                # programmer error in the resolver is a bug and must
                # surface, not silently un-gate event emission
                if _faults.classify(e) == _faults.KIND_ERROR:
                    raise
                return default

        for i in np.nonzero(verdict >= DROP_POLICY)[0]:
            if not _opt(_ep(i), "DropNotification", self.drop_notifications):
                continue
            addr = bytes(int(b) & 0xFF for b in peer_bytes[i])
            r = _reason(i)
            events.append(
                DropNotify(
                    reason=r,
                    endpoint=_ep(i),
                    src_identity=_identity(addr),
                    family=family,
                    peer_addr=addr,
                    dport=int(dports[i]),
                    proto=int(protos[i]),
                    ingress=ingress,
                    producer=producer if r == REASON_PREFILTER else "",
                )
            )
        # forwarded flows are the bulk of a batch — skip the per-flow
        # walk entirely unless traces can possibly be on
        trace_possible = self.trace_enabled or self.endpoint_options is not None
        for i in np.nonzero(verdict == FORWARD)[0] if trace_possible else ():
            if _opt(_ep(i), "TraceNotification", self.trace_enabled):
                addr = bytes(int(b) & 0xFF for b in peer_bytes[i])
                to_proxy = redirect is not None and bool(redirect[i])
                events.append(
                    TraceNotify(
                        obs_point=TRACE_TO_PROXY if to_proxy else TRACE_TO_ENDPOINT,
                        endpoint=_ep(i),
                        src_identity=_identity(addr),
                        family=family,
                        peer_addr=addr,
                        dport=int(dports[i]),
                        proto=int(protos[i]),
                        ingress=ingress,
                    )
                )
        # PolicyVerdictNotify reports EVERY flow's decision, allowed
        # flows included — same skip-unless-possibly-on contract as the
        # trace walk (this whole function is listener-gated cold path)
        vn_possible = (
            self.verdict_notifications or self.endpoint_options is not None
        )
        for i in range(len(verdict)) if vn_possible else ():
            if not _opt(
                _ep(i), "PolicyVerdictNotification",
                self.verdict_notifications,
            ):
                continue
            code = int(verdict[i])
            if code == FORWARD:
                if redirect is not None and bool(redirect[i]):
                    action, reason = 2, REASON_PROXY_REDIRECT
                else:
                    action, reason = 1, REASON_UNKNOWN
            else:
                action, reason = 0, _reason(i)
            addr = bytes(int(b) & 0xFF for b in peer_bytes[i])
            events.append(
                PolicyVerdictNotify(
                    action=action,
                    reason=reason,
                    endpoint=_ep(i),
                    src_identity=_identity(addr),
                    family=family,
                    peer_addr=addr,
                    dport=int(dports[i]),
                    proto=int(protos[i]),
                    ingress=ingress,
                    rule_index=int(rule[i]) if rule is not None else -1,
                )
            )
        if events:
            hub.publish_many(events)

    def _account_batch(
        self, verdict: np.ndarray, shard_of: Optional[np.ndarray] = None
    ) -> None:
        """Registry accounting for one completed batch (the metricsmap →
        pkg/metrics bridge). Post-host-sync by construction: callers
        pass the already-pulled numpy verdict array, so no new device
        syncs happen here. ``shard_of`` ([B] device index per flow,
        sharded dispatches only) switches verdicts_total to per-device
        series so hot shards are visible."""
        _metrics.verdict_batches.inc({"path": "pipeline"})
        if shard_of is None:
            counts = np.bincount(verdict.astype(np.int64), minlength=6)
            for code, outcome in _OUTCOME_NAMES:
                n = int(counts[code])
                if n:
                    _metrics.verdicts_total.inc({"outcome": outcome}, float(n))
            return
        for d in np.unique(shard_of):
            counts = np.bincount(
                verdict[shard_of == d].astype(np.int64), minlength=6
            )
            for code, outcome in _OUTCOME_NAMES:
                n = int(counts[code])
                if n:
                    _metrics.verdicts_total.inc(
                        {"outcome": outcome, "device": str(int(d))}, float(n)
                    )

    def _account_attribution(
        self,
        verdict: np.ndarray,
        rule: np.ndarray,
        l4x: np.ndarray,
        hits: Optional[np.ndarray],
        *,
        ingress: bool,
    ) -> None:
        """rule_hits_total / drop_reasons_total accounting for one
        attributed batch. Post-host-sync by construction (pulled numpy
        arrays in, no device syncs). ``hits=None`` means padded lanes
        polluted the device segment-sum — fall back to a host bincount
        over the (already trimmed) rule array."""
        names = self._attrib_names
        if hits is None:
            matched = rule[rule >= 0]
            hits = np.bincount(matched, minlength=len(names))
        direction = "ingress" if ingress else "egress"
        for r in np.nonzero(hits)[0]:
            origin = names[r] if r < len(names) else f"rule-{r}"
            _metrics.rule_hits_total.inc(
                {"origin": origin, "direction": direction}, float(hits[r])
            )
        pol = verdict == DROP_POLICY
        deny = pol & (rule >= 0)
        for reason, mask in (
            ("deny-rule", deny),
            ("no-l4-match", pol & ~deny & l4x),
            ("no-l3-match", pol & ~deny & ~l4x),
            ("prefilter", verdict == DROP_PREFILTER),
            ("no-service", verdict == DROP_NO_SERVICE),
            ("pipeline-degraded", verdict == DROP_DEGRADED),
        ):
            n = int(np.count_nonzero(mask))
            if n:
                labels = {"reason": reason}
                if reason == "prefilter":
                    # reason 144's device-kernel producer (the host
                    # admission gate labels its own rows "admission")
                    labels["producer"] = "prefilter"
                _metrics.drop_reasons_total.inc(labels, float(n))

    def _record_flows(
        self,
        peer_bytes: np.ndarray,
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        verdict: np.ndarray,
        rule: np.ndarray,
        l4x: np.ndarray,
        redirect: Optional[np.ndarray],
        *,
        ingress: bool,
    ) -> None:
        """Sampled FlowRecord feed for the flow-log ring: at most
        SAMPLE_CAP records per batch, drops first (they are the rare,
        interesting tail), then forwarded flows for the remainder —
        per-record host cost is bounded regardless of batch size."""
        ring = self.flow_ring
        if not ring.active:
            return
        import ipaddress as _ipa

        from ..monitor.events import (
            REASON_NO_SERVICE,
            REASON_PIPELINE_DEGRADED,
            REASON_POLICY_DENY,
            REASON_POLICY_NO_L3,
            REASON_POLICY_NO_L4,
            REASON_PREFILTER,
            REASON_PROXY_REDIRECT,
            reason_name,
        )
        from ..observe.flows import now as _flow_now

        take = list(np.nonzero(verdict >= DROP_POLICY)[0][:_FLOW_SAMPLE_CAP])
        if len(take) < _FLOW_SAMPLE_CAP:
            take.extend(
                np.nonzero(verdict == FORWARD)[0][
                    : _FLOW_SAMPLE_CAP - len(take)
                ]
            )
        if not take:
            return
        origins = self.engine.repo.rule_origins()
        outcome = dict(_OUTCOME_NAMES)
        labels_of = self.identity_labels
        ts = _flow_now()
        recs = []
        for i in take:
            code = int(verdict[i])
            ri = int(rule[i])
            if code == DROP_PREFILTER:
                reason = REASON_PREFILTER
            elif code == DROP_NO_SERVICE:
                reason = REASON_NO_SERVICE
            elif code == DROP_DEGRADED:
                reason = REASON_PIPELINE_DEGRADED
            elif code == DROP_POLICY:
                if ri >= 0:
                    reason = REASON_POLICY_DENY
                elif bool(l4x[i]):
                    reason = REASON_POLICY_NO_L4
                else:
                    reason = REASON_POLICY_NO_L3
            elif redirect is not None and bool(redirect[i]):
                reason = REASON_PROXY_REDIRECT
            else:
                reason = 0
            addr = bytes(int(b) & 0xFF for b in peer_bytes[i])
            peer_ip = str(_ipa.ip_address(addr))
            e = self.ipcache.lookup_by_ip(peer_ip)
            peer_ident = 0 if e is None else e.identity
            idx = int(ep_idx[i])
            ep_ident = (
                self._endpoints[idx]
                if 0 <= idx < len(self._endpoints)
                else 0
            )

            def _labels(ident: int) -> Tuple[str, ...]:
                if labels_of is None:
                    return ()
                try:
                    return tuple(labels_of(ident))
                except Exception as e:
                    # classified (policyd-failsafe): degrade to
                    # unlabeled records on environmental faults only —
                    # a buggy resolver surfaces instead of silently
                    # stripping every flow record's labels
                    if _faults.classify(e) == _faults.KIND_ERROR:
                        raise
                    return ()

            # flow orientation: ingress = peer → endpoint, egress =
            # endpoint → peer (the endpoint's own address is not known
            # to the datapath — only the peer side carries an IP)
            src_id, dst_id = (
                (peer_ident, ep_ident) if ingress else (ep_ident, peer_ident)
            )
            recs.append(
                FlowRecord(
                    ts=ts,
                    direction="ingress" if ingress else "egress",
                    src_identity=src_id,
                    dst_identity=dst_id,
                    src_labels=_labels(src_id),
                    dst_labels=_labels(dst_id),
                    src_ip=peer_ip if ingress else "",
                    dst_ip="" if ingress else peer_ip,
                    dport=int(dports[i]),
                    proto=int(protos[i]),
                    verdict=code,
                    verdict_name=outcome.get(code, str(code)),
                    reason=reason,
                    reason_name=(
                        "allowed" if reason == 0 else reason_name(reason)
                    ),
                    rule_index=ri,
                    rule_origin=(
                        origins[ri] if 0 <= ri < len(origins) else None
                    ),
                )
            )
        ring.push_many(recs)

    @staticmethod
    def _shard_map(spans, ndev: int, b: int) -> np.ndarray:
        """[B] device index per flow: P("flows") splits each padded
        chunk's dim 0 into ndev contiguous shards in mesh device
        order."""
        out = np.zeros(b, np.int32)
        for lo, hi, padded in spans:
            w = max(1, padded // ndev)
            out[lo:hi] = np.minimum(np.arange(hi - lo) // w, ndev - 1)
        return out

    def _chunk_spans(self, n: int, *, bucketed: bool, ndev: int):
        """Dispatch spans [(lo, hi, padded)] for an n-flow batch.

        Unbucketed (the no-CT full-batch path) keeps the exact shape —
        padded lanes would pollute the device-side counters — except
        under sharding, where the batch must split evenly across the
        mesh. Bucketed spans (the CT-miss tail) come off the fixed
        BUCKET_LADDER (ndev-rounded): full top-rung chunks first (zero
        pad, each its own overlapped enqueue), then the exact
        minimum-padded-lane rung cover of what remains (_tail_cover) —
        so the padded shape set stays ≤ len(BUCKET_LADDER) per
        static-arg combination while tail pad drops versus both the
        old largest-warm-bucket reuse (a 3000-flow tail dispatched as
        3×1024, now 2048+1024) and a single-bucket pad (1100 flows pad
        to 2048, not 4096)."""
        if not bucketed:
            return [(0, n, n + ((-n) % ndev) if ndev > 1 else n)]
        rungs = _ladder_rungs(ndev)
        top = rungs[-1]
        spans = []
        lo = 0
        while n - lo > top:
            spans.append((lo, lo + top, top))
            lo += top
        _lanes, _chunks, plan = _tail_cover(n - lo, rungs)
        for r in plan:  # largest-first: only the final chunk has pad
            live = min(r, n - lo)
            spans.append((lo, lo + live, r))
            lo += live
        return spans

    def _enqueue_one(
        self, t, peer_bytes, ep_idx, dports, protos, row_override,
        lo, hi, padded, *, family, pf_stage, ep_count, v6_fused,
        flow_sharding, rule_tab=None, n_rules=0, staging=None,
        ident_gather=False, psample=None,
    ):
        """Pad + upload + enqueue ONE chunk; returns the UN-PULLED
        device (verdict, redirect, counters) triple. Under sharding
        the flow arrays are committed split over the mesh's "flows"
        axis (the tests/test_multichip.py pattern) before the call.
        ``staging`` (bucketed dispatches only) collects the pre-pinned
        rung buffers the pad half wrote into, for release at the host
        pull; padded rungs then cost four memcpys instead of four
        np.pad allocations. ``psample`` (policyd-prof, the 1-in-N
        sampled batch only) makes the upload an explicit synchronous
        device_put so its wall time separates from the async program
        enqueue — identical avals, so the compiled program is the same
        one the unsampled path runs."""
        if _faults.hub.active:
            _faults.hub.check(_faults.SITE_H2D)
        pb = peer_bytes[lo:hi]
        ei = ep_idx[lo:hi]
        dp = dports[lo:hi]
        pr = protos[lo:hi]
        ro = None if row_override is None else row_override[lo:hi]
        pad = padded - (hi - lo)
        if pad and staging is not None:
            bufs = self._staging_acquire(padded, peer_bytes.shape[1])
            spb, sei, sdp, spr, sro = bufs
            m = hi - lo
            spb[:m] = pb
            spb[m:] = 0
            sei[:m] = ei
            sei[m:] = 0
            sdp[:m] = dp
            sdp[m:] = 0
            spr[:m] = pr
            spr[m:] = 0
            pb, ei, dp, pr = spb, sei, sdp, spr
            if ro is not None:
                # padded lanes must derive-by-LPM, never trust (-1)
                sro[:m] = ro
                sro[m:] = -1
                ro = sro
            staging.append(bufs)
        elif pad:
            pb, ei, dp, pr, ro = _pad_flows(pad, pb, ei, dp, pr,
                                            row_override=ro)
        peer = _pack_v4_u32(pb) if family == 4 else pb
        if psample is not None:
            # sampled h2d edge: upload explicitly and wait — the time
            # between here and the post-enqueue ready wait is then pure
            # device compute. device_put with sharding=None commits to
            # the default device; either way the avals (and therefore
            # the jit cache key / compiled program) are unchanged.
            _t0 = time.perf_counter()
            peer, ei, dp, pr = jax.block_until_ready(
                jax.device_put((peer, ei, dp, pr), flow_sharding)
            )
            if ro is not None:
                ro = jax.block_until_ready(
                    jax.device_put(ro, flow_sharding)
                )
            psample.add_h2d(time.perf_counter() - _t0)
        elif flow_sharding is not None:
            peer, ei, dp, pr = jax.device_put(
                (peer, ei, dp, pr), flow_sharding
            )
            if ro is not None:
                ro = jax.device_put(ro, flow_sharding)
        elif ro is not None:
            ro = jnp.asarray(ro)
        attrib = rule_tab is not None
        if family == 4:
            fn = process_flows_wide
            fargs = (t, peer, ei, dp, pr)
            fkw = dict(
                ep_count=ep_count, prefilter=pf_stage, row_override=ro,
                attrib=attrib, rule_tab=rule_tab, n_rules=n_rules,
                ident_gather=ident_gather,
            )
        else:
            fn = process_flows
            fargs = (t, peer, ei, dp, pr)
            fkw = dict(
                ep_count=ep_count, levels=16, prefilter=pf_stage,
                fused=v6_fused, row_override=ro, attrib=attrib,
                rule_tab=rule_tab, n_rules=n_rules,
                ident_gather=ident_gather,
            )
        if psample is not None:
            prof = self.profiler
            if prof is not None:
                # compile-time cost ledger: flops / bytes-accessed for
                # this (site, stable ladder shape), recorded once
                prof.note_jit_cost(
                    "dispatch",
                    (family, padded, pf_stage, ep_count, ro is not None,
                     v6_fused, attrib, ident_gather),
                    fn, fargs, fkw,
                )
        return fn(*fargs, **fkw)

    # -- policyd-failsafe: ladder level 2 (host fallback) ---------------
    def _host_tables(self, direction: int) -> Optional[Tuple]:
        """Host numpy copy of one direction's policymap columns/bitmaps,
        cached on the source object (the _replicated_policymap pattern).
        The pull itself touches the device — on a dead backend it fails
        classified, and the caller falls through to policy synthesis."""
        mat = self._mat.get(direction)
        if mat is None:
            return None
        pm = mat.tables
        src, ht = self._host_pm.get(direction, (None, None))
        if src is pm:
            return ht
        try:
            ht = (
                np.asarray(pm.col_ep),
                np.asarray(pm.col_port),
                np.asarray(pm.col_proto),
                np.asarray(pm.col_is_l3).astype(bool),
                np.asarray(pm.id_bits),
            )
        except BaseException as e:
            if _faults.classify(e) == _faults.KIND_ERROR:
                raise
            return None
        self._host_pm[direction] = (pm, ht)
        return ht

    def _host_verdicts(
        self, peer_bytes, ep_idx, dports, protos, *, ingress, family,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Numpy mirror of the device verdict path (the ladder's last
        rung). Identity resolution goes through the HOST ipcache — the
        authoritative source the device tries are built FROM — instead
        of mirroring the LPM walk bit-for-bit; the policymap decision
        mirrors ops/lookup.lookup_batch exactly (colsel → allow/red).
        O(B · C) numpy plus an O(B) python ipcache walk: an emergency
        path that keeps verdicts flowing, not a fast path. When even
        the host tables are unreachable, falls back to pure policy
        synthesis (FailOpen → forward, fail-closed → DROP_DEGRADED)."""
        import ipaddress as _ipa

        b = peer_bytes.shape[0]
        direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS
        ht = self._host_tables(direction)
        if ht is None:
            v = np.full(
                b, FORWARD if self._fail_open else DROP_DEGRADED, np.int8
            )
            if not self._fail_open and b:
                _metrics.drop_reasons_total.inc(
                    {"reason": "pipeline-degraded"}, float(b)
                )
            return v, np.zeros(b, bool)
        col_ep, col_port, col_proto, col_is_l3, id_bits = ht
        addrs = [
            _ipa.ip_address(bytes(int(x) & 0xFF for x in peer_bytes[i]))
            for i in range(b)
        ]
        idents = np.empty(b, np.int64)
        for i, a in enumerate(addrs):
            e = self.ipcache.lookup_by_ip(str(a))
            idents[i] = ID_WORLD if e is None else e.identity
        rows = np.asarray(self.engine.rows_or_negative(idents))
        world = np.asarray(
            self.engine.rows_or_negative(np.array([ID_WORLD], np.int64))
        )[0]
        rows = np.where(rows < 0, world, rows).astype(np.int64)
        # prefilter deny (ingress only, like the device pf stage)
        pf_drop = np.zeros(b, bool)
        if ingress:
            _, pf_cidrs = self.prefilter.dump()
            nets = [
                _ipa.ip_network(c)
                for c in pf_cidrs
                if (":" in c) == (family == 6)
            ]
            if nets:
                for i, a in enumerate(addrs):
                    pf_drop[i] = any(a in n for n in nets)
        w = id_bits.shape[1] // 2
        gathered = id_bits[np.clip(rows, 0, id_bits.shape[0] - 1)]
        shifts = np.arange(32, dtype=np.uint32)
        both = (
            ((gathered[:, :, None] >> shifts) & np.uint32(1))
            .astype(bool)
            .reshape(b, -1)
        )
        c = col_ep.shape[0]
        allow_bits = both[:, : w * 32][:, :c]
        red_bits = both[:, w * 32:][:, :c]
        ep = np.asarray(ep_idx, np.int64)
        colsel = (ep[:, None] == col_ep[None, :]) & (
            col_is_l3[None, :]
            | (
                (np.asarray(dports)[:, None] == col_port[None, :])
                & (np.asarray(protos)[:, None] == col_proto[None, :])
            )
        )
        hit = colsel & allow_bits
        allow = hit.any(axis=1)
        red = (hit & red_bits).any(axis=1)
        v = np.where(allow, np.int8(FORWARD), np.int8(DROP_POLICY))
        v = np.where(pf_drop, np.int8(DROP_PREFILTER), v).astype(np.int8)
        return v, (red & (v == FORWARD))

    def _host_enqueue(
        self, peer_bytes, ep_idx, dports, protos, *, ingress, family, bt,
    ) -> _Enqueued:
        """_dispatch_enqueue stand-in at ladder level 2: the "dispatch"
        phase computes on host numpy and the _Enqueued carries finished
        results — _dispatch_complete returns them without touching the
        device. Shapes/ordering of the completion half are unchanged so
        the FIFO queue, CT create, counters, and events all run as
        usual over host-produced verdicts."""
        with bt.phase("dispatch"):
            v, red = self._host_verdicts(
                peer_bytes, ep_idx, dports, protos,
                ingress=ingress, family=family,
            )
        attrib = self._dp_state[5] is not None
        return _Enqueued(
            (), [], peer_bytes.shape[0], False, 1,
            attrib=attrib, host=(v, red),
        )

    def _dispatch_enqueue(
        self,
        peer_bytes: np.ndarray,
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        *,
        ingress: bool,
        family: int,
        bucketed: bool = False,
        row_override: Optional[np.ndarray] = None,
        bt=_NOOP_BATCH,
    ) -> _Enqueued:
        """Non-blocking half of a dispatch: pad/chunk, upload, enqueue
        the fused device program(s), return un-pulled device arrays.
        The host pull lives in _dispatch_complete — with depth>1 it
        runs after successor batches were enqueued, so device execution
        hides behind their host prep."""
        direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS
        if self._ladder_level >= 2:
            # host fallback (ladder level 2): verdict on host numpy,
            # synchronously — there is no device work to overlap
            return self._host_enqueue(
                peer_bytes, ep_idx, dports, protos,
                ingress=ingress, family=family, bt=bt,
            )
        if _faults.hub.active:
            _faults.hub.check(_faults.SITE_DISPATCH)
        # ONE atomic snapshot read: tables + flags + sharding +
        # attribution swap together in rebuild(), so fused-ness,
        # placement, and the rule table always match the tables they
        # describe
        (
            tables_map, pf_empty, v6_fused, flow_sharding, ndev, attrib_el,
            ident2d, _shed,
        ) = self._dp_state
        t = tables_map[(direction, family)]
        rule_tab = None
        n_rules = 0
        if attrib_el is not None:
            rule_tab = attrib_el[0][direction]
            n_rules = attrib_el[1]
        b = peer_bytes.shape[0]
        # XDP prefilter guards traffic entering the node only, and an
        # empty deny set skips the walk entirely (it's one of the two
        # LPM walks that dominate the pipeline)
        pf_stage = ingress and not pf_empty[0 if family == 4 else 1]
        ep_count = max(1, len(self._endpoints))
        spans = self._chunk_spans(b, bucketed=bucketed, ndev=ndev)
        # pad-lane accounting on EVERY dispatch path (bucketed rung pad
        # and the unbucketed sharded ndev-rounding alike) — bench.py
        # derives pad_waste_pct as pad / (live + pad)
        pad_lanes = sum(p for _, _, p in spans) - b
        if pad_lanes:
            _metrics.dispatch_pad_lanes_total.inc(
                {"family": f"v{family}"}, float(pad_lanes)
            )
        # policyd-prof: one attribute read while off (None); while on,
        # every sample_every-th dispatch gets a live sample and pays
        # the synchronizing sandwiches (h2d inside _enqueue_one, the
        # ready wait below, d2h in _dispatch_complete)
        prof = self.profiler
        psample = (
            prof.begin_dispatch("dispatch", b) if prof is not None else None
        )
        tr = self.tracer
        if tr.active:
            # shape-bucket telemetry: the jit cache keys on padded
            # chunk shape + the static args below — a fresh key on
            # this pipeline ≈ one XLA recompile on dispatch
            for _lo, _hi, padded in spans:
                key = (
                    direction, family, padded, pf_stage, ep_count,
                    row_override is not None, v6_fused, ndev > 1,
                    rule_tab is not None, ident2d,
                )
                if key in self._seen_shapes:
                    _metrics.jit_shape_buckets_total.inc(
                        {"site": "dispatch", "result": "hit"}
                    )
                else:
                    self._seen_shapes.add(key)
                    _metrics.jit_shape_buckets_total.inc(
                        {"site": "dispatch", "result": "miss"}
                    )
            # each logical upload is one per-device slice transfer per
            # mesh device under sharding (P("flows") splits dim 0)
            _metrics.device_transfers_total.inc(
                {"direction": "h2d"},
                (4.0 + (row_override is not None)) * len(spans) * ndev,
            )
            # byte-ledger sibling (policyd-prof): logical upload bytes
            # — v4 packs to one u32 lane, v6 ships the raw int32
            # bytes; shard slices sum to the full array, so no ×ndev
            peer_w = 4 if family == 4 else peer_bytes.shape[1] * 4
            _metrics.device_transfer_bytes_total.inc(
                {"direction": "h2d"},
                float(sum(
                    p * (peer_w + 12 + (4 if row_override is not None else 0))
                    for _, _, p in spans
                )),
            )
            bt.mark(
                padded=int(sum(p for _, _, p in spans)), chunks=len(spans)
            )
        # "dispatch" covers the h2d uploads + the async XLA enqueue of
        # the FUSED device program (LPM walks + policymap lookup +
        # counter matmul trace as one jit — splitting them into
        # separate spans would de-fuse the program); the actual device
        # execution time aggregates into "host_sync" at completion.
        staging = [] if bucketed else None
        _pl_t0 = time.perf_counter() if psample is not None else 0.0
        with bt.phase("dispatch"):
            chunks = [
                self._enqueue_one(
                    t, peer_bytes, ep_idx, dports, protos, row_override,
                    lo, hi, padded, family=family, pf_stage=pf_stage,
                    ep_count=ep_count, v6_fused=v6_fused,
                    flow_sharding=flow_sharding, rule_tab=rule_tab,
                    n_rules=n_rules, staging=staging, ident_gather=ident2d,
                    psample=psample,
                )
                for lo, hi, padded in spans
            ]
            if psample is not None:
                # sampled compute edge: h2d already completed
                # synchronously inside _enqueue_one, so what remains of
                # the chunk loop — per-chunk program dispatch (slicing,
                # padding, jit call) plus the residual device wait here
                # — is charged to device_compute (on hardware the
                # dispatch overhead runs concurrently with execution;
                # splitting it would need a per-chunk sync that changes
                # what's being measured). Done INSIDE the dispatch span
                # so a sampled batch's trace and its decomposition
                # cover the same wall clock. This serializes THIS batch
                # against the pipeline overlap — the cost sampling
                # exists to amortize.
                jax.block_until_ready(chunks)
                psample.add_compute(
                    time.perf_counter() - _pl_t0 - psample.h2d_s
                )
                # rung occupancy: what the tuner/chunker chose vs what
                # was live — makes pad waste visible per sample
                psample.mark(
                    rungs=[int(p) for _, _, p in spans],
                    lanes=int(b),
                    pad_lanes=int(sum(p for _, _, p in spans) - b),
                    chunks=len(spans),
                    ndev=int(ndev),
                    depth=int(self.pipeline_depth),
                    family=int(family),
                    bucketed=bool(bucketed),
                )
        if bucketed:
            for _lo, _hi, padded in spans:
                self._warm_buckets.add(padded)
        exact = all(hi - lo == padded for lo, hi, padded in spans)
        return _Enqueued(chunks, spans, b, exact, ndev,
                         attrib=rule_tab is not None,
                         staging=staging or (), psample=psample)

    def _dispatch_complete(
        self, enq: _Enqueued, bt=_NOOP_BATCH
    ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
        """Blocking half: pull chunk results to host. With depth>1 the
        device worked through this batch while the host prepared its
        successors, so "host_sync" here measures the RESIDUAL wait.
        Counters come back None when padded lanes polluted the device
        accumulation (callers fall back to host-side np.add.at); the
        attribution rule-hit sums follow the same exact/fallback rule
        (None → host bincount over the pulled rule array). Attributed
        dispatches return (verdict, redirect, counters, rule,
        l4_covered, hits) — the attribution d2h pulls live HERE, in
        the completion half, so PR 3's enqueue/complete overlap is
        preserved."""
        if enq.host is not None:
            # ladder level 2: verdicts were computed on host at enqueue
            v, red = enq.host
            if not enq.attrib:
                return v, red, None
            # host fallback carries no per-rule attribution — report
            # "no rule decided" (-1) so rule_hits_total only ever
            # counts real device attributions
            b = enq.b
            return (
                v, red, None,
                np.full(b, -1, np.int32), np.zeros(b, bool), None,
            )
        if _faults.hub.active:
            # the injected "complete" fault fires BEFORE the pull — the
            # retry soundness argument in _finish_guarded relies on the
            # transient window preceding any host-state mutation
            _faults.hub.check(_faults.SITE_COMPLETE)
        if self.tracer.active:
            _metrics.device_transfers_total.inc(
                {"direction": "d2h"},
                (6.0 if enq.attrib else 3.0) * len(enq.chunks) * enq.ndev,
            )
            # byte-ledger sibling (policyd-prof): logical bytes the
            # pull below actually moves (counters/hits only when exact
            # — the inexact path never reads them). .nbytes on an
            # un-pulled device array is metadata, no sync.
            nb = 0
            for ch in enq.chunks:
                nb += int(ch[0].nbytes) + int(ch[1].nbytes)
                if enq.attrib:
                    nb += int(ch[3].nbytes) + int(ch[4].nbytes)
                if enq.exact:
                    nb += int(ch[2].nbytes)
                    if enq.attrib:
                        nb += int(ch[5].nbytes)
            _metrics.device_transfer_bytes_total.inc(
                {"direction": "d2h"}, float(nb)
            )
        ps = enq.psample
        _pt0 = time.perf_counter() if ps is not None else 0.0
        with bt.phase("host_sync"):
            b = enq.b
            rule = l4x = hits = None
            if len(enq.chunks) == 1:
                ch = enq.chunks[0]
                verdict = np.asarray(ch[0])[:b]
                redirect = np.asarray(ch[1])[:b]
                if enq.attrib:
                    rule = np.asarray(ch[3])[:b]
                    l4x = np.asarray(ch[4])[:b]
            else:
                verdict = np.empty(b, np.int8)
                redirect = np.empty(b, bool)
                if enq.attrib:
                    rule = np.empty(b, np.int32)
                    l4x = np.empty(b, bool)
                for (lo, hi, _padded), ch in zip(enq.spans, enq.chunks):
                    verdict[lo:hi] = np.asarray(ch[0])[: hi - lo]
                    redirect[lo:hi] = np.asarray(ch[1])[: hi - lo]
                    if enq.attrib:
                        rule[lo:hi] = np.asarray(ch[3])[: hi - lo]
                        l4x[lo:hi] = np.asarray(ch[4])[: hi - lo]
            if enq.exact:
                counters = np.asarray(enq.chunks[0][2])
                for ch in enq.chunks[1:]:
                    counters = counters + np.asarray(ch[2])
                if enq.attrib:
                    hits = np.asarray(enq.chunks[0][5])
                    for ch in enq.chunks[1:]:
                        hits = hits + np.asarray(ch[5])
            else:
                counters = None
        if ps is not None:
            # sampled d2h edge: the residual pull wait (compute already
            # completed at the enqueue half's ready sandwich)
            ps.add_d2h(time.perf_counter() - _pt0)
            prof = self.profiler
            if prof is not None:
                prof.complete(ps)
            enq.psample = None  # retry-idempotent: never retire twice
        if enq.staging:
            # the host pull above proves the device program finished —
            # only now are the pinned buffers safe to hand to the next
            # batch (JAX CPU zero-copy aliasing)
            self._staging_release(enq.staging)
            enq.staging = ()
        if not enq.attrib:
            return verdict, redirect, counters
        return verdict, redirect, counters, rule, l4x, hits

    def _dispatch(
        self,
        peer_bytes: np.ndarray,
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        *,
        ingress: bool,
        family: int,
        pad_to: Optional[int] = None,
        row_override: Optional[np.ndarray] = None,
    ) -> Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
        """Synchronous dispatch (enqueue + immediate pull) — kept for
        direct callers/tests; the pipelined path drives the two halves
        separately. ``pad_to`` is honored as "bucket this batch"."""
        tr = self.tracer
        bt = tr.current() if tr.active else _NOOP_BATCH
        enq = self._dispatch_enqueue(
            peer_bytes, ep_idx, dports, protos, ingress=ingress,
            family=family, bucketed=pad_to is not None,
            row_override=row_override, bt=bt,
        )
        return self._dispatch_complete(enq, bt)

    # -- bounded in-flight queue ---------------------------------------
    def _complete_oldest(self) -> bool:
        """Pull + finish the oldest in-flight batch. Returns False when
        nothing was queued. The finish closure runs OUTSIDE the queue
        lock (it publishes events and fires callbacks)."""
        with self._queue_lock:
            if not self._inflight:
                return False
            inf = self._inflight.popleft()
            _metrics.pipeline_inflight_depth.set(float(len(self._inflight)))
        # completion-half timing (p99 verdict-latency proxy): observed
        # only for batches admitted while the tuner was on
        tuner = self._tuner
        t0 = (
            time.perf_counter_ns()
            if tuner is not None and inf.enq_ns
            else 0
        )
        adm = self._admission
        wd = self._watchdog
        try:
            # the watchdog's stall clock starts when a thread ACTIVELY
            # pulls this batch — un-pulled in-flight batches are the
            # pipeline's normal lazy shape, not stalls
            if wd is not None:
                self._completing = (inf, time.monotonic())
            # classified completion (policyd-failsafe): transient
            # faults retry bounded, poisoned batches quarantine into a
            # degraded RESULT, and only programmer errors come back as
            # an exception for result() to surface raw
            value, exc = self._finish_guarded(inf)
            # publish under the queue lock, where the watchdog decides
            # abandonment: a batch it already resolved degraded must
            # not have its (late, possibly-poisoned) result overwrite
            # the published one
            with self._queue_lock:
                if not inf.abandoned:
                    inf.pending._value = value
                    inf.pending._exc = exc
        finally:
            if wd is not None:
                self._completing = None
            inf.pending._event.set()
            if inf.bt is not _NOOP_BATCH:
                inf.bt.end(self.monitor)
        if adm is not None and inf.t0:
            adm.observe_completion(time.monotonic() - inf.t0)
        if t0:
            new_depth = tuner.observe(
                self.pipeline_depth, inf.b, inf.enq_ns,
                time.perf_counter_ns() - t0, inf.occ,
            )
            # tuner armistice (policyd-overload): while the admission
            # gate shed recently, the depth controller must not probe
            # the queue UP — two controllers pushing the same knob in
            # opposite directions oscillate
            if new_depth is not None and not (
                new_depth > self.pipeline_depth
                and adm is not None
                and adm.shedding()
            ):
                self._apply_depth(new_depth)
        # policyd-survive: one-shot first-completion hook (the daemon's
        # restart_downtime stamp). One attribute read when unset.
        cb = self.on_first_batch
        if cb is not None:
            self.on_first_batch = None
            # a measurement hook must never fail the batch it measures
            try:
                cb()
            except Exception:  # policyd-lint: disable=ROBUST001
                pass
        return True

    def _complete_until(self, pending: PendingBatch) -> None:
        """Complete in-flight batches FIFO until ``pending`` is done.
        An empty queue with ``pending`` still unset means another
        thread popped it and is mid-finish — the caller's event wait
        covers that."""
        while not pending.done:
            if not self._complete_oldest():
                return

    def begin_drain(self) -> None:
        """Stop admitting new batches (graceful drain, policyd-survive):
        subsequent submits resolve immediately with the degraded shape
        while drain() FIFO-completes the in-flight queue."""
        self._draining = True

    def end_drain(self) -> None:
        """Re-open admission (a drain that was probed but not followed
        by process exit — tests, aborted shutdowns)."""
        self._draining = False

    def drain(self, deadline_s: Optional[float] = None) -> dict:
        """Complete every in-flight batch FIFO (barrier; daemon
        shutdown). With a deadline, batches still queued when it
        expires resolve DEGRADED instead of blocking exit — a drain
        never loses a verdict, it only downgrades late ones
        (verdicts_lost stays 0). → {completed, abandoned}."""
        completed = 0
        limit = (
            time.monotonic() + deadline_s if deadline_s is not None else None
        )
        while limit is None or time.monotonic() < limit:
            if not self._complete_oldest():
                break
            completed += 1
        abandoned = 0
        while True:
            with self._queue_lock:
                if not self._inflight:
                    break
                inf = self._inflight.popleft()
                inf.abandoned = True
                _metrics.pipeline_inflight_depth.set(
                    float(len(self._inflight))
                )
            inf.pending._value = self._degraded_result(inf)
            inf.pending._event.set()
            if inf.bt is not _NOOP_BATCH:
                inf.bt.end(self.monitor)
            abandoned += 1
        return {"completed": completed, "abandoned": abandoned}

    @property
    def inflight_depth(self) -> int:
        return len(self._inflight)

    def _submit(
        self,
        peer_bytes: np.ndarray,  # [B, 4|16] int32 peer address bytes
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        sports: Optional[np.ndarray],
        *,
        ingress: bool,
        family: int,
        peer_words: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        want_rev_nat: bool = False,
        tunnel_identities: Optional[np.ndarray] = None,
        gate: bool = True,
    ) -> PendingBatch:
        """Trace shell + queue admission around _submit_inner: the
        disabled cost is ONE ``tracer.active`` attribute read per batch
        (the hub's `active` pattern, observe/tracer.py). The trace is
        DETACHED from the thread-local stack once the enqueue half
        returns — it stays open and ends when the batch completes, so
        spans attach to the batch that completes, not the one being
        prepared — and admission beyond pipeline_depth completes the
        oldest batch first (the bounded in-flight queue).

        ``gate=False`` is the admission gate's internal re-entry for
        the kept remainder of a partially-shed batch — it must not be
        gated twice."""
        # policyd-overload admission gate: one attribute read when the
        # AdmissionControl option is off — the exact pre-option path
        if gate and self._admission is not None:
            gated = self._admission_gate(
                peer_bytes, ep_idx, dports, protos, sports,
                ingress=ingress, family=family, peer_words=peer_words,
                want_rev_nat=want_rev_nat,
                tunnel_identities=tunnel_identities,
            )
            if gated is not None:
                return gated
        # policyd-survive drain shed: a draining pipeline admits no new
        # work — resolve immediately with the degraded shape (FORWARD
        # under FailOpen, DROP_DEGRADED fail-closed; a shed flow still
        # gets a verdict, so verdicts_lost stays 0). The not-draining
        # path pays one GIL-atomic bool read.
        if self._draining:
            pending = PendingBatch(self)
            shell = _InFlight(
                pending, None, _NOOP_BATCH,
                b=peer_bytes.shape[0], rev=want_rev_nat,
            )
            pending._value = self._degraded_result(shell)
            pending._event.set()
            return pending
        tr = self.tracer
        # tuner timing: the enqueue half is everything up to queue
        # admission (prepare + CT pre-pass + h2d + async enqueue) —
        # captured only while DispatchAutoTune is on
        tuner = self._tuner
        t0 = time.perf_counter_ns() if tuner is not None else 0
        if tr.active:
            bt = tr.begin(
                f"v{family}-{'ingress' if ingress else 'egress'}",
                peer_bytes.shape[0],
            )
        else:
            bt = _NOOP_BATCH
        # classified enqueue (policyd-failsafe): a fault in the enqueue
        # half (rebuild / h2d / async dispatch) retries bounded on
        # transient, then resolves DEGRADED — the caller always gets a
        # PendingBatch whose result() carries a verdict per flow.
        # Programmer errors still raise raw (pre-failsafe contract).
        attempt = 0
        bo: Optional[Backoff] = None
        while True:
            try:
                inf = self._submit_inner(
                    peer_bytes, ep_idx, dports, protos, sports,
                    ingress=ingress, family=family, peer_words=peer_words,
                    want_rev_nat=want_rev_nat,
                    tunnel_identities=tunnel_identities, bt=bt,
                )
                break
            except BaseException as e:
                kind = _faults.classify(e)
                if kind == _faults.KIND_ERROR:
                    if bt is not _NOOP_BATCH:
                        bt.end(self.monitor)
                    raise
                self._note_fault(e, kind)
                if (
                    kind == _faults.KIND_TRANSIENT
                    and attempt < self.retry_limit
                ):
                    attempt += 1
                    if bo is None:
                        bo = Backoff(
                            min_s=self.retry_min_s, max_s=self.retry_max_s,
                            jitter=False,
                        )
                    time.sleep(bo.duration())
                    continue
                if bt is not _NOOP_BATCH:
                    bt.end(self.monitor)
                pending = PendingBatch(self)
                shell = _InFlight(
                    pending, None, bt,
                    b=peer_bytes.shape[0], rev=want_rev_nat,
                )
                pending._value = self._quarantine(shell)
                pending._event.set()
                return pending
        if bt is not _NOOP_BATCH:
            tr.detach(bt)
        if self._admission is not None or self._watchdog is not None:
            inf.t0 = time.monotonic()
        if inf.finish is None:
            # ran synchronously (device-CT donated-state path)
            if bt is not _NOOP_BATCH:
                bt.end(self.monitor)
            return inf.pending
        with self._queue_lock:
            self._inflight.append(inf)
            if tuner is not None:
                inf.enq_ns = time.perf_counter_ns() - t0
                inf.occ = len(self._inflight)
                inf.b = peer_bytes.shape[0]
            _metrics.pipeline_inflight_depth.set(float(len(self._inflight)))
            over = len(self._inflight) > self.pipeline_depth
        while over:
            self._complete_oldest()
            with self._queue_lock:
                over = len(self._inflight) > self.pipeline_depth
        return inf.pending

    def _submit_inner(
        self,
        peer_bytes: np.ndarray,  # [B, 4|16] int32 peer address bytes
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        sports: Optional[np.ndarray],
        *,
        ingress: bool,
        family: int,
        peer_words: Optional[Tuple[np.ndarray, np.ndarray]] = None,
        want_rev_nat: bool = False,
        tunnel_identities: Optional[np.ndarray] = None,
        bt=_NOOP_BATCH,
    ):
        with bt.phase("rebuild"):
            self.rebuild()
        with bt.phase("prepare"):
            ep_idx = np.asarray(ep_idx, np.int32)
            dports = np.asarray(dports, np.int32)
            protos = np.asarray(protos, np.int32)
            b = peer_bytes.shape[0]

            # Overlay path (bpf_overlay.c): decapped flows carry the
            # peer's security identity in the tunnel key — trust it over
            # the ipcache LPM when it resolves to a known device row;
            # unknown or zero identities fall back to the LPM walk.
            row_override: Optional[np.ndarray] = None
            if tunnel_identities is not None:
                row_override = self.engine.rows_or_negative(
                    np.asarray(tunnel_identities, np.int64)
                )

        # --- LB stage (egress only): VIP→backend translate -------------
        # bpf_lxc.c:444-455 — the service lookup precedes conntrack and
        # the policy check, so CT tracks the backend tuple and policy
        # sees the backend's identity, exactly like the kernel path.
        svc_drop: Optional[np.ndarray] = None
        revnat_vals: Optional[np.ndarray] = None
        if not ingress and self.lb is not None:
            with bt.phase("lb_translate"):
                lbt = self._lb_tables.get(family)
                if lbt is not None:
                    # hash over STABLE endpoint ids so unrelated
                    # endpoint churn cannot re-select backends for
                    # established flows
                    if self._endpoint_ids:
                        ep_ids = np.asarray(self._endpoint_ids, np.int64)[
                            np.clip(ep_idx, 0, len(self._endpoint_ids) - 1)
                        ]
                    else:
                        ep_ids = ep_idx
                    fh = flow_hash32(
                        peer_bytes, sports, dports, protos, ep_ids
                    )
                    nb, npo, rv, ok, nobk = lb_translate(
                        lbt,
                        jnp.asarray(peer_bytes),
                        jnp.asarray(dports),
                        jnp.asarray(protos),
                        jnp.asarray(fh),
                    )
                    ok = np.asarray(ok)
                    nobk = np.asarray(nobk)
                    if ok.any() or nobk.any():
                        peer_bytes = np.asarray(nb)
                        dports = np.asarray(npo, np.int32)
                        revnat_vals = np.asarray(rv).astype(np.uint16)
                        svc_drop = nobk
                        peer_words = None  # address changed — repack for CT

        # ── device-resident conntrack: ONE fused program per batch ──
        # Host fallbacks: any family with an active LB table (BOTH
        # directions — the CT is one bidirectional structure; an
        # egress VIP flow's entry must be visible to its ingress
        # reply, so the two directions must share a CT domain) and
        # overlay tunnel identities.
        if (
            self._device_ct_bits is not None
            and self._ladder_level < 2
            and sports is not None
            and svc_drop is None
            and row_override is None
            and (self.lb is None or self._lb_tables.get(family) is None)
        ):
            # the donated CT state is threaded batch-to-batch, so this
            # path stays synchronous: run now, return already-done
            result = self._process_device_ct(
                peer_bytes, ep_idx, dports, protos,
                np.asarray(sports, np.int32),
                ingress=ingress, family=family, want_rev_nat=want_rev_nat,
            )
            pending = PendingBatch(self)
            pending._value = result
            pending._event.set()
            return _InFlight(pending, None, bt)

        ct = self.conntrack
        if ct is None or sports is None:
            # No CT: full batch takes the device path (counters on MXU
            # when no padded lanes polluted them).
            enq = self._dispatch_enqueue(
                peer_bytes, ep_idx, dports, protos, ingress=ingress,
                family=family, row_override=row_override, bt=bt,
            )
            pending = PendingBatch(self)

            def finish():
                out = self._dispatch_complete(enq, bt)
                v, red, counters = out[:3]
                rule = l4x = hits = None
                if enq.attrib:
                    rule, l4x, hits = out[3:]
                with bt.phase("counters"):
                    if svc_drop is not None and svc_drop.any():
                        v = v.copy()
                        red = red.copy()
                        v[svc_drop] = DROP_NO_SERVICE
                        red[svc_drop] = False
                        # device counters classified these flows
                        # pre-override — accumulate host-side instead
                        # for this batch
                        counters = None
                        if rule is not None:
                            # no-backend flows never reached a rule —
                            # drop their attribution and re-derive the
                            # hit sums host-side
                            rule = rule.copy()
                            rule[svc_drop] = -1
                            hits = None
                    if counters is None:
                        with self._lock:
                            if self.counters.shape[0] == max(
                                1, len(self._endpoints)
                            ):
                                cls = np.select(
                                    [v == FORWARD, v == DROP_POLICY],
                                    [0, 1], default=2,
                                )
                                np.add.at(self.counters, (ep_idx, cls), 1)
                    else:
                        with self._lock:
                            if self.counters.shape == counters.shape:
                                self.counters += counters
                    self._account_batch(
                        v,
                        shard_of=(
                            self._shard_map(enq.spans, enq.ndev, b)
                            if enq.ndev > 1
                            else None
                        ),
                    )
                    if rule is not None:
                        self._account_attribution(
                            v, rule, l4x, hits, ingress=ingress
                        )
                with bt.phase("emit_events"):
                    self._emit_flow_events(
                        peer_bytes, ep_idx, dports, protos, v,
                        ingress=ingress, family=family, redirect=red,
                        rule=rule, l4_covered=l4x,
                    )
                    if rule is not None:
                        self._record_flows(
                            peer_bytes, ep_idx, dports, protos, v,
                            rule, l4x, red, ingress=ingress,
                        )
                if want_rev_nat:
                    # no CT → replies can't be recognized → no restore
                    return v, red, np.zeros(b, np.uint16)
                return v, red

            return _InFlight(pending, finish, bt, b=b, rev=want_rev_nat)

        # --- conntrack pre-pass (vectorized host) ----------------------
        with bt.phase("ct_prepass"):
            sports = np.asarray(sports, np.int64)
            if peer_words is not None:
                # caller already holds packed address words (IPv4 u32 path)
                peer_hi, peer_lo = peer_words
            else:
                bytes64 = peer_bytes.astype(np.uint64)
                if family == 4:
                    peer_lo = (
                        (bytes64[:, 0] << 24) | (bytes64[:, 1] << 16)
                        | (bytes64[:, 2] << 8) | bytes64[:, 3]
                    )
                    peer_hi = np.zeros(b, np.uint64)
                else:
                    shift = np.arange(7, -1, -1, dtype=np.uint64) * np.uint64(8)
                    peer_hi = (bytes64[:, :8] << shift).sum(axis=1, dtype=np.uint64)
                    peer_lo = (bytes64[:, 8:] << shift).sum(axis=1, dtype=np.uint64)
            direction = np.full(b, 0 if ingress else 1, np.uint64)
            ka, kb, kc = pack_keys(
                peer_hi, peer_lo, ep_idx.astype(np.uint64), sports,
                dports.astype(np.uint64), protos.astype(np.uint64), direction,
            )
            if want_rev_nat:
                from .conntrack import CT_REPLY

                # revNAT ids read under the SAME lock hold as the find:
                # a timer gc()/compact between the lookup and a post-hoc
                # revnat read could hand back another flow's id
                state, slot, ct_rev = ct.lookup_batch(ka, kb, kc, want_revnat=True)
                ct_rev[state != CT_REPLY] = 0
            else:
                state, slot = ct.lookup_batch(ka, kb, kc)
            miss = state == CT_NEW

        verdict = np.full(b, FORWARD, np.int8)
        redirect = np.zeros(b, bool)
        enq = None
        midx = None
        if miss.any():
            midx = np.nonzero(miss)[0]
            enq = self._dispatch_enqueue(
                peer_bytes[midx],
                ep_idx[midx],
                dports[midx],
                protos[midx],
                ingress=ingress,
                family=family,
                bucketed=True,
                row_override=(
                    None if row_override is None else row_override[midx]
                ),
                bt=bt,
            )
        # completion must not create CT entries verdicted under a basis
        # that moved while the batch was in flight
        ct_epoch = self._ct_epoch
        pending = PendingBatch(self)

        def finish():
            rule_full = l4x_full = None
            if enq is not None:
                out = self._dispatch_complete(enq, bt)
                v, red = out[0], out[1]
                at_rule = at_l4x = at_hits = None
                if enq.attrib:
                    at_rule, at_l4x, at_hits = out[3:]
                if svc_drop is not None:
                    sd = svc_drop[midx]
                    v = np.where(sd, np.int8(DROP_NO_SERVICE), v)
                    red = red & ~sd
                    if at_rule is not None and sd.any():
                        # no-backend flows never reached a rule
                        at_rule = np.where(sd, np.int32(-1), at_rule)
                        at_hits = None
                verdict[midx] = v
                redirect[midx] = red
                if at_rule is not None:
                    # CT-bypassed established flows took no policy
                    # decision this batch: rule -1, reason "allowed"
                    # (rule_hits_total counts decisions, not packets)
                    rule_full = np.full(b, -1, np.int32)
                    l4x_full = np.zeros(b, bool)
                    rule_full[midx] = at_rule
                    l4x_full[midx] = at_l4x
                    self._account_attribution(
                        v, at_rule, at_l4x, at_hits, ingress=ingress
                    )
                # CT entries for newly-allowed flows (ct_create4,
                # bpf_lxc.c:~560: only successful verdicts create
                # state). L7-redirect flows are EXCLUDED: a CT bypass
                # would return redirect=False on later packets and
                # route them around the proxy — proxied connections
                # stay on the policy path (the reference tracks them in
                # the proxymap instead).
                ok = (v == FORWARD) & ~red
                if (
                    ok.any()
                    and self.conntrack is ct
                    and self._ct_epoch == ct_epoch
                ):
                    with bt.phase("ct_create"):
                        oidx = midx[ok]
                        ct.create_batch(
                            ka[oidx],
                            kb[oidx],
                            kc[oidx],
                            revnat=(
                                None if revnat_vals is None
                                else revnat_vals[oidx]
                            ),
                        )

            # proxymap handoff: redirected flows carry their full
            # 5-tuple here (sports present) — record for the L7
            # front-end
            if self.on_redirect is not None and redirect.any():
                for i in np.nonzero(redirect)[0]:
                    self.on_redirect(
                        bytes(int(x) & 0xFF for x in peer_bytes[i]),
                        int(ep_idx[i]), int(sports[i]), int(dports[i]),
                        int(protos[i]), ingress, family,
                    )

            # host counter accumulation (CT hits included)
            with bt.phase("counters"):
                with self._lock:
                    if self.counters.shape[0] == max(1, len(self._endpoints)):
                        cls = np.select(
                            [verdict == FORWARD, verdict == DROP_POLICY],
                            [0, 1],
                            default=2,
                        )
                        np.add.at(self.counters, (ep_idx, cls), 1)
                self._account_batch(verdict)
            with bt.phase("emit_events"):
                self._emit_flow_events(
                    peer_bytes, ep_idx, dports, protos, verdict,
                    ingress=ingress, family=family, redirect=redirect,
                    rule=rule_full, l4_covered=l4x_full,
                )
                if rule_full is not None:
                    self._record_flows(
                        peer_bytes, ep_idx, dports, protos, verdict,
                        rule_full, l4x_full, redirect, ingress=ingress,
                    )
            if want_rev_nat:
                # revNAT restore (bpf/lib/lb.h lb4_rev_nat via the CT
                # entry's rev_nat_index): flows whose CT hit is in the
                # REPLY direction carry the id of the service that
                # translated the original request — the caller rewrites
                # the reply source back to that VIP (rev_nat_frontend()).
                return verdict, redirect, ct_rev
            return verdict, redirect

        return _InFlight(pending, finish, bt, b=b, rev=want_rev_nat)

    def _process_device_ct(
        self,
        peer_bytes: np.ndarray,
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        sports: np.ndarray,
        *,
        ingress: bool,
        family: int,
        want_rev_nat: bool,
    ):
        """Dispatch through the fused device-CT program and thread the
        donated CT state forward."""
        import time as _time

        from .device_ct import make_state

        tr = self.tracer
        bt = tr.current() if tr.active else _NOOP_BATCH
        direction = TRAFFIC_INGRESS if ingress else TRAFFIC_EGRESS
        # same atomic snapshot rule as _dispatch (fused flag must match
        # the tables it was computed with); the fused CT program is not
        # attributed — its drops keep the generic policy reason
        # the fused CT path keeps the plain jnp.take gather even under
        # a 2D plan (GSPMD all-gathers the sharded table — correct,
        # just unoptimized; the CT program is not ident-aware yet)
        tables_map, pf_empty, v6_fused, _fs, _ndev, _at, _i2d, _sh = (
            self._dp_state
        )
        t = tables_map[(direction, family)]
        b = peer_bytes.shape[0]
        pad = _bucket(b) - b
        valid = np.zeros(b + pad, bool)
        valid[:b] = True
        peer_bytes, ep_idx, dports, protos, sports, _ = _pad_flows(
            pad, peer_bytes, ep_idx, dports, protos, sports
        )
        peer = _pack_v4_u32(peer_bytes) if family == 4 else peer_bytes
        now = jnp.asarray(np.int32(_time.monotonic()))
        with self._lock:
            if self._device_ct is None:
                # policyd-survive re-upload: after a quarantine rescue
                # pulled device entries into the host table, the next
                # fresh device table seeds from the host CT so
                # re-promotion onto the fused path does not forget the
                # rescued flows a second time. Without a rescue (the
                # steady-state OFF path) this is one bool read and the
                # exact pre-PR zeros table.
                if self._device_ct_seed and self.conntrack is not None:
                    self._device_ct_seed = False
                    self._device_ct = self._seed_device_ct()
                else:
                    self._device_ct = make_state(self._device_ct_bits)
            state = self._device_ct
            with bt.phase("dispatch"):
                v, red, counters, new_state = process_flows_ct(
                    t,
                    state,
                    jnp.asarray(peer),
                    jnp.asarray(ep_idx),
                    jnp.asarray(dports),
                    jnp.asarray(protos),
                    jnp.asarray(sports),
                    jnp.asarray(np.int32(0 if ingress else 1)),
                    now,
                    jnp.asarray(valid),
                    ep_count=max(1, len(self._endpoints)),
                    prefilter=(
                        ingress
                        and not pf_empty[0 if family == 4 else 1]
                    ),
                    levels=16,
                    family=family,
                    fused=v6_fused if family == 6 else False,
                )
            self._device_ct = new_state
            with bt.phase("host_sync"):
                counters = np.asarray(counters)
            if self.counters.shape == counters.shape:
                self.counters += counters
        with bt.phase("host_sync"):
            verdict = np.asarray(v)[:b]
            redirect = np.asarray(red)[:b]
        with bt.phase("counters"):
            self._account_batch(verdict)
        if self.on_redirect is not None and redirect.any():
            for i in np.nonzero(redirect)[0]:
                self.on_redirect(
                    bytes(int(x) & 0xFF for x in peer_bytes[i]),
                    int(ep_idx[i]), int(sports[i]), int(dports[i]),
                    int(protos[i]), ingress, family,
                )
        self._emit_flow_events(
            peer_bytes[:b], ep_idx[:b], dports[:b], protos[:b], verdict,
            ingress=ingress, family=family, redirect=redirect,
        )
        if want_rev_nat:
            # no LB table was active on this path (fallback condition)
            return verdict, redirect, np.zeros(b, np.uint16)
        return verdict, redirect

    # ------------------------------------------------------------------
    def submit(
        self,
        src_ips: np.ndarray,  # [B] uint32 IPv4 host-order (peer address)
        ep_idx: np.ndarray,  # [B] int32 local endpoint index
        dports: np.ndarray,
        protos: np.ndarray,
        *,
        ingress: bool = True,
        sports: Optional[np.ndarray] = None,
        return_rev_nat: bool = False,
        tunnel_identities: Optional[np.ndarray] = None,
    ) -> PendingBatch:
        """Enqueue an IPv4 batch WITHOUT pulling its results: returns a
        PendingBatch whose .result() blocks on the device round-trip.
        Submitting the next batch before resolving the previous one
        overlaps host prep with device execution (bounded by
        VerdictPipelineDepth — admission past the bound completes the
        oldest in-flight batch first)."""
        src = np.asarray(src_ips)
        peer_bytes = ipv4_to_bytes(src)
        return self._submit(
            peer_bytes, ep_idx, dports, protos, sports,
            ingress=ingress, family=4,
            peer_words=(
                np.zeros(src.shape[0], np.uint64),
                src.astype(np.uint64),
            ),
            want_rev_nat=return_rev_nat,
            tunnel_identities=tunnel_identities,
        )

    def submit_v6(
        self,
        peer_bytes: np.ndarray,  # [B, 16] int32 address bytes
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        *,
        ingress: bool = True,
        sports: Optional[np.ndarray] = None,
        return_rev_nat: bool = False,
        tunnel_identities: Optional[np.ndarray] = None,
    ) -> PendingBatch:
        """IPv6 counterpart of submit()."""
        return self._submit(
            np.asarray(peer_bytes, np.int32), ep_idx, dports, protos, sports,
            ingress=ingress, family=6, want_rev_nat=return_rev_nat,
            tunnel_identities=tunnel_identities,
        )

    def process(
        self,
        src_ips: np.ndarray,  # [B] uint32 IPv4 host-order (peer address)
        ep_idx: np.ndarray,  # [B] int32 local endpoint index
        dports: np.ndarray,
        protos: np.ndarray,
        *,
        ingress: bool = True,
        sports: Optional[np.ndarray] = None,
        return_rev_nat: bool = False,
        tunnel_identities: Optional[np.ndarray] = None,
    ):
        """IPv4 batch → (verdicts [B] int8, redirect [B] bool);
        accumulates the per-endpoint counters. ``src_ips`` is the peer
        address (source for ingress, destination for egress). Passing
        ``sports`` with a conntrack-enabled pipeline activates the CT
        pre-pass (established/reply bypass + creation on allow).
        ``return_rev_nat`` appends a [B] uint16 array of revNAT ids for
        reply-direction CT hits (0 otherwise) — resolve with
        rev_nat_frontend() to restore the VIP on reply sources.
        ``tunnel_identities`` ([B] int, 0 = none) marks overlay-decapped
        flows whose encap key carried the peer identity — trusted over
        the ipcache LPM when known (bpf_overlay.c)."""
        return self.submit(
            src_ips, ep_idx, dports, protos,
            ingress=ingress, sports=sports, return_rev_nat=return_rev_nat,
            tunnel_identities=tunnel_identities,
        ).result()

    def process_v6(
        self,
        peer_bytes: np.ndarray,  # [B, 16] int32 address bytes
        ep_idx: np.ndarray,
        dports: np.ndarray,
        protos: np.ndarray,
        *,
        ingress: bool = True,
        sports: Optional[np.ndarray] = None,
        return_rev_nat: bool = False,
        tunnel_identities: Optional[np.ndarray] = None,
    ):
        """IPv6 batch (16-level LPM walk, bpf_lxc.c:848 tail_ipv6_*)."""
        return self.submit_v6(
            peer_bytes, ep_idx, dports, protos,
            ingress=ingress, sports=sports, return_rev_nat=return_rev_nat,
            tunnel_identities=tunnel_identities,
        ).result()

    def rev_nat_frontend(self, revnat_id: int):
        """revNAT id (from a return_rev_nat=True process call) → the
        original frontend L3n4Addr, or None."""
        if self.lb is None or not revnat_id:
            return None
        return self.lb.rev_nat(int(revnat_id))
