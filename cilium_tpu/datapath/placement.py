"""Placement subsystem — device selection and mesh planning.

The pipeline historically formed an implicit 1D ``Mesh(jax.devices(),
("flows",))`` inline: every visible device joined the mesh and every
table was fully replicated onto all of them. That is the single-host,
single-tenant assumption. This module makes placement an explicit,
testable object:

  PlacementConfig  — what the operator asked for (device subset,
                     2D ``flows×ident`` axes, per-host process index)
  MeshPlan         — what the pipeline actually runs on (the mesh,
                     the axis shardings, a generation counter)

``resolve_plan`` is the only constructor of MeshPlans. It is pure with
respect to its inputs (config + requested modes + excluded set +
previous plan), so the failsafe ladder, the runtime options, and the
daemon boot path all re-form the mesh through one piece of logic. The
generation counter bumps whenever the resolved device set or axis
layout changes — callers key placed-table caches on it so a ladder
demotion/re-promotion can never serve tables placed on a stale mesh.

2D sharding splits the device grid into ``flows × ident``: flow
batches shard over the ``flows`` axis exactly as before, while the
identity dimension (dim 0) of the policymap bitmaps / rule tables /
sel_match matrices shards over ``ident`` — per-device table bytes
stop scaling with the full identity count. LPM trie nodes stay
replicated (their gathers are row-random per flow, not identity-
indexed). With ``ident`` of size 1 or 2D off, the plan degenerates to
the exact historical 1D/replicated layout.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class PlacementConfig:
    """Operator-facing placement intent (DaemonConfig → pipeline ctor).

    ``device_ids``: explicit device subset (None = all visible).
    ``ident_axis``: requested size of the ``ident`` mesh axis when 2D
    sharding is on; the resolver shrinks it to the largest factor of
    the eligible device count that fits (≥2, else the plan stays 1D).
    ``process_index``: on multi-host platforms, restrict the plan to
    devices owned by this process (single-host: 0 matches everything;
    a non-matching index falls back to the unfiltered set rather than
    an empty mesh so a misconfigured daemon degrades, not crashes).
    """

    device_ids: Optional[Tuple[int, ...]] = None
    ident_axis: int = 2
    process_index: int = 0


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A resolved placement: the mesh the pipeline runs on right now.

    ``flow_sharding`` places per-flow batch arrays (``P("flows")``),
    ``table_sharding`` replicates across the whole mesh (``P()``), and
    ``ident_sharding`` — non-None only on a 2D plan — row-shards
    ``[N, *]`` identity tables (``P("ident", None)``). ``flows_size``
    is the flows-axis extent: the bucket-ladder rung rounding and the
    per-shard span math use it, NOT the total device count (on a
    ``{'flows': 4, 'ident': 2}`` mesh a batch splits 4 ways, not 8).
    """

    generation: int
    mesh: Optional[Mesh]
    flow_sharding: Optional[NamedSharding]
    table_sharding: Optional[NamedSharding]
    ident_sharding: Optional[NamedSharding]
    flows_size: int
    device_ids: Tuple[int, ...]
    axes: Dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def is_2d(self) -> bool:
        return self.ident_sharding is not None

    @property
    def ident_size(self) -> int:
        return self.axes.get("ident", 1)


#: The pre-mesh state: host-only, no placement at all. Callers start
#: from this so the first resolve always bumps to generation 1.
EMPTY_PLAN = MeshPlan(
    generation=0,
    mesh=None,
    flow_sharding=None,
    table_sharding=None,
    ident_sharding=None,
    flows_size=1,
    device_ids=(),
    axes={},
)


def _ident_factor(n: int, want: int) -> int:
    """Largest factor of ``n`` that is ≤ ``want`` and ≥ 2 (1 = no 2D
    split possible — odd/prime device counts fall back to 1D)."""
    best = 1
    for f in range(2, max(2, want) + 1):
        if f <= n and n % f == 0:
            best = f
    return best


def eligible_devices(
    config: Optional[PlacementConfig],
    excluded: FrozenSet[int] = frozenset(),
):
    """Devices the plan may use, in a deterministic order: the config
    subset (or all visible), filtered to this host's process, minus
    the failsafe-excluded set. Never returns empty: exclusion of every
    eligible device falls back to the FIRST CONFIG-ELIGIBLE device —
    not ``jax.devices()[0]`` — so a placement-restricted daemon never
    demotes onto hardware it was told not to touch."""
    all_devs = jax.devices()
    if config is not None and config.device_ids:
        wanted = set(config.device_ids)
        devs = [d for d in all_devs if d.id in wanted]
        if not devs:  # config names no visible device: degrade to all
            devs = list(all_devs)
    else:
        devs = list(all_devs)
    if config is not None:
        proc = [d for d in devs if d.process_index == config.process_index]
        if proc:
            devs = proc
    live = [d for d in devs if d.id not in excluded]
    if not live:
        live = devs[:1]
    return live


def resolve_plan(
    config: Optional[PlacementConfig],
    *,
    sharding: bool,
    mesh_2d: bool = False,
    excluded: FrozenSet[int] = frozenset(),
    prev: Optional[MeshPlan] = None,
) -> MeshPlan:
    """Resolve the placement intent into a MeshPlan.

    Returns ``prev`` unchanged (same generation) when the resolved
    device tuple AND axis layout match it — mesh identity is stable
    across no-op refreshes so jit caches and placed tables survive.
    Any real change (device lost to the ladder, sharding/2D toggled,
    config swap) produces a new plan with ``prev.generation + 1``.
    """
    prev = prev or EMPTY_PLAN
    devs = eligible_devices(config, excluded)
    n = len(devs)

    want_mesh = sharding and n > 1
    ident = 0
    if want_mesh and mesh_2d:
        want = config.ident_axis if config is not None else 2
        f = _ident_factor(n, want)
        if f >= 2 and n // f >= 1:
            ident = f

    if ident >= 2:
        axes = {"flows": n // ident, "ident": ident}
    elif want_mesh:
        axes = {"flows": n}
    else:
        axes = {}

    ids = tuple(d.id for d in devs)
    if ids == prev.device_ids and axes == prev.axes:
        return prev

    gen = prev.generation + 1
    if not want_mesh:
        return MeshPlan(
            generation=gen,
            mesh=None,
            flow_sharding=None,
            table_sharding=None,
            ident_sharding=None,
            flows_size=1,
            device_ids=ids,
            axes={},
        )

    if ident >= 2:
        grid = np.array(devs).reshape(n // ident, ident)
        mesh = Mesh(grid, ("flows", "ident"))
        return MeshPlan(
            generation=gen,
            mesh=mesh,
            flow_sharding=NamedSharding(mesh, P("flows")),
            table_sharding=NamedSharding(mesh, P()),
            # one spec serves every [N, *] rank-2 identity table
            # (id_bits, rule_tab, sel_match): rows shard, words stay
            ident_sharding=NamedSharding(mesh, P("ident", None)),
            flows_size=n // ident,
            device_ids=ids,
            axes=axes,
        )

    mesh = Mesh(np.array(devs), ("flows",))
    return MeshPlan(
        generation=gen,
        mesh=mesh,
        flow_sharding=NamedSharding(mesh, P("flows")),
        table_sharding=NamedSharding(mesh, P()),
        ident_sharding=None,
        flows_size=n,
        device_ids=ids,
        axes=axes,
    )
