# policyd: hot
"""Depth auto-tuner for the bounded in-flight dispatch queue
(policyd-autotune).

``verdict_pipeline_depth`` trades throughput for verdict latency: depth
1 is fully synchronous, deeper queues hide device execution behind host
prep of successor batches — but past the point where the device is
saturated, every extra slot only ages batches in flight (the completion
half IS the p99 verdict-latency proxy). PR 3 left the knob static even
though the pipeline already measures both halves of every batch; this
controller closes the loop.

Control law — a small hill climber over EWMA-smoothed epoch stats:

- Batches are folded into fixed-size epochs (enqueue-half ns,
  completion-half ns, queue occupancy at admission, flows served).
- At each epoch boundary the current depth's throughput proxy
  (flows / busy-second) and completion-half latency are EWMA-updated.
- The controller PROBES one step up only while the queue is saturated
  (mean occupancy ≈ depth — the submitter is blocking on admission, so
  a deeper queue could actually be used), then judges the probe against
  the anchor depth one epoch later: the step is kept only if throughput
  improved by ``improve`` without the completion-half latency degrading
  past ``degrade``; otherwise it backs off and a cooldown stops it from
  re-probing the same losing step every other epoch.
- Independent of probing, a depth whose completion latency sits
  ``degrade`` above the next-lower depth's record steps back down.

The tuner never touches the pipeline itself: ``observe()`` returns the
new target depth (or None) and the pipeline applies it, so the OFF path
stays exactly one attribute read (``pipeline._tuner is None``).

Bounds are a stable contract (ROADMAP): depth moves in
[min_depth, max_depth] only, max_depth defaulting to
``DaemonConfig.verdict_pipeline_max_depth`` (4).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

# epochs a failed up-probe locks its target depth out of re-probing —
# without this the controller oscillates d ↔ d+1 forever on a saturated
# queue where deeper never helps (the common host-bound case)
PROBE_COOLDOWN_EPOCHS = 8


class DepthTuner:
    """EWMA hill-climbing controller for ``verdict_pipeline_depth``."""

    def __init__(
        self,
        min_depth: int = 1,
        max_depth: int = 4,
        *,
        epoch: int = 16,
        alpha: float = 0.3,
        improve: float = 0.03,
        degrade: float = 0.25,
    ) -> None:
        self.min_depth = max(1, int(min_depth))
        self.max_depth = max(self.min_depth, int(max_depth))
        self.epoch = max(2, int(epoch))
        self.alpha = float(alpha)
        self.improve = float(improve)
        self.degrade = float(degrade)
        self._lock = threading.Lock()
        # depth → [vps_ewma, complete_lat_ns_ewma, epochs_seen]
        self._stats: Dict[int, List[float]] = {}
        self._probing = False
        self._anchor: Optional[int] = None
        self._cooldown: Dict[int, int] = {}  # depth → epochs locked out
        self.ups = 0
        self.downs = 0
        self._epochs = 0
        self._n = 0
        self._flows = 0
        self._enq_ns = 0
        self._comp_ns = 0
        self._occ = 0.0

    # -- hot-path API ----------------------------------------------------
    def observe(
        self,
        depth: int,
        flows: int,
        enqueue_ns: int,
        complete_ns: int,
        occupancy: int,
    ) -> Optional[int]:
        """Fold one completed batch into the current epoch. Returns the
        new target depth when the epoch closed with a decision, else
        None. Called from the completion half only — never on the
        enqueue hot path."""
        with self._lock:
            self._n += 1
            self._flows += int(flows)
            self._enq_ns += int(enqueue_ns)
            self._comp_ns += int(complete_ns)
            self._occ += float(occupancy)
            if self._n < self.epoch:
                return None
            return self._close_epoch(int(depth))

    # -- epoch boundary (held lock) --------------------------------------
    def _close_epoch(self, depth: int) -> Optional[int]:
        busy_s = (self._enq_ns + self._comp_ns) / 1e9
        vps = self._flows / busy_s if busy_s > 0 else 0.0
        lat = self._comp_ns / self._n
        occ = self._occ / self._n
        self._n = 0
        self._flows = 0
        self._enq_ns = 0
        self._comp_ns = 0
        self._occ = 0.0
        self._epochs += 1
        for d in list(self._cooldown):
            self._cooldown[d] -= 1
            if self._cooldown[d] <= 0:
                del self._cooldown[d]

        st = self._stats.get(depth)
        if st is None:
            st = self._stats[depth] = [vps, lat, 1.0]
        else:
            a = self.alpha
            st[0] += a * (vps - st[0])
            st[1] += a * (lat - st[1])
            st[2] += 1.0

        target = depth
        if self._probing:
            anchor = self._anchor
            self._probing = False
            base = None if anchor is None else self._stats.get(anchor)
            if (
                anchor is not None
                and anchor != depth
                and base is not None
                and (
                    st[0] < base[0] * (1.0 + self.improve)
                    or st[1] > base[1] * (1.0 + self.degrade)
                )
            ):
                # probe failed: no real throughput win, or it aged the
                # completion half — back off and stop re-trying for a while
                target = anchor
                self._cooldown[depth] = PROBE_COOLDOWN_EPOCHS
            # probe kept: the new depth is simply the depth we are at
        elif (
            depth < self.max_depth
            and occ >= depth - 0.5
            and self._cooldown.get(depth + 1, 0) <= 0
        ):
            # queue saturated — the submitter blocks on admission, so a
            # deeper queue is actually usable; probe one step up
            self._probing = True
            self._anchor = depth
            target = depth + 1
        elif depth > self.min_depth:
            lower = self._stats.get(depth - 1)
            if (
                lower is not None
                and st[1] > lower[1] * (1.0 + self.degrade)
                and st[0] < lower[0] * (1.0 + self.improve)
            ):
                # the depth we sit at costs latency and buys nothing the
                # next-lower depth didn't deliver
                target = depth - 1
        if target == depth:
            return None
        if target > depth:
            self.ups += 1
        else:
            self.downs += 1
        return target

    # -- cold-path API ---------------------------------------------------
    def snapshot(self) -> Dict:
        """State for GET /traces and the ``cilium-tpu traces`` header."""
        with self._lock:
            return {
                "min_depth": self.min_depth,
                "max_depth": self.max_depth,
                "epoch": self.epoch,
                "epochs_seen": self._epochs,
                "probing": self._probing,
                "adjustments": {"up": self.ups, "down": self.downs},
                "stats": {
                    str(d): {
                        "vps": round(s[0], 1),
                        "complete_lat_us": round(s[1] / 1e3, 1),
                        "epochs": int(s[2]),
                    }
                    for d, s in sorted(self._stats.items())
                },
            }
