"""Wire front-end: REAL packets off endpoint veths into the verdict
pipeline.

Reference position: bpf_lxc.c attached to the endpoint's lxc* device —
every packet entering/leaving the container crosses it and gets a
policy verdict. Without kernel offload, the userspace equivalent is an
AF_PACKET tap on the same host-side veth (created by the CNI layer,
plugins/netns.py): frames are drained into batches, their 5-tuples
parsed host-side, and the batch verdicted in ONE DatapathPipeline
call — the batching trade the whole framework is built around.

This is the demonstration-grade packet path (drop enforcement would
additionally require sitting inline, e.g. via a TAP pair or TC); its
role here is that the enforcement front-end consumes real wire bytes
end to end: netns → veth → AF_PACKET → parse → pipeline verdict.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..monitor.dissect import l2_offsets

ETH_P_ALL = 0x0003
ETH_P_IP = 0x0800

FLOW_FIELDS = ("src", "dst", "proto", "sport", "dport")


def parse_ipv4_frame(frame: bytes) -> Optional[Tuple[int, int, int, int, int]]:
    """Ethernet frame → (src_u32, dst_u32, proto, sport, dport) or None
    for non-IPv4 / truncated frames. Ports are 0 for non-TCP/UDP and
    for non-first fragments (their payload bytes are NOT L4 headers).

    L2 framing (ethertype / 802.1Q / truncation) comes from the shared
    monitor.dissect.l2_offsets rules; this is just the hot-loop tuple
    extraction on top."""
    l2 = l2_offsets(frame)
    if l2 is None:
        return None
    ethertype, ip0, _vlan = l2
    if ethertype != ETH_P_IP or len(frame) < ip0 + 20:
        return None
    ihl = (frame[ip0] & 0x0F) * 4
    if ihl < 20 or len(frame) < ip0 + ihl:
        return None
    proto = frame[ip0 + 9]
    (frag,) = struct.unpack_from(">H", frame, ip0 + 6)
    src, dst = struct.unpack_from(">II", frame, ip0 + 12)
    sport = dport = 0
    if (
        proto in (6, 17)
        and (frag & 0x1FFF) == 0  # first fragment only carries L4
        and len(frame) >= ip0 + ihl + 4
    ):
        sport, dport = struct.unpack_from(">HH", frame, ip0 + ihl)
    return src, dst, proto, sport, dport


class VethSniffer:
    """Collects IPv4 5-tuples from one interface (the endpoint's
    host-side veth) on a background thread."""

    def __init__(self, ifname: str) -> None:
        self.ifname = ifname
        self._sock = socket.socket(
            socket.AF_PACKET, socket.SOCK_RAW, socket.htons(ETH_P_ALL)
        )
        self._sock.bind((ifname, 0))
        self._sock.settimeout(0.2)
        self._lock = threading.Lock()
        self._flows: List[Tuple[int, int, int, int, int]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "VethSniffer":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                frame = self._sock.recv(65535)
            except socket.timeout:
                continue
            except OSError:
                return
            parsed = parse_ipv4_frame(frame)
            if parsed is not None:
                with self._lock:
                    self._flows.append(parsed)

    def drain(self) -> List[Tuple[int, int, int, int, int]]:
        with self._lock:
            out = self._flows
            self._flows = []
        return out

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        try:
            self._sock.close()
        except OSError:
            pass


class WireEnforcer:
    """Batches sniffed flows into pipeline verdicts.

    ``dst_endpoints`` maps destination IPv4 (dotted) → local endpoint
    id: a captured packet TO one of those addresses is an ingress flow
    for that endpoint (the tail_ipv4_policy position); everything else
    is ignored. Verdict counters accumulate per endpoint id."""

    def __init__(self, pipeline, dst_endpoints: Dict[str, int]) -> None:
        import ipaddress

        self.pipeline = pipeline
        self._dst_map = {
            int(ipaddress.IPv4Address(ip)): ep_id
            for ip, ep_id in dst_endpoints.items()
        }
        self.verdicts: Dict[int, Dict[int, int]] = {}  # ep → verdict → n

    def process_flows(
        self, flows: List[Tuple[int, int, int, int, int]]
    ) -> int:
        """Verdict one drained batch → number of flows enforced."""
        picked = []
        for src, dst, proto, sport, dport in flows:
            ep_id = self._dst_map.get(dst)
            if ep_id is None:
                continue
            idx = self.pipeline.endpoint_index(ep_id)
            if idx is None:
                continue  # endpoint gone/not synced: never verdict a
                # flow against whatever occupies another index
            picked.append((src, ep_id, idx, dport, proto, sport))
        if not picked:
            return 0
        src_ips = np.asarray([p[0] for p in picked], np.uint32)
        ep_ids = [p[1] for p in picked]
        ep_idx = np.asarray([p[2] for p in picked], np.int32)
        dports = np.asarray([p[3] for p in picked], np.int32)
        protos = np.asarray([p[4] for p in picked], np.int32)
        sports = np.asarray([p[5] for p in picked], np.int32)
        v, _red = self.pipeline.process(
            src_ips, ep_idx, dports, protos, ingress=True, sports=sports
        )
        for ep_id, verdict in zip(ep_ids, v):
            self.verdicts.setdefault(ep_id, {})
            self.verdicts[ep_id][int(verdict)] = (
                self.verdicts[ep_id].get(int(verdict), 0) + 1
            )
        return len(picked)

    def run_from(
        self, sniffers: List[VethSniffer], duration: float,
        poll_s: float = 0.1,
    ) -> int:
        """Drain+verdict loop for ``duration`` seconds → flows enforced."""
        total = 0
        deadline = time.monotonic() + duration
        while time.monotonic() < deadline:
            batch: List[Tuple[int, int, int, int, int]] = []
            for s in sniffers:
                batch.extend(s.drain())
            if batch:
                total += self.process_flows(batch)
            else:
                time.sleep(poll_s)
        return total
