"""Endpoint lifecycle (reference: pkg/endpoint, pkg/endpointmanager)."""

from .endpoint import Endpoint, EndpointState, RegenerationStats
from .manager import EndpointManager

__all__ = ["Endpoint", "EndpointState", "RegenerationStats", "EndpointManager"]
