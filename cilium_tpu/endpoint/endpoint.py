"""Endpoint: a workload whose policy is enforced.

Reference: pkg/endpoint/endpoint.go (struct :288, state machine
:264-270,442-450), policy.go (regeneration pipeline :506-812), and the
desired/realized policymap sync (endpoint.go:2572).

Regeneration here = recompute the endpoint's desired policymap entries
through the device engine (ops/materialize for this endpoint's
identity), then diff desired vs realized into the endpoint's PolicyMap
— the syncPolicyMap semantics — while the datapath pipeline swaps its
device tables wholesale. The per-phase wall time lands in
RegenerationStats (spanstat, pkg/endpoint/metrics.go).
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import metrics
from ..identity.model import Identity
from ..labels import LabelArray, parse_label_array
from ..maps.policymap import PolicyMap
from ..ops.materialize import PolicyKey
from ..option import OptionMap
from ..utils.spanstat import SpanStat


class EndpointState(str, enum.Enum):
    # endpoint.go state strings (:264-270)
    CREATING = "creating"
    WAITING_FOR_IDENTITY = "waiting-for-identity"
    READY = "ready"
    WAITING_TO_REGENERATE = "waiting-to-regenerate"
    REGENERATING = "regenerating"
    RESTORING = "restoring"
    DISCONNECTING = "disconnecting"
    DISCONNECTED = "disconnected"
    INVALID = "invalid"


# Legal transitions (endpoint.go SetStateLocked:442).
_TRANSITIONS = {
    EndpointState.CREATING: {EndpointState.WAITING_FOR_IDENTITY, EndpointState.READY, EndpointState.DISCONNECTING, EndpointState.INVALID},
    EndpointState.WAITING_FOR_IDENTITY: {EndpointState.READY, EndpointState.DISCONNECTING},
    EndpointState.READY: {EndpointState.WAITING_TO_REGENERATE, EndpointState.DISCONNECTING},
    EndpointState.WAITING_TO_REGENERATE: {EndpointState.REGENERATING, EndpointState.DISCONNECTING},
    EndpointState.REGENERATING: {EndpointState.READY, EndpointState.WAITING_TO_REGENERATE, EndpointState.DISCONNECTING},
    EndpointState.RESTORING: {EndpointState.WAITING_TO_REGENERATE, EndpointState.DISCONNECTING},
    EndpointState.DISCONNECTING: {EndpointState.DISCONNECTED},
    EndpointState.DISCONNECTED: set(),
    EndpointState.INVALID: set(),
}


@dataclasses.dataclass
class RegenerationStats:
    total: SpanStat = dataclasses.field(default_factory=SpanStat)
    policy_calculation: SpanStat = dataclasses.field(default_factory=SpanStat)
    map_sync: SpanStat = dataclasses.field(default_factory=SpanStat)
    success: bool = False


class Endpoint:
    def __init__(
        self,
        endpoint_id: int,
        labels: LabelArray,
        *,
        ipv4: Optional[str] = None,
        ipv6: Optional[str] = None,
        container_id: str = "",
        pod_name: str = "",
        parent_options: Optional[OptionMap] = None,
    ) -> None:
        self.id = endpoint_id
        self.labels = labels
        self.ipv4 = ipv4
        self.ipv6 = ipv6
        self.container_id = container_id
        self.pod_name = pod_name
        self.identity: Optional[Identity] = None
        self.options = OptionMap(parent=parent_options)
        self.state = EndpointState.CREATING
        self.policy_revision = 0  # realized revision
        self.policy_map = PolicyMap(name=f"cilium_policy_{endpoint_id}")
        self.desired: Dict[PolicyKey, int] = {}
        self.stats = RegenerationStats()
        self._lock = threading.RLock()
        # One builder per endpoint at a time (the reference serializes
        # via the build queue, pkg/endpoint/policy.go:812).
        self._build_lock = threading.Lock()
        self._state_log: List[Tuple[float, EndpointState]] = [(time.time(), self.state)]
        # bounded status log: state moves + regeneration outcomes (the
        # per-endpoint status log `cilium endpoint log` prints;
        # pkg/endpoint StatusLog / endpoint_log.go)
        self.status_log: collections.deque = collections.deque(maxlen=64)
        self._log_status("state", self.state.value)

    def _log_status(self, code: str, message: str) -> None:
        with self._lock:
            self.status_log.append((time.time(), code, message))

    def status_log_snapshot(self):
        """Copy under the lock: builder threads append concurrently and
        a bare iteration would raise 'deque mutated during iteration'."""
        with self._lock:
            return list(self.status_log)

    # -- state machine --------------------------------------------------
    def set_state(self, new: EndpointState) -> bool:
        with self._lock:
            if new == self.state:
                return True
            if new not in _TRANSITIONS.get(self.state, set()):
                return False
            self.state = new
            self._state_log.append((time.time(), new))
            self._log_status("state", new.value)
            return True

    def set_identity(self, identity: Identity) -> None:
        with self._lock:
            self.identity = identity
            if self.state in (EndpointState.CREATING, EndpointState.WAITING_FOR_IDENTITY):
                self.state = EndpointState.READY

    # -- desired/realized sync -----------------------------------------
    def sync_policy_map(self, desired: Dict[PolicyKey, int]) -> Tuple[int, int]:
        """Diff desired vs realized and apply (endpoint.go:2572):
        returns (added, deleted)."""
        with self._lock:
            realized = {k: e.proxy_port for k, e in self.policy_map.dump()}
            added = deleted = 0
            for key, proxy in desired.items():
                if realized.get(key) != proxy:
                    self.policy_map.allow(key, proxy)
                    added += 1
            for key in realized:
                if key not in desired:
                    self.policy_map.delete(key)
                    deleted += 1
            self.desired = dict(desired)
            return added, deleted

    def regenerate(self, pipeline, reason: str = "", proxy=None) -> bool:
        """One regeneration pass against the shared datapath pipeline
        (the regenerateBPF orchestration, pkg/endpoint/bpf.go:362).
        Serialized per endpoint via the build lock. When ``proxy`` is
        given, L7 redirects are created/updated/removed to match the
        resolved L4 policy (addNewRedirects / removeOldRedirects,
        pkg/endpoint/bpf.go:488-497)."""
        with self._build_lock:
            if not self.set_state(EndpointState.WAITING_TO_REGENERATE):
                if self.state != EndpointState.WAITING_TO_REGENERATE:
                    return False
            self.set_state(EndpointState.REGENERATING)
            stats = self.stats = RegenerationStats()
            ok = False
            try:
                with stats.total:
                    with stats.policy_calculation:
                        pipeline.rebuild()
                        snaps = pipeline.snapshots()
                        idx = pipeline.endpoint_index(self.id)
                        desired = snaps[idx].entries if idx is not None else {}
                    if proxy is not None:
                        self._update_redirects(pipeline, proxy)
                    with stats.map_sync:
                        self.sync_policy_map(desired)
                    # Stamp the revision the engine actually compiled, not
                    # a re-read of repo.revision: a rule batch landing
                    # after the rebuild must not be reported as realized.
                    compiled = pipeline.engine._compiled
                    self.policy_revision = (
                        compiled.revision if compiled is not None else 0
                    )
                ok = True
            finally:
                stats.success = ok
                self._log_status(
                    "regen-ok" if ok else "regen-fail",
                    (reason or "regeneration")
                    + f" ({stats.total.total() * 1000:.1f}ms, "
                      f"rev {self.policy_revision})",
                )
                self.set_state(EndpointState.READY)
                metrics.endpoint_regeneration_count.inc(
                    labels={"outcome": "success" if ok else "failure"}
                )
                metrics.endpoint_regeneration_time.observe(stats.total.total())
            return ok

    def _update_redirects(self, pipeline, proxy) -> None:
        """Create/update redirects for every L7-bearing filter in the
        resolved L4 policy; remove stale ones. Identity scoping per rule
        comes from matching filter endpoint selectors over the registry
        (the NPDS policy translation, pkg/envoy/server.go:267-331)."""
        from ..l7.http_policy import HTTPPolicy
        from ..l7.kafka_policy import KafkaACL
        from ..policy.api import HTTPRule, KafkaRule

        engine = pipeline.engine
        l4 = engine.repo.resolve_l4_policy(self.labels)
        identities = list(engine.registry)
        wanted = set()
        for direction_map, ingress in ((l4.ingress, True), (l4.egress, False)):
            for f in direction_map:
                if not f.is_redirect:
                    continue
                http_rules, kafka_rules = [], []
                for sel, rules in f.l7_rules_per_ep.items():
                    if sel.is_wildcard:
                        idents = None
                    else:
                        idents = {i.id for i in identities if sel.matches(i.labels)}
                    for hr in rules.http:
                        http_rules.append((hr, idents))
                    for kr in rules.kafka:
                        kafka_rules.append((kr, idents))
                    if not rules.http and not rules.kafka:
                        # Wildcarded L7: this peer flows through the
                        # proxy unrestricted (wildcardL3L4Rules).
                        if f.l7_parser == "http":
                            http_rules.append((HTTPRule(), idents))
                        elif f.l7_parser == "kafka":
                            kafka_rules.append((KafkaRule(), idents))
                proxy.create_or_update_redirect(
                    self.id,
                    f.port,
                    f.l7_parser,
                    ingress=ingress,
                    http_policy=HTTPPolicy(http_rules) if f.l7_parser == "http" else None,
                    kafka_acl=KafkaACL(kafka_rules) if f.l7_parser == "kafka" else None,
                )
                wanted.add((f.port, ingress))
        for key, r in proxy.redirects().items():
            if r.endpoint_id == self.id and (r.dst_port, r.ingress) not in wanted:
                proxy.remove_redirect(r.endpoint_id, r.dst_port, r.ingress)

    # -- snapshot/restore (pkg/endpoint/restore.go) ---------------------
    def to_snapshot(self) -> str:
        return json.dumps(
            {
                "id": self.id,
                "labels": list(self.labels.to_strings()),
                "ipv4": self.ipv4,
                "ipv6": self.ipv6,
                "container_id": self.container_id,
                "pod_name": self.pod_name,
                "policy_revision": self.policy_revision,
                "state": self.state.value,
            }
        )

    @classmethod
    def from_snapshot(cls, blob: str, parent_options: Optional[OptionMap] = None) -> "Endpoint":
        d = json.loads(blob)
        ep = cls(
            d["id"],
            parse_label_array(d["labels"]),
            ipv4=d.get("ipv4"),
            ipv6=d.get("ipv6"),
            container_id=d.get("container_id", ""),
            pod_name=d.get("pod_name", ""),
            parent_options=parent_options,
        )
        ep.state = EndpointState.RESTORING
        ep.policy_revision = d.get("policy_revision", 0)
        return ep

    def status(self) -> Dict:
        return {
            "id": self.id,
            "state": self.state.value,
            "identity": self.identity.id if self.identity else None,
            "labels": list(self.labels.to_strings()),
            "ipv4": self.ipv4,
            "policy-revision": self.policy_revision,
            "policy-map-entries": len(self.policy_map),
        }
