"""Endpoint registry + regeneration fan-out.

Reference: pkg/endpointmanager/manager.go — global registry with
lookups by cilium ID / container ID / pod name / IPv4 (:78-143),
`RegenerateAllEndpoints` (:271) fanning out to the builder worker pool
(daemon/daemon.go:235 StartEndpointBuilders, default #CPUs), and the
conntrack GC driver (EnableConntrackGC).
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
from typing import Dict, List, Optional

from ..maps.ctmap import ConntrackMap
from ..utils.controller import ControllerManager
from .endpoint import Endpoint


class EndpointManager:
    def __init__(self, workers: Optional[int] = None,
                 controllers: Optional[ControllerManager] = None) -> None:
        self._lock = threading.RLock()
        self._by_id: Dict[int, Endpoint] = {}
        self._by_container: Dict[str, Endpoint] = {}
        self._by_pod: Dict[str, Endpoint] = {}
        self._by_ipv4: Dict[str, Endpoint] = {}
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=workers or os.cpu_count() or 4,
            thread_name_prefix="ep-builder",
        )
        # shared with the daemon when embedded (one status registry);
        # standalone managers own their own
        self._controllers = controllers or ControllerManager()

    # -- registry -------------------------------------------------------
    def insert(self, ep: Endpoint) -> None:
        with self._lock:
            self._by_id[ep.id] = ep
            if ep.container_id:
                self._by_container[ep.container_id] = ep
            if ep.pod_name:
                self._by_pod[ep.pod_name] = ep
            if ep.ipv4:
                self._by_ipv4[ep.ipv4] = ep

    def remove(self, ep: Endpoint) -> None:
        with self._lock:
            self._by_id.pop(ep.id, None)
            if ep.container_id:
                self._by_container.pop(ep.container_id, None)
            if ep.pod_name:
                self._by_pod.pop(ep.pod_name, None)
            if ep.ipv4:
                self._by_ipv4.pop(ep.ipv4, None)

    def lookup(self, endpoint_id: int) -> Optional[Endpoint]:
        return self._by_id.get(endpoint_id)

    def lookup_container(self, container_id: str) -> Optional[Endpoint]:
        return self._by_container.get(container_id)

    def lookup_pod(self, pod_name: str) -> Optional[Endpoint]:
        return self._by_pod.get(pod_name)

    def lookup_ipv4(self, ip: str) -> Optional[Endpoint]:
        return self._by_ipv4.get(ip)

    def endpoints(self) -> List[Endpoint]:
        with self._lock:
            return list(self._by_id.values())

    def __len__(self) -> int:
        return len(self._by_id)

    # -- regeneration fan-out ------------------------------------------
    def regenerate_all(self, pipeline, reason: str = "", proxy=None) -> int:
        """Queue every endpoint to the builder pool; returns the count
        that regenerated successfully (RegenerateAllEndpoints). A
        failing endpoint counts as unsuccessful, it never aborts the
        fan-out. Passing ``proxy`` reconciles L7 redirects per
        endpoint (without it, policy changes would never refresh the
        redirects' identity scoping)."""
        eps = self.endpoints()
        futures = [
            self._pool.submit(ep.regenerate, pipeline, reason, proxy)
            for ep in eps
        ]
        ok = 0
        for f in futures:
            try:
                ok += 1 if f.result() else 0
            except Exception:  # noqa: BLE001 — per-endpoint failure isolated
                pass
        return ok

    # -- conntrack GC ---------------------------------------------------
    def enable_conntrack_gc(self, ctmap, interval: float = 60.0) -> None:
        """Periodic CT reaping; accepts any table with a gc() method
        (maps.ctmap.ConntrackMap or datapath.conntrack.FlowConntrack)."""
        self._controllers.update_controller(
            "ct-gc", lambda: ctmap.gc(), run_interval=interval
        )

    def shutdown(self) -> None:
        self._controllers.remove_all()
        self._pool.shutdown(wait=False)
