"""PolicyEngine: the device-backed policy resolver.

The TPU-native counterpart of the reference's per-endpoint regeneration
entry points (pkg/endpoint/policy.go regeneratePolicy →
repository.AllowsIngress*): owns a Repository + IdentityRegistry,
compiles them into device tensors, refreshes when revisions move, and
answers batched verdict queries.

The refresh is the "datapath compile" of this framework — instead of
clang→llc per endpoint (pkg/datapath/loader/compile.go), it re-packs
numpy tables and lets jit shape-bucketing reuse compiled XLA programs.

Refresh is **incremental** where the reference's is per-endpoint
(pkg/endpoint/policy.go:506-552 revision gate): identity churn becomes
device row updates (id_bits + sel_match rows), and rule imports that
fit the existing shape buckets append matrix cells in place
(compiler.DirectionPacker) with only the new selector columns
recomputed. Full recompiles happen only on bucket overflow, rule
deletion, or vocab word growth.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import metrics as _metrics
from . import u8proto
from .compiler import (
    CompiledPolicy,
    compile_policy_state,
    host_selector_matches,
    try_append_rules,
)
from .compiler.program import rule_origin_arrays, subject_sids, unpack_conjuncts
from .identity import IdentityRegistry
from .identity.model import MAX_USER_IDENTITY
from .ops.bitmap import compute_selector_matches
from .ops.verdict import (
    ALLOW,
    ATTR_NAMES,
    AttribTables,
    DevicePolicy,
    DeviceTables,
    Verdict,
    verdict_batch,
)
from .policy.repository import Repository

PROTO_TCP = u8proto.TCP
PROTO_UDP = u8proto.UDP


@jax.jit
def _set_rows(buf: jnp.ndarray, idx: jnp.ndarray, rows: jnp.ndarray) -> jnp.ndarray:
    # No donation: concurrent readers may still hold the old buffer.
    return buf.at[idx].set(rows)


@jax.jit
def _set_rows2(
    a: jnp.ndarray,
    b: jnp.ndarray,
    idx: jnp.ndarray,
    rows_a: jnp.ndarray,
    rows_b: jnp.ndarray,
):
    """Row-update two buffers in ONE dispatch (device round trips
    dominate small updates, especially over the axon tunnel)."""
    return a.at[idx].set(rows_a), b.at[idx].set(rows_b)


@jax.jit
def _set_rows_cols(
    buf: jnp.ndarray,
    rows: jnp.ndarray,  # [k] int32
    cols: jnp.ndarray,  # [w] int32
    vals: jnp.ndarray,  # [k, w]
) -> jnp.ndarray:
    """Sparse rows × word-window scatter for sel_match: a new selector
    matching k identities uploads O(k · window) words, not [N, S/32].
    Duplicate row indices (power-of-two padding repeats the last row)
    carry identical values, so the scatter stays deterministic."""
    return buf.at[rows[:, None], cols[None, :]].set(vals)


@jax.jit
def _set_col_window(
    buf: jnp.ndarray,
    start_word: jnp.ndarray,  # scalar int32
    window: jnp.ndarray,  # [N, w]
) -> jnp.ndarray:
    """Dense fallback when most identities match the appended
    selectors: upload the whole touched word window (still O(N · w),
    never the full matrix). Traced start keeps one program per width."""
    return jax.lax.dynamic_update_slice(buf, window, (jnp.int32(0), start_word))


def _pow2_rows(rows: np.ndarray) -> np.ndarray:
    """Pad a row-index list to a power-of-two bucket (min 8) by
    repeating the last row, bounding _set_rows_cols recompiles."""
    k = rows.shape[0]
    bucket = 8
    while bucket < k:
        bucket <<= 1
    if bucket == k:
        return rows
    return np.concatenate([rows, np.repeat(rows[-1:], bucket - k)])


def _pack_match_words(m: np.ndarray) -> np.ndarray:
    """[k, S] bool → [k, S/32] uint32 in sel_match bit order (S is a
    multiple of 128, so the byte view folds cleanly into words)."""
    packed = np.packbits(m, axis=1, bitorder="little")  # [k, S/8] uint8
    return packed.view(np.uint32).reshape(m.shape[0], m.shape[1] // 32)


class PolicyEngine:
    # Delta-log ring consumed by DatapathPipeline for incremental
    # policymap materialization.
    DELTA_LOG_CAP = 512

    def __init__(self, repo: Repository, registry: IdentityRegistry) -> None:
        self.repo = repo
        self.registry = registry
        self._lock = threading.Lock()
        self._compiled: Optional[CompiledPolicy] = None
        self._state = None  # compiler.CompileState
        self._device: Optional[DevicePolicy] = None
        self._sel_match_host: Optional[np.ndarray] = None
        # Dense row table for the compact ranges (reserved + user,
        # < 65536) and a dict for sparse local/CIDR identities
        # (≥ LOCAL_IDENTITY_BASE = 1<<24) — a dense table over the full
        # numeric space would be ~64MB per refresh.
        self._low_rows: Optional[np.ndarray] = None
        self._high_rows: dict = {}
        self._conj_unpacked = None  # cached unpack_conjuncts result
        # Identity change feed (registry observer) + outward delta log.
        self._pending_idents: List[Tuple[object, bool]] = []
        registry.observe(
            lambda ident, added: self._pending_idents.append((ident, added))
        )
        self.delta_seq = 0
        self._delta_log: List[Tuple[int, str, tuple]] = []
        self._bg_refresh: Optional[threading.Thread] = None
        self._install_gen = 0  # bumps on every _install_compiled
        # (key, {ingress: AttribTables}, n_rules) — rule-origin tables
        # for verdict attribution, rebuilt when the compile moves
        self._attrib_cache: Optional[tuple] = None

    # ------------------------------------------------------------------
    def _log_delta(self, kind: str, payload: tuple) -> None:
        self.delta_seq += 1
        self._delta_log.append((self.delta_seq, kind, payload))
        if len(self._delta_log) > self.DELTA_LOG_CAP:
            del self._delta_log[: len(self._delta_log) - self.DELTA_LOG_CAP]

    def deltas_since(self, seq: int):
        """Refresh deltas with seq > ``seq`` (oldest first), or None when
        the log has been truncated past that point (consumer must do a
        full rebuild)."""
        with self._lock:
            if seq >= self.delta_seq:
                return []
            if self._delta_log and self._delta_log[0][0] > seq + 1:
                return None
            if not self._delta_log and self.delta_seq > seq:
                return None
            return list(e for e in self._delta_log if e[0] > seq)

    # ------------------------------------------------------------------
    def _stale(self) -> bool:
        c = self._compiled
        return (
            c is None
            or c.revision != self.repo.revision
            or c.identity_version != self.registry.version
        )

    # policyd: refresh-path
    def refresh(self, force: bool = False) -> CompiledPolicy:
        """Recompile (or incrementally patch) if repository or identity
        state moved (the revision gate of pkg/endpoint/policy.go:506).

        A snapshot-RESTORED engine (untrusted counters, revision < 0)
        refreshes in the BACKGROUND instead: the restored tables keep
        serving verdicts while the O(identities × rules) recompile runs
        — the pinned-map continuity the reference gets from maps that
        outlive the agent (daemon/state.go:53,135). Every other path is
        synchronous as before."""
        with self._lock:
            if not force and not self._stale():
                return self._compiled  # type: ignore[return-value]
            if (
                not force
                and self._compiled is not None
                and self._compiled.revision < 0
            ):
                self._kick_background_refresh()
                return self._compiled
            if force or self._compiled is None:
                return self._full_refresh()

            t0 = time.perf_counter()
            c = self._compiled
            rule_ops = []
            if c.revision != self.repo.revision:
                rule_ops = self.repo.changes_since(c.revision)
                if rule_ops is None or any(
                    op not in ("add", "delete") for _, op, _ in rule_ops
                ):
                    return self._full_refresh()
            if rule_ops and self._state is None:
                # snapshot-restored engines carry no incremental
                # CompileState: any rule movement means a full rebuild
                return self._full_refresh()

            if not self._apply_identity_delta():
                return self._full_refresh()
            # Each applied op advances c.revision to ITS revision (never a
            # re-read of repo.revision): a concurrent AddList landing
            # between changes_since() and here must stay stale so the
            # next refresh picks it up (otherwise its rules — including
            # deny rules → fail-open — would never compile).
            for rev, op, payload in rule_ops:
                if op == "add":
                    # payload is the tuple of rules added at that rev
                    if not self._apply_rule_append(list(payload), rev):
                        return self._full_refresh()
                else:  # "delete": payload = (labels, deleted_rules)
                    if len(payload) < 2 or not self._apply_rule_delete(
                        list(payload[1]), rev
                    ):
                        return self._full_refresh()
            _metrics.engine_refresh_seconds.observe(
                time.perf_counter() - t0, {"kind": "incremental"}
            )
            _metrics.engine_refreshes_total.inc({"kind": "incremental"})
            return c

    @staticmethod
    def _compute_full(repo, registry):
        """The expensive half of a full refresh (host compile + device
        upload), lock-free so the background-continuity path can run it
        while restored tables keep serving."""
        compiled, state = compile_policy_state(repo, registry)
        sel_match = compute_selector_matches(
            jnp.asarray(compiled.id_bits),
            jnp.asarray(compiled.conj_req),
            jnp.asarray(compiled.conj_forbid),
            jnp.asarray(compiled.conj_valid),
            jnp.asarray(compiled.req_count),
        )
        device = DevicePolicy(
            id_bits=jnp.asarray(compiled.id_bits),
            sel_match=sel_match,
            ingress=DeviceTables.from_host(compiled.ingress),
            egress=DeviceTables.from_host(compiled.egress),
        )
        return compiled, state, sel_match, device

    def _install_compiled(self, compiled, state, sel_match, device) -> None:
        """Swap a computed full-refresh result in (lock held)."""
        self._install_gen += 1
        self._device = device
        # np.array (copy): asarray on a device buffer is read-only and
        # the incremental paths mutate this in place.
        self._sel_match_host = np.array(sel_match)
        low = np.full(MAX_USER_IDENTITY + 1, -1, np.int32)
        high: dict = {}
        for ident, row in compiled.id_to_row.items():
            if ident < low.size:
                low[ident] = row
            else:
                high[ident] = row
        self._low_rows = low
        self._high_rows = high
        self._compiled = compiled
        self._state = state
        self._conj_unpacked = None
        self._pending_idents.clear()
        self._log_delta("full", ())

    def _full_refresh(self) -> CompiledPolicy:
        t0 = time.perf_counter()
        compiled, state, sel_match, device = self._compute_full(
            self.repo, self.registry
        )
        self._install_compiled(compiled, state, sel_match, device)
        _metrics.engine_refresh_seconds.observe(
            time.perf_counter() - t0, {"kind": "full"}
        )
        _metrics.engine_refreshes_total.inc({"kind": "full"})
        return compiled

    # -- incremental paths ---------------------------------------------
    # policyd: refresh-path
    def _apply_identity_delta(self) -> bool:
        """Apply pending identity adds/releases as device row updates.
        False → caller must full-rebuild."""
        c = self._compiled
        assert c is not None
        target_version = self.registry.version
        if c.identity_version == target_version:
            return True
        pend = list(self._pending_idents)
        # The observer feed must cover exactly the version gap; if the
        # engine attached late or events were lost, rebuild.
        if len(pend) != target_version - c.identity_version:
            return False
        if self.registry.padded_rows() != c.id_bits.shape[0]:
            # row-capacity bucket crossed → the device tables reshape
            # and every jitted program over them recompiles
            _metrics.jit_shape_buckets_total.inc(
                {"site": "engine_rows", "result": "miss"}
            )
            return False
        _metrics.jit_shape_buckets_total.inc(
            {"site": "engine_rows", "result": "hit"}
        )

        vocab = self.registry.vocab
        touched: List[int] = []
        plans: List[Tuple[int, bool, object]] = []
        for ident, added in pend:
            row = self.registry.row(ident.id)
            if row is None:
                return False
            if added:
                bits = vocab.identity_bits(ident.labels)  # may grow vocab
                plans.append((row, True, (ident, bits)))
            else:
                plans.append((row, False, ident))
        if vocab.num_words > c.num_words:
            return False  # new label words → conjunct arrays reshape

        events: List[Tuple[int, int, bool]] = []
        for row, added, info in plans:
            if added:
                ident, bits = info
                c.id_bits[row] = vocab.pack(bits, c.num_words)
                c.row_ids[row] = ident.id
                c.row_live[row] = True
                c.id_to_row[ident.id] = row
                self._set_row_index(ident.id, row)
                events.append((row, ident.id, True))
            else:
                ident = info
                c.id_bits[row] = 0
                c.row_live[row] = False
                c.id_to_row.pop(ident.id, None)
                self._set_row_index(ident.id, -1)
                events.append((row, ident.id, False))
            touched.append(row)

        rows = sorted(set(touched))
        idx = np.asarray(rows, np.int32)
        # Recompute sel_match rows host-side (small [k, S] matmul);
        # unpacked conjunct operands are cached across identity churn.
        sub_bits = c.id_bits[idx]
        if self._conj_unpacked is None:
            self._conj_unpacked = unpack_conjuncts(c.conj_req, c.conj_forbid)
        m = host_selector_matches(
            sub_bits,
            c.conj_req,
            c.conj_forbid,
            c.conj_valid,
            c.req_count,
            unpacked=self._conj_unpacked,
        )  # [k, S]
        words = _pack_match_words(m)
        assert self._sel_match_host is not None
        self._sel_match_host[idx] = words

        device = self._device
        assert device is not None
        new_bits, new_match = _set_rows2(
            device.id_bits,
            device.sel_match,
            jnp.asarray(idx),
            jnp.asarray(sub_bits),
            jnp.asarray(words),
        )
        self._device = DevicePolicy(
            id_bits=new_bits,
            sel_match=new_match,
            ingress=device.ingress,
            egress=device.egress,
        )
        # Only the processed prefix is consumed — events racing in during
        # this delta stay queued and are covered by the next refresh.
        c.identity_version = target_version
        del self._pending_idents[: len(pend)]
        _metrics.engine_delta_rows_total.inc(value=len(events))
        # payload: (row, identity_id, live) events in apply order
        self._log_delta("rows", tuple(events))
        return True

    # policyd: refresh-path
    @staticmethod
    def _patch_tables(tables: DeviceTables, writes) -> DeviceTables:
        """Apply a DirectionPacker write log as per-matrix scatters —
        only the touched cells travel to the device, not the matrices.
        Transposed fields (deny_t/allow_t/en_t/ee_t) swap indices."""
        if not writes:
            return tables
        by_name: dict = {}
        for name, i, j, v in writes:
            by_name.setdefault(name, []).append((i, j, v))
        transposed = {"deny": "deny_t", "allow": "allow_t", "en": "en_t", "ee": "ee_t"}
        direct = {
            "s1": "s1_mat", "p1": "p1_mat", "gpn": "gpn_mat", "gpe": "gpe_mat",
            "s7": "s7_mat", "p7": "p7_mat", "g7": "g7_mat",
        }
        reps: dict = {}
        for name, items in by_name.items():
            ii = np.asarray([x[0] for x in items])
            jj = np.asarray([x[1] for x in items])
            # value carried per write: 1 for appends, 0 for deletion
            # retractions (DirectionPacker.remove_rule)
            # control-plane scatter prep: one upload per touched table
            # (≤9 names), not a per-flow loop — the serving path never
            # runs this
            vv8 = jnp.asarray(  # policyd-lint: disable=TPU002
                np.asarray([x[2] for x in items], np.int8)
            )
            if name in transposed:
                field = transposed[name]
                mat = getattr(tables, field)
                reps[field] = mat.at[jj, ii].set(vv8)
            elif name in direct:
                field = direct[name]
                mat = getattr(tables, field)
                reps[field] = mat.at[ii, jj].set(vv8)
            elif name == "group_no_peers":
                reps["group_no_peers"] = tables.group_no_peers.at[ii].set(
                    jnp.asarray(np.asarray([x[2] for x in items], bool))
                )
            elif name == "port_vocab":
                # (pid, port, proto): jj = port, third = proto
                vv = np.asarray([x[2] for x in items])
                reps["ports"] = tables.ports.at[ii].set(jnp.asarray(jj, jnp.int32))
                reps["protos"] = tables.protos.at[ii].set(jnp.asarray(vv, jnp.int32))
            else:  # pragma: no cover - unknown write kind
                raise KeyError(name)
        return tables.replace(**reps)

    # policyd: refresh-path
    def _apply_rule_append(self, rules, revision: int) -> bool:
        """Append a rule batch in place, advancing the compiled revision
        to the op's own revision. False → full rebuild needed."""
        c = self._compiled
        assert c is not None and self._state is not None
        res = try_append_rules(c, self._state, self.registry, rules, revision)
        if res is None:
            return False
        self._conj_unpacked = None  # conjunct rows changed
        old_s, new_s = res
        new_match = None
        if new_s > old_s:
            # New selector columns: match against ALL identities, then
            # OR the bits into the packed words (columns were zero).
            m = host_selector_matches(
                c.id_bits,
                c.conj_req[old_s:new_s],
                c.conj_forbid[old_s:new_s],
                c.conj_valid[old_s:new_s],
                c.req_count[old_s:new_s],
            )  # [N, k]
            sm = self._sel_match_host
            assert sm is not None
            for j, sid in enumerate(range(old_s, new_s)):
                col = m[:, j]
                if col.any():
                    sm[:, sid >> 5] |= col.astype(np.uint32) << np.uint32(sid & 31)
            # CSR-style device update: only the word WINDOW the new
            # selector bits land in moves, and only for the rows that
            # matched — k identities cost O(k · window) words, not the
            # full [N, S/32] re-upload this used to be.
            w0, w1 = old_s >> 5, (new_s - 1) >> 5
            cols = np.arange(w0, w1 + 1, dtype=np.int32)
            touched = np.nonzero(m.any(axis=1))[0]
            new_match = self._scatter_sel_window(sm, touched, cols)
            if touched.size:
                # payload: (sel_lo, sel_hi, touched identity rows) — the
                # CSR column-delta consumers (pipeline placed-copy
                # patching) replay against the host mirror's FINAL
                # state, so re-application is idempotent and ordering
                # against "rows" events is irrelevant
                self._log_delta(
                    "cols", (old_s, new_s, tuple(int(r) for r in touched))
                )
                # host counter: ``touched`` is the np row index set
                # from the host mirror diff, never a device array
                _metrics.engine_delta_cols_total.inc(value=int(touched.size))  # policyd-lint: disable=TPU005
        device = self._device
        assert device is not None
        self._device = DevicePolicy(
            id_bits=device.id_bits,
            sel_match=(
                new_match if new_match is not None else device.sel_match
            ),
            ingress=self._patch_tables(
                device.ingress, self._state.ingress.take_writes()
            ),
            egress=self._patch_tables(
                device.egress, self._state.egress.take_writes()
            ),
        )
        # payload: op + the subject selector ids the batch touches —
        # every verdict term is subject-gated, so these columns bound
        # the policymap cells the delta can change (the pipeline's
        # patch_endpoints_state contract)
        self._log_delta(
            "rules", ("add", subject_sids(rules, self._state.table))
        )
        return True

    # policyd: refresh-path
    def _scatter_sel_window(
        self, sm: np.ndarray, touched: np.ndarray, cols: np.ndarray
    ):
        """Upload the changed sel_match word window: row-sparse scatter
        when few identities matched, dense column window otherwise."""
        device = self._device
        assert device is not None
        if touched.size == 0:
            # no identity matches the new selectors — their device bits
            # were zero and stay zero
            return device.sel_match
        if touched.size <= max(8, sm.shape[0] // 4):
            rows = _pow2_rows(touched.astype(np.int32))
            return _set_rows_cols(
                device.sel_match,
                jnp.asarray(rows),
                jnp.asarray(cols),
                jnp.asarray(sm[np.ix_(rows, cols)]),
            )
        return _set_col_window(
            device.sel_match,
            jnp.int32(cols[0]),
            jnp.asarray(np.ascontiguousarray(sm[:, cols])),
        )

    # policyd: refresh-path
    def _apply_rule_delete(self, rules, revision: int) -> bool:
        """Retract a deleted rule batch in place (the incremental
        counterpart of repository.go DeleteByLabels:286): refcounted
        matrix cells drop to zero and are scattered to the device as
        value-0 writes — no recompile, no re-upload. False → full
        rebuild needed (a rule this compile never attributed)."""
        c = self._compiled
        state = self._state
        assert c is not None and state is not None
        ing, eg = state.ingress, state.egress
        keys = [id(r) for r in rules]
        # check attribution FIRST: a partial removal (ingress done,
        # egress unknown) would leave the two directions inconsistent
        if any(k not in ing.rule_cells or k not in eg.rule_cells for k in keys):
            return False
        for k in keys:
            ing.remove_rule(k)
            eg.remove_rule(k)
        ing.refresh_entry_views()
        eg.refresh_entry_views()
        device = self._device
        assert device is not None
        self._device = DevicePolicy(
            id_bits=device.id_bits,
            sel_match=device.sel_match,
            ingress=self._patch_tables(device.ingress, ing.take_writes()),
            egress=self._patch_tables(device.egress, eg.take_writes()),
        )
        c.revision = revision
        # deletes only retract cells under the removed rules' subject
        # selectors (refcounted 0-writes) — same column-bounding
        # contract as appends; the selectors stay interned, so this
        # lookup never grows the table
        self._log_delta("rules", ("del", subject_sids(rules, state.table)))
        return True

    def _kick_background_refresh(self) -> None:
        """Start (at most one) background full refresh (lock held)."""
        if self._bg_refresh is not None and self._bg_refresh.is_alive():
            return
        gen = self._install_gen  # what the bg result would replace

        def run():
            try:
                result = self._compute_full(self.repo, self.registry)
                with self._lock:
                    if self._install_gen != gen:
                        # someone installed a NEWER compile while this
                        # one ran (e.g. refresh(force=True)) — dropping
                        # ours is the only safe move: installing would
                        # roll enforcement back to an older rule set
                        return
                    self._install_compiled(*result)
            except Exception as e:
                # a failed background compile leaves the restored
                # tables serving; the next refresh() retries. Only
                # environmental failures are absorbed — a programmer
                # error (classified KIND_ERROR) re-raises and kills
                # this thread loudly via threading.excepthook instead
                # of hiding a TypeError behind a warning forever
                from . import faults as _faults

                if _faults.classify(e) == _faults.KIND_ERROR:
                    raise
                from .utils.logging import get_logger

                get_logger("engine").warning(
                    "background refresh failed",
                    fields={"err": f"{type(e).__name__}: {e}"},
                )

        t = threading.Thread(target=run, daemon=True)
        self._bg_refresh = t
        t.start()

    def wait_refreshed(self, timeout: Optional[float] = None) -> bool:
        """Block until a pending background refresh (if any) lands —
        tests and shutdown paths use this; serving paths never do."""
        t = self._bg_refresh
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def wait_device(self) -> None:
        """Block until every in-flight device update (row scatters,
        sel_match windows, table patches) has completed. The refresh
        path itself never calls this — updates stay enqueue-only — but
        tests and the churn bench need a completion edge to measure the
        true device RTT of a delta."""
        with self._lock:
            device = self._device
        if device is not None:
            jax.block_until_ready((device.id_bits, device.sel_match))

    # -- compiled-state snapshots (pinned-map persistence analog) -------
    def save_snapshot(self, path: str, mats=None) -> None:
        """Persist the compiled arrays (+ optional materialized
        policymaps, {direction: MaterializedState}) so a restart can
        re-load instead of re-deriving (daemon/state.go:53,135 role —
        the kernel's pinned maps keep serving across agent restarts).

        Array COPIES are taken under the engine lock (the incremental
        paths mutate them in place); the serialize + fsync — the slow
        part at scale — runs outside it so verdict serving never stalls
        behind a disk write."""
        import copy as _copy
        import dataclasses as _dc

        from .compiler.snapshot import save_compiled_state

        with self._lock:
            if self._compiled is None or self._sel_match_host is None:
                raise RuntimeError("nothing compiled to snapshot")
            c = self._compiled

            def copy_arrays(obj):
                return _dc.replace(obj, **{
                    f.name: getattr(obj, f.name).copy()
                    for f in _dc.fields(obj)
                    if isinstance(getattr(obj, f.name), np.ndarray)
                })

            compiled = copy_arrays(c)
            compiled.id_to_row = dict(c.id_to_row)
            compiled.ingress = copy_arrays(c.ingress)
            compiled.egress = copy_arrays(c.egress)
            sel_match = self._sel_match_host.copy()
            mats_copy = None
            if mats:
                mats_copy = {
                    d: _dc.replace(
                        st,
                        allow_nc=st.allow_nc.copy(),
                        red_nc=st.red_nc.copy(),
                        ep_rows=st.ep_rows.copy(),
                        ep_slots=_copy.deepcopy(st.ep_slots),
                        endpoint_identity_ids=list(
                            st.endpoint_identity_ids
                        ),
                    )
                    for d, st in mats.items()
                }
        save_compiled_state(path, compiled, sel_match, mats_copy)

    def restore_snapshot(self, path: str, *, trust_counters: bool = False):
        """Load a snapshot and bring the device tables up on it.
        → {direction: MaterializedState} (empty if none were saved), or
        None when the file is absent/unreadable.

        Mirrors the reference's restore semantics: the LOADED state
        serves immediately (last-known-good continuity); the normal
        ``refresh()`` gate re-derives when the inputs move.

        ``trust_counters`` may ONLY be True when the live repo/registry
        are the very objects the snapshot was taken from (same
        process): then matching revision counters mean matching
        content and refresh() stays a no-op. Across a restart the
        counters come from a DEAD process — a fresh repository restarts
        its numbering, so an equal revision is a coincidence, not
        equality; the default re-stamps them to a sentinel that forces
        the first refresh() to recompile (serving the restored tables
        until it lands)."""
        from .compiler.snapshot import load_compiled_state
        from .ops.materialize import state_from_snapshot

        loaded = load_compiled_state(path)
        if loaded is None:
            return None
        compiled, sel_match_host, mat_fields = loaded
        if not trust_counters:
            compiled.revision = -1
            compiled.identity_version = -1
        with self._lock:
            self._device = DevicePolicy(
                id_bits=jnp.asarray(compiled.id_bits),
                sel_match=jnp.asarray(sel_match_host),
                ingress=DeviceTables.from_host(compiled.ingress),
                egress=DeviceTables.from_host(compiled.egress),
            )
            self._sel_match_host = sel_match_host
            low = np.full(MAX_USER_IDENTITY + 1, -1, np.int32)
            high: dict = {}
            for ident, row in compiled.id_to_row.items():
                if ident < low.size:
                    low[ident] = row
                else:
                    high[ident] = row
            self._low_rows = low
            self._high_rows = high
            self._compiled = compiled
            self._state = None  # no incremental state: rule ops rebuild
            self._conj_unpacked = None
            self._pending_idents.clear()
            self._log_delta("full", ())
        return {
            d: state_from_snapshot(compiled.row_ids, f)
            for d, f in mat_fields.items()
        }

    def _set_row_index(self, ident_id: int, row: int) -> None:
        assert self._low_rows is not None
        if ident_id < self._low_rows.size:
            self._low_rows[ident_id] = row
        elif row < 0:
            self._high_rows.pop(ident_id, None)
        else:
            self._high_rows[ident_id] = row

    # ------------------------------------------------------------------
    @property
    def device_policy(self) -> DevicePolicy:
        self.refresh()
        assert self._device is not None
        return self._device

    def snapshot(self) -> Tuple[CompiledPolicy, DevicePolicy]:
        """A consistent (compiled, device) pair from one refresh —
        callers must never mix row/selector layouts across refreshes."""
        self.refresh()
        with self._lock:
            assert self._compiled is not None and self._device is not None
            return self._compiled, self._device

    def sel_match_rows(
        self,
        rows: Sequence[int],
        words: Optional[Sequence[int]] = None,
    ) -> Optional[np.ndarray]:
        """Bounded FINAL-STATE copy of the host sel_match mirror: the
        requested identity rows (× the requested packed words, all words
        when None) as a fresh array — the delta-replay source for the
        pipeline's placed-copy patching (ops/materialize
        patch_selector_rows / patch_selector_cols). Final-state reads
        make replay idempotent regardless of event ordering. None when
        the engine has no compile yet or an index is out of the mirror's
        bounds (layout moved — caller must full re-place)."""
        ridx = np.asarray(rows, np.int64)
        widx = None if words is None else np.asarray(words, np.int64)
        with self._lock:
            sm = self._sel_match_host
            if sm is None:
                return None
            if ridx.size and (ridx.min() < 0 or ridx.max() >= sm.shape[0]):
                return None
            if widx is not None and widx.size and (
                widx.min() < 0 or widx.max() >= sm.shape[1]
            ):
                return None
            if widx is None:
                return sm[ridx].copy()
            return sm[np.ix_(ridx, widx)].copy()

    def _rows_snapshot(
        self, low: np.ndarray, high: dict, identity_ids: Sequence[int]
    ) -> np.ndarray:
        ids = np.asarray(identity_ids, dtype=np.int64)
        rows = np.empty(ids.shape, np.int32)
        in_low = ids < low.size
        if (ids < 0).any():
            raise KeyError("negative identity in batch")
        rows[in_low] = low[ids[in_low]]
        for i in np.nonzero(~in_low)[0]:
            rows[i] = high.get(int(ids[i]), -1)
        if (rows < 0).any():
            raise KeyError("unknown identity in batch")
        return rows

    def rows(self, identity_ids: Sequence[int]) -> np.ndarray:
        self.refresh()
        assert self._low_rows is not None
        return self._rows_snapshot(self._low_rows, self._high_rows, identity_ids)

    def rows_or_negative(self, identity_ids: np.ndarray) -> np.ndarray:
        """[B] device rows with -1 for unknown/invalid identities — the
        tolerant variant for datapath inputs that CARRY an identity
        (overlay tunnel keys) where an unknown value must fall back,
        not raise."""
        self.refresh()
        with self._lock:
            low = self._low_rows
            high = dict(self._high_rows)
        assert low is not None
        ids = np.asarray(identity_ids, np.int64)
        rows = np.full(ids.shape, -1, np.int32)
        ok = (ids > 0) & (ids < low.size)
        rows[ok] = low[ids[ok]]
        hi = ids >= low.size
        if hi.any():
            # per-UNIQUE-id dict lookups, vectorized scatter: overlay
            # tunnel keys commonly carry high-range (local/CIDR)
            # identities and batches run to millions of flows
            uniq, inv = np.unique(ids[hi], return_inverse=True)
            vals = np.fromiter(
                (high.get(int(u), -1) for u in uniq), np.int32, len(uniq)
            )
            rows[hi] = vals[inv]
        return rows

    # -- verdict attribution (policyd-flows) ---------------------------
    def attribution(
        self, ingress: bool = True, expect_revision: Optional[int] = None
    ):
        """(AttribTables, n_rules) for the attribution kernel variant,
        or None when unavailable — a snapshot-restored engine carries no
        CompileState (no per-rule cell attribution) until its first full
        recompile lands. Cached per (install_gen, revision): identity
        churn keeps the cache, any rule movement (append, delete, full
        rebuild) rebuilds it from the packers' rule_cells refcounts.

        ``expect_revision`` lets a caller that already holds a
        (compiled, device) snapshot demand tables consistent with it: a
        rule mutation racing the two reads returns None (the caller's
        next rebuild re-materializes with matching tables) instead of
        shape-mismatched origin arrays."""
        self.refresh()
        with self._lock:
            state, c = self._state, self._compiled
            if state is None or c is None:
                return None
            if expect_revision is not None and c.revision != expect_revision:
                return None
            key = (self._install_gen, c.revision)
            cache = self._attrib_cache
            if cache is None or cache[0] != key:
                with self.repo._lock:
                    rules = list(self.repo.rules)
                keys = [id(r) for r in rules]
                tabs = {}
                for ing, packer in (
                    (True, state.ingress),
                    (False, state.egress),
                ):
                    d, a, k = rule_origin_arrays(packer, keys)
                    tabs[ing] = AttribTables(
                        # bounded static unroll (exactly 2 directions),
                        # control-plane cache build — not per-flow
                        deny_rule=jnp.asarray(d),  # policyd-lint: disable=TPU002
                        allow_rule=jnp.asarray(a),  # policyd-lint: disable=TPU002
                        combo_rule=jnp.asarray(k),  # policyd-lint: disable=TPU002
                    )
                cache = self._attrib_cache = (key, tabs, len(rules))
            return cache[1][ingress], cache[2]

    # ------------------------------------------------------------------
    def verdicts(
        self,
        subj_ids: Sequence[int],
        peer_ids: Sequence[int],
        dports: Sequence[int],
        protos: Sequence[int],
        *,
        ingress: bool = True,
        has_l4: Optional[Sequence[bool]] = None,
        attrib: bool = False,
    ):
        """Batched verdicts by identity number. ``subj`` is the endpoint
        whose policy applies (dst for ingress, src for egress). With
        ``attrib=True`` → (Verdict, Attribution, hits[R]); raises
        RuntimeError when rule-origin tables are unavailable
        (snapshot-restored engine before its first recompile)."""
        origin = n_rules = None
        if attrib:
            at = self.attribution(ingress)
            if at is None:
                raise RuntimeError(
                    "verdict attribution unavailable: engine has no "
                    "compile state (snapshot-restored?)"
                )
            origin, n_rules = at
        # Snapshot device + row tables under one lock acquisition so a
        # concurrent repo/registry mutation can't mix row indices from a
        # newer compilation into older device tables.
        self.refresh()
        with self._lock:
            device = self._device
            low = self._low_rows.copy() if self._low_rows is not None else None
            high = dict(self._high_rows)
        assert device is not None and low is not None
        _metrics.verdict_batches.inc({"path": "engine"})
        n = len(subj_ids)
        hl4 = np.ones(n, dtype=bool) if has_l4 is None else np.asarray(has_l4, bool)
        args = (
            device,
            jnp.asarray(self._rows_snapshot(low, high, subj_ids)),
            jnp.asarray(self._rows_snapshot(low, high, peer_ids)),
            jnp.asarray(np.asarray(dports, np.int32)),
            jnp.asarray(np.asarray(protos, np.int32)),
            jnp.asarray(hl4),
        )
        if not attrib:
            return verdict_batch(*args, ingress=ingress)
        return verdict_batch(
            *args, ingress=ingress, attrib=True, origin=origin, n_rules=n_rules
        )

    def explain_one(
        self,
        subj_id: int,
        peer_id: int,
        dport: int = 0,
        proto: int = PROTO_TCP,
        *,
        ingress: bool = True,
        l4: bool = True,
    ) -> dict:
        """Replay ONE flow through the verdict kernel with attribution
        on and name the deciding rule — the `cilium policy trace`-style
        explain backend."""
        verdict, at, _hits = self.verdicts(
            [subj_id], [peer_id], [dport], [proto],
            ingress=ingress, has_l4=[l4], attrib=True,
        )
        rule_idx = int(at.rule[0])
        reason = int(at.reason[0])
        origins = self.repo.rule_origins()
        return {
            "decision": int(verdict.decision[0]),
            "allowed": int(verdict.decision[0]) == ALLOW,
            "l3": int(verdict.l3[0]),
            "l7_redirect": bool(verdict.l7_redirect[0]),
            "reason_code": reason,
            "reason": ATTR_NAMES.get(reason, str(reason)),
            "rule_index": rule_idx,
            "rule": origins[rule_idx] if 0 <= rule_idx < len(origins) else None,
        }

    def verdict_one(
        self,
        subj_id: int,
        peer_id: int,
        dport: int = 0,
        proto: int = PROTO_TCP,
        *,
        ingress: bool = True,
        l4: bool = True,
    ) -> Tuple[int, int]:
        """Single query → (decision, l3_decision); the `cilium policy
        trace` fast path."""
        v = self.verdicts(
            [subj_id], [peer_id], [dport], [proto], ingress=ingress, has_l4=[l4]
        )
        return int(v.decision[0]), int(v.l3[0])
