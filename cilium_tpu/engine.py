"""PolicyEngine: the device-backed policy resolver.

The TPU-native counterpart of the reference's per-endpoint regeneration
entry points (pkg/endpoint/policy.go regeneratePolicy →
repository.AllowsIngress*): owns a Repository + IdentityRegistry,
compiles them into device tensors, refreshes when revisions move, and
answers batched verdict queries.

The refresh is the "datapath compile" of this framework — instead of
clang→llc per endpoint (pkg/datapath/loader/compile.go), it re-packs
numpy tables and lets jit shape-bucketing reuse compiled XLA programs.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from . import u8proto
from .compiler import CompiledPolicy, compile_policy
from .identity import IdentityRegistry
from .identity.model import MAX_USER_IDENTITY
from .ops.bitmap import compute_selector_matches
from .ops.verdict import DevicePolicy, DeviceTables, Verdict, verdict_batch
from .policy.repository import Repository

PROTO_TCP = u8proto.TCP
PROTO_UDP = u8proto.UDP


class PolicyEngine:
    def __init__(self, repo: Repository, registry: IdentityRegistry) -> None:
        self.repo = repo
        self.registry = registry
        self._lock = threading.Lock()
        self._compiled: Optional[CompiledPolicy] = None
        self._device: Optional[DevicePolicy] = None
        # Dense row table for the compact ranges (reserved + user,
        # < 65536) and a dict for sparse local/CIDR identities
        # (≥ LOCAL_IDENTITY_BASE = 1<<24) — a dense table over the full
        # numeric space would be ~64MB per refresh.
        self._low_rows: Optional[np.ndarray] = None
        self._high_rows: dict = {}

    # ------------------------------------------------------------------
    def _stale(self) -> bool:
        c = self._compiled
        return (
            c is None
            or c.revision != self.repo.revision
            or c.identity_version != self.registry.version
        )

    def refresh(self, force: bool = False) -> CompiledPolicy:
        """Recompile if repository or identity state moved (the revision
        gate of pkg/endpoint/policy.go:506)."""
        with self._lock:
            if not force and not self._stale():
                return self._compiled  # type: ignore[return-value]
            compiled = compile_policy(self.repo, self.registry)
            sel_match = compute_selector_matches(
                jnp.asarray(compiled.id_bits),
                jnp.asarray(compiled.conj_req),
                jnp.asarray(compiled.conj_forbid),
                jnp.asarray(compiled.conj_valid),
                jnp.asarray(compiled.req_count),
            )
            self._device = DevicePolicy(
                id_bits=jnp.asarray(compiled.id_bits),
                sel_match=sel_match,
                ingress=DeviceTables.from_host(compiled.ingress),
                egress=DeviceTables.from_host(compiled.egress),
            )
            low = np.full(MAX_USER_IDENTITY + 1, -1, np.int32)
            high: dict = {}
            for ident, row in compiled.id_to_row.items():
                if ident < low.size:
                    low[ident] = row
                else:
                    high[ident] = row
            self._low_rows = low
            self._high_rows = high
            self._compiled = compiled
            return compiled

    @property
    def device_policy(self) -> DevicePolicy:
        self.refresh()
        assert self._device is not None
        return self._device

    def snapshot(self) -> Tuple[CompiledPolicy, DevicePolicy]:
        """A consistent (compiled, device) pair from one refresh —
        callers must never mix row/selector layouts across refreshes."""
        self.refresh()
        with self._lock:
            assert self._compiled is not None and self._device is not None
            return self._compiled, self._device

    def _rows_snapshot(
        self, low: np.ndarray, high: dict, identity_ids: Sequence[int]
    ) -> np.ndarray:
        ids = np.asarray(identity_ids, dtype=np.int64)
        rows = np.empty(ids.shape, np.int32)
        in_low = ids < low.size
        if (ids < 0).any():
            raise KeyError("negative identity in batch")
        rows[in_low] = low[ids[in_low]]
        for i in np.nonzero(~in_low)[0]:
            rows[i] = high.get(int(ids[i]), -1)
        if (rows < 0).any():
            raise KeyError("unknown identity in batch")
        return rows

    def rows(self, identity_ids: Sequence[int]) -> np.ndarray:
        self.refresh()
        assert self._low_rows is not None
        return self._rows_snapshot(self._low_rows, self._high_rows, identity_ids)

    # ------------------------------------------------------------------
    def verdicts(
        self,
        subj_ids: Sequence[int],
        peer_ids: Sequence[int],
        dports: Sequence[int],
        protos: Sequence[int],
        *,
        ingress: bool = True,
        has_l4: Optional[Sequence[bool]] = None,
    ) -> Verdict:
        """Batched verdicts by identity number. ``subj`` is the endpoint
        whose policy applies (dst for ingress, src for egress)."""
        # Snapshot device + row tables under one lock acquisition so a
        # concurrent repo/registry mutation can't mix row indices from a
        # newer compilation into older device tables.
        self.refresh()
        with self._lock:
            device = self._device
            low, high = self._low_rows, self._high_rows
        assert device is not None and low is not None
        n = len(subj_ids)
        hl4 = np.ones(n, dtype=bool) if has_l4 is None else np.asarray(has_l4, bool)
        return verdict_batch(
            device,
            jnp.asarray(self._rows_snapshot(low, high, subj_ids)),
            jnp.asarray(self._rows_snapshot(low, high, peer_ids)),
            jnp.asarray(np.asarray(dports, np.int32)),
            jnp.asarray(np.asarray(protos, np.int32)),
            jnp.asarray(hl4),
            ingress=ingress,
        )

    def verdict_one(
        self,
        subj_id: int,
        peer_id: int,
        dport: int = 0,
        proto: int = PROTO_TCP,
        *,
        ingress: bool = True,
        l4: bool = True,
    ) -> Tuple[int, int]:
        """Single query → (decision, l3_decision); the `cilium policy
        trace` fast path."""
        v = self.verdicts(
            [subj_id], [peer_id], [dport], [proto], ingress=ingress, has_l4=[l4]
        )
        return int(v.decision[0]), int(v.l3[0])
